"""quest-lint: AST static analyzer for quest_tpu's compiled-path invariants.

The dominant bug classes here are mechanical, not algorithmic (PR-1
post-mortem, ADVICE r4/r5): an env knob read at trace time but missing
from the compiled-program cache key returns STALE programs when the knob
flips; a Python int leaking into Pallas index math traces as i64 under
x64 and fails Mosaic legalization; a host conversion (float()/np.asarray)
on a tracer aborts tracing with an opaque error far from the cause. QuEST
itself ships validation as a first-class layer (QuEST_validation.c); this
module is the JAX/Pallas equivalent, enforced by tooling instead of
reviewer memory.

Rules (each suppressible per line with `# quest-lint: disable=RULE` or
per file with `# quest-lint: disable-file=RULE`):

  QL001  cache-key completeness — an environment knob read reachable
         from a jitted / fused / Pallas path must be registered in
         env.KNOBS as scope 'keyed' (threaded into engine_mode_key(),
         hence into every compiled cache key and the eager workers'
         static `mode` argument) or 'import_once' (resolved once per
         process, stale-proof by construction).
  QL002  i32 kernel hygiene — inside Pallas kernels, iota/arange must
         pin an i32 dtype and index arithmetic must not name i64
         dtypes, feed bare Python-int bounds to fori_loop, or pass
         bare Python-int operands to lax.rem/div (the sweep drivers'
         slot arithmetic): Python ints trace as i64 under x64 and
         break Mosaic legalization.
  QL003  tracer leaks — no float()/int()/bool()/complex()/.item()/
         np.asarray()/np.array() on tracer-typed values in
         jit-reachable code.
  QL004  knobs parse loudly — every QUEST_* read in package code
         routes through env.knob_value()'s validating parser, and
         every QUEST_* name read anywhere is registered in env.KNOBS.

The jit-reachability analysis is a conservative intra-package call
graph: roots are functions decorated with jax.jit (directly or through
functools.partial), functions passed to jax.jit(...) / shard_map(...) /
pl.pallas_call(...), and callables handed to the lax control-flow
primitives; edges follow plain calls, module-attribute calls through
import aliases, and locally defined closures. Pallas-kernel reachability
is the same propagation seeded only from pallas_call operands.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import tokenize
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES = {
    "QL001": "cache-key completeness: compiled-path knob reads must be "
             "registered as keyed/import_once in env.KNOBS",
    "QL002": "i32 kernel hygiene: Pallas index math must pin i32 dtypes",
    "QL003": "tracer leaks: no host conversions on traced values in "
             "jit-reachable code",
    "QL004": "knobs parse loudly: QUEST_* reads route through the "
             "registry's validating parser",
}

_DISABLE_MARK = "quest-lint:"

# jnp/np spellings accepted as an explicit 32-bit (or narrower) index dtype
_I32_NAMES = {"int32", "uint32", "int16", "int8", "i32"}
_I64_NAMES = {"int64", "uint64", "i64"}

# lax control-flow / mapping primitives whose callable arguments are
# traced: a function handed to one of these inherits jit-reachability
_HOF_NAMES = {"map", "scan", "fori_loop", "while_loop", "cond", "switch",
              "vmap", "pmap", "checkpoint", "remat", "custom_jvp",
              "custom_vjp", "run_scoped", "associative_scan"}

# conversions that force a traced value onto the host (QL003)
_CONVERSIONS = {"float", "int", "bool", "complex"}


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self, root: Optional[str] = None) -> str:
        path = os.path.relpath(self.path, root) if root else self.path
        return f"{path}:{self.line}:{self.col}: {self.rule} {self.message}"


# ---------------------------------------------------------------------------
# per-file model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _EnvRead:
    name: str               # the QUEST_* (or other) variable name
    line: int
    col: int
    func: Optional[str]     # enclosing function qualname (None: module scope)
    via_registry: bool      # knob_value()/knob_current() vs raw os.environ


@dataclasses.dataclass
class _FuncInfo:
    qualname: str
    line: int
    static_params: Set[str] = dataclasses.field(default_factory=set)
    params: List[str] = dataclasses.field(default_factory=list)
    calls: List[Tuple[Optional[str], str]] = dataclasses.field(
        default_factory=list)          # (module or None=local, name)
    jit_root: bool = False
    kernel_root: bool = False
    parent: Optional[str] = None       # enclosing function qualname
    # names with positive evidence of being tracers: assigned from a
    # jnp/lax call, or non-static parameters of a jit-root function
    traced_names: Set[str] = dataclasses.field(default_factory=set)
    # local callable aliases: `kernel = functools.partial(f, ...)` binds
    # a name later handed to pallas_call/jit — the compile_segment
    # idiom. Without this map the kernel body is INVISIBLE to the
    # kernel-reachability propagation and QL002 never checks it (found
    # while extending coverage to the sweep drivers, this PR).
    local_callables: Dict[str, str] = dataclasses.field(
        default_factory=dict)


class _FileModel:
    def __init__(self, path: str, module: Optional[str], tree: ast.Module,
                 source: str):
        self.path = path
        self.module = module            # dotted name for package files
        self.tree = tree
        self.source = source
        self.import_alias: Dict[str, str] = {}   # local alias -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name->(mod,orig)
        self.funcs: Dict[str, _FuncInfo] = {}
        self.env_reads: List[_EnvRead] = []
        # cross-module callable operands of jit/pallas_call/HOFs:
        # ((module, name), is_kernel) — resolved into extra roots during
        # propagation
        self.foreign_roots: List[Tuple[Tuple[str, str], bool]] = []
        # (line, col, func, node) index of interesting calls for QL002/3
        self.conversion_sites: List[Tuple[ast.AST, Optional[str]]] = []
        self.kernel_sites: List[Tuple[ast.AST, Optional[str]]] = []
        self.uses_pallas = "pallas" in source
        self.suppressed_lines: Dict[int, Set[str]] = {}
        self.suppressed_file: Set[str] = set()
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                text = tok.string.lstrip("#").strip()
                if not text.startswith(_DISABLE_MARK):
                    continue
                body = text[len(_DISABLE_MARK):].strip()
                if body.startswith("disable-file="):
                    rules = body[len("disable-file="):]
                    self.suppressed_file.update(
                        r.strip() for r in rules.split(",") if r.strip())
                elif body.startswith("disable="):
                    rules = body[len("disable="):]
                    self.suppressed_lines.setdefault(
                        tok.start[0], set()).update(
                        r.strip() for r in rules.split(",") if r.strip())
        except tokenize.TokenError:        # pragma: no cover - parse guard
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.suppressed_file:
            return True
        return rule in self.suppressed_lines.get(line, set())


def _module_name_for(path: str, root: str) -> Optional[str]:
    """Dotted module name for files under the quest_tpu package, None
    for scripts/tests (they are linted but excluded from the package
    call graph)."""
    rel = os.path.relpath(path, root)
    parts = rel.split(os.sep)
    if "quest_tpu" in parts:
        parts = parts[parts.index("quest_tpu"):]
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)
    return None


# ---------------------------------------------------------------------------
# AST visitors
# ---------------------------------------------------------------------------


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _unwrap_partial(node: ast.AST) -> ast.AST:
    """functools.partial(f, ...) -> f (for jit decorators and
    pallas_call kernels assembled through partial)."""
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func) or ""
        if dotted.split(".")[-1] == "partial" and node.args:
            return _unwrap_partial(node.args[0])
    return node


def _is_jit_expr(node: ast.AST) -> bool:
    dotted = _dotted(node) or ""
    return dotted.split(".")[-1] == "jit"


def _static_names_from_jit(call: ast.Call) -> Set[str]:
    """static_argnames of a (possibly partial-wrapped) jax.jit call."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            val = kw.value
            elems = val.elts if isinstance(val, (ast.Tuple, ast.List)) \
                else [val]
            for e in elems:
                s = _const_str(e)
                if s:
                    out.add(s)
    return out


class _Collector(ast.NodeVisitor):
    """One pass over a file: functions, call edges, env reads, and the
    QL002/QL003 site indexes."""

    def __init__(self, model: _FileModel):
        self.m = model
        self.stack: List[str] = []      # function qualname stack

    # -- imports ----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.m.import_alias[alias.asname or alias.name.split(".")[0]] = \
                alias.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            for alias in node.names:
                local = alias.asname or alias.name
                # `from quest_tpu.ops import apply as A` binds a MODULE
                # alias; `from quest_tpu.env import knob_value` binds a
                # function. Record both ways; resolution tries module
                # first, then (module, original-name).
                self.m.import_alias[local] = f"{node.module}.{alias.name}"
                self.m.from_imports[local] = (node.module, alias.name)
        self.generic_visit(node)

    # -- functions --------------------------------------------------------
    def _handle_func(self, node) -> None:
        qual = ".".join(self.stack + [node.name]) if self.stack else node.name
        info = _FuncInfo(qualname=qual, line=node.lineno,
                         parent=self.stack[-1] if self.stack else None)
        a = node.args
        info.params = [x.arg for x in
                       (list(getattr(a, "posonlyargs", [])) + list(a.args)
                        + list(a.kwonlyargs))]
        for dec in node.decorator_list:
            target = dec
            if isinstance(dec, ast.Call):
                inner = _unwrap_partial(dec)
                if inner is not dec and _is_jit_expr(inner):
                    info.jit_root = True
                    info.static_params |= _static_names_from_jit(dec)
                    continue
                target = dec.func
            if _is_jit_expr(target):
                info.jit_root = True
                if isinstance(dec, ast.Call):
                    info.static_params |= _static_names_from_jit(dec)
        if info.jit_root:
            info.traced_names |= set(info.params) - info.static_params
        self.m.funcs[qual] = info
        self.stack.append(qual)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _handle_func
    visit_AsyncFunctionDef = _handle_func

    # -- calls ------------------------------------------------------------
    def _resolve_local(self, name: str) -> Optional[_FuncInfo]:
        """Function bound to a local bare name: innermost enclosing
        scope's nested defs first, then module scope."""
        scope = self.stack[-1] if self.stack else None
        while scope:
            f = self.m.funcs.get(scope + "." + name)
            if f:
                return f
            scope = self.m.funcs[scope].parent \
                if scope in self.m.funcs else None
        return self.m.funcs.get(name)

    def _record_callable_ref(self, node: ast.AST, kernel: bool = False):
        """A value used as a callable operand (jit(f), pallas_call(k),
        shard_map(f), a lax HOF body): the target is TRACED regardless
        of whether the constructing function ever runs under jit, so
        mark it a root directly; also record a call edge so closures
        over kernel-reachable scopes propagate."""
        node = _unwrap_partial(node)
        name = _dotted(node)
        if not name:
            return
        cur = self.stack[-1] if self.stack else None
        if "." not in name:
            # resolve partial-alias locals through enclosing scopes:
            # `kernel = functools.partial(f, ...)` then
            # `pallas_call(kernel, ...)` must root f
            scope = cur
            while scope:
                alias = self.m.funcs[scope].local_callables.get(name)
                if alias is not None:
                    name = alias
                    break
                scope = self.m.funcs[scope].parent \
                    if scope in self.m.funcs else None
        head = name.split(".")[0]
        if head in self.m.import_alias and "." in name:
            tgt = (self.m.import_alias[head], name.split(".", 1)[1])
        elif name in self.m.from_imports:
            tgt = self.m.from_imports[name]
        else:
            tgt = (None, name)
        if tgt[0] is None:
            f = self._resolve_local(tgt[1])
            if f is not None:
                if kernel:
                    f.kernel_root = True
                else:
                    f.jit_root = True
        else:
            # cross-module operand: recorded for the propagation pass
            self.m.foreign_roots.append((tgt, kernel))
        if cur:
            self.m.funcs[cur].calls.append(tgt)

    def visit_Call(self, node: ast.Call) -> None:
        cur = self.stack[-1] if self.stack else None
        dotted = _dotted(node.func) or ""
        leaf = dotted.split(".")[-1]

        # env reads: os.environ.get / os.getenv / knob_value / knob_current
        if dotted in ("os.environ.get", "environ.get", "os.getenv",
                      "getenv"):
            var = _const_str(node.args[0]) if node.args else None
            if var:
                self.m.env_reads.append(_EnvRead(
                    var, node.lineno, node.col_offset, cur, False))
        elif leaf in ("knob_value", "knob_current"):
            var = _const_str(node.args[0]) if node.args else None
            if var:
                self.m.env_reads.append(_EnvRead(
                    var, node.lineno, node.col_offset, cur, True))

        # jit roots by expression: jax.jit(f), shard_map(f, ...). lax
        # HOFs trace their bodies even outside jit, so those are roots
        # too — but only when the call is module-qualified or resolves
        # to a jax import (the BUILTIN map() must not root host code).
        if leaf == "jit" and node.args:
            self._record_callable_ref(node.args[0])
        elif leaf == "shard_map" and node.args:
            self._record_callable_ref(node.args[0])
        elif leaf == "pallas_call" and node.args:
            self._record_callable_ref(node.args[0], kernel=True)
        elif leaf in _HOF_NAMES and node.args:
            from_jax = ("." in dotted) or (
                dotted in self.m.from_imports
                and self.m.from_imports[dotted][0].startswith("jax"))
            if from_jax:
                # first callable-looking positional arg is the body
                for a in node.args:
                    inner = _unwrap_partial(a)
                    if _dotted(inner):
                        self._record_callable_ref(a)
                        break

        # ordinary call edge
        if cur and dotted:
            head = dotted.split(".")[0]
            if "." in dotted and head in self.m.import_alias:
                self.m.funcs[cur].calls.append(
                    (self.m.import_alias[head], dotted.split(".", 1)[1]))
            elif "." not in dotted:
                if dotted in self.m.from_imports:
                    self.m.funcs[cur].calls.append(
                        self.m.from_imports[dotted])
                else:
                    self.m.funcs[cur].calls.append((None, dotted))
            elif dotted.startswith("self."):
                self.m.funcs[cur].calls.append(
                    (None, dotted.split(".", 1)[1]))

        # QL003 conversion sites
        if (leaf in _CONVERSIONS and not dotted.count(".")) \
                or leaf == "item" \
                or dotted in ("np.asarray", "np.array", "numpy.asarray",
                              "numpy.array", "onp.asarray", "onp.array"):
            self.m.conversion_sites.append((node, cur))

        # QL002 kernel dtype sites (rem/div: the pipelined sweep
        # driver's slot arithmetic — a bare Python-int operand makes
        # the mixed-dtype op fail to lower under x64)
        if leaf in ("arange", "iota", "broadcasted_iota", "fori_loop",
                    "astype", "rem", "div") or leaf in _I64_NAMES:
            self.m.kernel_sites.append((node, cur))

        self.generic_visit(node)

    def _jax_numeric_call(self, node: ast.AST) -> bool:
        """Whether `node` is a call into jax/jnp/lax (its result is a
        traced array whenever the function runs under a trace)."""
        if not isinstance(node, ast.Call):
            return False
        dotted = _dotted(node.func) or ""
        head = dotted.split(".")[0]
        mod = self.m.import_alias.get(head, head)
        return mod.split(".")[0] == "jax"

    def _handle_assign_value(self, targets, value) -> None:
        if not self.stack:
            return
        f = self.m.funcs[self.stack[-1]]
        if isinstance(value, ast.Call):
            inner = _unwrap_partial(value)
            if inner is not value:
                # callable alias: `kernel = functools.partial(fn, ...)`
                name = _dotted(inner)
                if name:
                    for t in targets:
                        if isinstance(t, ast.Name):
                            f.local_callables[t.id] = name
        if not self._jax_numeric_call(value):
            return
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                if isinstance(e, ast.Name):
                    f.traced_names.add(e.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._handle_assign_value(node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._handle_assign_value([node.target], node.value)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # os.environ["X"] reads (Load context only; stores are writes)
        if isinstance(node.ctx, ast.Load):
            dotted = _dotted(node.value) or ""
            if dotted in ("os.environ", "environ"):
                var = _const_str(node.slice)
                if var:
                    cur = self.stack[-1] if self.stack else None
                    self.m.env_reads.append(_EnvRead(
                        var, node.lineno, node.col_offset, cur, False))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# reachability
# ---------------------------------------------------------------------------


def _propagate(models: Dict[str, _FileModel], attr: str) -> Set[Tuple[str, str]]:
    """Fixed-point propagation of a root flag ('jit_root'/'kernel_root')
    through the call graph. Returns {(module, qualname)} reachable."""
    # index: (module, bare name) -> [(module, qualname)]
    by_name: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for mod, m in models.items():
        for qual in m.funcs:
            bare = qual.split(".")[-1]
            by_name.setdefault((mod, bare), []).append((mod, qual))

    reached: Set[Tuple[str, str]] = set()
    work: List[Tuple[str, str]] = []
    for mod, m in models.items():
        for qual, f in m.funcs.items():
            if getattr(f, attr):
                reached.add((mod, qual))
                work.append((mod, qual))
        for (tmod, tname), is_kernel in m.foreign_roots:
            if is_kernel != (attr == "kernel_root"):
                continue
            for hit in by_name.get((tmod, tname.split(".")[-1]), []):
                if hit not in reached:
                    reached.add(hit)
                    work.append(hit)

    def resolve(src_mod: str, src_qual: str,
                tgt: Tuple[Optional[str], str]) -> List[Tuple[str, str]]:
        tmod, tname = tgt
        if tmod is not None:
            # exact module match, else (from-import of a function) the
            # module itself may be the function's home
            hits = by_name.get((tmod, tname.split(".")[-1]), [])
            if hits:
                return hits
            # `from quest_tpu.ops import apply as A` + A.foo: tmod is
            # quest_tpu.ops.apply already handled; `from quest_tpu import
            # env` + env.knob_value: same shape. Nothing else to try.
            return []
        # local: innermost enclosing scope first, then module scope
        m = models[src_mod]
        scope = src_qual
        while scope:
            qual = scope + "." + tname
            if qual in m.funcs:
                return [(src_mod, qual)]
            scope = m.funcs[scope].parent if scope in m.funcs else None
        if tname in m.funcs:
            return [(src_mod, tname)]
        # method call on self/instance: any class method with that name
        hits = [h for h in by_name.get((src_mod, tname.split(".")[-1]), [])
                if "." in h[1]]
        return hits

    while work:
        mod, qual = work.pop()
        f = models[mod].funcs[qual]
        for tgt in f.calls:
            for hit in resolve(mod, qual, tgt):
                if hit not in reached:
                    reached.add(hit)
                    work.append(hit)
        # nested defs referenced by bare name are resolved through
        # `calls` already (closures are invoked or passed to HOFs)
    return reached


def _enclosing_chain(m: _FileModel, qual: Optional[str]) -> List[str]:
    out = []
    while qual:
        out.append(qual)
        qual = m.funcs[qual].parent if qual in m.funcs else None
    return out


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _knob_registry():
    from quest_tpu.env import KNOBS
    return KNOBS


def _is_i32_dtype_node(node: ast.AST) -> bool:
    dotted = _dotted(node) or _const_str(node) or ""
    return dotted.split(".")[-1] in _I32_NAMES


def _check_ql001(models: Dict[str, _FileModel],
                 reach: Set[Tuple[str, str]],
                 out: List[Violation]) -> None:
    knobs = _knob_registry()
    for mod, m in models.items():
        if m.module is None:
            continue                      # scripts/tests are driver code
        for r in m.env_reads:
            if not r.name.lstrip("_").startswith("QUEST_"):
                continue
            if r.func is None:
                continue                  # import-time read: stale-proof
            chain = _enclosing_chain(m, r.func)
            if not any((mod, q) in reach for q in chain):
                continue
            k = knobs.get(r.name)
            if k is None or k.scope not in ("keyed", "import_once"):
                scope = "unregistered" if k is None else f"scope={k.scope!r}"
                out.append(Violation(
                    "QL001", m.path, r.line, r.col,
                    f"knob {r.name} is read on a jit/Pallas-reachable "
                    f"path but is {scope} in env.KNOBS: register it as "
                    f"scope='keyed' (threads it into engine_mode_key() "
                    f"and every compiled cache key) or 'import_once', "
                    f"or the compiled caches go stale when it flips"))


def _check_ql002(models: Dict[str, _FileModel],
                 kreach: Set[Tuple[str, str]],
                 out: List[Violation]) -> None:
    for mod, m in models.items():
        if not m.uses_pallas:
            continue
        for node, func in m.kernel_sites:
            chain = _enclosing_chain(m, func)
            key_mod = mod if m.module else m.path
            if not any((key_mod, q) in kreach for q in chain):
                continue
            dotted = _dotted(node.func) or ""
            leaf = dotted.split(".")[-1]
            if leaf in ("iota", "broadcasted_iota"):
                dtype = node.args[0] if node.args else None
                for kw in node.keywords:
                    if kw.arg in ("dtype",):
                        dtype = kw.value
                if dtype is None or not _is_i32_dtype_node(dtype):
                    out.append(Violation(
                        "QL002", m.path, node.lineno, node.col_offset,
                        f"{leaf} inside a Pallas kernel must pin an i32 "
                        f"dtype (jnp.int32): wider index dtypes trace as "
                        f"i64 under x64 and fail Mosaic legalization"))
            elif leaf == "arange":
                dtype = None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype = kw.value
                if dtype is None or (not _is_i32_dtype_node(dtype)
                                     and _dotted(dtype) is not None
                                     and _dotted(dtype).split(".")[-1]
                                     in _I64_NAMES):
                    out.append(Violation(
                        "QL002", m.path, node.lineno, node.col_offset,
                        "jnp.arange inside a Pallas kernel must pass an "
                        "explicit non-i64 dtype (index math: jnp.int32) — "
                        "the default promotes to i64 under x64"))
            elif leaf == "astype":
                if node.args and _dotted(node.args[0]) and \
                        _dotted(node.args[0]).split(".")[-1] in _I64_NAMES:
                    out.append(Violation(
                        "QL002", m.path, node.lineno, node.col_offset,
                        "astype(i64) inside a Pallas kernel: Mosaic "
                        "cannot lower 64-bit index math; use jnp.int32"))
            elif leaf in _I64_NAMES:
                out.append(Violation(
                    "QL002", m.path, node.lineno, node.col_offset,
                    f"{leaf} constructor inside a Pallas kernel: Mosaic "
                    f"cannot lower 64-bit index math; use jnp.int32"))
            elif leaf == "fori_loop":
                for bound in node.args[:2]:
                    if isinstance(bound, ast.Constant) \
                            and isinstance(bound.value, int):
                        out.append(Violation(
                            "QL002", m.path, node.lineno, node.col_offset,
                            "fori_loop bound is a bare Python int inside "
                            "a Pallas kernel: it traces as i64 under x64 "
                            "(pin with jnp.int32(...) so the carry stays "
                            "32-bit)"))
                        break
            elif leaf in ("rem", "div") and "." in dotted:
                # the sweep/pipelined drivers' slot arithmetic
                # (lax.rem(step, nbuf)): a bare Python-int operand
                # traces as i64 under x64, and a mixed-dtype rem fails
                # to lower in interpret mode and legalize in Mosaic
                for arg in node.args:
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, int):
                        out.append(Violation(
                            "QL002", m.path, node.lineno, node.col_offset,
                            f"lax.{leaf} with a bare Python-int operand "
                            f"inside a Pallas kernel: it traces as i64 "
                            f"under x64 and the mixed-dtype op fails "
                            f"Mosaic legalization (pin with "
                            f"jnp.int32(...))"))
                        break


def _check_ql003(models: Dict[str, _FileModel],
                 reach: Set[Tuple[str, str]],
                 out: List[Violation]) -> None:
    """Tracer leaks need POSITIVE evidence of tracedness: the operand is
    a non-static parameter of a jit-rooted function, a name assigned
    from a jnp/lax call, or such a call inline. Trace-time host math on
    concrete operands (baking a named gate's numpy matrix into the
    program, normalizing static target tuples) is a deliberate idiom
    here and must not be flagged."""
    for mod, m in models.items():
        if m.module is None:
            continue
        for node, func in m.conversion_sites:
            chain = _enclosing_chain(m, func)
            if not any((mod, q) in reach for q in chain):
                continue
            f = m.funcs.get(func) if func else None
            dotted = _dotted(node.func) or ""
            leaf = dotted.split(".")[-1]
            if leaf == "item":
                recv = node.func.value if isinstance(node.func,
                                                     ast.Attribute) else None
                if recv is not None and _traced_evidence(recv, f, m):
                    out.append(Violation(
                        "QL003", m.path, node.lineno, node.col_offset,
                        ".item() on a traced value in jit-reachable code "
                        "forces it onto the host and aborts tracing; keep "
                        "the value on-device or hoist the read out of the "
                        "compiled path"))
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if not _traced_evidence(arg, f, m):
                continue
            if leaf in _CONVERSIONS:
                out.append(Violation(
                    "QL003", m.path, node.lineno, node.col_offset,
                    f"{leaf}() on a traced value in jit-reachable code "
                    f"aborts tracing at run time (ConcretizationTypeError "
                    f"far from the cause); convert outside the compiled "
                    f"path or mark the argument static"))
            else:
                out.append(Violation(
                    "QL003", m.path, node.lineno, node.col_offset,
                    f"{dotted}() materializes a traced value on the host; "
                    f"inside jit-reachable code that is a tracer leak — "
                    f"use the jnp equivalent or hoist it out"))


def _traced_evidence(arg: ast.AST, f: Optional[_FuncInfo],
                     m: _FileModel) -> bool:
    """Whether the expression demonstrably involves a traced value."""
    if isinstance(arg, ast.Name):
        return bool(f and arg.id in f.traced_names)
    if isinstance(arg, (ast.Attribute, ast.Subscript)):
        # x[0] / x.real of a traced x — but x.shape[i] etc. are static
        base = arg
        while isinstance(base, (ast.Attribute, ast.Subscript)):
            if isinstance(base, ast.Attribute) and base.attr in (
                    "shape", "ndim", "size", "dtype"):
                return False
            base = base.value
        return _traced_evidence(base, f, m)
    if isinstance(arg, ast.Call):
        dotted = _dotted(arg.func) or ""
        head = dotted.split(".")[0]
        mod = m.import_alias.get(head, head)
        if mod.split(".")[0] == "jax":
            return True
        return any(_traced_evidence(a, f, m) for a in arg.args)
    if isinstance(arg, ast.BinOp):
        return _traced_evidence(arg.left, f, m) \
            or _traced_evidence(arg.right, f, m)
    if isinstance(arg, ast.UnaryOp):
        return _traced_evidence(arg.operand, f, m)
    return False


def _check_ql004(models: Dict[str, _FileModel],
                 out: List[Violation]) -> None:
    knobs = _knob_registry()
    for mod, m in models.items():
        for r in m.env_reads:
            if not r.name.lstrip("_").startswith("QUEST_"):
                continue
            if r.name not in knobs:
                out.append(Violation(
                    "QL004", m.path, r.line, r.col,
                    f"knob {r.name} is not registered in env.KNOBS: "
                    f"every QUEST_* knob needs a registry entry with a "
                    f"validating parser (name, parse, default, scope)"))
                continue
            if (m.module is not None and m.module != "quest_tpu.env"
                    and not r.via_registry):
                out.append(Violation(
                    "QL004", m.path, r.line, r.col,
                    f"direct os.environ read of {r.name} bypasses the "
                    f"registry's validating parser; use "
                    f"env.knob_value({r.name!r}) so malformed input "
                    f"raises at the read site"))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(os.path.abspath(p))
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.abspath(
                            os.path.join(dirpath, fn)))
    return out


def run_lint(paths: Sequence[str],
             rules: Optional[Sequence[str]] = None,
             root: Optional[str] = None) -> List[Violation]:
    """Lint `paths` (files or directories); returns unsuppressed
    violations sorted by location. `rules` restricts to a subset of
    RULES; `root` anchors module-name resolution (default: the common
    ancestor containing the quest_tpu package)."""
    files = collect_files(paths)
    if root is None:
        root = os.path.commonpath(files) if files else os.getcwd()
        while root != os.path.dirname(root) and not os.path.isdir(
                os.path.join(root, "quest_tpu")):
            root = os.path.dirname(root)

    models: Dict[str, _FileModel] = {}
    violations: List[Violation] = []
    for path in files:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            violations.append(Violation(
                "QL000", path, e.lineno or 0, e.offset or 0,
                f"syntax error: {e.msg}"))
            continue
        module = _module_name_for(path, root)
        m = _FileModel(path, module, tree, source)
        _Collector(m).visit(tree)
        # key: dotted module for package files, path for driver files
        models[module or path] = m

    reach = _propagate(models, "jit_root")
    kreach = _propagate(models, "kernel_root")

    active = set(rules) if rules else set(RULES)
    if "QL001" in active:
        _check_ql001(models, reach, violations)
    if "QL002" in active:
        _check_ql002(models, kreach, violations)
    if "QL003" in active:
        _check_ql003(models, reach, violations)
    if "QL004" in active:
        _check_ql004(models, violations)

    by_path = {m.path: m for m in models.values()}
    kept = [v for v in violations
            if not (v.path in by_path
                    and by_path[v.path].suppressed(v.rule, v.line))]
    kept.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return kept
