"""quest-lint: AST static analyzer for quest_tpu's compiled-path invariants.

The dominant bug classes here are mechanical, not algorithmic (PR-1
post-mortem, ADVICE r4/r5): an env knob read at trace time but missing
from the compiled-program cache key returns STALE programs when the knob
flips; a Python int leaking into Pallas index math traces as i64 under
x64 and fails Mosaic legalization; a host conversion (float()/np.asarray)
on a tracer aborts tracing with an opaque error far from the cause. QuEST
itself ships validation as a first-class layer (QuEST_validation.c); this
module is the JAX/Pallas equivalent, enforced by tooling instead of
reviewer memory.

Rules (each suppressible per line with `# quest-lint: disable=RULE` or
per file with `# quest-lint: disable-file=RULE`):

  QL001  cache-key completeness — an environment knob read reachable
         from a jitted / fused / Pallas path must be registered in
         env.KNOBS as scope 'keyed' (threaded into engine_mode_key(),
         hence into every compiled cache key and the eager workers'
         static `mode` argument) or 'import_once' (resolved once per
         process, stale-proof by construction).
  QL002  i32 kernel hygiene — inside Pallas kernels, iota/arange must
         pin an i32 dtype and index arithmetic must not name i64
         dtypes, feed bare Python-int bounds to fori_loop, or pass
         bare Python-int operands to lax.rem/div (the sweep drivers'
         slot arithmetic): Python ints trace as i64 under x64 and
         break Mosaic legalization.
  QL003  tracer leaks — no float()/int()/bool()/complex()/.item()/
         np.asarray()/np.array() on tracer-typed values in
         jit-reachable code.
  QL004  knobs parse loudly — every QUEST_* read in package code
         routes through env.knob_value()'s validating parser, and
         every QUEST_* name read anywhere is registered in env.KNOBS.
  QL005  lock discipline — a class that owns a threading lock declares
         a `_GUARDED_BY` table (lock attr -> guarded attrs); guarded
         attributes may only be touched inside `with self.<lock>` or
         from private methods the intra-class call graph proves are
         only reached under it. `# quest-lint: disable=QL005(reason)`
         escapes are themselves flagged when they suppress nothing.
  QL006  use-after-donate — calling a donate_argnums-carrying compiled
         entry (the compiled*/jit dispatch family) consumes the
         argument buffer; any later use of the donated binding in the
         same function is the PR-13 deleted-input bug class.
  QL007  blocking-under-lock — no device syncs (block_until_ready /
         .item() / np.asarray), time.sleep, subprocess, or file I/O
         while holding a declared serve/fleet lock (lexically or via
         a lock-held private method): the watchdog-deadlock class.
  QL008  atomic-write discipline — write-mode open() in the
         persistence modules (checkpoint chains, plan cache) must ride
         the temp+rename commit idiom; a bare final-path write is a
         torn-resume bug.
  QL009  fault-site integrity — every literal fired through
         faults.check()/._fault() names a catalog site, and every
         faults.SITES entry has >= 1 firing call site in the package
         and >= 1 test arming it.

The jit-reachability analysis is a conservative intra-package call
graph: roots are functions decorated with jax.jit (directly or through
functools.partial), functions passed to jax.jit(...) / shard_map(...) /
pl.pallas_call(...), and callables handed to the lax control-flow
primitives; edges follow plain calls, module-attribute calls through
import aliases, and locally defined closures. Pallas-kernel reachability
is the same propagation seeded only from pallas_call operands.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

RULES = {
    "QL001": "cache-key completeness: compiled-path knob reads must be "
             "registered as keyed/import_once in env.KNOBS",
    "QL002": "i32 kernel hygiene: Pallas index math must pin i32 dtypes",
    "QL003": "tracer leaks: no host conversions on traced values in "
             "jit-reachable code",
    "QL004": "knobs parse loudly: QUEST_* reads route through the "
             "registry's validating parser",
    "QL005": "lock discipline: _GUARDED_BY attributes are only touched "
             "under their declared lock (or from lock-held methods)",
    "QL006": "use-after-donate: a donated dispatch input must not be "
             "used again in the same function",
    "QL007": "blocking-under-lock: no device syncs, sleeps, subprocess "
             "or file I/O while holding a serve/fleet lock",
    "QL008": "atomic-write discipline: persistence-module writes ride "
             "the temp+rename commit idiom",
    "QL009": "fault-site integrity: fired sites are cataloged, every "
             "catalog site is fired and armed by a test",
}

_DISABLE_MARK = "quest-lint:"

# jnp/np spellings accepted as an explicit 32-bit (or narrower) index dtype
_I32_NAMES = {"int32", "uint32", "int16", "int8", "i32"}
_I64_NAMES = {"int64", "uint64", "i64"}

# lax control-flow / mapping primitives whose callable arguments are
# traced: a function handed to one of these inherits jit-reachability
_HOF_NAMES = {"map", "scan", "fori_loop", "while_loop", "cond", "switch",
              "vmap", "pmap", "checkpoint", "remat", "custom_jvp",
              "custom_vjp", "run_scoped", "associative_scan"}

# conversions that force a traced value onto the host (QL003)
_CONVERSIONS = {"float", "int", "bool", "complex"}

# suppression grammar: RULE or RULE(reason). Reason-carrying
# suppressions are AUDITED — one that suppresses nothing is itself
# flagged (QL005's reviewed-escape contract); bare ones keep the
# original fire-and-forget semantics.
_SUPP_RE = re.compile(r"(QL\d{3})\s*(?:\(([^)]*)\))?")

# QL005: lock constructors recognized in __init__, and the reserved
# _GUARDED_BY key for single-owner-thread (lock-free by contract)
# attributes. A "|"-joined key ("_lock|_cond") means entering a `with`
# on ANY of the named attributes counts as holding the scope
# (Condition(self._lock) wraps the same lock).
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_OWNER_KEY = "<owner-thread>"

# QL006: compiled-entry factories whose donate=True result consumes its
# input buffer, per the circuit/sharded dispatch family's contract
_DONATING_FACTORIES = {
    "compiled", "compiled_banded", "compiled_fused", "compiled_sharded",
    "compiled_sharded_banded", "compiled_sharded_fused",
}

# QL008: the modules whose on-disk artifacts power crash recovery —
# every write-mode open here must ride the temp+rename commit idiom
_PERSISTENCE_MODULES = {
    "quest_tpu.checkpoint", "quest_tpu.plan",
    "quest_tpu.resilience.durable",
}

# QL009: fault-site-shaped string literals ("serve.dispatch")
_SITE_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z][a-z0-9_]*$")


@dataclasses.dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def render(self, root: Optional[str] = None) -> str:
        path = os.path.relpath(self.path, root) if root else self.path
        return f"{path}:{self.line}:{self.col}: {self.rule} {self.message}"


# ---------------------------------------------------------------------------
# per-file model
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _EnvRead:
    name: str               # the QUEST_* (or other) variable name
    line: int
    col: int
    func: Optional[str]     # enclosing function qualname (None: module scope)
    via_registry: bool      # knob_value()/knob_current() vs raw os.environ


@dataclasses.dataclass
class _AttrAccess:
    """One `self.<attr>` touch inside a class body (QL005)."""
    attr: str
    line: int
    col: int
    method: Optional[str]   # enclosing function qualname
    write: bool
    locks: FrozenSet[str]   # self-lock names lexically held at the site


@dataclasses.dataclass
class _ClassInfo:
    """Per-class index for the lock-discipline rules (QL005/QL007)."""
    name: str
    line: int
    guarded_by: Optional[Dict[str, Tuple[str, ...]]] = None
    guarded_line: int = 0
    guard_parse_error: Optional[str] = None
    lock_attrs: Dict[str, int] = dataclasses.field(default_factory=dict)
    methods: Set[str] = dataclasses.field(default_factory=set)
    accesses: List[_AttrAccess] = dataclasses.field(default_factory=list)
    # (caller root method, callee bare name, locks held at site, line)
    self_calls: List[Tuple[str, str, FrozenSet[str], int]] = \
        dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _FuncInfo:
    qualname: str
    line: int
    static_params: Set[str] = dataclasses.field(default_factory=set)
    params: List[str] = dataclasses.field(default_factory=list)
    calls: List[Tuple[Optional[str], str]] = dataclasses.field(
        default_factory=list)          # (module or None=local, name)
    jit_root: bool = False
    kernel_root: bool = False
    parent: Optional[str] = None       # enclosing function qualname
    node: Optional[ast.AST] = None     # the def node (QL006 re-walk)
    # QL006: local names bound to donate-carrying compiled entries
    # (name -> donated positional indices), and the taint sites where
    # such an entry consumed a binding: (binding, line, col)
    donating: Dict[str, Tuple[int, ...]] = dataclasses.field(
        default_factory=dict)
    donate_taints: List[Tuple[str, int, int]] = dataclasses.field(
        default_factory=list)
    has_rename: bool = False           # os.rename/os.replace (QL008)
    # names with positive evidence of being tracers: assigned from a
    # jnp/lax call, or non-static parameters of a jit-root function
    traced_names: Set[str] = dataclasses.field(default_factory=set)
    # local callable aliases: `kernel = functools.partial(f, ...)` binds
    # a name later handed to pallas_call/jit — the compile_segment
    # idiom. Without this map the kernel body is INVISIBLE to the
    # kernel-reachability propagation and QL002 never checks it (found
    # while extending coverage to the sweep drivers, this PR).
    local_callables: Dict[str, str] = dataclasses.field(
        default_factory=dict)


class _FileModel:
    def __init__(self, path: str, module: Optional[str], tree: ast.Module,
                 source: str):
        self.path = path
        self.module = module            # dotted name for package files
        self.tree = tree
        self.source = source
        self.import_alias: Dict[str, str] = {}   # local alias -> module
        self.from_imports: Dict[str, Tuple[str, str]] = {}  # name->(mod,orig)
        self.funcs: Dict[str, _FuncInfo] = {}
        self.env_reads: List[_EnvRead] = []
        # cross-module callable operands of jit/pallas_call/HOFs:
        # ((module, name), is_kernel) — resolved into extra roots during
        # propagation
        self.foreign_roots: List[Tuple[Tuple[str, str], bool]] = []
        # (line, col, func, node) index of interesting calls for QL002/3
        self.conversion_sites: List[Tuple[ast.AST, Optional[str]]] = []
        self.kernel_sites: List[Tuple[ast.AST, Optional[str]]] = []
        self.uses_pallas = "pallas" in source
        # line -> {rule: reason-or-None}; file-level: rule -> (reason, line)
        self.suppressed_lines: Dict[int, Dict[str, Optional[str]]] = {}
        self.suppressed_file: Dict[str, Tuple[Optional[str], int]] = {}
        # QL005/QL007 class index; QL007 candidate blocking calls:
        # (node, func, locks held, class name, human label)
        self.classes: Dict[str, _ClassInfo] = {}
        self.blocking_sites: List[Tuple[ast.Call, Optional[str],
                                        FrozenSet[str], str, str]] = []
        # QL008: write-mode opens (node, func qualname)
        self.write_opens: List[Tuple[ast.Call, Optional[str]]] = []
        # QL009: fired/armed fault-site literals + the scanned catalog
        self.fault_fires: List[Tuple[str, int, int]] = []
        self.fault_arms: Set[str] = set()
        self.site_strings: Set[str] = set()
        self.sites_catalog: Optional[Tuple[Tuple[str, ...], int]] = None
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                text = tok.string.lstrip("#").strip()
                if not text.startswith(_DISABLE_MARK):
                    continue
                body = text[len(_DISABLE_MARK):].strip()
                if body.startswith("disable-file="):
                    spec = body[len("disable-file="):]
                    for rule, reason in _SUPP_RE.findall(spec):
                        self.suppressed_file[rule] = (
                            reason or None, tok.start[0])
                elif body.startswith("disable="):
                    spec = body[len("disable="):]
                    # trailing comment guards its own line; a comment-
                    # only line guards the line below it
                    line = tok.start[0]
                    if not tok.line[:tok.start[1]].strip():
                        line += 1
                    entry = self.suppressed_lines.setdefault(line, {})
                    for rule, reason in _SUPP_RE.findall(spec):
                        entry[rule] = reason or None
        except tokenize.TokenError:        # pragma: no cover - parse guard
            pass

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.suppressed_file:
            return True
        return rule in self.suppressed_lines.get(line, {})


def _module_name_for(path: str, root: str) -> Optional[str]:
    """Dotted module name for files under the quest_tpu package, None
    for scripts/tests (they are linted but excluded from the package
    call graph)."""
    rel = os.path.relpath(path, root)
    parts = rel.split(os.sep)
    if "quest_tpu" in parts:
        parts = parts[parts.index("quest_tpu"):]
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)
    return None


# ---------------------------------------------------------------------------
# AST visitors
# ---------------------------------------------------------------------------


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _unwrap_partial(node: ast.AST) -> ast.AST:
    """functools.partial(f, ...) -> f (for jit decorators and
    pallas_call kernels assembled through partial)."""
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func) or ""
        if dotted.split(".")[-1] == "partial" and node.args:
            return _unwrap_partial(node.args[0])
    return node


def _is_jit_expr(node: ast.AST) -> bool:
    dotted = _dotted(node) or ""
    return dotted.split(".")[-1] == "jit"


def _static_names_from_jit(call: ast.Call) -> Set[str]:
    """static_argnames of a (possibly partial-wrapped) jax.jit call."""
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            val = kw.value
            elems = val.elts if isinstance(val, (ast.Tuple, ast.List)) \
                else [val]
            for e in elems:
                s = _const_str(e)
                if s:
                    out.add(s)
    return out


def _parse_guarded_by(node: ast.AST):
    """Parse a `_GUARDED_BY` class annotation: a dict literal mapping a
    lock attribute name (``"_lock"``, the alias form ``"_lock|_cond"``
    for a Condition wrapping the same Lock, or the reserved
    ``"<owner-thread>"`` for single-owner lock-free state) to a
    tuple/list/set of guarded attribute names.  Returns
    ``(mapping, error)`` — exactly one is None."""
    if not isinstance(node, ast.Dict):
        return None, "_GUARDED_BY must be a dict literal"
    out: Dict[str, Tuple[str, ...]] = {}
    for k, v in zip(node.keys, node.values):
        key = _const_str(k) if k is not None else None
        if key is None:
            return None, "_GUARDED_BY keys must be string literals"
        if not isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            return None, (f"_GUARDED_BY[{key!r}] must be a tuple/list/set "
                          "of attribute-name literals")
        attrs: List[str] = []
        for e in v.elts:
            s = _const_str(e)
            if s is None:
                return None, (f"_GUARDED_BY[{key!r}] must contain only "
                              "string literals")
            attrs.append(s)
        out[key] = tuple(attrs)
    return out, None


def _donate_positions_of(call: ast.AST) -> Tuple[int, ...]:
    """Donated positional indices of the compiled entry a call
    expression builds, or () when it donates nothing.  Recognizes the
    circuit compile factories (`compiled*(..., donate=True)` — they
    donate position 0, the amplitude planes) and literal
    `jax.jit(..., donate_argnums=...)`.  A conditional
    `donate_argnums=(0,) if donate else ()` is deliberately treated as
    non-donating: the call sites guard themselves."""
    if not isinstance(call, ast.Call):
        return ()
    leaf = (_dotted(call.func) or "").split(".")[-1]
    if leaf in _DONATING_FACTORIES:
        for kw in call.keywords:
            if kw.arg == "donate" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is True:
                return (0,)
        return ()
    if leaf == "jit":
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                vals = tuple(e.value for e in v.elts
                             if isinstance(e, ast.Constant)
                             and isinstance(e.value, int))
                if len(vals) == len(v.elts):
                    return vals
        return ()
    return ()


class _Collector(ast.NodeVisitor):
    """One pass over a file: functions, call edges, env reads, and the
    QL002/QL003 site indexes."""

    def __init__(self, model: _FileModel):
        self.m = model
        self.stack: List[str] = []      # function qualname stack
        self.class_stack: List[_ClassInfo] = []
        self.lock_stack: List[str] = []  # self-lock names lexically held

    # -- imports ----------------------------------------------------------
    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.m.import_alias[alias.asname or alias.name.split(".")[0]] = \
                alias.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module:
            for alias in node.names:
                local = alias.asname or alias.name
                # `from quest_tpu.ops import apply as A` binds a MODULE
                # alias; `from quest_tpu.env import knob_value` binds a
                # function. Record both ways; resolution tries module
                # first, then (module, original-name).
                self.m.import_alias[local] = f"{node.module}.{alias.name}"
                self.m.from_imports[local] = (node.module, alias.name)
        self.generic_visit(node)

    # -- classes (QL005/QL007 lock index) ---------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        qual = ".".join([c.name for c in self.class_stack] + [node.name]) \
            if self.class_stack else node.name
        ci = _ClassInfo(name=qual, line=node.lineno)
        for stmt in node.body:
            tgt = val = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                tgt, val = stmt.targets[0].id, stmt.value
            elif isinstance(stmt, ast.AnnAssign) \
                    and isinstance(stmt.target, ast.Name) \
                    and stmt.value is not None:
                tgt, val = stmt.target.id, stmt.value
            if tgt == "_GUARDED_BY":
                ci.guarded_line = stmt.lineno
                ci.guarded_by, ci.guard_parse_error = \
                    _parse_guarded_by(val)
        self.m.classes[qual] = ci
        self.class_stack.append(ci)
        self.generic_visit(node)
        self.class_stack.pop()

    def _handle_with(self, node) -> None:
        pushed = 0
        for item in node.items:
            d = _dotted(item.context_expr)
            if d and d.startswith("self.") and d.count(".") == 1:
                self.lock_stack.append(d.split(".", 1)[1])
                pushed += 1
        self.generic_visit(node)
        if pushed:
            del self.lock_stack[-pushed:]

    visit_With = _handle_with
    visit_AsyncWith = _handle_with

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.class_stack and self.stack \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            self.class_stack[-1].accesses.append(_AttrAccess(
                node.attr, node.lineno, node.col_offset,
                self.stack[-1], isinstance(node.ctx, (ast.Store, ast.Del)),
                frozenset(self.lock_stack)))
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        # QL009 arming evidence: site-shaped string literals
        v = node.value
        if isinstance(v, str) and 2 < len(v) < 64 and "." in v \
                and _SITE_RE.match(v):
            self.m.site_strings.add(v)

    # -- functions --------------------------------------------------------
    def _handle_func(self, node) -> None:
        qual = ".".join(self.stack + [node.name]) if self.stack else node.name
        info = _FuncInfo(qualname=qual, line=node.lineno,
                         parent=self.stack[-1] if self.stack else None,
                         node=node)
        if self.class_stack and not self.stack:
            self.class_stack[-1].methods.add(node.name)
        a = node.args
        info.params = [x.arg for x in
                       (list(getattr(a, "posonlyargs", [])) + list(a.args)
                        + list(a.kwonlyargs))]
        for dec in node.decorator_list:
            target = dec
            if isinstance(dec, ast.Call):
                inner = _unwrap_partial(dec)
                if inner is not dec and _is_jit_expr(inner):
                    info.jit_root = True
                    info.static_params |= _static_names_from_jit(dec)
                    continue
                target = dec.func
            if _is_jit_expr(target):
                info.jit_root = True
                if isinstance(dec, ast.Call):
                    info.static_params |= _static_names_from_jit(dec)
        if info.jit_root:
            info.traced_names |= set(info.params) - info.static_params
        self.m.funcs[qual] = info
        self.stack.append(qual)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _handle_func
    visit_AsyncFunctionDef = _handle_func

    # -- calls ------------------------------------------------------------
    def _resolve_local(self, name: str) -> Optional[_FuncInfo]:
        """Function bound to a local bare name: innermost enclosing
        scope's nested defs first, then module scope."""
        scope = self.stack[-1] if self.stack else None
        while scope:
            f = self.m.funcs.get(scope + "." + name)
            if f:
                return f
            scope = self.m.funcs[scope].parent \
                if scope in self.m.funcs else None
        return self.m.funcs.get(name)

    def _record_callable_ref(self, node: ast.AST, kernel: bool = False):
        """A value used as a callable operand (jit(f), pallas_call(k),
        shard_map(f), a lax HOF body): the target is TRACED regardless
        of whether the constructing function ever runs under jit, so
        mark it a root directly; also record a call edge so closures
        over kernel-reachable scopes propagate."""
        node = _unwrap_partial(node)
        name = _dotted(node)
        if not name:
            return
        cur = self.stack[-1] if self.stack else None
        if "." not in name:
            # resolve partial-alias locals through enclosing scopes:
            # `kernel = functools.partial(f, ...)` then
            # `pallas_call(kernel, ...)` must root f
            scope = cur
            while scope:
                alias = self.m.funcs[scope].local_callables.get(name)
                if alias is not None:
                    name = alias
                    break
                scope = self.m.funcs[scope].parent \
                    if scope in self.m.funcs else None
        head = name.split(".")[0]
        if head in self.m.import_alias and "." in name:
            tgt = (self.m.import_alias[head], name.split(".", 1)[1])
        elif name in self.m.from_imports:
            tgt = self.m.from_imports[name]
        else:
            tgt = (None, name)
        if tgt[0] is None:
            f = self._resolve_local(tgt[1])
            if f is not None:
                if kernel:
                    f.kernel_root = True
                else:
                    f.jit_root = True
        else:
            # cross-module operand: recorded for the propagation pass
            self.m.foreign_roots.append((tgt, kernel))
        if cur:
            self.m.funcs[cur].calls.append(tgt)

    def visit_Call(self, node: ast.Call) -> None:
        cur = self.stack[-1] if self.stack else None
        dotted = _dotted(node.func) or ""
        leaf = dotted.split(".")[-1]

        # env reads: os.environ.get / os.getenv / knob_value / knob_current
        if dotted in ("os.environ.get", "environ.get", "os.getenv",
                      "getenv"):
            var = _const_str(node.args[0]) if node.args else None
            if var:
                self.m.env_reads.append(_EnvRead(
                    var, node.lineno, node.col_offset, cur, False))
        elif leaf in ("knob_value", "knob_current"):
            var = _const_str(node.args[0]) if node.args else None
            if var:
                self.m.env_reads.append(_EnvRead(
                    var, node.lineno, node.col_offset, cur, True))

        # jit roots by expression: jax.jit(f), shard_map(f, ...). lax
        # HOFs trace their bodies even outside jit, so those are roots
        # too — but only when the call is module-qualified or resolves
        # to a jax import (the BUILTIN map() must not root host code).
        if leaf == "jit" and node.args:
            self._record_callable_ref(node.args[0])
        elif leaf == "shard_map" and node.args:
            self._record_callable_ref(node.args[0])
        elif leaf == "pallas_call" and node.args:
            self._record_callable_ref(node.args[0], kernel=True)
        elif leaf in _HOF_NAMES and node.args:
            from_jax = ("." in dotted) or (
                dotted in self.m.from_imports
                and self.m.from_imports[dotted][0].startswith("jax"))
            if from_jax:
                # first callable-looking positional arg is the body
                for a in node.args:
                    inner = _unwrap_partial(a)
                    if _dotted(inner):
                        self._record_callable_ref(a)
                        break

        # ordinary call edge
        if cur and dotted:
            head = dotted.split(".")[0]
            if "." in dotted and head in self.m.import_alias:
                self.m.funcs[cur].calls.append(
                    (self.m.import_alias[head], dotted.split(".", 1)[1]))
            elif "." not in dotted:
                if dotted in self.m.from_imports:
                    self.m.funcs[cur].calls.append(
                        self.m.from_imports[dotted])
                else:
                    self.m.funcs[cur].calls.append((None, dotted))
            elif dotted.startswith("self."):
                self.m.funcs[cur].calls.append(
                    (None, dotted.split(".", 1)[1]))

        # QL003 conversion sites
        if (leaf in _CONVERSIONS and not dotted.count(".")) \
                or leaf == "item" \
                or dotted in ("np.asarray", "np.array", "numpy.asarray",
                              "numpy.array", "onp.asarray", "onp.array"):
            self.m.conversion_sites.append((node, cur))

        # QL002 kernel dtype sites (rem/div: the pipelined sweep
        # driver's slot arithmetic — a bare Python-int operand makes
        # the mixed-dtype op fail to lower under x64)
        if leaf in ("arange", "iota", "broadcasted_iota", "fori_loop",
                    "astype", "rem", "div") or leaf in _I64_NAMES:
            self.m.kernel_sites.append((node, cur))

        head = dotted.split(".")[0] if dotted else ""

        # QL008: temp+rename evidence and write-mode opens
        if cur and head == "os" and leaf in ("rename", "replace"):
            self.m.funcs[cur].has_rename = True
        if dotted == "open":
            mode = _const_str(node.args[1]) if len(node.args) > 1 else None
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = _const_str(kw.value) or mode
            if mode and any(c in mode for c in "wax+"):
                self.m.write_opens.append((node, cur))
        elif leaf in ("write_text", "write_bytes") and "." in dotted:
            self.m.write_opens.append((node, cur))

        # QL005: self-method call edges with their lexical lock context
        if self.class_stack and self.stack and dotted.startswith("self.") \
                and dotted.count(".") == 1:
            self.class_stack[-1].self_calls.append(
                (self.stack[0], dotted.split(".", 1)[1],
                 frozenset(self.lock_stack), node.lineno))

        # QL007: candidate blocking calls inside lock-owning classes
        if self.class_stack and self.stack:
            label = self._blocking_label(dotted, leaf, head)
            if label:
                self.m.blocking_sites.append(
                    (node, cur, frozenset(self.lock_stack),
                     self.class_stack[-1].name, label))

        # QL009: fired / armed fault-site literals
        s0 = _const_str(node.args[0]) if node.args else None
        if s0:
            if leaf == "check" and dotted.endswith(".check"):
                recv = dotted[:-len(".check")]
                rmod = self.m.import_alias.get(recv, recv)
                if rmod.split(".")[-1] == "faults":
                    self.m.fault_fires.append(
                        (s0, node.lineno, node.col_offset))
            elif dotted == "self._fault":
                self.m.fault_fires.append(
                    (s0, node.lineno, node.col_offset))
            elif leaf == "inject":
                self.m.fault_arms.add(s0)
            elif leaf == "parse_plan":
                for part in s0.split(";"):
                    site = part.split(":", 1)[0].strip()
                    if site:
                        self.m.fault_arms.add(site)

        # QL006: a call through a donate-carrying compiled entry taints
        # the bindings it consumes
        if cur and isinstance(node.func, ast.Name):
            positions = self._donating_positions(node.func.id)
            if positions:
                f = self.m.funcs[cur]
                end = getattr(node, "end_lineno", node.lineno)
                for p in positions:
                    if p < len(node.args):
                        b = _dotted(node.args[p])
                        if b:
                            f.donate_taints.append(
                                (b, node.lineno, node.col_offset, end))

        self.generic_visit(node)

    def _blocking_label(self, dotted: str, leaf: str,
                        head: str) -> Optional[str]:
        """Human label when the call blocks (QL007), else None."""
        if leaf == "block_until_ready":
            return "jax.block_until_ready (device sync)"
        if dotted == "time.sleep" or (
                dotted == "sleep"
                and self.m.from_imports.get("sleep", ("", ""))[0]
                == "time"):
            return "time.sleep"
        mod = self.m.import_alias.get(head, head)
        if mod.split(".")[0] == "subprocess" and "." in dotted:
            return f"{dotted} (subprocess)"
        if dotted == "open":
            return "open() file I/O"
        if leaf == "item" and "." in dotted:
            return ".item() (device sync)"
        if dotted in ("np.asarray", "np.array", "numpy.asarray",
                      "numpy.array"):
            return f"{dotted} (host materialization)"
        return None

    def _donating_positions(self, name: str) -> Tuple[int, ...]:
        """Donated positional indices when `name` is locally bound to a
        donate-carrying compiled entry (scope chain, like locals)."""
        scope = self.stack[-1] if self.stack else None
        while scope:
            info = self.m.funcs.get(scope)
            if info is None:
                break
            if name in info.donating:
                return info.donating[name]
            scope = info.parent
        return ()

    def _jax_numeric_call(self, node: ast.AST) -> bool:
        """Whether `node` is a call into jax/jnp/lax (its result is a
        traced array whenever the function runs under a trace)."""
        if not isinstance(node, ast.Call):
            return False
        dotted = _dotted(node.func) or ""
        head = dotted.split(".")[0]
        mod = self.m.import_alias.get(head, head)
        return mod.split(".")[0] == "jax"

    def _handle_assign_value(self, targets, value) -> None:
        if not self.stack:
            return
        f = self.m.funcs[self.stack[-1]]
        if isinstance(value, ast.Call):
            inner = _unwrap_partial(value)
            if inner is not value:
                # callable alias: `kernel = functools.partial(fn, ...)`
                name = _dotted(inner)
                if name:
                    for t in targets:
                        if isinstance(t, ast.Name):
                            f.local_callables[t.id] = name
            # QL006: `fn = circ.compiled_fused(..., donate=True)` /
            # `fn = jax.jit(g, donate_argnums=(0,))` binds a
            # buffer-consuming entry
            positions = _donate_positions_of(value)
            if positions:
                for t in targets:
                    if isinstance(t, ast.Name):
                        f.donating[t.id] = positions
        if not self._jax_numeric_call(value):
            return
        for t in targets:
            elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
            for e in elts:
                if isinstance(e, ast.Name):
                    f.traced_names.add(e.id)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._handle_assign_value(node.targets, node.value)
        # QL005: lock attributes created in __init__
        if self.class_stack and self.stack \
                and self.stack[0] == "__init__" \
                and isinstance(node.value, ast.Call):
            leaf = (_dotted(node.value.func) or "").split(".")[-1]
            if leaf in _LOCK_FACTORIES:
                for t in node.targets:
                    d = _dotted(t)
                    if d and d.startswith("self.") and d.count(".") == 1:
                        self.class_stack[-1].lock_attrs[
                            d.split(".", 1)[1]] = node.lineno
        # QL009: the module-level fault-site catalog (faults.SITES)
        if not self.stack and not self.class_stack \
                and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "SITES" \
                and isinstance(node.value, (ast.Tuple, ast.List)) \
                and os.path.basename(self.m.path) == "faults.py":
            elts = node.value.elts
            vals = tuple(e.value for e in elts
                         if isinstance(e, ast.Constant)
                         and isinstance(e.value, str))
            if vals and len(vals) == len(elts):
                self.m.sites_catalog = (vals, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._handle_assign_value([node.target], node.value)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # os.environ["X"] reads (Load context only; stores are writes)
        if isinstance(node.ctx, ast.Load):
            dotted = _dotted(node.value) or ""
            if dotted in ("os.environ", "environ"):
                var = _const_str(node.slice)
                if var:
                    cur = self.stack[-1] if self.stack else None
                    self.m.env_reads.append(_EnvRead(
                        var, node.lineno, node.col_offset, cur, False))
        self.generic_visit(node)


# ---------------------------------------------------------------------------
# reachability
# ---------------------------------------------------------------------------


def _propagate(models: Dict[str, _FileModel], attr: str) -> Set[Tuple[str, str]]:
    """Fixed-point propagation of a root flag ('jit_root'/'kernel_root')
    through the call graph. Returns {(module, qualname)} reachable."""
    # index: (module, bare name) -> [(module, qualname)]
    by_name: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
    for mod, m in models.items():
        for qual in m.funcs:
            bare = qual.split(".")[-1]
            by_name.setdefault((mod, bare), []).append((mod, qual))

    reached: Set[Tuple[str, str]] = set()
    work: List[Tuple[str, str]] = []
    for mod, m in models.items():
        for qual, f in m.funcs.items():
            if getattr(f, attr):
                reached.add((mod, qual))
                work.append((mod, qual))
        for (tmod, tname), is_kernel in m.foreign_roots:
            if is_kernel != (attr == "kernel_root"):
                continue
            for hit in by_name.get((tmod, tname.split(".")[-1]), []):
                if hit not in reached:
                    reached.add(hit)
                    work.append(hit)

    def resolve(src_mod: str, src_qual: str,
                tgt: Tuple[Optional[str], str]) -> List[Tuple[str, str]]:
        tmod, tname = tgt
        if tmod is not None:
            # exact module match, else (from-import of a function) the
            # module itself may be the function's home
            hits = by_name.get((tmod, tname.split(".")[-1]), [])
            if hits:
                return hits
            # `from quest_tpu.ops import apply as A` + A.foo: tmod is
            # quest_tpu.ops.apply already handled; `from quest_tpu import
            # env` + env.knob_value: same shape. Nothing else to try.
            return []
        # local: innermost enclosing scope first, then module scope
        m = models[src_mod]
        scope = src_qual
        while scope:
            qual = scope + "." + tname
            if qual in m.funcs:
                return [(src_mod, qual)]
            scope = m.funcs[scope].parent if scope in m.funcs else None
        if tname in m.funcs:
            return [(src_mod, tname)]
        # method call on self/instance: any class method with that name
        hits = [h for h in by_name.get((src_mod, tname.split(".")[-1]), [])
                if "." in h[1]]
        return hits

    while work:
        mod, qual = work.pop()
        f = models[mod].funcs[qual]
        for tgt in f.calls:
            for hit in resolve(mod, qual, tgt):
                if hit not in reached:
                    reached.add(hit)
                    work.append(hit)
        # nested defs referenced by bare name are resolved through
        # `calls` already (closures are invoked or passed to HOFs)
    return reached


def _enclosing_chain(m: _FileModel, qual: Optional[str]) -> List[str]:
    out = []
    while qual:
        out.append(qual)
        qual = m.funcs[qual].parent if qual in m.funcs else None
    return out


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _knob_registry():
    from quest_tpu.env import KNOBS
    return KNOBS


def _is_i32_dtype_node(node: ast.AST) -> bool:
    dotted = _dotted(node) or _const_str(node) or ""
    return dotted.split(".")[-1] in _I32_NAMES


def _check_ql001(models: Dict[str, _FileModel],
                 reach: Set[Tuple[str, str]],
                 out: List[Violation]) -> None:
    knobs = _knob_registry()
    for mod, m in models.items():
        if m.module is None:
            continue                      # scripts/tests are driver code
        for r in m.env_reads:
            if not r.name.lstrip("_").startswith("QUEST_"):
                continue
            if r.func is None:
                continue                  # import-time read: stale-proof
            chain = _enclosing_chain(m, r.func)
            if not any((mod, q) in reach for q in chain):
                continue
            k = knobs.get(r.name)
            if k is None or k.scope not in ("keyed", "import_once"):
                scope = "unregistered" if k is None else f"scope={k.scope!r}"
                out.append(Violation(
                    "QL001", m.path, r.line, r.col,
                    f"knob {r.name} is read on a jit/Pallas-reachable "
                    f"path but is {scope} in env.KNOBS: register it as "
                    f"scope='keyed' (threads it into engine_mode_key() "
                    f"and every compiled cache key) or 'import_once', "
                    f"or the compiled caches go stale when it flips"))


def _check_ql002(models: Dict[str, _FileModel],
                 kreach: Set[Tuple[str, str]],
                 out: List[Violation]) -> None:
    for mod, m in models.items():
        if not m.uses_pallas:
            continue
        for node, func in m.kernel_sites:
            chain = _enclosing_chain(m, func)
            key_mod = mod if m.module else m.path
            if not any((key_mod, q) in kreach for q in chain):
                continue
            dotted = _dotted(node.func) or ""
            leaf = dotted.split(".")[-1]
            if leaf in ("iota", "broadcasted_iota"):
                dtype = node.args[0] if node.args else None
                for kw in node.keywords:
                    if kw.arg in ("dtype",):
                        dtype = kw.value
                if dtype is None or not _is_i32_dtype_node(dtype):
                    out.append(Violation(
                        "QL002", m.path, node.lineno, node.col_offset,
                        f"{leaf} inside a Pallas kernel must pin an i32 "
                        f"dtype (jnp.int32): wider index dtypes trace as "
                        f"i64 under x64 and fail Mosaic legalization"))
            elif leaf == "arange":
                dtype = None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype = kw.value
                if dtype is None or (not _is_i32_dtype_node(dtype)
                                     and _dotted(dtype) is not None
                                     and _dotted(dtype).split(".")[-1]
                                     in _I64_NAMES):
                    out.append(Violation(
                        "QL002", m.path, node.lineno, node.col_offset,
                        "jnp.arange inside a Pallas kernel must pass an "
                        "explicit non-i64 dtype (index math: jnp.int32) — "
                        "the default promotes to i64 under x64"))
            elif leaf == "astype":
                if node.args and _dotted(node.args[0]) and \
                        _dotted(node.args[0]).split(".")[-1] in _I64_NAMES:
                    out.append(Violation(
                        "QL002", m.path, node.lineno, node.col_offset,
                        "astype(i64) inside a Pallas kernel: Mosaic "
                        "cannot lower 64-bit index math; use jnp.int32"))
            elif leaf in _I64_NAMES:
                out.append(Violation(
                    "QL002", m.path, node.lineno, node.col_offset,
                    f"{leaf} constructor inside a Pallas kernel: Mosaic "
                    f"cannot lower 64-bit index math; use jnp.int32"))
            elif leaf == "fori_loop":
                for bound in node.args[:2]:
                    if isinstance(bound, ast.Constant) \
                            and isinstance(bound.value, int):
                        out.append(Violation(
                            "QL002", m.path, node.lineno, node.col_offset,
                            "fori_loop bound is a bare Python int inside "
                            "a Pallas kernel: it traces as i64 under x64 "
                            "(pin with jnp.int32(...) so the carry stays "
                            "32-bit)"))
                        break
            elif leaf in ("rem", "div") and "." in dotted:
                # the sweep/pipelined drivers' slot arithmetic
                # (lax.rem(step, nbuf)): a bare Python-int operand
                # traces as i64 under x64, and a mixed-dtype rem fails
                # to lower in interpret mode and legalize in Mosaic
                for arg in node.args:
                    if isinstance(arg, ast.Constant) \
                            and isinstance(arg.value, int):
                        out.append(Violation(
                            "QL002", m.path, node.lineno, node.col_offset,
                            f"lax.{leaf} with a bare Python-int operand "
                            f"inside a Pallas kernel: it traces as i64 "
                            f"under x64 and the mixed-dtype op fails "
                            f"Mosaic legalization (pin with "
                            f"jnp.int32(...))"))
                        break


def _check_ql003(models: Dict[str, _FileModel],
                 reach: Set[Tuple[str, str]],
                 out: List[Violation]) -> None:
    """Tracer leaks need POSITIVE evidence of tracedness: the operand is
    a non-static parameter of a jit-rooted function, a name assigned
    from a jnp/lax call, or such a call inline. Trace-time host math on
    concrete operands (baking a named gate's numpy matrix into the
    program, normalizing static target tuples) is a deliberate idiom
    here and must not be flagged."""
    for mod, m in models.items():
        if m.module is None:
            continue
        for node, func in m.conversion_sites:
            chain = _enclosing_chain(m, func)
            if not any((mod, q) in reach for q in chain):
                continue
            f = m.funcs.get(func) if func else None
            dotted = _dotted(node.func) or ""
            leaf = dotted.split(".")[-1]
            if leaf == "item":
                recv = node.func.value if isinstance(node.func,
                                                     ast.Attribute) else None
                if recv is not None and _traced_evidence(recv, f, m):
                    out.append(Violation(
                        "QL003", m.path, node.lineno, node.col_offset,
                        ".item() on a traced value in jit-reachable code "
                        "forces it onto the host and aborts tracing; keep "
                        "the value on-device or hoist the read out of the "
                        "compiled path"))
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if not _traced_evidence(arg, f, m):
                continue
            if leaf in _CONVERSIONS:
                out.append(Violation(
                    "QL003", m.path, node.lineno, node.col_offset,
                    f"{leaf}() on a traced value in jit-reachable code "
                    f"aborts tracing at run time (ConcretizationTypeError "
                    f"far from the cause); convert outside the compiled "
                    f"path or mark the argument static"))
            else:
                out.append(Violation(
                    "QL003", m.path, node.lineno, node.col_offset,
                    f"{dotted}() materializes a traced value on the host; "
                    f"inside jit-reachable code that is a tracer leak — "
                    f"use the jnp equivalent or hoist it out"))


def _traced_evidence(arg: ast.AST, f: Optional[_FuncInfo],
                     m: _FileModel) -> bool:
    """Whether the expression demonstrably involves a traced value."""
    if isinstance(arg, ast.Name):
        return bool(f and arg.id in f.traced_names)
    if isinstance(arg, (ast.Attribute, ast.Subscript)):
        # x[0] / x.real of a traced x — but x.shape[i] etc. are static
        base = arg
        while isinstance(base, (ast.Attribute, ast.Subscript)):
            if isinstance(base, ast.Attribute) and base.attr in (
                    "shape", "ndim", "size", "dtype"):
                return False
            base = base.value
        return _traced_evidence(base, f, m)
    if isinstance(arg, ast.Call):
        dotted = _dotted(arg.func) or ""
        head = dotted.split(".")[0]
        mod = m.import_alias.get(head, head)
        if mod.split(".")[0] == "jax":
            return True
        return any(_traced_evidence(a, f, m) for a in arg.args)
    if isinstance(arg, ast.BinOp):
        return _traced_evidence(arg.left, f, m) \
            or _traced_evidence(arg.right, f, m)
    if isinstance(arg, ast.UnaryOp):
        return _traced_evidence(arg.operand, f, m)
    return False


def _check_ql004(models: Dict[str, _FileModel],
                 out: List[Violation]) -> None:
    knobs = _knob_registry()
    for mod, m in models.items():
        for r in m.env_reads:
            if not r.name.lstrip("_").startswith("QUEST_"):
                continue
            if r.name not in knobs:
                out.append(Violation(
                    "QL004", m.path, r.line, r.col,
                    f"knob {r.name} is not registered in env.KNOBS: "
                    f"every QUEST_* knob needs a registry entry with a "
                    f"validating parser (name, parse, default, scope)"))
                continue
            if (m.module is not None and m.module != "quest_tpu.env"
                    and not r.via_registry):
                out.append(Violation(
                    "QL004", m.path, r.line, r.col,
                    f"direct os.environ read of {r.name} bypasses the "
                    f"registry's validating parser; use "
                    f"env.knob_value({r.name!r}) so malformed input "
                    f"raises at the read site"))


# ---------------------------------------------------------------------------
# QL005 — lock discipline
# ---------------------------------------------------------------------------


def _lock_groups(ci: _ClassInfo) -> Dict[str, FrozenSet[str]]:
    """guarded-by key -> the set of lock attr names that satisfy it
    (the `"_lock|_cond"` alias form accepts either)."""
    return {key: frozenset(key.split("|"))
            for key in (ci.guarded_by or {}) if key != _OWNER_KEY}


def _held_methods(ci: _ClassInfo, group: FrozenSet[str]) -> Set[str]:
    """Methods provably only reached with a lock of `group` held:
    greatest fixed point over the intra-class call graph.  Seeded with
    private helpers that have at least one internal call site; a method
    is demoted when any call site lacks the lock and the caller is not
    itself held.  Public methods never qualify — external callers
    don't hold the lock."""
    callees = {c for (_caller, c, _locks, _ln) in ci.self_calls}
    held = {name for name in ci.methods
            if name.startswith("_") and not name.startswith("__")
            and name in callees}
    changed = True
    while changed:
        changed = False
        for (caller, callee, locks, _ln) in ci.self_calls:
            if callee not in held:
                continue
            if locks & group:
                continue
            if caller in held:
                continue
            held.discard(callee)
            changed = True
    return held


def _check_ql005(models: Dict[str, _FileModel],
                 out: List[Violation]) -> None:
    for mod, m in models.items():
        for ci in m.classes.values():
            if ci.guard_parse_error:
                out.append(Violation(
                    "QL005", m.path, ci.guarded_line, 0,
                    f"malformed _GUARDED_BY on {ci.name}: "
                    f"{ci.guard_parse_error}"))
                continue
            if ci.guarded_by is None:
                # classes that own a lock must declare what it guards
                if ci.lock_attrs:
                    lock, line = sorted(ci.lock_attrs.items(),
                                        key=lambda kv: kv[1])[0]
                    out.append(Violation(
                        "QL005", m.path, line, 0,
                        f"{ci.name} creates self.{lock} but declares no "
                        f"_GUARDED_BY: list the attributes the lock "
                        f"guards (see docs/ANALYSIS.md)"))
                continue
            groups = _lock_groups(ci)
            guarded: Dict[str, FrozenSet[str]] = {}
            for key, attrs in ci.guarded_by.items():
                if key == _OWNER_KEY:
                    for a in attrs:
                        guarded[a] = frozenset()
                    continue
                locks = groups[key]
                if not locks & set(ci.lock_attrs):
                    out.append(Violation(
                        "QL005", m.path, ci.guarded_line, 0,
                        f"_GUARDED_BY key {key!r} on {ci.name} names no "
                        f"lock created in __init__ "
                        f"(have: {sorted(ci.lock_attrs) or 'none'})"))
                    continue
                for a in attrs:
                    guarded[a] = locks
            held_cache: Dict[FrozenSet[str], Set[str]] = {}
            declared = set(guarded) | set(ci.lock_attrs)
            for acc in ci.accesses:
                if acc.method and acc.method.split(".")[0] == "__init__":
                    continue  # construction happens-before publication
                locks = guarded.get(acc.attr)
                if locks is None:
                    # completeness: writes to undeclared shared attrs
                    if acc.write and acc.attr not in declared \
                            and not acc.attr.startswith("__"):
                        out.append(Violation(
                            "QL005", m.path, acc.line, acc.col,
                            f"{ci.name}.{acc.attr} is written outside "
                            f"__init__ but missing from _GUARDED_BY: "
                            f"declare its lock (or put it under "
                            f"'<owner-thread>' if single-owner)"))
                    continue
                if not locks:
                    continue  # <owner-thread>: trusted single-owner
                if acc.locks & locks:
                    continue
                root = acc.method.split(".")[0] if acc.method else None
                if locks not in held_cache:
                    held_cache[locks] = _held_methods(ci, locks)
                if root in held_cache[locks]:
                    continue
                kind = "write to" if acc.write else "read of"
                out.append(Violation(
                    "QL005", m.path, acc.line, acc.col,
                    f"unlocked {kind} {ci.name}.{acc.attr}: "
                    f"_GUARDED_BY says hold self.{sorted(locks)[0]} "
                    f"(wrap in `with self.{sorted(locks)[0]}:` or call "
                    f"from a lock-held helper)"))


# ---------------------------------------------------------------------------
# QL006 — use-after-donate
# ---------------------------------------------------------------------------


def _first_use_after_donate(f: _FuncInfo, binding: str, taint_line: int,
                            end_line: int) -> Optional[Tuple[int, int]]:
    """(line, col) of the first Load of `binding` (or its root name)
    after the donating call, unless a rebind/del of the name between
    the taint and the use clears it (`amps = fn(amps)` is the blessed
    idiom).  Conservative per-function, line-ordered."""
    root = binding.split(".")[0]
    stores: List[int] = []
    loads: List[Tuple[int, int]] = []
    for n in ast.walk(f.node):
        if isinstance(n, ast.Name) and n.id == root:
            if isinstance(n.ctx, (ast.Store, ast.Del)):
                stores.append(n.lineno)
            elif n.lineno > end_line:
                # the donated binding itself, or any dotted use of it
                loads.append((n.lineno, n.col_offset))
        elif isinstance(n, ast.Attribute) \
                and isinstance(n.ctx, (ast.Store, ast.Del)) \
                and _dotted(n) == binding:
            stores.append(n.lineno)
    for line, col in sorted(loads):
        if any(taint_line <= s <= line for s in stores):
            return None  # rebound before (or at) this use: cleared
        if "." in binding:
            # dotted binding (state.amps): only a matching dotted load
            # counts — the root object itself stays valid
            continue
        return (line, col)
    if "." in binding:
        # re-walk for the exact dotted expression in Load context
        for n in ast.walk(f.node):
            if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load) \
                    and n.lineno > end_line and _dotted(n) == binding:
                if not any(taint_line <= s <= n.lineno for s in stores):
                    return (n.lineno, n.col_offset)
    return None


def _check_ql006(models: Dict[str, _FileModel],
                 out: List[Violation]) -> None:
    for mod, m in models.items():
        for f in m.funcs.values():
            if not f.donate_taints or f.node is None:
                continue
            for (binding, line, col, end) in f.donate_taints:
                hit = _first_use_after_donate(f, binding, line, end)
                if hit:
                    out.append(Violation(
                        "QL006", m.path, hit[0], hit[1],
                        f"use of {binding} after it was donated to a "
                        f"compiled entry at line {line}: the buffer is "
                        f"deleted on dispatch (the PR-13 run_evolution "
                        f"bug); copy before the call or rebind the "
                        f"result"))


# ---------------------------------------------------------------------------
# QL007 — blocking calls under a serve/fleet lock
# ---------------------------------------------------------------------------


def _check_ql007(models: Dict[str, _FileModel],
                 out: List[Violation]) -> None:
    for mod, m in models.items():
        for (node, func, locks, cls, label) in m.blocking_sites:
            ci = m.classes.get(cls)
            if ci is None or not ci.lock_attrs:
                continue
            own = set(ci.lock_attrs)
            held = locks & own
            root = func.split(".")[0] if func else None
            if not held and root is not None:
                # call-graph propagation: a private helper only ever
                # entered with the lock held blocks just the same
                for group in (set(_lock_groups(ci).values())
                              or {frozenset(own)}):
                    if root in _held_methods(ci, group):
                        held = group & own
                        break
            if not held:
                continue
            if root == "__init__":
                continue
            lock = sorted(held)[0]
            out.append(Violation(
                "QL007", m.path, node.lineno, node.col_offset,
                f"{label} while holding self.{lock} in {cls}: every "
                f"other thread contending for the lock stalls behind "
                f"this call (the watchdog-deadlock class); move it "
                f"outside the critical section"))


# ---------------------------------------------------------------------------
# QL008 — atomic-write discipline in persistence modules
# ---------------------------------------------------------------------------


def _check_ql008(models: Dict[str, _FileModel],
                 out: List[Violation]) -> None:
    for mod, m in models.items():
        if m.module not in _PERSISTENCE_MODULES:
            continue
        for (node, func) in m.write_opens:
            chain = _enclosing_chain(m, func)
            # the temp+rename idiom: any function on the enclosing
            # chain whose subtree performs os.replace/os.rename makes
            # the write crash-atomic (write tmp, fsync, rename)
            safe = any(m.funcs[q].has_rename for q in chain
                       if q in m.funcs)
            if not safe and func is not None:
                # nested helpers: the top-level enclosing def may carry
                # the rename while the helper does the open
                top = chain[-1] if chain else func
                info = m.funcs.get(top)
                if info is not None and info.node is not None:
                    safe = any(
                        isinstance(n, ast.Call)
                        and (_dotted(n.func) or "") in
                        ("os.rename", "os.replace")
                        for n in ast.walk(info.node))
            if safe:
                continue
            out.append(Violation(
                "QL008", m.path, node.lineno, node.col_offset,
                f"bare write in {m.module} outside a temp+rename "
                f"scope: a crash mid-write leaves a torn file the "
                f"resume path will read (PR-12 gang-tmp class); write "
                f"to a tmp name and os.replace() into place"))


# ---------------------------------------------------------------------------
# QL009 — fault-site catalog integrity
# ---------------------------------------------------------------------------


def _is_test_file(m: _FileModel, root: str) -> bool:
    rel = os.path.relpath(m.path, root)
    base = os.path.basename(m.path)
    return rel.split(os.sep)[0] == "tests" and (
        base.startswith("test_") or base == "conftest.py")


def _site_catalog(models: Dict[str, _FileModel]):
    """(sites, path, line) from the scanned faults.py, else from the
    importable package (single-file lint runs still validate literals
    against the real catalog), else None."""
    for m in models.values():
        if m.sites_catalog is not None:
            return m.sites_catalog[0], m.path, m.sites_catalog[1]
    try:
        from quest_tpu.resilience import faults as _faults
        return tuple(_faults.SITES), None, 0
    except Exception:                      # pragma: no cover - import guard
        return None


def _check_ql009(models: Dict[str, _FileModel], root: str,
                 out: List[Violation]) -> None:
    cat = _site_catalog(models)
    if cat is None:                        # pragma: no cover - import guard
        return
    sites, cat_path, cat_line = cat
    known = set(sites)
    fires: Dict[str, int] = {}
    arms: Set[str] = set()
    have_tests = False
    for mod, m in models.items():
        if _is_test_file(m, root):
            have_tests = True
            arms |= m.fault_arms
            arms |= {s for s in m.site_strings if s in known}
        for (site, line, col) in m.fault_fires:
            fires[site] = fires.get(site, 0) + 1
            if site not in known:
                out.append(Violation(
                    "QL009", m.path, line, col,
                    f"fault site {site!r} is not in faults.SITES: a "
                    f"typo here makes the injection plan silently "
                    f"never fire; add it to the catalog or fix the "
                    f"literal"))
    # coverage legs only when the catalog itself and the test tree are
    # both in scope (single-file runs stay literal-validation only)
    if cat_path is None or not have_tests:
        return
    for site in sites:
        if site not in fires:
            out.append(Violation(
                "QL009", cat_path, cat_line, 0,
                f"catalog site {site!r} has no firing call site "
                f"(faults.check/self._fault literal) anywhere in the "
                f"tree: dead catalog entries rot into armed-but-"
                f"silent pins"))
        if site not in arms:
            out.append(Violation(
                "QL009", cat_path, cat_line, 0,
                f"catalog site {site!r} is never armed by any test "
                f"(no inject()/parse_plan()/literal in tests/): the "
                f"failure path it guards is untested"))


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(os.path.abspath(p))
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.abspath(
                            os.path.join(dirpath, fn)))
    return out


def run_lint(paths: Sequence[str],
             rules: Optional[Sequence[str]] = None,
             root: Optional[str] = None) -> List[Violation]:
    """Lint `paths` (files or directories); returns unsuppressed
    violations sorted by location. `rules` restricts to a subset of
    RULES; `root` anchors module-name resolution (default: the common
    ancestor containing the quest_tpu package)."""
    files = collect_files(paths)
    if root is None:
        root = os.path.commonpath(files) if files else os.getcwd()
        while root != os.path.dirname(root) and not os.path.isdir(
                os.path.join(root, "quest_tpu")):
            root = os.path.dirname(root)

    models: Dict[str, _FileModel] = {}
    violations: List[Violation] = []
    for path in files:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            violations.append(Violation(
                "QL000", path, e.lineno or 0, e.offset or 0,
                f"syntax error: {e.msg}"))
            continue
        module = _module_name_for(path, root)
        m = _FileModel(path, module, tree, source)
        _Collector(m).visit(tree)
        # key: dotted module for package files, path for driver files
        models[module or path] = m

    reach = _propagate(models, "jit_root")
    kreach = _propagate(models, "kernel_root")

    active = set(rules) if rules else set(RULES)
    if "QL001" in active:
        _check_ql001(models, reach, violations)
    if "QL002" in active:
        _check_ql002(models, kreach, violations)
    if "QL003" in active:
        _check_ql003(models, reach, violations)
    if "QL004" in active:
        _check_ql004(models, violations)
    if "QL005" in active:
        _check_ql005(models, violations)
    if "QL006" in active:
        _check_ql006(models, violations)
    if "QL007" in active:
        _check_ql007(models, violations)
    if "QL008" in active:
        _check_ql008(models, violations)
    if "QL009" in active:
        _check_ql009(models, root, violations)

    by_path = {m.path: m for m in models.values()}
    used: Set[Tuple[str, int, str]] = set()
    kept: List[Violation] = []
    for v in violations:
        m = by_path.get(v.path)
        if m is not None and m.suppressed(v.rule, v.line):
            if v.rule in m.suppressed_lines.get(v.line, {}):
                used.add((v.path, v.line, v.rule))
            else:
                used.add((v.path, -1, v.rule))
            continue
        kept.append(v)
    # audited escapes: a reasoned `disable=QLnnn(reason)` that
    # suppresses nothing is itself flagged — stale escapes are how
    # real violations sneak back in. Bare (reasonless) suppressions
    # keep the old fire-and-forget semantics.
    for m in by_path.values():
        for line, entry in m.suppressed_lines.items():
            for rule, reason in entry.items():
                if reason is None or rule not in active:
                    continue
                if (m.path, line, rule) not in used:
                    kept.append(Violation(
                        rule, m.path, line, 0,
                        f"unused suppression disable={rule}({reason}): "
                        f"no {rule} violation on this line; remove the "
                        f"stale escape"))
        for rule, (reason, line) in m.suppressed_file.items():
            if reason is None or rule not in active:
                continue
            if (m.path, -1, rule) not in used:
                kept.append(Violation(
                    rule, m.path, line, 0,
                    f"unused suppression disable-file={rule}({reason}): "
                    f"no {rule} violation in this file; remove the "
                    f"stale escape"))
    kept.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return kept
