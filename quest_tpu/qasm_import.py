"""OPENQASM 2.0 importer: text -> Circuit.

The reference can only EXPORT QASM (QuEST_qasm.c); importing is a
migration on-ramp it never had. Two dialects are accepted:

  * the recorder's own output (quest_tpu/qasm.py, format-compatible
    with the reference logger): ``Ctrl-`` prefixes — operands are the
    controls first, target(s) last — capitalized ``Rx/Ry/Rz``,
    ``U(rz2, ry, rz1)`` ZYZ lines meaning Rz(rz2)@Ry(ry)@Rz(rz1),
    ``measure q[i] -> c[i]``, ``reset``, and comment lines. The
    importer understands the recorder's CONVENTIONS, not just its
    gate names: a ``Ctrl-…Rz``/``Ctrl-…U`` line followed by the
    "Restoring the discarded global phase" comment and its
    uncontrolled ``Rz`` fix-up line is folded back into the exact
    controlled phase / controlled unitary the recorder was describing
    (the fix-up convention comes from qasm_recordControlledParamGate /
    qasm_recordControlledUnitary, QuEST_qasm.c:246-298, and is not an
    exact gate sequence on its own — reconstructing the source gate is
    both exact and faithful to intent);
  * standard qelib1 gates: ``cx/cz/ccx/cswap/cu1/crz/u1/u2/u3/id/
    sdg/tdg`` plus ``barrier`` (ignored) and ``pi``-arithmetic in
    parameters (``rz(pi/4)``). Lowercase ``u(theta,phi,lambda)`` is
    the qelib1 u3 convention; dispatch is CASE-SENSITIVE because the
    recorder's capitalized ``U(rz2,ry,rz1)`` names a different
    convention with the same letter. The OPENQASM builtin capital
    ``U(theta,phi,lambda)`` is recognized per file: an ``include``
    line with no recorder markers (``Ctrl-`` prefixes / restore
    comments) switches capital U to the spec (u3) order.

Round-trip guarantee: ``from_qasm(c.to_qasm())`` applies the same
unitary as ``c`` up to global phase (angles pass through %g text at
~1e-6 relative) for every circuit whose ops the exporter can express
as gate lines (i.e. everything except >=2-target general unitaries
and channels, which degrade to comments).

QASM-2 classical conditionals (``if (c==k)``) are rejected with a
pointer at the dynamic-circuit API (Circuit.gate_if), which is
strictly more general.
"""

from __future__ import annotations

import ast
import math
import re

import numpy as np

from quest_tpu.validation import QuESTError

_OPERAND = re.compile(r"([A-Za-z_][A-Za-z0-9_]*)\[(\d+)\]")
_DECL = re.compile(r"(qreg|creg)\s+([A-Za-z_][A-Za-z0-9_]*)\[(\d+)\]")
_RESTORE_MARK = "Restoring the discarded global phase"


def _rz(t):
    return np.diag([np.exp(-0.5j * t), np.exp(0.5j * t)])


def _ry(t):
    c, s = math.cos(t / 2), math.sin(t / 2)
    return np.array([[c, -s], [s, c]], dtype=np.complex128)


def _rx(t):
    c, s = math.cos(t / 2), math.sin(t / 2)
    return np.array([[c, -1j * s], [-1j * s, c]], dtype=np.complex128)


def _u_zyz(a, b, c):
    """The recorder's U(rz2, ry, rz1) line: Rz(rz2) @ Ry(ry) @ Rz(rz1)."""
    return _rz(a) @ _ry(b) @ _rz(c)


def _u3(theta, phi, lam):
    """Standard u3(theta, phi, lambda) = Rz(phi) Ry(theta) Rz(lambda)
    with the qelib1 phase convention."""
    u = _rz(phi) @ _ry(theta) @ _rz(lam)
    return u * np.exp(0.5j * (phi + lam))


def _eval_param(text: str) -> float:
    """Numeric parameter with pi-arithmetic (``pi/2``, ``3*pi/4``,
    ``-0.5``): a safe AST walk, not eval()."""
    try:
        node = ast.parse(text.strip(), mode="eval").body
    except SyntaxError:
        raise QuESTError(f"unparseable QASM parameter: {text!r}")

    def walk(nd):
        if isinstance(nd, ast.Constant) and isinstance(nd.value, (int, float)):
            return float(nd.value)
        if isinstance(nd, ast.Name) and nd.id.lower() == "pi":
            return math.pi
        if isinstance(nd, ast.UnaryOp) and isinstance(nd.op, (ast.USub, ast.UAdd)):
            v = walk(nd.operand)
            return -v if isinstance(nd.op, ast.USub) else v
        if isinstance(nd, ast.BinOp) and isinstance(
                nd.op, (ast.Add, ast.Sub, ast.Mult, ast.Div)):
            a, b = walk(nd.left), walk(nd.right)
            op = type(nd.op)
            if op is ast.Add:
                return a + b
            if op is ast.Sub:
                return a - b
            if op is ast.Mult:
                return a * b
            return a / b
        raise QuESTError(f"unsupported QASM parameter expression: {text!r}")

    return walk(node)


# name (lowercased, Ctrl- prefixes stripped) -> (n_params, n_gate_qubits)
_GATES = {
    "h": (0, 1), "x": (0, 1), "y": (0, 1), "z": (0, 1), "s": (0, 1),
    "t": (0, 1), "sdg": (0, 1), "tdg": (0, 1), "id": (0, 1),
    "rx": (1, 1), "ry": (1, 1), "rz": (1, 1), "phase": (1, 1),
    "u1": (1, 1), "u2": (2, 1), "u3": (3, 1), "u": (3, 1),
    "swap": (0, 2), "sqrtswap": (0, 2),
    "cx": (0, 2), "cnot": (0, 2), "cz": (0, 2), "cu1": (1, 2),
    "crz": (1, 2),
    "ccx": (0, 3), "cswap": (0, 3),
}

# gates that are (controls, base) compounds in the standard dialect
_COMPOUND_CONTROLS = {"cx": 1, "cnot": 1, "ccx": 2, "cswap": 1, "crz": 1}

_FIXED = {
    "sdg": np.diag([1.0, -1.0j]),
    "tdg": np.diag([1.0, np.exp(-0.25j * math.pi)]),
}


def _tokenize(text: str):
    """('stmt', code) / ('comment', text) items, in order."""
    items = []
    for raw in text.splitlines():
        code, _, comment = raw.partition("//")
        code = code.strip()
        for s in code.split(";"):
            s = s.strip()
            if s:
                items.append(("stmt", s))
        if comment.strip():
            items.append(("comment", comment.strip()))
    return items


def _split_head(stmt: str):
    """(head, rest) of a gate statement, head normalized to
    ``name(params)`` / ``name``. The QASM lexer permits arbitrary
    whitespace between tokens — ``rz(pi/2)q[0];``, ``rz (pi/2) q[0];``
    and ``rz(pi/2) q[0];`` are all legal — so when a ``(`` appears and
    everything before it is a single bare name, the head ends at the
    MATCHING close paren (depth-counted: parameters may themselves
    parenthesize, ``rz(2*(1+1))``). Operand lists never contain parens,
    so a ``(`` always opens the parameter list. Otherwise the head is
    the first space-separated token."""
    op = stmt.find("(")
    pre = stmt[:op].strip() if op != -1 else ""
    if op != -1 and pre and not re.search(r"\s", pre):
        depth = 0
        for j in range(op, len(stmt)):
            if stmt[j] == "(":
                depth += 1
            elif stmt[j] == ")":
                depth -= 1
                if depth == 0:
                    return pre + stmt[op:j + 1], stmt[j + 1:]
        raise QuESTError(f"unbalanced parentheses in: {stmt!r}")
    head, _, rest = stmt.partition(" ")
    return head, rest


def _split_params(ptext: str):
    """Top-level comma split of a parameter list body (depth-aware, so
    ``2*(1+1), pi`` yields two items)."""
    out, depth, start = [], 0, 0
    for j, ch in enumerate(ptext):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(ptext[start:j])
            start = j + 1
    out.append(ptext[start:])
    return [p for p in out if p.strip()]


def _parse_gate_head(stmt: str):
    """(name_lower, params, nctrl, qubit_indices, reg_names, raw_name)
    of a gate statement. ``raw_name`` preserves case: the recorder
    dialect's ``U`` and qelib1's ``u`` name DIFFERENT conventions and
    are dispatched case-sensitively by the caller."""
    head, rest = _split_head(stmt)
    name, params = head, []
    if "(" in head:
        name, ptext = head.split("(", 1)
        if ptext.endswith(")"):
            ptext = ptext[:-1]
        params = [_eval_param(p) for p in _split_params(ptext)]
    nctrl = 0
    while name.lower().startswith("ctrl-"):
        nctrl += 1
        name = name[len("ctrl-"):]
    operands = _OPERAND.findall(rest)
    return (name.lower(), params, nctrl,
            [int(i) for _, i in operands], [r for r, _ in operands], name)


def _qubit_operands(rest, qreg_name, circ, stmt):
    """Qubit indices named in an operand list. Indexed creg operands
    (``-> c[i]``) are ignored; a BARE register name means every qubit —
    the recorder emits whole-register ``reset q;`` / ``h q;`` lines for
    initZeroState / initPlusState (qasm.record_init_zero/_plus)."""
    ops = _OPERAND.findall(rest)
    qubits = [int(i) for r, i in ops if r == qreg_name]
    if qubits:
        return qubits
    if ops and not qubits:
        raise QuESTError(f"operand outside qreg {qreg_name!r}: {stmt!r}")
    if re.search(rf"(^|[\s,]){re.escape(qreg_name)}([\s,;]|$)",
                 rest.replace("->", " ")):
        return list(range(circ.num_qubits))
    raise QuESTError(f"malformed operand list in: {stmt!r}")


def _is_uncontrolled_rz(item):
    """(angle, qubit) of an uncontrolled single-qubit Rz statement, else
    None. The caller checks the qubit against the preceding controlled
    line's target — the recorder always applies its fix-up there
    (qasm.py record_gate/record_unitary; ref QuEST_qasm.c:252-298) — so
    a foreign file with a coincidental restore comment is not folded."""
    if item is None or item[0] != "stmt":
        return None
    name, params, nctrl, qubits, _, _ = _parse_gate_head(item[1])
    if name == "rz" and nctrl == 0 and len(params) == 1 and len(qubits) == 1:
        return params[0], qubits[0]
    return None


def circuit_from_qasm(text: str, u_dialect: str | None = None,
                      transpile: bool | None = None):
    """Parse OPENQASM 2.0 text into a Circuit (see module docstring for
    the accepted dialects and the recorder-convention folding).

    `u_dialect` pins the capital-``U`` parameter convention: ``"spec"``
    (OPENQASM 2.0 builtin ``U(theta, phi, lambda)``) or ``"recorder"``
    (the recorder's ``U(rz2, ry, rz1)`` ZYZ order). Default ``None``
    applies the marker heuristic below — and warns on stderr the first
    time a capital U is read as ZYZ in a file with an OPENQASM header
    but NO recorder markers, because a spec-compliant file needs no
    ``include`` for its builtin U and would otherwise parse silently
    with the wrong parameter order (ADVICE r4 item 1).

    `transpile` routes the imported stream through the circuit
    transpiler (quest_tpu/transpile.py, docs/TRANSPILE.md) — foreign
    corpora arrive rebased into long 1q+CX chains, exactly what the
    rewriter reverses. ``None`` follows QUEST_TRANSPILE ('auto' takes
    the rewrite only when strictly cheaper under the banded cost
    model); ``True`` takes it whenever it changed the stream; ``False``
    never rewrites. The rewrite report (ops_in/ops_out, per-pass
    attribution) rides on the returned circuit as
    ``_transpile_report`` when a rewrite was applied."""
    from quest_tpu.circuit import Circuit
    from quest_tpu.ops import matrices as M

    if u_dialect not in (None, "spec", "recorder"):
        raise ValueError(
            f"u_dialect must be None, 'spec' or 'recorder', got "
            f"{u_dialect!r}")

    fixed = {
        "h": M.HADAMARD, "x": M.PAULI_X, "y": M.PAULI_Y, "z": M.PAULI_Z,
        "s": np.diag([1.0, 1.0j]),
        "t": np.diag(M.T_DIAG), **_FIXED,
    }

    items = _tokenize(text)
    circ = None
    qreg_name = None

    # Capital-U dialect disambiguation: the recorder's ``U(rz2,ry,rz1)``
    # and the OPENQASM 2.0 builtin ``U(theta,phi,lambda)`` collide on
    # the same letter with different parameter orders. The recorder
    # never emits ``include``; spec/qelib1 files never emit ``Ctrl-``
    # prefixes or restore comments. A file carrying an include and no
    # recorder markers reads capital U as the spec builtin (= u3);
    # anything else — in particular every recorder/reference export —
    # keeps the ZYZ dialect, preserving the round-trip guarantee.
    has_include = any(k == "stmt" and s.lower().startswith("include")
                      for k, s in items)
    has_header = any(k == "stmt" and s.lower().startswith("openqasm")
                     for k, s in items)
    has_recorder_marker = any(
        (k == "stmt" and s.lower().startswith("ctrl-"))
        or (k == "comment" and _RESTORE_MARK in s)
        for k, s in items)
    if u_dialect is not None:
        spec_builtin_u = u_dialect == "spec"
        warn_ambiguous_u = False
    else:
        spec_builtin_u = has_include and not has_recorder_marker
        # header + no include + no recorder markers: the heuristic keeps
        # ZYZ (round-trip guarantee) but a spec-compliant file lands
        # here too — one warning per parse, silenceable via u_dialect
        warn_ambiguous_u = (has_header and not has_include
                            and not has_recorder_marker)
    _u_warned = [False]

    def _warn_u_once():
        if warn_ambiguous_u and not _u_warned[0]:
            _u_warned[0] = True
            import sys
            print(
                "[qasm_import] capital U read in the recorder's "
                "U(rz2, ry, rz1) ZYZ order; this file has an OPENQASM "
                "header but no recorder markers, so if it means the "
                "spec builtin U(theta, phi, lambda) pass "
                "u_dialect='spec' (u_dialect='recorder' silences this)",
                file=sys.stderr)

    def need_circuit():
        if circ is None:
            raise QuESTError("QASM gate line before any qreg declaration")
        return circ

    i = 0
    while i < len(items):
        kind, stmt = items[i]
        i += 1
        if kind == "comment":
            continue
        low = stmt.lower()
        if low.startswith("openqasm") or low.startswith("include"):
            continue
        if low.startswith("barrier"):
            continue
        if low.startswith("if"):
            raise QuESTError(
                "QASM-2 classical conditionals are not imported; express "
                "feedback with the dynamic-circuit API (Circuit.measure + "
                "Circuit.gate_if), which conditions on individual "
                "measurement outcomes")
        m = _DECL.match(stmt)
        if m:
            dkind, name, size = m.group(1), m.group(2), int(m.group(3))
            if dkind == "qreg":
                if circ is not None:
                    raise QuESTError("multiple qreg declarations are not "
                                     "supported")
                circ = Circuit(size)
                qreg_name = name
            continue
        if low.startswith("measure"):
            for q in _qubit_operands(stmt.split(None, 1)[1] if " " in stmt
                                     else "", qreg_name, need_circuit(),
                                     stmt):
                need_circuit().measure(q)
            continue
        if low.startswith("reset"):
            # the recorder emits whole-register `reset q;` for
            # initZeroState (qasm.record_init_zero)
            for q in _qubit_operands(stmt.split(None, 1)[1] if " " in stmt
                                     else "", qreg_name, need_circuit(),
                                     stmt):
                need_circuit().reset(q)
            continue

        name, params, nctrl, qubits, regs, raw_name = _parse_gate_head(stmt)
        if name not in _GATES:
            raise QuESTError(f"unknown QASM gate {name!r} in {stmt!r}")
        want_params, base_qubits = _GATES[name]
        if len(params) != want_params:
            raise QuESTError(
                f"gate {name!r} takes {want_params} parameter(s), got "
                f"{len(params)}: {stmt!r}")
        if any(r != qreg_name for r in regs):
            raise QuESTError(f"operand outside qreg {qreg_name!r}: {stmt!r}")
        if (not qubits and nctrl == 0 and _GATES[name][1] == 1
                and name not in _COMPOUND_CONTROLS):
            # whole-register 1q gate, e.g. the recorder's `h q;` for
            # initPlusState (qasm.record_init_plus): one gate per qubit,
            # re-queued as indexed statements (head keeps its params)
            head, rest = _split_head(stmt)
            for q in reversed(_qubit_operands(rest, qreg_name,
                                              need_circuit(), stmt)):
                items.insert(i, ("stmt", f"{head} {qreg_name}[{q}]"))
            continue
        nctrl += _COMPOUND_CONTROLS.get(name, 0)
        if name in _COMPOUND_CONTROLS:
            base_qubits -= _COMPOUND_CONTROLS[name]
        if name in ("swap", "sqrtswap") and nctrl:
            # recorder dialect: a plain swap is emitted as Ctrl-swap with
            # the first swap qubit riding as the "control"
            # (qasm.record_gate("swap", t1, (t0,)))
            nctrl -= 1
        if len(qubits) != nctrl + base_qubits:
            raise QuESTError(
                f"gate {name!r} with {nctrl} control(s) takes "
                f"{nctrl + base_qubits} operand(s), got {len(qubits)}: "
                f"{stmt!r}")
        controls, gate_qubits = qubits[:nctrl], qubits[nctrl:]
        c = need_circuit()

        # --- recorder-convention folding -------------------------------
        # a restore comment + uncontrolled Rz fix-up after a controlled
        # Rz/U line identifies the exporter's controlled-phase /
        # controlled-unitary convention; fold back to the source gate.
        # The fold only fires when the fix-up matches the recorder's
        # actual convention — Rz on the SAME target, and (for the phase
        # case) angle == param/2 — so a foreign file with a coincidental
        # comment falls through to literal interpretation.
        restore_phase = None
        recorder_u = raw_name == "U" and not spec_builtin_u
        if (controls and (name == "rz" or recorder_u)
                and i < len(items) and items[i][0] == "comment"
                and _RESTORE_MARK in items[i][1]):
            fix = _is_uncontrolled_rz(
                items[i + 1] if i + 1 < len(items) else None)
            if fix is not None:
                fix_angle, fix_qubit = fix
                matches = fix_qubit == qubits[-1] and (
                    name != "rz"
                    or math.isclose(fix_angle, params[0] / 2.0,
                                    rel_tol=1e-5, abs_tol=1e-9))
                if matches:
                    restore_phase = fix_angle
                    i += 2      # consume the comment and the fix-up line
        if restore_phase is not None and name == "rz":
            # qasm_recordControlledParamGate: controlled PHASE SHIFT of
            # angle = the Ctrl-Rz parameter (fix-up was angle/2)
            c.cphase(params[0], *qubits)
            continue
        if restore_phase is not None and name == "u":
            # qasm_recordControlledUnitary: u = e^{i phase} * ZYZ
            u = np.exp(1j * restore_phase) * _u_zyz(*params)
            c.gate(u, (gate_qubits[0],), controls=tuple(controls))
            continue

        if name == "id":
            continue
        if name == "cz":
            c.cphase(math.pi, *qubits)
            continue
        if name in ("cu1", "u1", "phase"):
            angle = params[0]
            if name == "cu1" or controls:
                c.cphase(angle, *qubits)   # diag phase: fully symmetric
            else:
                c.phase(gate_qubits[0], angle)
            continue
        if name in ("swap", "sqrtswap", "cswap"):
            a, b = gate_qubits
            if controls:
                mat = M.SQRT_SWAP if name == "sqrtswap" else M.SWAP
                c.gate(mat, (a, b), controls=tuple(controls))
            elif name == "sqrtswap":
                c.sqrt_swap(a, b)
            else:
                c.swap(a, b)
            continue
        if name in ("cx", "cnot", "ccx"):
            if len(controls) == 1:
                c.cnot(controls[0], gate_qubits[0])
            else:
                c.gate(M.PAULI_X, (gate_qubits[0],),
                       controls=tuple(controls))
            continue

        # 1-qubit gates (fixed, rotations, u-lines)
        t = gate_qubits[0]
        if name in fixed:
            mat = fixed[name]
        elif name in ("rx",):
            mat = _rx(params[0])
        elif name == "ry":
            mat = _ry(params[0])
        elif name in ("rz", "crz"):
            mat = _rz(params[0])
        elif name == "u":
            # case-sensitive dispatch: the recorder (and the reference
            # logger it mirrors) emits capitalized ``U(rz2,ry,rz1)``
            # meaning Rz@Ry@Rz with no phase factor, while qelib1's
            # lowercase ``u(theta,phi,lambda)`` is u3 — same letter,
            # different convention, different unitary. Spec files
            # (include + no recorder markers) read capital U as the
            # builtin, i.e. the u3 order.
            if recorder_u:
                _warn_u_once()
                mat = _u_zyz(*params)
            else:
                mat = _u3(*params)
        elif name == "u3":
            mat = _u3(*params)
        elif name == "u2":
            mat = _u3(math.pi / 2, params[0], params[1])
        else:  # pragma: no cover — the table above is exhaustive
            raise QuESTError(f"unhandled gate {name!r}")
        if not controls:
            # use the named builders so re-export stays named
            builder = {"h": c.h, "x": c.x, "y": c.y, "z": c.z, "s": c.s,
                       "t": c.t}.get(name)
            if builder is not None:
                builder(t)
            elif name == "rx":
                c.rx(t, params[0])
            elif name == "ry":
                c.ry(t, params[0])
            elif name == "rz":
                c.rz(t, params[0])
            else:
                c.gate(mat, (t,))
        else:
            c.gate(mat, (t,), controls=tuple(controls))

    if circ is None:
        raise QuESTError("QASM text declares no qreg")
    from quest_tpu import transpile as T
    if transpile is False:
        return circ
    if transpile is True:
        tc, rep = T.transpile_cached(circ)
        return tc if rep["changed"] else circ
    out, _rep = T.maybe_transpile(circ)
    return out
