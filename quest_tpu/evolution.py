"""Trotterized real- and imaginary-time evolution at sweep speed
(docs/EVOLUTION.md).

The TPU brute-force paper (arXiv:2111.10466) is ground states AND time
evolution; the stack already has both halves of the engine — the
commutation-aware diagonal pooling of the scheduler (ops/fusion.py,
docs/SCHEDULER.md) and the one-sweep Pauli-sum expectation engine
(ops/expec.py, docs/EXPECTATION.md) — but until now no dynamics
workload rode them: a Trotter step written against the eager gate API
pays one full-state pass per non-commuting term
(gates.multi_rotate_pauli's flip-form is one pass, but there are M of
them per step).

`trotter_circuit` compiles a `expec.PauliSum`-shaped Hamiltonian into a
Circuit whose per-step layer is emitted POOLING-FIRST:

  * every I/Z-only term exponentiates EXACTLY to a parity phase
    (exp(-i tau c Z..Z) = multiRotateZ(2 tau c)); the whole diagonal
    block is emitted as one contiguous run and pre-composed into
    k-qubit `ComposedDiag` groups (fusion.compose_diag_runs — the
    pooling entry for synthesized layers), which the Pallas planner
    lowers to additive MultiPhaseStage/DiagVecStage stages riding ONE
    HBM sweep;
  * off-diagonal terms partition into FRAMES — maximal families whose
    X/Y support can share one basis-rotation conjugation (U P U+ = Z
    per rotated qubit, the multi_rotate_pauli convention) — so each
    frame costs its rotation band operators ONCE for every term in it,
    and the rotated cores are again a pooled diagonal run;
  * order-2 (Strang) emission telescopes across steps: the trailing
    half-group of step s merges with the leading half-group of step
    s+1, so a k-step quench carries k-1 full interior groups, not
    2k halves.

The result: a 30q TFIM order-2 step lowers to a steady-state THREE HBM
sweeps through `compiled_fused(iters=steps)` (the band geometry floor —
one sublane-region sweep plus one per scattered 7-bit band, the same
bound QFT-30 meets at 6), versus ~2n per-term passes for the legacy
emission. `QUEST_TROTTER_FUSION=0` (keyed knob) restores the honest
per-term baseline: per-term emission, dispatched through the eager
per-term workers exactly as a user would write the loop today
(one flip-form pass per term per application).

`run_evolution` drives the workload end-to-end: chunked fused dispatch,
per-chunk energy tracking through the fused expec reduction on the
DEVICE-RESIDENT state (only the scalar expectation ever reaches the
host), imaginary-time projection with in-trace renormalization, durable
deep quenches through `resilience.durable.run_durable` (the Trotter
descriptor rides the checkpoint cursor and is validated at resume), and
sharded meshes. `trotter_ansatz` is the variational surface: dt and the
coefficient vector are RUNTIME operands of one traced program (the
ops/expec.py contract), so a VQE/QAOA optimizer loop over an evolved
ansatz — including one that REBUILDS the ansatz every iteration —
compiles zero programs after warmup (`variational.sweep`'s value-keyed
program cache; CompileAuditor-pinned in tests/test_evolution.py).

Introspection: `TrotterCircuit.plan_stats()["trotter"]` reports steps,
order, diag-group/frame counts and `hbm_sweeps_per_step` — the
STEADY-STATE marginal sweep rate ((sweeps(2m) - sweeps(m)) / m, so the
one-time boundary segment of a deep quench does not bias the per-step
figure) — CPU-assertable without a chip, gated in
scripts/check_evolution_golden.py.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import precision
from quest_tpu.circuit import Circuit, GateOp
from quest_tpu.ops import apply as A
from quest_tpu.ops import expec as E
from quest_tpu.ops import fusion as F
from quest_tpu.state import Qureg

_SQ2 = 1.0 / np.sqrt(2.0)
# U P U+ = Z for P in {X, Y}: the multi_rotate_pauli basis convention
# (circuit.Circuit.multi_rotate_pauli / ref QuEST_common.c:410-447) —
# applied U ... parity ... U+, so the rotated core is a pure Z string
_TO_Z = {
    1: np.array([[_SQ2, _SQ2], [-_SQ2, _SQ2]], dtype=np.complex128),
    2: np.array([[_SQ2, -1j * _SQ2], [-1j * _SQ2, _SQ2]],
                dtype=np.complex128),
}

_NOISE_KINDS = ("depolarising", "damping", "dephasing")


def fusion_enabled() -> bool:
    """QUEST_TROTTER_FUSION (keyed, default on): pooled frame-grouped
    Trotter emission + fused-engine dispatch; 0 restores the legacy
    per-term emission, dispatched through the eager per-term workers
    (one flip-form pass per term — the honest reference baseline the
    bench A/Bs against)."""
    from quest_tpu.env import knob_value
    return knob_value("QUEST_TROTTER_FUSION")


# ---------------------------------------------------------------------------
# the Trotter plan: diagonal block + basis-rotation frames
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Frame:
    """One basis-rotation family: `axes` maps each rotated qubit to its
    X(1)/Y(2) axis; every term in `terms` is diagonal in the rotated
    frame (its X/Y support matches `axes`, its Z dressing sits on
    unrotated qubits)."""
    axes: Tuple[Tuple[int, int], ...]
    terms: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class TrotterPlan:
    """Static (hashable) evolution plan: one commuting DIAGONAL group
    (I/Z-only terms), one group per FRAME, plus the all-identity terms
    (a global phase). `supports[i]` is term i's nonzero-support qubit
    tuple — the parity targets of its (possibly rotated) Z core."""
    n: int
    diag: Tuple[int, ...]
    identity: Tuple[int, ...]
    frames: Tuple[_Frame, ...]
    supports: Tuple[Tuple[int, ...], ...]

    @property
    def num_groups(self) -> int:
        return (1 if self.diag else 0) + len(self.frames)

    def group_seq(self) -> Tuple:
        """The Strang group sequence: the diagonal block first (it is
        the cheapest to repeat at the halved ends), then each frame."""
        seq: List = []
        if self.diag:
            seq.append(("diag", self.diag))
        for f in self.frames:
            seq.append(("frame", f))
        return tuple(seq)


@functools.lru_cache(maxsize=256)
def _plan_trotter(codes_key) -> TrotterPlan:
    n = len(codes_key[0]) if codes_key else 0
    diag: List[int] = []
    identity: List[int] = []
    supports: List[Tuple[int, ...]] = []
    offdiag: List[Tuple[int, Tuple[Tuple[int, int], ...],
                        Tuple[int, ...]]] = []
    for i, row in enumerate(codes_key):
        xy = tuple((q, p) for q, p in enumerate(row) if p in (1, 2))
        z = tuple(q for q, p in enumerate(row) if p == 3)
        supports.append(tuple(q for q, p in enumerate(row) if p))
        if not xy and not z:
            identity.append(i)
        elif not xy:
            diag.append(i)
        else:
            offdiag.append((i, xy, z))
    # greedy first-fit frame assignment: a term joins a frame iff its
    # X/Y axes agree with the frame's on every shared qubit, none of
    # its X/Y qubits carries another in-frame term's Z dressing, and
    # none of its Z qubits is rotated by the frame — exactly the
    # condition under which ALL the frame's cores stay diagonal in the
    # one rotated basis
    frames: List[List] = []      # [axes dict, z_blocked set, term list]
    for i, xy, z in offdiag:
        placed = False
        for fr in frames:
            axes, zb, terms = fr
            if any(axes.get(q, p) != p or q in zb for q, p in xy):
                continue
            if any(q in axes for q in z):
                continue
            axes.update(xy)
            zb.update(z)
            terms.append(i)
            placed = True
            break
        if not placed:
            frames.append([dict(xy), set(z), [i]])
    return TrotterPlan(
        n=n, diag=tuple(diag), identity=tuple(identity),
        frames=tuple(_Frame(tuple(sorted(a.items())), tuple(t))
                     for a, _, t in frames),
        supports=tuple(supports))


def as_pauli_sum(hamiltonian, coeffs=None, num_qubits: int = None
                 ) -> E.PauliSum:
    """Normalize the Hamiltonian argument every evolution entry point
    accepts — an `expec.PauliSum`, a (codes, coeffs) pair, or a codes
    array with `coeffs=` — into one validated PauliSum spec."""
    if isinstance(hamiltonian, E.PauliSum):
        if coeffs is not None:
            raise ValueError("pass coefficients inside the PauliSum, "
                             "not as a separate coeffs= argument")
        return hamiltonian
    if coeffs is None and isinstance(hamiltonian, tuple) \
            and len(hamiltonian) == 2:
        hamiltonian, coeffs = hamiltonian
    codes = np.asarray(hamiltonian)
    if num_qubits is None:
        if codes.ndim != 2:
            raise ValueError(
                "pass num_qubits= (or a 2-D codes array) so the term "
                "width is unambiguous")
        num_qubits = int(codes.shape[1])
    return E.PauliSum.of(codes, coeffs, num_qubits)


# ---------------------------------------------------------------------------
# circuit emission
# ---------------------------------------------------------------------------


class TrotterCircuit(Circuit):
    """A Circuit compiled from a Hamiltonian by `trotter_circuit`.
    Carries its Trotter descriptor and extends `plan_stats()` with the
    "trotter" record (steps, order, group counts, and the steady-state
    `hbm_sweeps_per_step` — the CI-gated sweep-speed metric). Treat it
    as IMMUTABLE: equal (hamiltonian, dt, order, steps, noise) calls
    return the same memoized instance, so serve requests over equal
    evolution jobs share one program family (circuit.program_key keys
    on object identity)."""

    trotter: dict

    def plan_stats(self, density: bool = False, batch: int = None,
                   devices: int = None) -> dict:
        # a noisy circuit only runs as a Circuit on the density
        # register (the trajectory path unravels it and reports
        # through trajectories.plan_stats), so plan it there
        density = density or self.trotter["noise"] is not None
        rec = super().plan_stats(density=density, batch=batch,
                                 devices=devices)
        # report THIS circuit's emission (the memoized `pooled` bit),
        # not whatever the knob reads now — a knob flip after build
        # changes what the NEXT trotter_circuit call emits, never what
        # this one dispatches
        rec["trotter"] = trotter_plan_stats(
            self.trotter["spec"], self.trotter["dt"],
            order=self.trotter["order"], steps=self.trotter["steps"],
            density=density, pooled=self.trotter["pooled"],
            noise=self.trotter["noise"])
        return rec

    def _plan_extra(self, density: bool) -> dict:
        # the plan IR's subsystem-extension hook (quest_tpu/plan.py):
        # autotuned TrotterCircuit plans carry the frame record too
        density = density or self.trotter["noise"] is not None
        return {"trotter": trotter_plan_stats(
            self.trotter["spec"], self.trotter["dt"],
            order=self.trotter["order"], steps=self.trotter["steps"],
            density=density, pooled=self.trotter["pooled"],
            noise=self.trotter["noise"])}


def _zy_angle(coef: float, tau: float, scale: float) -> float:
    # exp(-i tau c P) == exp(-i angle/2 P) at angle = 2 tau c
    return 2.0 * float(coef) * float(tau) * float(scale)


def _emit_group(c: Circuit, plan: TrotterPlan, spec: E.PauliSum,
                group, tau: float, scale: float, pooled: bool) -> None:
    kind, payload = group
    if kind == "diag":
        ops = [GateOp("parity", plan.supports[i], (), (),
                      _zy_angle(spec.coeffs[i], tau, scale))
               for i in payload]
        if pooled:
            ops = F.compose_diag_runs(ops)
        c.ops.extend(ops)
        return
    frame: _Frame = payload
    for q, ax in frame.axes:
        c.gate(_TO_Z[ax], (q,))
    ops = [GateOp("parity", plan.supports[i], (), (),
                  _zy_angle(spec.coeffs[i], tau, scale))
           for i in frame.terms]
    if pooled:
        ops = F.compose_diag_runs(ops)
    c.ops.extend(ops)
    for q, ax in frame.axes:
        c.gate(np.asarray(_TO_Z[ax]).conj().T, (q,))


def _emit_identity_phase(c: Circuit, theta: float) -> None:
    """The all-identity terms' global phase exp(-i theta), as a uniform
    single-qubit diagonal (diagonal-class: pools/fuses like any other
    phase; its density dual conjugates away, as a global phase must)."""
    if abs(theta) < 1e-300 or c.num_qubits == 0:
        return
    p = np.exp(-1j * theta)
    c._add("diagonal", (0,), np.array([p, p], dtype=np.complex128))


def _emit_noise(c: Circuit, noise) -> None:
    kind, prob = noise
    for q in range(c.num_qubits):
        getattr(c, kind)(q, prob)


def _emit_trotter(c: Circuit, plan: TrotterPlan, spec: E.PauliSum,
                  dt: float, order: int, steps: int, noise,
                  pooled: bool) -> None:
    seq = plan.group_seq()
    m = len(seq)
    telescope = pooled and noise is None and order == 2 and m > 1
    for s in range(steps):
        if m:
            if order == 1 or m == 1:
                for g in seq:
                    _emit_group(c, plan, spec, g, dt, 1.0, pooled)
            elif telescope:
                # Strang with the leading half-group merged into the
                # previous step's trailing one: G1 appears at full
                # weight between interior steps, half at the ends
                if s == 0:
                    _emit_group(c, plan, spec, seq[0], dt, 0.5, pooled)
                for g in seq[1:-1]:
                    _emit_group(c, plan, spec, g, dt, 0.5, pooled)
                _emit_group(c, plan, spec, seq[-1], dt, 1.0, pooled)
                for g in reversed(seq[1:-1]):
                    _emit_group(c, plan, spec, g, dt, 0.5, pooled)
                _emit_group(c, plan, spec, seq[0], dt,
                            0.5 if s == steps - 1 else 1.0, pooled)
            else:
                _emit_group(c, plan, spec, seq[0], dt, 0.5, pooled)
                for g in seq[1:-1]:
                    _emit_group(c, plan, spec, g, dt, 0.5, pooled)
                _emit_group(c, plan, spec, seq[-1], dt, 1.0, pooled)
                for g in reversed(seq[1:-1]):
                    _emit_group(c, plan, spec, g, dt, 0.5, pooled)
                _emit_group(c, plan, spec, seq[0], dt, 0.5, pooled)
        if noise is not None:
            _emit_noise(c, noise)
    if plan.identity and pooled:
        # legacy per-term emission drops the global phase, exactly like
        # the reference's all-identity multiRotatePauli no-op
        theta = float(dt) * float(steps) * sum(
            float(spec.coeffs[i]) for i in plan.identity)
        _emit_identity_phase(c, theta)
    c._compiled.clear()


@functools.lru_cache(maxsize=64)
def _trotter_circuit_cached(spec: E.PauliSum, dt: float, order: int,
                            steps: int, noise, pooled: bool
                            ) -> TrotterCircuit:
    plan = _plan_trotter(spec.codes)
    c = TrotterCircuit(spec.num_qubits)
    c.trotter = {"spec": spec, "dt": dt, "order": order, "steps": steps,
                 "noise": noise, "pooled": pooled, "plan": plan}
    _emit_trotter(c, plan, spec, dt, order, steps, noise, pooled)
    return c


def trotter_circuit(hamiltonian, dt, *, coeffs=None, num_qubits=None,
                    order: int = 2, steps: int = 1,
                    noise=None) -> TrotterCircuit:
    """Compile exp(-i dt H)^steps into a Circuit via the order-1 (Lie)
    or order-2 (Strang) product formula over the plan's commuting
    groups (diagonal block + basis-rotation frames). With
    QUEST_TROTTER_FUSION=1 (default) the emission is pooled — composed
    diagonal groups, shared frame rotations, telescoped Strang halves —
    so the fused engine runs a step in a few HBM sweeps; with 0 it is
    the legacy per-term stream. `noise=(kind, prob)` with kind in
    {depolarising, damping, dephasing} appends the per-qubit channel
    after every step (the trajectory path: run the returned circuit
    through `trajectories.run_batched` or
    `run_evolution_trajectories`).

    Memoized BY VALUE: equal arguments return the SAME TrotterCircuit,
    so repeated serve submissions of one evolution job coalesce into
    one program family, and rebuilt-but-equal circuits hit every
    compiled-program cache. Treat the returned circuit as immutable."""
    spec = as_pauli_sum(hamiltonian, coeffs, num_qubits)
    if order not in (1, 2):
        raise ValueError(f"order must be 1 or 2, got {order!r}")
    steps = int(steps)
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if noise is not None:
        kind, prob = noise
        if kind not in _NOISE_KINDS:
            raise ValueError(
                f"noise kind must be one of {_NOISE_KINDS}, got {kind!r}")
        noise = (kind, float(prob))
    return _trotter_circuit_cached(spec, float(dt), order, steps, noise,
                                   fusion_enabled())


# ---------------------------------------------------------------------------
# plan introspection (CPU-assertable — the Circuit.plan_stats discipline)
# ---------------------------------------------------------------------------


def _fused_sweeps(circ: Circuit, n: int, density: bool) -> int:
    """HBM passes one application of `circ` costs on the engine that
    would actually run it (fused kernel sweeps on the kernel tier,
    banded full-state passes below it) — pure host planning."""
    from quest_tpu.ops import pallas_band as PB
    flat = circ._planned_flat(n, density)
    if PB.usable(n):
        items = F.plan(flat, n, bands=PB.plan_bands(n))
        return len(PB.maybe_sweep(PB.segment_plan(items, n), n))
    return F.plan_stats(F.plan(flat, n))["full_state_passes"]


def _per_term_passes(plan: TrotterPlan, order: int) -> int:
    """The legacy model: one flip-form pass per term application per
    step (gates.multi_rotate_z / multi_rotate_pauli — what the eager
    per-term loop dispatches; all-identity terms are no-ops, exactly
    like the reference)."""
    applied = len(plan.diag) + sum(len(f.terms) for f in plan.frames)
    if order == 1:
        return applied
    # Strang applies the first group's terms twice (half steps), the
    # last once, interior groups twice
    seq = plan.group_seq()
    if len(seq) <= 1:
        return applied
    total = 0
    for gi, g in enumerate(seq):
        cnt = (len(g[1]) if g[0] == "diag" else len(g[1].terms))
        total += cnt if gi == len(seq) - 1 else 2 * cnt
    return total


def _diag_group_count(plan: TrotterPlan) -> int:
    """Composed-diagonal groups one pooled step emits (the diag block's
    groups plus each frame's rotated core groups)."""
    count = 0
    for kind, payload in plan.group_seq():
        idx = payload if kind == "diag" else payload.terms
        ops = [GateOp("parity", plan.supports[i], (), (), 0.0)
               for i in idx]
        count += len(F.compose_diag_runs(ops))
    return count


def trotter_plan_stats(hamiltonian, dt, *, coeffs=None, num_qubits=None,
                       order: int = 2, steps: int = 1,
                       density: bool = False,
                       pooled: bool = None, noise=None) -> dict:
    """The "trotter" plan record, CPU-side (no compile, no chip):
    term/group/frame counts, the pooled emission's STEADY-STATE
    `hbm_sweeps_per_step` — the marginal rate (sweeps(2m) - sweeps(m))/m
    over the fused engine's sweep plan, so a deep quench's one-time
    boundary segment does not bias the per-step figure — and the legacy
    per-term model `baseline_hbm_sweeps_per_step` (one flip-form pass
    per term application). With QUEST_TROTTER_FUSION=0
    `hbm_sweeps_per_step` REPORTS the baseline: that is what the legacy
    dispatch runs (the expec.plan_stats convention), and the record is
    what scripts/check_evolution_golden.py pins against the fused one.
    `pooled` overrides the knob read — TrotterCircuit.plan_stats passes
    the emission its circuit was actually built with, and its `noise`:
    a noisy step disables Strang telescoping and interleaves per-qubit
    channels, so the marginal is measured over the NOISY emission —
    planned on the density register, the one register kind that runs
    channels as a Circuit (the trajectory path unravels instead and
    reports through trajectories.plan_stats)."""
    spec = as_pauli_sum(hamiltonian, coeffs, num_qubits)
    plan = _plan_trotter(spec.codes)
    fused = fusion_enabled() if pooled is None else bool(pooled)
    baseline = _per_term_passes(plan, order)
    plan_density = density or noise is not None
    n = 2 * spec.num_qubits if plan_density else spec.num_qubits
    if fused:
        m = 4
        c1 = _trotter_circuit_cached(spec, float(dt), order, m, noise,
                                     True)
        c2 = _trotter_circuit_cached(spec, float(dt), order, 2 * m,
                                     noise, True)
        marginal = (_fused_sweeps(c2, n, plan_density)
                    - _fused_sweeps(c1, n, plan_density)) / m
        sweeps_per_step = marginal
    else:
        sweeps_per_step = float(baseline)
    return {
        "steps": int(steps),
        "order": int(order),
        "terms": len(spec.codes),
        "diag_terms": len(plan.diag),
        "identity_terms": len(plan.identity),
        "frames": len(plan.frames),
        "diag_groups": _diag_group_count(plan),
        "fusion": bool(fused),
        "noise": noise,
        "hbm_sweeps_per_step": sweeps_per_step,
        "baseline_hbm_sweeps_per_step": baseline,
    }


# ---------------------------------------------------------------------------
# the traced core: runtime coefficients + dt (the variational surface)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=256)
def _frame_band_ops(axes: Tuple[Tuple[int, int], ...], n: int):
    """Per-band composed rotation operators of one frame (and their
    inverses), as concrete numpy pairs for ops/apply.apply_band —
    ceil(width/7) MXU passes per frame side instead of one per rotated
    qubit."""
    by_band: Dict[int, np.ndarray] = {}
    for q, ax in axes:
        b = F._band_of(q)
        ql, w = F.band_range(n, b)
        emb = F.embed_operator(_TO_Z[ax], [q - ql], [], [], w)
        cur = by_band.get(b)
        by_band[b] = emb if cur is None else emb @ cur
    out = []
    for b in sorted(by_band):
        ql, w = F.band_range(n, b)
        op = by_band[b]
        inv = op.conj().T
        out.append((ql, w, (op.real.copy(), op.imag.copy()),
                    (inv.real.copy(), inv.imag.copy())))
    return tuple(out)


def _parity_decay(amps, n: int, targets, w):
    """Imaginary-time diagonal factor exp(-w * s(j)) with s the parity
    sign of `targets` — the non-unitary counterpart of
    apply_parity_phase, elementwise over the same split view."""
    targets = tuple(int(t) for t in targets)
    dims, axis_of = A._split_view(n, targets, ())
    re = amps[0].reshape(dims)
    im = amps[1].reshape(dims)
    sign = A.parity_sign(len(dims), axis_of, targets, amps.dtype)
    f = jnp.exp(-jnp.asarray(w, amps.dtype) * sign)
    return jnp.stack([(re * f).reshape(-1), (im * f).reshape(-1)])


def _global_phase(amps, theta):
    """exp(-i theta) on the whole register (the identity terms)."""
    t = jnp.asarray(theta, amps.dtype)
    c, s = jnp.cos(t), jnp.sin(t)
    return jnp.stack([amps[0] * c + amps[1] * s,
                      amps[1] * c - amps[0] * s])


def _apply_group_traced(amps, n, cf, tau, plan: TrotterPlan, group,
                        scale: float, imag: bool):
    kind, payload = group
    if kind == "diag":
        for i in payload:
            w = cf[i] * tau * scale
            if imag:
                amps = _parity_decay(amps, n, plan.supports[i], w)
            else:
                amps = A.apply_parity_phase(amps, n, plan.supports[i],
                                            2.0 * w)
        return amps
    frame: _Frame = payload
    bands = _frame_band_ops(frame.axes, n)
    for ql, w_, fwd, _inv in bands:
        amps = A.apply_band(amps, n, fwd, ql, w_, ())
    for i in frame.terms:
        w = cf[i] * tau * scale
        if imag:
            amps = _parity_decay(amps, n, plan.supports[i], w)
        else:
            amps = A.apply_parity_phase(amps, n, plan.supports[i],
                                        2.0 * w)
    for ql, w_, _fwd, inv in bands:
        amps = A.apply_band(amps, n, inv, ql, w_, ())
    return amps


def step_schedule(plan: TrotterPlan, order: int):
    """The per-step (group, scale) splitting schedule: order 1 applies
    each group once; order 2 is the symmetric Strang arrangement with
    halved end groups. The ONE place the splitting lives — shared by
    the traced step below and by the adjoint engine
    (quest_tpu/adjoint.py), which replays the identical schedule
    gate-by-gate so its gradients differentiate exactly the program
    `evolve_planes` runs."""
    seq = plan.group_seq()
    if order == 1 or len(seq) <= 1:
        return tuple((g, 1.0) for g in seq)
    return tuple([(seq[0], 0.5)] + [(g, 0.5) for g in seq[1:-1]]
                 + [(seq[-1], 1.0)]
                 + [(g, 0.5) for g in reversed(seq[1:-1])]
                 + [(seq[0], 0.5)])


def _step_traced(amps, n, cf, tau, plan: TrotterPlan, order: int,
                 imag: bool, renorm: bool):
    for g, scale in step_schedule(plan, order):
        amps = _apply_group_traced(amps, n, cf, tau, plan, g, scale,
                                   imag)
    if plan.identity:
        tot = sum(cf[i] for i in plan.identity) * tau
        if imag:
            amps = amps * jnp.exp(-jnp.asarray(tot, amps.dtype))
        else:
            amps = _global_phase(amps, tot)
    if renorm:
        acc = precision.accum_dtype(amps.dtype)
        norm = jnp.sqrt(jnp.sum(amps.astype(acc) ** 2))
        amps = amps / jnp.maximum(norm, 1e-300).astype(amps.dtype)
    return amps


def evolve_planes(amps, n: int, coeffs, dt, plan: TrotterPlan, *,
                  steps: int = 1, order: int = 2,
                  imag_time: bool = False, renorm: bool = None):
    """The traced evolution core: `steps` Trotter steps over (2, 2^n)
    statevector planes with the COEFFICIENT VECTOR and dt as runtime
    operands — the plan (term structure) is the only static input, so
    an optimizer loop changing either retraces nothing, and `jax.grad`
    flows through every op (parity phases, band rotations, the
    imaginary-time decays and renormalization are all plain jnp).
    `renorm` defaults to `imag_time` (projection needs it; real time is
    unitary)."""
    cf = jnp.asarray(coeffs, amps.dtype)
    tau = jnp.asarray(dt, amps.dtype)
    renorm = imag_time if renorm is None else renorm
    for _ in range(int(steps)):
        amps = _step_traced(amps, n, cf, tau, plan, order, imag_time,
                            renorm)
    return amps


def trotter_ansatz(hamiltonian, *, num_qubits: int = None,
                   order: int = 2, steps: int = 1,
                   imag_time: bool = False) -> Callable:
    """Ansatz over the EVOLVED state for `variational.expectation`:
    returns `ansatz(amps, params)` with params = (coeffs, dt) — both
    runtime operands of one traced program. `hamiltonian` supplies the
    term STRUCTURE only (a PauliSum's coefficients are ignored here;
    the optimizer owns them through params). The returned callable
    carries `program_key`, the value identity `variational.expectation`
    and `variational.sweep` key their program caches on — a rebuilt
    ansatz with equal arguments hits the warm compiled program instead
    of retracing (the zero-retrace optimizer-loop contract, pinned in
    tests/test_evolution.py)."""
    if isinstance(hamiltonian, E.PauliSum):
        codes_key = hamiltonian.codes
        n = hamiltonian.num_qubits
    else:
        codes = np.asarray(hamiltonian)
        n = int(codes.shape[1]) if num_qubits is None else int(num_qubits)
        codes_key = E.parse_pauli_sum(codes, n)
    if order not in (1, 2):
        raise ValueError(f"order must be 1 or 2, got {order!r}")
    plan = _plan_trotter(codes_key)

    def ansatz(amps, params):
        coeffs, dt = params
        return evolve_planes(amps, n, coeffs, dt, plan, steps=steps,
                             order=order, imag_time=imag_time)

    ansatz.program_key = ("trotter_ansatz", codes_key, n, order,
                          int(steps), bool(imag_time))
    ansatz.num_qubits = n
    return ansatz


@functools.partial(jax.jit,
                   static_argnames=("n", "plan", "order", "chunk",
                                    "imag", "renorm"))
def _chunk_traced(amps, coeffs, dt, *, n, plan, order, chunk, imag,
                  renorm):
    def body(_, a):
        return _step_traced(a, n, coeffs, dt, plan, order, imag, renorm)
    return jax.lax.fori_loop(0, chunk, body, amps)


# ---------------------------------------------------------------------------
# run_evolution: the workload driver
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EvolutionResult:
    """What a quench returns: the final register, the energy track —
    `energies[k, j]` is observable j at step `energy_steps[k]`
    (row 0 is the initial state) — and the run's stats record."""
    state: Qureg
    energies: np.ndarray
    energy_steps: np.ndarray
    stats: dict


def _observable_plans(observables, spec, nq: int):
    specs = []
    for obs in observables:
        o = as_pauli_sum(obs, num_qubits=nq)
        if o.num_qubits != nq:
            raise ValueError(
                f"observable is over {o.num_qubits} qubits but the "
                f"evolution register has {nq}")
        specs.append(o)
    return specs


def _measure_energies(q: Qureg, amps, specs) -> List[float]:
    """Fused expec reductions on the DEVICE-RESIDENT planes: only the
    scalar expectations reach the host (calculations dispatches to the
    grouped engine, sharded registers take the per-shard psum path)."""
    from quest_tpu import calculations as C
    qq = q.replace_amps(amps)
    return [C.calc_expec_pauli_sum(qq, np.asarray(o.codes),
                                   np.asarray(o.coeffs)) for o in specs]


def _legacy_step(q: Qureg, plan: TrotterPlan, spec: E.PauliSum,
                 dt: float, order: int) -> Qureg:
    """One legacy per-term step through the EAGER workers — what a user
    writes against the gate API today: one flip-form full-state pass
    per term application (gates.multi_rotate_pauli), no pooling, no
    frames. The honest baseline QUEST_TROTTER_FUSION=0 restores."""
    from quest_tpu.ops import gates as G

    def apply_terms(q, idx, scale):
        for i in idx:
            row = spec.codes[i]
            targets = plan.supports[i]
            paulis = tuple(row[t] for t in targets)
            q = G.multi_rotate_pauli(
                q, targets, paulis,
                _zy_angle(spec.coeffs[i], dt, scale))
        return q

    seq = plan.group_seq()
    groups = [(g[1] if g[0] == "diag" else g[1].terms) for g in seq]
    if order == 1 or len(groups) <= 1:
        for idx in groups:
            q = apply_terms(q, idx, 1.0)
        return q
    for idx in groups[:-1]:
        q = apply_terms(q, idx, 0.5)
    q = apply_terms(q, groups[-1], 1.0)
    for idx in reversed(groups[:-1]):
        q = apply_terms(q, idx, 0.5)
    return q


def run_evolution(hamiltonian, dt, steps: int, *, state: Qureg,
                  coeffs=None, order: int = 2, observables=None,
                  energy_every: int = None, imag_time: bool = False,
                  engine: str = None, mesh=None, interpret: bool = False,
                  durable_dir: str = None, durable_every: int = None
                  ) -> EvolutionResult:
    """Run a `steps`-step Trotter quench of `state` under `hamiltonian`
    end-to-end (docs/EVOLUTION.md):

      * REAL TIME (default): the pooled circuit dispatches through the
        fused engine in chunks of `energy_every` steps
        (`compiled_fused(iters=...)` — sweep fusion merges across the
        unrolled steps), recording every observable in `observables`
        (PauliSum specs; default [hamiltonian]) through the fused expec
        reduction on the device-resident state after each chunk — no
        host round-trip per step, only scalars land.
      * IMAGINARY TIME (`imag_time=True`): exp(-dt H) steps with
        in-trace renormalization after every step — ground-state
        projection; runs the traced core under one jit per chunk
        (coefficients and dt stay runtime operands).
      * DURABLE (`durable_dir=`): the whole quench rides
        `resilience.durable.run_durable` — checkpoints at the engine's
        launch boundaries every `durable_every` (default
        QUEST_DURABLE_EVERY) with the Trotter descriptor validated in
        the cursor; a preempted quench resumes BIT-IDENTICAL to an
        uninterrupted one (tests/test_evolution.py). Incompatible with
        `energy_every` (the planes are the resume payload; observables
        evaluate on the final state).
      * `mesh=` runs the sharded engines (energy via the per-shard
        psum path); `engine` pins 'fused'/'banded' like run_durable.

    With QUEST_TROTTER_FUSION=0 the run is the honest legacy baseline:
    per-term eager dispatch, one flip-form pass per term application —
    the A/B the bench's evolution scenario measures."""
    spec = as_pauli_sum(hamiltonian, coeffs, num_qubits=None)
    if state.num_qubits != spec.num_qubits:
        raise ValueError(
            f"Hamiltonian is over {spec.num_qubits} qubits but the "
            f"register has {state.num_qubits}")
    steps = int(steps)
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if order not in (1, 2):
        raise ValueError(f"order must be 1 or 2, got {order!r}")
    plan = _plan_trotter(spec.codes)
    nq = spec.num_qubits
    n = state.num_state_qubits
    density = state.is_density
    fused = fusion_enabled()
    if observables is None:
        observables = [spec]
    specs = _observable_plans(observables, spec, nq)

    if durable_dir is not None:
        if energy_every is not None:
            raise ValueError(
                "durable_dir= is incompatible with energy_every=: the "
                "durable executor owns the step loop and the planes are "
                "the resume payload; observables evaluate on the final "
                "state (docs/EVOLUTION.md)")
        if imag_time:
            raise ValueError(
                "durable imaginary-time evolution is not supported: "
                "the renormalizing step is not a Circuit the durable "
                "executor can cut (docs/EVOLUTION.md)")
        from quest_tpu.resilience.durable import run_durable
        circ = trotter_circuit(spec, dt, order=order, steps=steps)
        # the EvolutionResult contract (row 0 = initial state) holds on
        # the durable path too: measure before dispatch, final after
        initial = _measure_energies(state, state.amps, specs)
        out = run_durable(
            circ, state, durable_dir, every=durable_every,
            engine=engine, mesh=mesh, interpret=interpret,
            cursor_extra={
                "workload": "trotter",
                "trotter_steps": steps,
                "trotter_order": order,
                "trotter_dt": repr(float(dt)),
                "trotter_terms": len(spec.codes),
            })
        energies = np.asarray([initial,
                               _measure_energies(out, out.amps, specs)])
        return EvolutionResult(
            state=out, energies=energies,
            energy_steps=np.asarray([0, steps]),
            stats={"engine": "durable", "steps": steps, "order": order})

    chunk = steps if energy_every is None else int(energy_every)
    if chunk < 1:
        raise ValueError(f"energy_every must be >= 1, got {chunk}")
    record: List[List[float]] = [_measure_energies(state, state.amps,
                                                   specs)]
    rec_steps = [0]
    dispatches = 0

    if imag_time:
        if mesh is not None or density:
            raise ValueError(
                "imaginary-time evolution runs on single-mesh "
                "statevector registers (docs/EVOLUTION.md)")
        if engine is not None:
            raise ValueError(
                "imaginary-time evolution has no engine= choice: the "
                "renormalizing step runs as one traced XLA program "
                "(docs/EVOLUTION.md)")
        amps = state.amps.reshape(2, -1)
        cf = jnp.asarray(np.asarray(spec.coeffs), amps.dtype)
        tau = jnp.asarray(float(dt), amps.dtype)
        done = 0
        while done < steps:
            m = min(chunk, steps - done)
            amps = _chunk_traced(amps, cf, tau, n=n, plan=plan,
                                 order=order, chunk=m, imag=True,
                                 renorm=True)
            dispatches += 1
            done += m
            record.append(_measure_energies(state, amps, specs))
            rec_steps.append(done)
        q = state.replace_amps(amps)
        return EvolutionResult(
            state=q, energies=np.asarray(record),
            energy_steps=np.asarray(rec_steps),
            stats={"engine": "traced-imag", "steps": steps,
                   "order": order, "dispatches": dispatches})

    if not fused:
        if mesh is not None or engine is not None:
            raise ValueError(
                "QUEST_TROTTER_FUSION=0 runs the legacy per-term EAGER "
                "baseline on a single device — mesh= and engine= have "
                "no legacy counterpart; unset the knob for sharded or "
                "engine-pinned evolution (docs/EVOLUTION.md)")
        q = state
        done = 0
        while done < steps:
            m = min(chunk, steps - done)
            for _ in range(m):
                q = _legacy_step(q, plan, spec, float(dt), order)
            done += m
            dispatches += m
            record.append(_measure_energies(q, q.amps, specs))
            rec_steps.append(done)
        return EvolutionResult(
            state=q, energies=np.asarray(record),
            energy_steps=np.asarray(rec_steps),
            stats={"engine": "legacy-per-term", "steps": steps,
                   "order": order, "dispatches": dispatches})

    circ = trotter_circuit(spec, dt, order=order, steps=1)
    if engine not in (None, "fused", "banded"):
        raise ValueError(
            f"engine must be None, 'fused' or 'banded', got {engine!r}")
    if engine is None and mesh is None:
        # auto-resolve like the bench ladder: the Pallas fused engine
        # needs a kernel-tier f32 register AND a kernel-capable backend
        # (CPU runs Pallas only under interpret=True); everything else
        # rides the banded XLA program — same math, full-state passes
        from quest_tpu.ops import pallas_band as PB
        kernel_ok = (jax.devices()[0].platform in ("tpu", "axon")
                     or interpret)
        if not (PB.usable(n) and state.amps.dtype == jnp.float32
                and kernel_ok):
            engine = "banded"

    def compiled_for(m: int):
        if mesh is not None:
            # engine= pins the per-shard engine exactly like run_durable:
            # 'fused' = the Pallas sharded kernel path, None/'banded' =
            # the shard_map banded XLA program (the CPU-safe default)
            if engine == "fused":
                inner = circ.compiled_sharded_fused(
                    n, density, mesh, donate=True, interpret=interpret)
            else:
                inner = circ.compiled_sharded_banded(n, density, mesh,
                                                     donate=True)

            def run(a, inner=inner, m=m):
                for _ in range(m):
                    a = inner(a)
                return a
            return run
        if engine == "banded":
            return circ.compiled_banded(n, density, donate=True,
                                        iters=m)
        return circ.compiled_fused(n, density, donate=True,
                                   interpret=interpret, iters=m)

    # fresh device buffer: the chunk programs donate their input, and
    # donating the CALLER's planes would delete the register they still
    # hold (state.clone's buffer-aliasing rule)
    from quest_tpu.state import _device_copy
    amps = _device_copy(state.amps)
    if mesh is not None:
        from quest_tpu.parallel.mesh import amp_sharding
        amps = jax.device_put(amps, amp_sharding(mesh))
    fns: Dict[int, Callable] = {}
    done = 0
    while done < steps:
        m = min(chunk, steps - done)
        fn = fns.get(m)
        if fn is None:
            fn = fns[m] = compiled_for(m)
        amps = fn(amps)
        dispatches += 1
        done += m
        record.append(_measure_energies(state, amps, specs))
        rec_steps.append(done)
    q = state.replace_amps(amps)
    return EvolutionResult(
        state=q, energies=np.asarray(record),
        energy_steps=np.asarray(rec_steps),
        stats={"engine": (f"sharded-{engine or 'banded'}"
                          if mesh is not None else engine or "fused"),
               "steps": steps, "order": order,
               "dispatches": dispatches})


def run_evolution_trajectories(hamiltonian, dt, steps: int, shots: int,
                               *, noise, key=None, coeffs=None,
                               order: int = 2, observable=None,
                               engine: str = None,
                               interpret: bool = False,
                               chunk: int = None,
                               durable_dir: str = None,
                               durable_every: int = None):
    """Noisy Trotter evolution through the EXISTING channel path:
    builds the per-step-noise circuit (`trotter_circuit(noise=)`) and
    unravels `shots` stochastic trajectories through
    `trajectories.run_batched` — or, with `durable_dir=`, through the
    durable trajectory executor (checkpointed shot chunks, resume
    bit-identical). Returns (planes, draws) exactly like run_batched;
    `observable=` accepts a PauliSum and reduces per shot on device."""
    spec = as_pauli_sum(hamiltonian, coeffs, num_qubits=None)
    circ = trotter_circuit(spec, dt, order=order, steps=steps,
                           noise=noise)
    if key is None:
        key = jax.random.key(0)
    if observable is not None and not callable(observable):
        observable = E.resolve_observable(observable, spec.num_qubits)
    if durable_dir is not None:
        if observable is not None:
            raise ValueError(
                "durable_dir= is incompatible with observable=: the "
                "planes are the resume payload (docs/RESILIENCE.md)")
        from quest_tpu.resilience.durable import run_durable_trajectories
        return run_durable_trajectories(
            circ, key, shots, durable_dir, every=durable_every,
            chunk=chunk, engine=engine, interpret=interpret)
    from quest_tpu import trajectories as T
    return T.run_batched(circ, key, shots, engine=engine,
                         interpret=interpret, chunk=chunk,
                         observable=observable)
