"""Multi-replica serving: routing, failover, tenancy — the fleet layer.

`ServeEngine` (serve/engine.py) is one worker thread over one set of
queues: supervised, breakered, degradable — but ONE replica. This
module is the production shape above it (docs/SERVING.md §fleet): a
`ServeFleet` owns N ServeEngine replicas and makes the existing
resilience machinery compose across them.

    fleet = ServeFleet(replicas=4)            # knobs: QUEST_SERVE_*
    fut = fleet.submit(circuit, state=planes,
                       tenant="alice", priority=1)
    out = fut.result()

Three contracts, each pinned in tests/test_fleet.py:

  * ROUTING WITH FAILOVER — requests route to the replica that has the
    program warm (a `program_key()` -> replica affinity map; compiled
    programs cache on the Circuit instance, so "warm" here means the
    replica's worker has traced/dispatched this program family before
    and its queues coalesce with like requests). When the affinity
    replica's backlog runs a full launch deeper than the least-loaded
    replica, the request SPILLS to the least-loaded one instead of
    queueing behind the hot spot. When a replica exhausts its restart
    budget and goes FAILED, its queued-but-undispatched requests —
    which the engine resolves with RejectedError under the PR-6
    `_active`-ledger contract — REQUEUE onto surviving replicas in
    arrival order; requests whose launch had already started still
    fail typed (their outcome is unknown — no double-serve), EXCEPT
    durable jobs, whose checkpoint-chain resume makes re-dispatch
    provably serve-once (docs/RESILIENCE.md §durable). The affinity
    map rebuilds as the requeued requests re-route. A fleet with one
    survivor degrades to single-engine behavior; a fleet with none
    goes loudly FAILED — every future resolves typed, never a hang.
  * TENANT ADMISSION + PRIORITY SHED — per-tenant pending quotas
    (`QUEST_SERVE_TENANT_QUOTA`, admission.TenantQuota) bound how much
    of the fleet one tenant's burst can occupy. Fleet PRESSURE is the
    queued fraction of the healthy replicas' capacity plus an
    open-breaker term (each open breaker prices as one max_batch of
    backlog — a program riding the degradation ladder serves slower,
    so its queue is effectively deeper). When pressure crosses
    `QUEST_SERVE_SHED_THRESHOLD`, the LOWEST pending priority class
    sheds with typed `ShedError` naming the pressure cause: an
    incoming request above the lowest queued class EVICTS a queued
    lowest-class victim (cancel-while-queued — an eviction never
    aborts a launch) and takes its place; an incoming request at or
    below the lowest queued class sheds itself. A paying tenant's
    deadline is therefore never burned behind shed-able free traffic.
  * DURABLE LONG JOBS — `submit(..., durable_dir=)` routes the request
    through `resilience.durable.run_durable` at the replica's worker,
    checkpointing at the executor's launch boundaries. A replica crash
    or an injected `durable.preempt` kill mid-job RESUMES the job from
    its checkpoint chain — in place, after a supervised restart, or on
    a failover replica — instead of failing the future, bit-identical
    to an uninterrupted run.

Fault sites `fleet.route` / `fleet.failover` / `fleet.shed`
(resilience.faults) thread through the paths above behind the one
`ACTIVE` flag — zero cost when no plan is armed — so the chaos soak
can kill replicas and force shed decisions deterministically.

Metrics (the fleet's registry, shared by every replica so one
`snapshot()`/`scrape()` covers the whole fleet): counters
`fleet_requests_routed`, `fleet_affinity_hits`, `fleet_affinity_spills`,
`fleet_failovers`, `fleet_requeued_requests`, `fleet_durable_jobs`,
`shed_requests`, `shed_requests_p{N}`, `shed_evictions`,
`tenant_quota_rejections`; gauges `fleet_replicas`,
`fleet_replicas_healthy`, `fleet_pressure`.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Tuple

from quest_tpu.resilience import faults as _F
from quest_tpu.resilience.breaker import CLOSED as _CLOSED
from quest_tpu.serve import metrics as M
from quest_tpu.serve.admission import (DeadlineExceeded, RejectedError,
                                       ShedError, TenantQuota,
                                       TenantQuotaExceeded)
from quest_tpu.serve.engine import ServeEngine


class _Ticket:
    """One fleet request: the user-facing future plus everything needed
    to resubmit it to another replica on failover."""

    __slots__ = ("future", "circuit", "kind", "state", "shots", "key",
                 "observable", "density", "durable_dir", "durable_every",
                 "tenant", "priority", "route_key", "expiry", "submit_t",
                 "replica", "inner", "requeues", "shed_cause", "seq")

    def __init__(self, circuit, kind, state, shots, key, observable,
                 density, durable_dir, durable_every, tenant, priority,
                 route_key, expiry, seq):
        self.future: Future = Future()
        self.circuit = circuit
        self.kind = kind                  # 'apply' | 'traj' | 'durable'
        self.state = state
        self.shots = shots
        self.key = key
        self.observable = observable
        self.density = density
        self.durable_dir = durable_dir
        self.durable_every = durable_every
        self.tenant = tenant
        self.priority = priority
        self.route_key = route_key        # program key for affinity
        self.expiry = expiry              # absolute monotonic or None
        self.submit_t = time.monotonic()
        self.replica: int = -1            # index currently holding it
        self.inner: Optional[Future] = None
        self.requeues = 0                 # failover hops ridden
        self.shed_cause: Optional[BaseException] = None
        self.seq = seq                    # arrival order (requeue order)


class ServeFleet:
    """N supervised ServeEngine replicas behind one submit() — the
    millions-of-users shape of the serving stack (docs/SERVING.md
    §fleet). Thread-safe `submit()`; each replica keeps its own worker
    thread, queues, supervisor, breakers and degradation ladder; the
    fleet adds program-key routing, fleet-level failover, tenant
    quotas, priority load-shedding and durable long jobs.

    Construction keywords override the QUEST_SERVE_* knobs for THIS
    fleet: `replicas` (QUEST_SERVE_REPLICAS), `tenant_quota` (a
    parse_tenant_quota dict or a bare int, QUEST_SERVE_TENANT_QUOTA),
    `shed_threshold` (QUEST_SERVE_SHED_THRESHOLD), `priorities`
    (QUEST_SERVE_PRIORITIES). Every other keyword passes through to
    each ServeEngine replica (max_wait_ms, max_queue, max_batch,
    interpret, traj_engine, restart_max, backoff_base_s,
    breaker_threshold, breaker_cooldown_s, ladder). `registry` defaults
    to the process-wide one and is SHARED with every replica, so one
    snapshot/scrape covers the fleet."""

    # the fleet RLock (reentrant: shed-eviction callbacks re-enter it)
    # and what it guards (quest-lint QL005, docs/ANALYSIS.md)
    _GUARDED_BY = {
        "_lock": ("_affinity", "_pending", "_tenant_pending", "_seq",
                  "_rr", "_failed_noted", "_closed", "_failure_cause",
                  "_retired", "_requeue_cap"),
    }

    def __init__(self, replicas: Optional[int] = None, *,
                 process: Optional[bool] = None,
                 tenant_quota=None,
                 shed_threshold: Optional[float] = None,
                 priorities: Optional[int] = None,
                 registry: Optional[M.Registry] = None,
                 **engine_kw):
        from quest_tpu.env import knob_value
        if replicas is None:
            replicas = knob_value("QUEST_SERVE_REPLICAS")
        if int(replicas) < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if process is None:
            process = knob_value("QUEST_FLEET_PROC")
        self.process = bool(process)
        if tenant_quota is None:
            tenant_quota = knob_value("QUEST_SERVE_TENANT_QUOTA")
        if isinstance(tenant_quota, int):
            tenant_quota = {"default": tenant_quota}
        if shed_threshold is None:
            shed_threshold = knob_value("QUEST_SERVE_SHED_THRESHOLD")
        if not (0.0 < float(shed_threshold) <= 1.0):
            raise ValueError(
                f"shed_threshold must be in (0, 1], got {shed_threshold}")
        if priorities is None:
            priorities = knob_value("QUEST_SERVE_PRIORITIES")
        if int(priorities) < 1:
            raise ValueError(f"priorities must be >= 1, got {priorities}")
        self.registry = registry if registry is not None else M.REGISTRY
        self.tenant_quota = TenantQuota(tenant_quota)
        self.shed_threshold = float(shed_threshold)
        self.priorities = int(priorities)
        # durable_mesh may be a PER-REPLICA list (heterogeneous fleet:
        # a big-mesh replica runs deep jobs sharded, a small survivor
        # resumes them elastically after failover — docs/RESILIENCE.md
        # §elastic); a single mesh (or None) applies to every replica
        meshes = engine_kw.pop("durable_mesh", None)
        if not isinstance(meshes, (list, tuple)):
            meshes = [meshes] * int(replicas)
        if len(meshes) != int(replicas):
            raise ValueError(
                f"durable_mesh list has {len(meshes)} entries for "
                f"{replicas} replicas")
        if self.process and any(m is not None for m in meshes):
            raise ValueError(
                "process replicas build their own mesh from their own "
                "environment; durable_mesh= is a thread-replica option "
                "(docs/SERVING.md §process-fleet)")
        self._engine_kw = dict(engine_kw)
        self._engines: List[ServeEngine] = [
            self._make_replica(i, durable_mesh=meshes[i])
            for i in range(int(replicas))]
        # replicas retired by the elastic scale-down path: closed but
        # kept in _engines as tombstones so ticket indices never dangle
        self._retired: set = set()
        # the requeue bound: a request may hop at most once past every
        # replica and once more (the survivor it lands on may fail
        # later too) before it fails typed — failover can never loop
        self._requeue_cap = 2 * len(self._engines)
        # REENTRANT: a shed eviction cancels the victim's inner future
        # under this lock, and Future.cancel() runs the victim's
        # completion callback synchronously on the cancelling thread —
        # which re-enters the lock to drop the victim from the ledger
        self._lock = threading.RLock()
        # insertion-ordered and BOUNDED: one entry per program family
        # would otherwise grow forever on a fleet serving one-off
        # circuits; beyond the cap the stalest pin falls out (its next
        # request just re-routes least-loaded and re-pins)
        self._affinity: "OrderedDict[tuple, int]" = OrderedDict()
        self._affinity_cap = 4096
        # insertion-ordered pending-ticket ledger: the shed victim scan
        # and the tenant pending counts read it under the fleet lock
        self._pending: "OrderedDict[int, _Ticket]" = OrderedDict()
        self._tenant_pending: Dict[str, int] = {}
        self._seq = 0
        self._rr = 0                      # round-robin tiebreak cursor
        self._failed_noted: set = set()   # replica deaths already tallied
        self._closed = False
        self._failure_cause: Optional[BaseException] = None
        self.registry.gauge("fleet_replicas").set(len(self._engines))
        self.registry.gauge("fleet_replicas_healthy").set(
            len(self._engines))
        # hot-path metric handles, hoisted once (the engine.py pattern)
        self._m_routed = self.registry.counter("fleet_requests_routed")
        self._m_aff = self.registry.counter("fleet_affinity_hits")
        self._m_spill = self.registry.counter("fleet_affinity_spills")
        self._m_pressure = self.registry.gauge("fleet_pressure")

    def _make_replica(self, idx: int, durable_mesh=None):
        """One replica at index `idx`: an in-process ServeEngine, or —
        under `process=True` / QUEST_FLEET_PROC — a serve.ipc
        ReplicaProxy fronting a supervised worker process with its own
        interpreter and JAX runtime (docs/SERVING.md §process-fleet).
        Both expose the same engine surface; the fleet logic above
        never branches on the backend again."""
        if self.process:
            from quest_tpu.serve.ipc import ReplicaProxy
            return ReplicaProxy(registry=self.registry, name=f"r{idx}",
                                **self._engine_kw)
        return ServeEngine(registry=self.registry, name=f"r{idx}",
                           durable_mesh=durable_mesh, **self._engine_kw)

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        """'running' while any replica serves | 'failed' (every replica
        exhausted its restart budget) | 'closed'."""
        # quest-lint: disable=QL005(observability fast path: racy flag read, engine.state contract)
        if self._closed:
            return "closed"
        if any(e.state == "running" for e in self._engines):
            return "running"
        return "failed"

    # duck-type attributes serve.warmup() reads off an engine: warming
    # ONE replica warms the whole fleet, because compiled programs
    # cache on the Circuit instance, process-wide (docs/BATCHING.md)
    @property
    def max_batch(self) -> int:
        return self._engines[0].max_batch

    @property
    def interpret(self) -> bool:
        return self._engines[0].interpret

    @property
    def traj_engine(self):
        return self._engines[0].traj_engine

    @property
    def replicas(self) -> int:
        """Live (non-retired) replica count — what the elastic
        autoscaler grows and shrinks; scale-down tombstones stay in
        `_engines` so in-flight ticket indices never dangle."""
        with self._lock:
            return len(self._engines) - len(self._retired)

    def plan(self, circuit, *, batch: Optional[int] = None,
             density: bool = False, dtype=None):
        """ServeEngine.plan for the fleet: one priced ProgramPlan covers
        every replica (plans are content-addressed per circuit + mode,
        not per replica — docs/PLANNING.md)."""
        return self._engines[0].plan(circuit, batch=batch,
                                     density=density, dtype=dtype)

    def stats(self) -> dict:
        """Per-replica health: state, queued depth, restart budget left
        — the figure an operator reads next to the fleet metrics —
        plus the process-wide plan-cache counters (hits vs searches:
        a warm-restarted fleet shows zero searches, docs/PLANNING.md)."""
        from quest_tpu import plan as P
        with self._lock:
            pressure = self._pressure_locked()
            retired = set(self._retired)
        return {
            "pressure": pressure,
            "process": self.process,
            "plan_cache": P.cache_stats(),
            "replicas": [
                {"name": e.name, "state": e.state, "pending": e._pending,
                 "restarts_remaining": e._supervisor.remaining,
                 "retired": i in retired}
                for i, e in enumerate(self._engines)],
        }

    # -- submit ------------------------------------------------------------

    def submit(self, circuit, state=None, shots: Optional[int] = None, *,
               key=None, deadline_s: Optional[float] = None,
               observable: Optional[Callable] = None,
               density: bool = False,
               durable_dir: Optional[str] = None,
               durable_every: Optional[int] = None,
               tenant: Optional[str] = None,
               priority: int = 0) -> Future:
        """ServeEngine.submit semantics plus the fleet layer: `tenant`
        names the submitting tenant for quota accounting (None = the
        shared 'anon' bucket), `priority` its class in
        [0, QUEST_SERVE_PRIORITIES) — higher classes shed later and may
        evict queued lower-class requests under pressure. Raises
        `TenantQuotaExceeded` over quota, `ShedError` when this request
        sheds, `RejectedError` when the fleet is closed/FAILED or every
        replica refuses the request."""
        if not (0 <= int(priority) < self.priorities):
            raise ValueError(
                f"priority must be in [0, {self.priorities}) "
                f"(QUEST_SERVE_PRIORITIES), got {priority}")
        tenant = "anon" if tenant is None else str(tenant)
        kind, route_key = self._route_key(circuit, state, shots, key,
                                          density, durable_dir)
        now = time.monotonic()
        expiry = None if deadline_s is None else now + float(deadline_s)
        with self._lock:
            if self._closed:
                self.registry.counter("serve_requests_rejected").inc()
                raise RejectedError(
                    "Invalid operation: fleet closed — submit() after "
                    "ServeFleet.close(); create a new fleet "
                    "(docs/SERVING.md §fleet).")
            healthy = self._healthy_locked()
            if not healthy:
                self.registry.counter("serve_requests_rejected").inc()
                raise RejectedError(
                    f"Invalid operation: ServeFleet is FAILED — every "
                    f"replica exhausted its restart budget; last cause: "
                    f"{self._failure_cause!r} (docs/SERVING.md §fleet)."
                ) from self._failure_cause
            try:
                self.tenant_quota.admit(
                    tenant, self._tenant_pending.get(tenant, 0))
            except TenantQuotaExceeded:
                self.registry.counter("tenant_quota_rejections").inc()
                raise
            pressure = self._pressure_locked()
            self._m_pressure.set(pressure)
            evict = None
            if pressure >= self.shed_threshold:
                evict = self._shed_locked(pressure, int(priority))
            ticket = _Ticket(circuit, kind, state, shots, key,
                             observable, density, durable_dir,
                             durable_every, tenant, int(priority),
                             route_key, expiry, self._seq)
            self._seq += 1
            idx = self._pick_replica_locked(route_key, healthy)
            ticket.replica = idx
            self._pending[id(ticket)] = ticket
            n_tenant = self._tenant_pending.get(tenant, 0) + 1
            self._tenant_pending[tenant] = n_tenant
            self.registry.gauge(f"tenant_pending_{tenant}").set(n_tenant)
        # the evicted victim's inner future was cancelled under the
        # lock; its callback (fleet lock again) may run on this thread
        # via cancel() — complete bookkeeping happens there
        if _F.ACTIVE:
            try:
                _F.check("fleet.route", program=route_key, replica=idx,
                         tenant=tenant, priority=int(priority))
            except BaseException:
                self.registry.counter("serve_faults_injected").inc()
                with self._lock:
                    self._forget_locked(ticket)
                raise
        try:
            self._submit_to(ticket, idx)
        except BaseException:
            with self._lock:
                self._forget_locked(ticket)
            raise
        self._m_routed.inc()
        if kind == "durable":
            self.registry.counter("fleet_durable_jobs").inc()
        if evict is not None:
            # tallied after the admit so the victim's shed never masks
            # a failed submit of the evictor
            self.registry.counter("shed_evictions").inc()
        # cancel-while-queued propagates to the replica: attached last,
        # so no cancel can race the submit path above (the caller only
        # holds the future once we return)
        ticket.future.add_done_callback(
            lambda f, t=ticket: self._on_outer_done(t, f))
        return ticket.future

    def _on_outer_done(self, ticket: _Ticket, f: Future) -> None:
        """Outer-future completion hook; only cancellation needs work:
        propagate it to the queued inner request (best-effort — a
        dispatched launch is never aborted, its result is simply
        discarded) and release the ledger/quota slot."""
        if not f.cancelled():
            return
        inner = ticket.inner
        if inner is not None and inner.cancel():
            self._engines[ticket.replica].reap_cancelled()
        with self._lock:
            self._forget_locked(ticket)

    def _route_key(self, circuit, state, shots, key, density,
                   durable_dir) -> Tuple[str, tuple]:
        """(kind, program key) for affinity routing — the SAME program
        identities the engines queue by (Circuit.program_key /
        trajectories.program_key), so "routed to the warm replica"
        means routed to the replica whose queues already coalesce this
        family."""
        if (state is None) == (shots is None):
            raise ValueError(
                "submit() takes exactly one of state= (apply request) "
                "or shots= (trajectory request)")
        if state is not None:
            import numpy as np
            dtype = getattr(state, "dtype", np.float32)
            base = circuit.program_key(density=density,
                                       interpret=self.interpret,
                                       dtype=dtype)
            if durable_dir is not None:
                return "durable", base + ("durable",)
            return "apply", base
        from quest_tpu import trajectories as T
        _, qkey = T.program_key(circuit, engine=self.traj_engine,
                                interpret=self.interpret)
        return "traj", qkey

    # -- routing -----------------------------------------------------------

    def _healthy_locked(self) -> List[int]:
        return [i for i, e in enumerate(self._engines)
                if e.state == "running" and i not in self._retired]

    def _pick_replica_locked(self, route_key: tuple,
                             healthy: List[int]) -> int:
        """Affinity if warm and not overloaded; else least-loaded.
        Overload = the affinity replica's queued depth runs at least a
        full launch (max_batch) deeper than the least-loaded healthy
        replica — at that point queueing behind the warm program costs
        more than a cold trace elsewhere, so the request SPILLS (the
        affinity pin stays: the next uncongested request still routes
        warm)."""
        depth = {i: self._engines[i]._pending for i in healthy}
        aff = self._affinity.get(route_key)
        least = min(healthy, key=lambda i: (depth[i], i))
        if aff is not None and aff in depth:
            self._affinity.move_to_end(route_key)
            if depth[aff] - depth[least] < self._engines[aff].max_batch:
                self._m_aff.inc()
                return aff
            self._m_spill.inc()
            return least
        # new program family: least-loaded, round-robin on ties so
        # program families spread across the fleet instead of piling
        # onto replica 0 at startup
        min_depth = depth[least]
        ties = [i for i in healthy if depth[i] == min_depth]
        idx = ties[self._rr % len(ties)]
        self._rr += 1
        self._affinity[route_key] = idx
        while len(self._affinity) > self._affinity_cap:
            self._affinity.popitem(last=False)
        return idx

    def _submit_to(self, ticket: _Ticket, idx: int) -> None:
        """Hand `ticket` to replica `idx`; tries the other healthy
        replicas on a synchronous RejectedError (that replica's queue
        is full or it failed between the pick and the submit). Raises
        only when every healthy replica refused."""
        with self._lock:
            retired = set(self._retired)
        order = [idx] + [i for i in range(len(self._engines)) if i != idx]
        last: Optional[BaseException] = None
        for i in order:
            if i in retired:
                continue
            eng = self._engines[i]
            if eng.state != "running":
                continue
            remaining = (None if ticket.expiry is None
                         else ticket.expiry - time.monotonic())
            if remaining is not None and remaining <= 0:
                raise DeadlineExceeded(
                    "Invalid operation: the request's deadline elapsed "
                    "before it could be routed to a replica "
                    "(docs/SERVING.md §fleet).")
            try:
                inner = eng.submit(
                    ticket.circuit,
                    state=ticket.state, shots=ticket.shots,
                    key=ticket.key, deadline_s=remaining,
                    observable=ticket.observable, density=ticket.density,
                    durable_dir=ticket.durable_dir,
                    durable_every=ticket.durable_every)
            except RejectedError as e:
                last = e
                continue
            ticket.replica = i
            ticket.inner = inner
            inner.add_done_callback(
                lambda fut, t=ticket: self._on_inner_done(t, fut))
            return
        with self._lock:
            self._forget_locked(ticket)
        raise last if last is not None else RejectedError(
            "Invalid operation: no replica accepted the request "
            "(docs/SERVING.md §fleet).")

    # -- completion + failover ---------------------------------------------

    def _forget_locked(self, ticket: _Ticket) -> None:
        if self._pending.pop(id(ticket), None) is not None:
            n = self._tenant_pending.get(ticket.tenant, 1) - 1
            if n:
                self._tenant_pending[ticket.tenant] = n
            else:
                self._tenant_pending.pop(ticket.tenant, None)
            self.registry.gauge(
                f"tenant_pending_{ticket.tenant}").set(n)

    def _resolve(self, ticket: _Ticket, result=None,
                 exc: Optional[BaseException] = None) -> None:
        with self._lock:
            self._forget_locked(ticket)
        f = ticket.future
        if f.done():
            return
        if not f.set_running_or_notify_cancel():
            return
        if exc is not None:
            f.set_exception(exc)
        else:
            f.set_result(result)

    def _on_inner_done(self, ticket: _Ticket, fut: Future) -> None:
        """Runs on the owning replica's worker thread (or the evicting
        submitter's, for a cancel): transfer the inner result/error to
        the user-facing future, or REQUEUE onto a survivor when the
        replica died with the request still safe to re-serve."""
        if ticket.future.cancelled():
            # the caller walked away: drop the ledger slot and never
            # failover/re-serve abandoned work
            with self._lock:
                self._forget_locked(ticket)
            return
        if fut.cancelled():
            # inner-only cancel = the shed eviction (queued-only)
            exc = ticket.shed_cause or ShedError(
                "Invalid operation: the request was load-shed while "
                "queued (docs/SERVING.md §fleet).")
            self._resolve(ticket, exc=exc)
            return
        exc = fut.exception()
        if exc is None:
            self._resolve(ticket, result=fut.result())
            return
        replica_failed = (
            self._engines[ticket.replica].state == "failed")
        # REQUEUE-SAFE: the engine resolves queued-but-undispatched
        # requests of a FAILED worker with RejectedError (the _active
        # ledger contract) — those never launched, so re-serving them
        # elsewhere cannot double-serve. Durable jobs are requeue-safe
        # even past dispatch (their retry is a checkpoint-chain resume,
        # docs/RESILIENCE.md §durable) — the engine converges them to
        # the same RejectedError on death. Everything else that died
        # WITH the replica had an unknown launch outcome: it fails
        # typed, exactly like the single-engine contract.
        requeueable = (replica_failed
                       and isinstance(exc, RejectedError)
                       and not isinstance(exc, DeadlineExceeded))
        if not requeueable:
            self._resolve(ticket, exc=exc)
            return
        with self._lock:
            self._note_failed_locked(ticket.replica)
            healthy = self._healthy_locked()
            ticket.requeues += 1
            if not healthy:
                # only a true no-survivors state defines the fleet's
                # failure cause; a single ticket exhausting its hop cap
                # while peers serve must not pollute it
                self._failure_cause = exc
            if not healthy or ticket.requeues > self._requeue_cap:
                cause = exc
                healthy = []
            else:
                target = self._pick_replica_locked(ticket.route_key,
                                                   healthy)
        if not healthy:
            self._resolve(ticket, exc=RejectedError(
                f"Invalid operation: request lost its replica and no "
                f"survivor could take it (hops: {ticket.requeues}); "
                f"last cause: {cause!r} (docs/SERVING.md §fleet)."))
            return
        if _F.ACTIVE:
            try:
                _F.check("fleet.failover", replica=ticket.replica,
                         target=target)
            except BaseException as e:  # noqa: BLE001 - typed resolve
                self.registry.counter("serve_faults_injected").inc()
                self._resolve(ticket, exc=e)
                return
        if _F.ACTIVE:
            # the requeue site proper (vs fleet.failover, the decision
            # point above): fires as the ticket is RE-SUBMITTED to its
            # survivor, so chaos plans can fail the requeue hop itself
            # — e.g. while a durable chain waits on disk — without
            # touching first-time routing (docs/RESILIENCE.md)
            try:
                _F.check("fleet.requeue", replica=ticket.replica,
                         target=target, hops=ticket.requeues,
                         durable=ticket.kind == "durable")
            except BaseException as e:  # noqa: BLE001 - typed resolve
                self.registry.counter("serve_faults_injected").inc()
                self._resolve(ticket, exc=e)
                return
        self.registry.counter("fleet_requeued_requests").inc()
        try:
            self._submit_to(ticket, target)
        except BaseException as e:      # noqa: BLE001 - typed resolve
            self._resolve(ticket, exc=e)

    def _note_failed_locked(self, idx: int) -> None:
        """A replica went FAILED: tally the failover event ONCE (the
        per-ticket tally is fleet_requeued_requests), drop its affinity
        pins (requeued and future requests re-route, rebuilding the map
        on survivors) and refresh the health gauge."""
        if idx not in self._failed_noted:
            self._failed_noted.add(idx)
            self.registry.counter("fleet_failovers").inc()
        for k in [k for k, v in self._affinity.items() if v == idx]:
            del self._affinity[k]
        self.registry.gauge("fleet_replicas_healthy").set(
            len(self._healthy_locked()))

    # -- elasticity (serve/autoscaler.py drives these) -----------------------

    def add_replica(self) -> int:
        """Grow the fleet by one replica (thread or process per the
        fleet's backend). Returns its index. The spawn happens OUTSIDE
        the fleet lock — a process boot takes seconds and submits must
        keep flowing — so two concurrent callers simply add two
        replicas."""
        with self._lock:
            if self._closed:
                raise RejectedError(
                    "Invalid operation: add_replica() after "
                    "ServeFleet.close() (docs/SERVING.md "
                    "§process-fleet).")
        eng = self._make_replica(len(self._engines))
        with self._lock:
            if self._closed:
                closed_race = True
            else:
                closed_race = False
                self._engines.append(eng)
                self._requeue_cap = 2 * len(self._engines)
                live = len(self._engines) - len(self._retired)
                self.registry.gauge("fleet_replicas").set(live)
                self.registry.gauge("fleet_replicas_healthy").set(
                    len(self._healthy_locked()))
        if closed_race:
            eng.close(timeout_s=5.0)
            raise RejectedError(
                "Invalid operation: fleet closed while the new replica "
                "was booting (docs/SERVING.md §process-fleet).")
        self.registry.counter("fleet_scale_ups").inc()
        return len(self._engines) - 1

    def remove_replica(self, timeout_s: Optional[float] = 30.0) -> int:
        """Shrink the fleet by one replica: the least-loaded running
        one retires — new requests stop routing to it immediately, its
        queued requests DRAIN (never shed by a scale-down), then it
        closes. Returns the retired index. Refuses to remove the last
        live replica."""
        with self._lock:
            if self._closed:
                raise RejectedError(
                    "Invalid operation: remove_replica() after "
                    "ServeFleet.close() (docs/SERVING.md "
                    "§process-fleet).")
            healthy = self._healthy_locked()
            if len(healthy) <= 1:
                raise ValueError(
                    "cannot retire the last live replica — scale-down "
                    "floors at 1 (QUEST_FLEET_MIN_REPLICAS governs the "
                    "autoscaler's own floor)")
            # least-loaded retires (cheapest drain); newest breaks ties
            # so long-lived warm replicas keep their affinity pins
            idx = min(healthy,
                      key=lambda i: (self._engines[i]._pending, -i))
            self._retired.add(idx)
            for k in [k for k, v in self._affinity.items() if v == idx]:
                del self._affinity[k]
            live = len(self._engines) - len(self._retired)
            self.registry.gauge("fleet_replicas").set(live)
            self.registry.gauge("fleet_replicas_healthy").set(
                len(self._healthy_locked()))
        eng = self._engines[idx]
        try:
            eng.drain(timeout_s=timeout_s)
        except RejectedError:
            pass        # already failed/closed: nothing left to drain
        except TimeoutError:
            # the drain window expired with requests still incomplete:
            # closing now would resolve them rejected, and a scale-down
            # must NEVER lose accepted work — roll the retirement back
            # (routing resumes) and let the caller retry a later tick
            with self._lock:
                self._retired.discard(idx)
                live = len(self._engines) - len(self._retired)
                self.registry.gauge("fleet_replicas").set(live)
                self.registry.gauge("fleet_replicas_healthy").set(
                    len(self._healthy_locked()))
            raise TimeoutError(
                f"scale-down of replica {idx} aborted: its drain did "
                f"not complete within timeout_s={timeout_s} — the "
                f"retirement rolled back so no accepted request is "
                f"lost (docs/SERVING.md §process-fleet)")
        eng.close(timeout_s=timeout_s)
        self.registry.counter("fleet_scale_downs").inc()
        return idx

    def scrape(self) -> str:
        """One Prometheus exposition for the whole fleet. Thread
        replicas share the fleet registry, so this is its scrape;
        process replicas keep their registries in their own
        interpreters, so the fleet merges the per-replica heartbeat
        snapshots into the fleet-level metrics (docs/SERVING.md
        §process-fleet: counters/gauges sum, histogram quantiles take
        the worst replica — the alerting-conservative merge)."""
        if not self.process:
            return self.registry.scrape()
        snaps = [self.registry.snapshot()]
        for e in self._engines:
            snap = getattr(e, "snapshot", None)
            if snap is not None:
                snaps.append(snap())
        return M.render_snapshot(M.merge_snapshots(snaps))

    # -- pressure + shedding -----------------------------------------------

    def _pressure_locked(self) -> float:
        """Fleet pressure in [0, ~1+]: queued depth over the healthy
        replicas' bounded capacity, plus each not-CLOSED breaker priced
        as one max_batch of extra backlog (a program on the degradation
        ladder serves slower, so its queue is effectively deeper).
        Breakers are counted from THIS fleet's own replicas — the
        registry's serve_breakers_open gauge is process-wide, and an
        unrelated engine sharing the default registry must not shed
        this fleet's traffic."""
        healthy = self._healthy_locked()
        if not healthy:
            return 1.0
        capacity = sum(self._engines[i]._admission.max_queue
                       for i in healthy)
        queued = sum(self._engines[i]._pending for i in healthy)
        open_breakers = sum(
            1 for i in healthy
            for br in list(self._engines[i]._breakers.values())
            if br.state != _CLOSED)
        max_batch = max(self._engines[i].max_batch for i in healthy)
        return (queued + open_breakers * max_batch) / max(capacity, 1)

    def _shed_locked(self, pressure: float,
                     priority: int) -> Optional[_Ticket]:
        """The shed decision under pressure (docs/SERVING.md §fleet):
        find the lowest-priority QUEUED ticket that can still be
        cancelled. If the incoming request outranks it, evict it (the
        victim sheds, the incoming is admitted) and return it; if the
        incoming request is itself in the lowest class, raise ShedError
        for the incoming. Either way 100% of sheds land on the lowest
        pending class until it is exhausted."""
        cause = (f"fleet pressure {pressure:.3f} >= "
                 f"QUEST_SERVE_SHED_THRESHOLD={self.shed_threshold} "
                 f"(queued depth + open-breaker backlog over healthy "
                 f"capacity)")
        victim = None
        for t in self._pending.values():
            if t.priority < priority and (
                    victim is None or t.priority < victim.priority):
                victim = t
                if victim.priority == 0:
                    break
        if _F.ACTIVE:
            try:
                _F.check("fleet.shed", pressure=pressure,
                         priority=priority, evict=victim is not None)
            except BaseException:
                self.registry.counter("serve_faults_injected").inc()
                raise
        if victim is not None:
            # cancel succeeds only while the victim is still queued at
            # its replica (admission contract); a dispatched victim is
            # not shed-able — walk on to the next lowest. The typed
            # cause is built per candidate: the ticket that actually
            # sheds must be the one the message names.
            for t in sorted(
                    (t for t in self._pending.values()
                     if t.priority < priority),
                    key=lambda t: (t.priority, t.seq)):
                t.shed_cause = ShedError(
                    f"Invalid operation: request (priority "
                    f"{t.priority}, tenant {t.tenant!r}) was load-shed "
                    f"for a priority-{priority} request: {cause} "
                    f"(docs/SERVING.md §fleet).")
                if t.inner is not None and t.inner.cancel():
                    # free the victim's queue slot NOW: the engine
                    # worker would only sweep the cancelled request at
                    # its next wake, and at the hard queue bound the
                    # evicting submit would still see a full queue and
                    # be rejected — shedding the victim for nothing
                    self._engines[t.replica].reap_cancelled()
                    self.registry.counter("shed_requests").inc()
                    self.registry.counter(
                        f"shed_requests_p{t.priority}").inc()
                    return t
                t.shed_cause = None
            # nothing evictable (all dispatched): the incoming request
            # is admitted — launches are never aborted
            return None
        self.registry.counter("shed_requests").inc()
        self.registry.counter(f"shed_requests_p{priority}").inc()
        raise ShedError(
            f"Invalid operation: request (priority {priority}) was "
            f"load-shed — it sits in the lowest pending priority class "
            f"and {cause} (docs/SERVING.md §fleet).")

    # -- drain / close -----------------------------------------------------

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Flush every queued request on every replica and block until
        each fleet future has resolved — including requests that
        failover mid-drain (the requeue lands on a survivor whose own
        worker flushes it). TimeoutError when `timeout_s` elapses with
        futures still unresolved; on a fully FAILED fleet it returns
        once every future has resolved typed (never hangs)."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._lock:
            closed = self._closed
        if closed:
            raise RejectedError(
                "Invalid operation: fleet closed — drain() after "
                "ServeFleet.close() (docs/SERVING.md §fleet).")
        self._drain(deadline)

    def _drain(self, deadline: Optional[float]) -> None:
        from concurrent.futures import wait as _wait
        while True:
            with self._lock:
                futures = [t.future for t in self._pending.values()]
                inners = [t.inner for t in self._pending.values()
                          if t.inner is not None]
            if not futures and not inners:
                return
            for eng in self._engines:
                if eng.state != "running":
                    continue
                step = (0.25 if deadline is None
                        else max(0.0, min(0.25,
                                          deadline - time.monotonic())))
                try:
                    eng.drain(timeout_s=step)
                except TimeoutError:
                    pass
                except RejectedError:
                    pass
            # wait on the INNER futures: the outer ones resolve from
            # inner callbacks, and waiting here (briefly) avoids a busy
            # spin while a requeued request rides a survivor's queue
            done_wait = 0.05
            if inners:
                _wait(inners, timeout=done_wait)
            else:
                time.sleep(done_wait)
            with self._lock:
                remaining = len(self._pending)
            if not remaining:
                return
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"ServeFleet.drain() timed out with {remaining} "
                    f"request(s) unresolved")

    def close(self, timeout_s: Optional[float] = None) -> None:
        """Drain, then close every replica. Idempotent. `timeout_s` is
        ONE overall budget: the drain and every engine close share it
        (a wedged 4-replica fleet closes within ~timeout_s, not 5x)."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._lock:
            already = self._closed
            self._closed = True
        if not already:
            try:
                self._drain(deadline)
            except TimeoutError:
                pass
        for eng in self._engines:
            step = (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))
            eng.close(timeout_s=step)

    def __enter__(self) -> "ServeFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
