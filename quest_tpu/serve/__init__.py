"""quest_tpu.serve — continuous-batching execution service.

The request-serving runtime in front of the batched engines
(docs/SERVING.md): `ServeEngine` coalesces compatible requests from
many clients into full `env.batch_bucket` buckets and dispatches ONE
batched launch per bucket; `serve.admission` supplies bounded-queue
rejection, deadlines and cancellation; `serve.metrics` the zero-dep
counters/histograms; `serve.warmup` pre-compiles a declared workload's
bucket grid.

`quest_tpu.serve.metrics` imports only the standard library — the
compile-cache listener (precision.py) and scripts/serve_stats.py rely
on that. Everything else loads lazily through this namespace so
importing the metrics module never drags jax in.
"""

from quest_tpu.serve import metrics  # noqa: F401  (zero-dep, eager)
# `warmup` the FUNCTION shares its name with the submodule, and a bare
# `import quest_tpu.serve.warmup` anywhere binds the MODULE over the
# package attribute, permanently shadowing a lazy export (the module
# attribute is only set on the parent at first load, so importing the
# submodule HERE and rebinding the name right after is ordering-proof).
# warmup.py is stdlib-only at import time, so this stays jax-free.
from quest_tpu.serve.warmup import default_buckets, warmup  # noqa: F401,E402

_LAZY = {
    "ServeEngine": ("quest_tpu.serve.engine", "ServeEngine"),
    "ServeFleet": ("quest_tpu.serve.fleet", "ServeFleet"),
    "ReplicaProxy": ("quest_tpu.serve.ipc", "ReplicaProxy"),
    "Autoscaler": ("quest_tpu.serve.autoscaler", "Autoscaler"),
    "RejectedError": ("quest_tpu.serve.admission", "RejectedError"),
    "DeadlineExceeded": ("quest_tpu.serve.admission", "DeadlineExceeded"),
    "ShedError": ("quest_tpu.serve.admission", "ShedError"),
    "DispatchTimeout": ("quest_tpu.serve.admission", "DispatchTimeout"),
    "TenantQuota": ("quest_tpu.serve.admission", "TenantQuota"),
    "TenantQuotaExceeded": ("quest_tpu.serve.admission",
                            "TenantQuotaExceeded"),
    "AdmissionController": ("quest_tpu.serve.admission",
                            "AdmissionController"),
}

__all__ = ["metrics", "default_buckets", "warmup"] + sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module 'quest_tpu.serve' has no "
                             f"attribute {name!r}") from None
    import importlib
    mod = importlib.import_module(mod_name)
    for k, (m, a) in _LAZY.items():
        if m == mod_name:
            globals()[k] = getattr(mod, a)
    return globals()[name]
