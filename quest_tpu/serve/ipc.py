"""Process-replica IPC: the dispatch boundary that breaks the GIL.

PR 11's bench was honest about thread replicas: on a CPU host two
ServeEngine worker THREADS price below one, because every replica's
tracing and dispatch serializes on the one interpreter lock. This
module is the fix the distributed simulators converge on (mpiQulacs
arXiv:2203.16044, PennyLane-Lightning MPI arXiv:2508.13615): each
replica becomes a supervised WORKER PROCESS — its own interpreter, its
own JAX runtime, its own ServeEngine — fronted by a `ReplicaProxy`
that duck-types the exact engine surface `ServeFleet` already routes,
sheds and fails over against (docs/SERVING.md §process-fleet). The
fleet layer does not know the difference: `ServeFleet(process=True)`
swaps `ServeEngine` for `ReplicaProxy` and every contract pinned in
tests/test_fleet.py rides on unchanged.

Wire protocol — a Unix socketpair per replica, carrying length-prefixed
pickle frames (docs/SERVING.md §process-fleet for the layout):

    +----------------+----------------------------+
    | 8 bytes, BE    | pickle.dumps(payload) ...  |
    | payload length | payload["t"] = frame type  |
    +----------------+----------------------------+

parent -> worker: init, submit, cancel, drain, close
worker -> parent: hello, result, drained, hb (heartbeat)

Circuits travel as VALUE-KEYED program descriptors: a content digest
over the op stream plus (first shipment per worker boot) the ops
themselves. The worker caches rebuilt Circuit objects by digest, so
repeat submits of an equal-valued circuit hit the worker's on-instance
compiled-program cache, and — because the PR-15 plan cache and the XLA
compile cache are content-addressed files on SHARED disk — a warm
worker boots as a LOAD, never a re-search (tests/test_ipc.py pins the
concurrent-warmup discipline).

Supervision (the Supervisor policy class, reused verbatim from the
thread story — resilience/supervisor.py): a worker that stops
heartbeating for `_HB_MISS` intervals, EOFs its pipe, or reports its
in-process engine FAILED is killed and respawned under the proxy's
restart budget, and the proxy RESUBMITS every incomplete request to
the fresh worker. That resubmit is provably serve-once across the
process boundary — stronger than the thread contract: a SIGKILLed
process delivered no result frame for an incomplete request and never
will, circuit application is pure, and durable jobs re-enter their
checkpoint-chain resume (docs/RESILIENCE.md §durable) — so even
requests whose launch had started are safe to re-serve. Budget
exhausted => the proxy goes FAILED and resolves its incomplete futures
with the requeue-typed `RejectedError`, which hands them to the
fleet's existing failover requeue onto surviving replicas.

Fault sites `fleet.spawn` / `ipc.send` / `ipc.recv`
(resilience.faults) thread through spawn and both pump directions
behind the one `ACTIVE` flag, so the chaos soak can break pipes and
fail spawns deterministically.
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import subprocess
import sys
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, Optional

from quest_tpu.resilience import faults as _F
from quest_tpu.resilience.breaker import OPEN
from quest_tpu.resilience.supervisor import Supervisor
from quest_tpu.serve import metrics as M
from quest_tpu.serve.admission import AdmissionController, RejectedError

# frame header: one 8-byte big-endian unsigned length
_HDR = struct.Struct(">Q")
# a frame larger than this is a torn/poisoned header, not a payload
# (the biggest real payload is one batched state plane — far below)
_MAX_FRAME = 1 << 34
# heartbeat intervals a worker may miss before it is declared lost
_HB_MISS = 4
# seconds the proxy waits for a fresh worker's hello (interpreter +
# jax import + engine construction; generous — a slow boot is not a
# dead boot)
_BOOT_TIMEOUT_S = 120.0
# extra seconds past the caller's own timeout granted to a drain round
# trip before the proxy gives up on the reply
_RPC_SLACK_S = 5.0


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, payload: dict) -> None:
    """Serialize `payload` and write one length-prefixed frame. Raises
    OSError on a broken transport and TypeError/pickle.PicklingError on
    an unpicklable payload — both loud, never a partial frame."""
    blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(blob)) + blob)


def recv_frame(sock: socket.socket) -> dict:
    """Read one frame. Raises EOFError on a closed transport (including
    mid-frame — a torn frame is a loss, never a silent retry),
    socket.timeout on the socket's timeout, ValueError on a poisoned
    length header."""
    hdr = _recv_exact(sock, _HDR.size)
    (n,) = _HDR.unpack(hdr)
    if n > _MAX_FRAME:
        raise ValueError(
            f"ipc frame header claims {n} bytes (> {_MAX_FRAME}): torn "
            f"or poisoned stream (docs/SERVING.md §process-fleet)")
    return pickle.loads(_recv_exact(sock, n))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError(
                f"ipc peer closed mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


# ---------------------------------------------------------------------------
# circuit + key wire codecs
# ---------------------------------------------------------------------------


def circuit_digest(circuit) -> str:
    """Value key for `circuit` on the wire: sha256 over the pickled
    (num_qubits, ops) stream — equal-valued circuits share one digest,
    so the worker's rebuilt-Circuit cache (and through it the
    on-instance compiled-program cache and the content-addressed plan
    cache) dedupes across clients and across proxy respawns. Cached on
    the instance, invalidated if more ops are appended."""
    import hashlib
    cached = getattr(circuit, "_ipc_digest", None)
    if cached is not None and cached[0] == len(circuit.ops):
        return cached[1]
    blob = pickle.dumps((circuit.num_qubits, circuit.ops),
                        protocol=pickle.HIGHEST_PROTOCOL)
    dg = hashlib.sha256(blob).hexdigest()
    circuit._ipc_digest = (len(circuit.ops), dg)
    return dg


def circuit_descriptor(circuit) -> dict:
    """The full shippable form (first shipment per worker boot)."""
    return {"num_qubits": circuit.num_qubits, "ops": list(circuit.ops)}


def rebuild_circuit(desc: dict):
    """Worker-side inverse of circuit_descriptor."""
    from quest_tpu.circuit import Circuit
    c = Circuit(desc["num_qubits"])
    c.ops = list(desc["ops"])
    return c


def encode_key(key):
    """PRNG keys cross the boundary as ('typed', key_data) or ('raw',
    uint32 array) — the STYLE survives, because it is part of the
    worker-side program identity (serve/warmup.py)."""
    if key is None:
        return None
    import numpy as np
    arr = key if hasattr(key, "dtype") else np.asarray(key)
    try:
        import jax.dtypes
        typed = jax.dtypes.issubdtype(arr.dtype, jax.dtypes.prng_key)
    except (TypeError, AttributeError, ImportError):
        typed = False
    if typed:
        import jax
        return ("typed", np.asarray(jax.random.key_data(arr)))
    return ("raw", np.asarray(arr))


def decode_key(enc):
    if enc is None:
        return None
    import jax
    if enc[0] == "typed":
        return jax.random.wrap_key_data(jax.numpy.asarray(enc[1]))
    return enc[1]


class _BreakerMirror:
    """Parent-side stand-in for one OPEN worker breaker: the fleet's
    pressure model only reads `.state != CLOSED`, so mirroring the
    open COUNT from the heartbeat is exact for pricing."""

    __slots__ = ("state",)

    def __init__(self):
        self.state = OPEN


def wire_exc(exc: BaseException) -> BaseException:
    """An exception the wire can carry: the instance itself when it
    pickle-round-trips (our typed admission errors do), else a
    RejectedError naming the original — a worker error NEVER strands a
    future for want of picklability."""
    try:
        pickle.loads(pickle.dumps(exc))
        return exc
    except Exception:
        return RejectedError(
            f"Invalid operation: worker-side "
            f"{type(exc).__name__}: {exc} (unpicklable original — "
            f"docs/SERVING.md §process-fleet).")


# ---------------------------------------------------------------------------
# the proxy
# ---------------------------------------------------------------------------


class ReplicaProxy:
    """One supervised worker process behind the ServeEngine duck-type.

    Exposes exactly the surface `ServeFleet` reads off a replica —
    `submit/drain/close/reap_cancelled/plan/state/name/max_batch/
    interpret/traj_engine/_pending/_admission/_breakers/_supervisor` —
    so the fleet's routing, pressure, shed and failover logic runs
    unchanged over processes (docs/SERVING.md §process-fleet).

    Admission is enforced PROXY-side against the same `max_queue`
    bound the worker engine runs: the proxy counts every incomplete
    request (queued or dispatched), the worker counts only queued —
    the proxy bound is strictly tighter, so a submit the proxy admits
    is never queue-rejected by the worker (the fleet's `_submit_to`
    relies on SYNCHRONOUS RejectedError to try the next replica).
    """

    # what the proxy lock guards (quest-lint QL005, docs/ANALYSIS.md).
    # _wlock serializes frame WRITES only (one bounded pipe write at a
    # time) and is never taken with _lock held — the lock-order audit
    # in tests/test_lint.py pins both orders cycle-free.
    _GUARDED_BY = {
        "_lock": ("_inflight", "_payloads", "_pending", "_state",
                  "_failure_cause", "_last_hb", "_last_snapshot",
                  "_breakers", "_shipped", "_next_id", "_generation",
                  "_respawning", "_healthy_noted", "_rpc_waiters"),
        "_wlock": ("_sock",),
        # the Popen handle is owned by whichever SINGLE thread holds
        # the transport: the booting constructor, or the one loss
        # handler the _respawning flag admits at a time
        "<owner-thread>": ("_proc",),
    }

    def __init__(self, *, name: Optional[str] = None,
                 registry: Optional[M.Registry] = None,
                 heartbeat_s: Optional[float] = None,
                 restart_max: Optional[int] = None,
                 backoff_base_s: float = 0.05,
                 **engine_kw):
        from quest_tpu.env import knob_value
        if heartbeat_s is None:
            heartbeat_s = knob_value("QUEST_HEARTBEAT_S")
        if restart_max is None:
            restart_max = knob_value("QUEST_SERVE_RESTART_MAX")
        if engine_kw.get("durable_mesh") is not None:
            raise ValueError(
                "process replicas build their own mesh from their own "
                "environment; durable_mesh= is a thread-replica "
                "option (docs/SERVING.md §process-fleet)")
        engine_kw.pop("durable_mesh", None)
        self.name = name or "proc"
        self.heartbeat_s = float(heartbeat_s)
        self.registry = registry if registry is not None else M.REGISTRY
        # mirror the engine-side knob resolution so fleet routing sees
        # the same max_batch / interpret / traj_engine it would on a
        # thread replica
        max_queue = engine_kw.get("max_queue")
        if max_queue is None:
            max_queue = knob_value("QUEST_SERVE_MAX_QUEUE")
        max_batch = engine_kw.get("max_batch")
        if max_batch is None:
            max_batch = knob_value("QUEST_SERVE_MAX_BATCH")
        self.max_batch = int(max_batch)
        self.interpret = bool(engine_kw.get("interpret", False))
        self.traj_engine = engine_kw.get("traj_engine")
        self._engine_kw = dict(engine_kw)
        self._admission = AdmissionController(max_queue)
        # the PROCESS restart budget (heartbeat loss / EOF / engine
        # death), distinct from the worker-internal engine budget
        self._supervisor = Supervisor(restart_max, base_s=backoff_base_s)
        self._lock = threading.Lock()
        self._wlock = threading.Lock()
        self._inflight: Dict[int, Future] = {}
        self._payloads: Dict[int, dict] = {}    # rid -> FULL payload
        self._rpc_waiters: Dict[int, Future] = {}
        self._pending = 0
        self._next_id = 0
        self._state = "running"
        self._failure_cause: Optional[BaseException] = None
        self._shipped: set = set()      # digests this worker boot has
        self._breakers: Dict[tuple, _BreakerMirror] = {}
        self._last_snapshot: dict = {}
        self._generation = 0
        self._respawning = False
        self._healthy_noted = True      # first result after a respawn
        self._last_hb = time.monotonic()
        self._m_losses = self.registry.counter("ipc_worker_losses")
        self._m_respawns = self.registry.counter("ipc_worker_respawns")
        self._m_resubmits = self.registry.counter("ipc_resubmits")
        self._proc: Optional[subprocess.Popen] = None
        self._sock: Optional[socket.socket] = None
        self._spawn(respawn=False)
        self._start_rx(self._generation)

    # -- spawn / transport -------------------------------------------------

    def _spawn(self, respawn: bool) -> None:
        """Boot one worker process and wait for its hello. Raises on a
        failed exec or a boot that never says hello — the caller
        (constructor or loss handler) owns the budget decision."""
        if _F.ACTIVE:
            _F.check("fleet.spawn", replica=self.name, respawn=respawn)
        parent, child = socket.socketpair()
        env = os.environ.copy()
        # one interpreter per core is the scaling model: an
        # oversubscribed intra-op thread pool in every worker would
        # thrash the host the replicas are meant to share
        env.setdefault("OMP_NUM_THREADS", "1")
        env.setdefault("OPENBLAS_NUM_THREADS", "1")
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "quest_tpu.serve.worker_main",
                 "--fd", str(child.fileno())],
                pass_fds=(child.fileno(),), env=env,
                stdin=subprocess.DEVNULL)
        except OSError:
            parent.close()
            child.close()
            raise
        child.close()
        try:
            send_frame(parent, {
                "t": "init", "name": self.name,
                "heartbeat_s": self.heartbeat_s,
                "engine_kw": self._engine_kw})
            parent.settimeout(_BOOT_TIMEOUT_S)
            hello = recv_frame(parent)
            if hello.get("t") != "hello":
                raise RuntimeError(
                    f"worker {self.name} booted with {hello!r}, not "
                    f"hello")
            if hello.get("error") is not None:
                raise RuntimeError(
                    f"worker {self.name} failed to build its engine: "
                    f"{hello['error']}")
        except BaseException:
            parent.close()
            proc.kill()
            proc.wait()
            raise
        # the rx pump polls at a fraction of the heartbeat so a lost
        # worker is noticed within one interval
        parent.settimeout(max(0.05, self.heartbeat_s / 2.0))
        with self._lock:
            self._generation += 1
            self._last_hb = time.monotonic()
            self._shipped = set()
            self._healthy_noted = False
        with self._wlock:
            self._sock = parent
        self._proc = proc

    def _start_rx(self, gen: int) -> None:
        t = threading.Thread(target=self._rx_main, args=(gen,),
                             name=f"ipc-rx-{self.name}", daemon=True)
        t.start()

    def _send(self, payload: dict) -> None:
        """Write one frame to the current worker. OSError/EOF here is a
        transport loss — the caller decides whether that fails the
        request or triggers loss handling."""
        if _F.ACTIVE:
            _F.check("ipc.send", replica=self.name, type=payload["t"])
        with self._wlock:
            sock = self._sock
            if sock is None:
                raise OSError("ipc transport is down")
            # a pipe write is a bounded kernel-buffer copy; serializing
            # writers here is the framing guarantee (no interleaved
            # frames), and no code path nests another lock under it
            # (the rx pump takes it only bare, to peek at the socket)
            send_frame(sock, payload)

    def _send_submit(self, payload: dict) -> None:
        """Ship one submit payload, attaching the circuit descriptor on
        the digest's first trip to THIS worker boot (the value-keyed
        descriptor discipline — module docstring)."""
        dg = payload["digest"]
        with self._lock:
            first = dg not in self._shipped
            if first:
                self._shipped.add(dg)
        wire = dict(payload)
        if not first:
            wire["circ"] = None
        self._send(wire)

    # -- engine duck-type --------------------------------------------------

    @property
    def state(self) -> str:
        """'running' | 'failed' (process restart budget exhausted) |
        'closed' — the ServeEngine.state contract."""
        # quest-lint: disable=QL005(observability fast path: racy flag read, engine.state contract)
        return self._state

    def plan(self, circuit, *, batch: Optional[int] = None,
             density: bool = False, dtype=None):
        """ServeEngine.plan for a process replica: plans are
        content-addressed host artifacts on SHARED disk, so pricing in
        the parent and loading in the worker are the same plan
        (docs/PLANNING.md)."""
        import numpy as np

        from quest_tpu import plan as P
        return P.autotune(circuit,
                          state_kind="density" if density else "pure",
                          dtype=np.float32 if dtype is None else dtype,
                          batch=batch)

    def submit(self, circuit, state=None, shots: Optional[int] = None, *,
               key=None, deadline_s: Optional[float] = None,
               observable=None, density: bool = False,
               durable_dir: Optional[str] = None,
               durable_every: Optional[int] = None) -> Future:
        """ServeEngine.submit over the wire: admission is checked
        proxy-side (synchronous RejectedError — the fleet's retry
        contract), the payload ships as a value-keyed descriptor, and
        the returned future resolves from the worker's result frame."""
        import numpy as np
        if (state is None) == (shots is None):
            raise ValueError(
                "submit() takes exactly one of state= (apply request) "
                "or shots= (trajectory request)")
        dg = circuit_digest(circuit)
        enc_key = encode_key(key)
        np_state = None if state is None else np.asarray(state)
        payload = {
            "t": "submit", "digest": dg,
            "circ": circuit_descriptor(circuit),
            "state": np_state, "shots": shots, "key": enc_key,
            "observable": observable, "density": bool(density),
            "durable_dir": durable_dir, "durable_every": durable_every,
            "deadline_s": deadline_s,
        }
        with self._lock:
            if self._state == "closed":
                raise RejectedError(
                    "Invalid operation: submit() after close() — this "
                    "process replica is shut down (docs/SERVING.md "
                    "§process-fleet).")
            if self._state == "failed":
                raise RejectedError(
                    f"Invalid operation: process replica {self.name!r} "
                    f"is FAILED — its respawn budget is exhausted; "
                    f"last cause: {self._failure_cause!r} "
                    f"(docs/SERVING.md §process-fleet)."
                ) from self._failure_cause
            self._admission.admit(self._pending)
            rid = self._next_id
            self._next_id += 1
            payload["id"] = rid
            fut: Future = Future()
            self._inflight[rid] = fut
            self._payloads[rid] = payload
            self._pending += 1
            gen = self._generation
            respawning = self._respawning
        if respawning:
            # the loss handler owns the transport: it will resubmit
            # every payload in the ledger (ours included) once the
            # fresh worker is up
            return fut
        try:
            self._send_submit(payload)
        except (TypeError, AttributeError, pickle.PicklingError) as e:
            # AttributeError is pickle's voice for a local/lambda
            # callable ("Can't pickle local object ...")
            with self._lock:
                self._drop_locked(rid)
            raise ValueError(
                f"process replicas require picklable request payloads "
                f"(state/key/observable): {e!r} — run this workload on "
                f"thread replicas (ServeFleet(process=False)) or make "
                f"the observable a module-level callable "
                f"(docs/SERVING.md §process-fleet)") from e
        except OSError as e:
            # transport died under the submit: the request is already
            # in the ledger, so it rides the loss handler's resubmit
            self._on_worker_loss(gen, e)
        return fut

    def reap_cancelled(self) -> int:
        """Drop inflight requests whose futures were cancelled (the
        fleet's shed eviction path) and tell the worker to reap its
        side. Returns the number dropped."""
        with self._lock:
            gone = [rid for rid, f in self._inflight.items()
                    if f.cancelled()]
            for rid in gone:
                self._drop_locked(rid)
        for rid in gone:
            try:
                self._send({"t": "cancel", "id": rid})
            except OSError:
                break   # loss handling owns the transport now
        return len(gone)

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Flush the worker's queues: one drain RPC, bounded by
        `timeout_s` at the worker plus transport slack here."""
        with self._lock:
            if self._state == "closed":
                raise RejectedError(
                    "Invalid operation: drain() after close() "
                    "(docs/SERVING.md §process-fleet).")
            if self._state == "failed" or self._respawning:
                return      # futures resolve via fail/resubmit paths
            rid = self._next_id
            self._next_id += 1
            waiter: Future = Future()
            self._rpc_waiters[rid] = waiter
        try:
            self._send({"t": "drain", "id": rid, "timeout_s": timeout_s})
            wait = (None if timeout_s is None
                    else timeout_s + _RPC_SLACK_S)
            reply = waiter.result(timeout=wait)
        except OSError:
            return          # worker lost mid-drain; loss handler runs
        except (TimeoutError, _FutureTimeout):
            # on 3.10 Future.result raises concurrent.futures'
            # TimeoutError, a DIFFERENT class from the builtin (they
            # merge in 3.11) — re-raise as the builtin so callers'
            # `except TimeoutError` contracts hold
            raise TimeoutError(
                f"replica {self.name!r} drain() reply overdue "
                f"(timeout_s={timeout_s})") from None
        finally:
            with self._lock:
                self._rpc_waiters.pop(rid, None)
        if not reply.get("ok", False):
            err = reply.get("error")
            if isinstance(err, BaseException):
                raise err
            raise TimeoutError(str(err))

    def close(self, timeout_s: Optional[float] = None) -> None:
        """Graceful worker shutdown: drain-and-exit RPC, then
        terminate/kill as escalation. Idempotent."""
        with self._lock:
            if self._state == "closed":
                return
            was_failed = self._state == "failed"
            self._state = "closed"
            leftovers = list(self._inflight.values())
            self._inflight.clear()
            self._payloads.clear()
            self._pending = 0
        proc = self._proc
        if not was_failed and proc is not None:
            try:
                self._send({"t": "close", "timeout_s": timeout_s})
            except OSError:
                pass
            try:
                proc.wait(timeout=(timeout_s if timeout_s is not None
                                   else 30.0))
            except subprocess.TimeoutExpired:
                proc.terminate()
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
        elif proc is not None:
            proc.kill()
            proc.wait()
        with self._wlock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
        for f in leftovers:
            if not f.done() and f.set_running_or_notify_cancel():
                f.set_exception(RejectedError(
                    "Invalid operation: process replica closed with "
                    "the request incomplete (docs/SERVING.md "
                    "§process-fleet)."))

    # -- stats -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The worker registry's last heartbeat snapshot (counters/
        gauges/histograms) — what the fleet's aggregated scrape merges
        (docs/SERVING.md §process-fleet)."""
        with self._lock:
            return dict(self._last_snapshot)

    def worker_pid(self) -> Optional[int]:
        """The live worker's OS pid (tests SIGKILL/SIGSTOP it)."""
        proc = self._proc
        return None if proc is None else proc.pid

    # -- rx pump + supervision ---------------------------------------------

    def _rx_main(self, gen: int) -> None:
        """One pump per worker generation: results, heartbeats, RPC
        replies; detects loss (EOF, poisoned frame, heartbeat silence,
        engine-FAILED heartbeat) and hands off to the loss handler."""
        while True:
            with self._lock:
                if self._generation != gen or self._state == "closed":
                    return
                last_hb = self._last_hb
            with self._wlock:
                sock = self._sock
            if sock is None:
                return
            try:
                frame = recv_frame(sock)
            except socket.timeout:
                if (time.monotonic() - last_hb
                        > _HB_MISS * self.heartbeat_s):
                    self._on_worker_loss(gen, TimeoutError(
                        f"worker {self.name!r} missed {_HB_MISS} "
                        f"heartbeats (QUEST_HEARTBEAT_S="
                        f"{self.heartbeat_s})"))
                    return
                continue
            except (EOFError, OSError, ValueError,
                    pickle.UnpicklingError) as e:
                self._on_worker_loss(gen, e)
                return
            if _F.ACTIVE:
                try:
                    _F.check("ipc.recv", replica=self.name,
                             type=frame.get("t"))
                except BaseException as e:  # noqa: BLE001 - typed loss
                    self.registry.counter("serve_faults_injected").inc()
                    self._on_worker_loss(gen, e)
                    return
            if not self._on_frame(gen, frame):
                return

    def _on_frame(self, gen: int, frame: dict) -> bool:
        """Dispatch one worker frame; False ends this pump."""
        t = frame.get("t")
        if t == "result":
            with self._lock:
                fut = self._inflight.pop(frame["id"], None)
                self._payloads.pop(frame["id"], None)
                if fut is not None:
                    self._pending -= 1
                note_healthy = not self._healthy_noted
                self._healthy_noted = True
            if note_healthy:
                # first completed request since the (re)spawn: the
                # worker is serving, refill the crash-loop budget (the
                # engine's record_success-after-dispatch policy)
                self._supervisor.record_success()
            if fut is None or fut.done():
                return True
            if not fut.set_running_or_notify_cancel():
                return True
            if frame.get("ok"):
                fut.set_result(frame.get("value"))
            else:
                fut.set_exception(frame.get("error"))
            return True
        if t == "hb":
            with self._lock:
                self._last_hb = time.monotonic()
                self._last_snapshot = frame.get("snapshot", {})
                self._breakers = {
                    ("worker", i): _BreakerMirror()
                    for i in range(int(frame.get("open_breakers", 0)))}
            if frame.get("state") == "failed":
                # the worker's ENGINE exhausted its own budget: the
                # process is alive but serving nothing — treat as a
                # worker loss so the respawn gets a fresh engine
                self._on_worker_loss(gen, RejectedError(
                    f"worker {self.name!r} engine went FAILED "
                    f"in-process (docs/SERVING.md §process-fleet)."))
                return False
            return True
        if t == "drained":
            with self._lock:
                waiter = self._rpc_waiters.pop(frame["id"], None)
            if waiter is not None and not waiter.done():
                waiter.set_result(frame)
            return True
        return True     # unknown frame types are forward-compatible

    def _drop_locked(self, rid: int) -> None:
        if self._inflight.pop(rid, None) is not None:
            self._pending -= 1
        self._payloads.pop(rid, None)

    def _on_worker_loss(self, gen: int, cause: BaseException) -> None:
        """Kill + respawn under the Supervisor budget, resubmitting
        every incomplete request to the fresh worker (serve-once-safe
        across a dead process — module docstring); budget exhausted =>
        FAILED, incomplete futures resolve requeue-typed so the fleet
        fails them over."""
        with self._lock:
            if (self._state != "running" or self._respawning
                    or self._generation != gen):
                return
            self._respawning = True
            self._breakers = {}
            # dead worker's RPC replies are never coming; callers time
            # out on their own slack
            self._rpc_waiters.clear()
        self._m_losses.inc()
        proc = self._proc
        if proc is not None:
            proc.kill()
            proc.wait()
        with self._wlock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None
        while True:
            with self._lock:
                if self._state != "running":
                    self._respawning = False
                    return
            delay = self._supervisor.next_backoff()
            if delay is None:
                self._fail(cause)
                return
            if delay > 0:
                time.sleep(delay)
            try:
                self._spawn(respawn=True)
                break
            except BaseException as e:  # noqa: BLE001 - budget loop
                cause = e
        self._m_respawns.inc()
        with self._lock:
            if self._state != "running":
                # closed mid-respawn: close() already resolved the
                # ledger; reap the worker we just booted
                self._respawning = False
                proc, self._proc = self._proc, None
            else:
                new_gen = self._generation
                resubmit = [self._payloads[rid]
                            for rid in sorted(self._payloads)]
                # snapshot + flag-clear are ATOMIC: a submit landing
                # after this block sends itself on the new socket, one
                # landing before it is in the snapshot — no window
                # where a payload is neither
                self._respawning = False
                proc = None
        if proc is not None:
            proc.kill()
            proc.wait()
            return
        self._start_rx(new_gen)
        for payload in resubmit:
            try:
                self._send_submit(payload)
                self._m_resubmits.inc()
            except OSError as e:
                self._on_worker_loss(new_gen, e)
                return


    def _fail(self, cause: BaseException) -> None:
        with self._lock:
            if self._state != "running":
                self._respawning = False
                return
            self._state = "failed"
            self._failure_cause = cause
            self._respawning = False
            leftovers = list(self._inflight.values())
            self._inflight.clear()
            self._payloads.clear()
            self._pending = 0
        # requeue-typed (RejectedError, never DeadlineExceeded): the
        # fleet's failover contract re-serves these on survivors — safe
        # across a dead process, which delivered no result and never
        # will (module docstring)
        for f in leftovers:
            if not f.done() and f.set_running_or_notify_cancel():
                f.set_exception(RejectedError(
                    f"Invalid operation: process replica {self.name!r} "
                    f"lost its worker past the respawn budget; last "
                    f"cause: {cause!r} — the fleet requeues this "
                    f"request on a survivor (docs/SERVING.md "
                    f"§process-fleet)."))

    def __enter__(self) -> "ReplicaProxy":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
