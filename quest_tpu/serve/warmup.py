"""Cold-start control for the serving engine: pre-compile the bucket grid.

A cold `ServeEngine` pays each bucket's trace+compile on the first
request that lands in it — seconds to minutes of first-request latency
on chip (the f64-26q warmup measured ~297 s). `warmup()` walks a
declared workload's (circuit, bucket) grid up front, so the first real
request is a cache hit. It composes with the persistent compile cache
(`enable_compile_cache`, `.jax_cache`): a warmed program whose XLA
binary is already on disk re-traces in milliseconds, and the returned
per-program `compile_s` shows exactly which entries the disk cache
saved (tests/test_serve.py pins that a warmed mixed stream retraces
NOTHING — the CompileAuditor zero-retrace acceptance gate).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence


def default_buckets(max_batch: int) -> tuple:
    """The pow2 bucket grid up to `max_batch` — every bucket a mixed
    stream of <= max_batch coalesced states can resolve to under
    QUEST_BATCH_BUCKET=pow2 (env.batch_bucket)."""
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b <<= 1
    buckets.append(max_batch)
    return tuple(dict.fromkeys(buckets))


def warmup(engine, circuits, buckets: Optional[Sequence[int]] = None,
           density: bool = False, dtype=None, key=None,
           kind: Optional[str] = None) -> Dict:
    """Pre-compile every (circuit, bucket) program the engine can
    dispatch for a declared workload.

    `engine` is a ServeEngine OR a ServeFleet (docs/SERVING.md §fleet):
    compiled programs cache on the Circuit instance process-wide, so
    one warm pass covers every replica of a fleet — this function only
    reads the engine-shaped attributes (max_batch, interpret,
    traj_engine, state), which the fleet exposes identically.

    `circuits`: the Circuit objects (the SAME objects later submitted —
    compiled programs cache on the instance). `kind` declares which
    program family the workload will request: 'apply' (state= submits),
    'traj' (shots= submits — always the statevector unraveling,
    whatever `density` says: submit() rejects density trajectory
    requests), or None (default) to infer per circuit — noisy circuits
    (noise channels present) warm the trajectory program, unitary ones
    the batched apply program. The inference is only a heuristic:
    shots= submits are VALID for a unitary circuit (zero channels), so
    a workload serving one that way must pass kind='traj' or the first
    real request still cold-compiles. `buckets` defaults to the pow2
    grid up to the engine's max_batch; each entry is a declared BATCH
    SIZE (a request's shot count, a coalesced total) mapped through
    the same bucket rule the dispatch side uses — round up to the
    `env.batch_bucket` grid for apply programs, cap down to the
    largest bucket that fits (`engine.traj_dispatch_bucket`,
    run_batched's rule) for trajectory ones.
    `dtype` must match the planes the workload will submit
    (default f32): the plane dtype is part of `Circuit.program_key`
    (f64 rides the banded fallback — a DIFFERENT traced program), so
    an f64 workload warmed at f32 would still cold-compile on its
    first real request. `key` must match the PRNG key STYLE trajectory
    requests will submit (default `jax.random.key(0)`, the same default
    as submit()): a typed key and a raw uint32 `jax.random.PRNGKey` are
    different traced inputs — the style rides the engine's queue key —
    so a raw-key workload warmed with typed keys would still
    cold-compile its first real request.

    Returns {"programs": {label: compile_s}, "plans": {label: plan
    summary}, "plan_cache": counter deltas, "total_s": float} where
    label is "c{i}:b{bucket}" in grid order — per-program compile+warm
    wall seconds, so operators can see what the persistent .jax_cache
    saved (a disk hit re-traces in milliseconds). "plans" records the
    priced autotuner's verdict per apply-kind circuit (engine, total_ms,
    source — docs/PLANNING.md): with a warm plan cache every source is
    'cache' and the "plan_cache" searches delta is 0, the same
    load-not-search contract the compile cache gives the programs
    (scripts/check_plan_golden.py pins both on a warm restart)."""
    import jax
    import numpy as np

    from quest_tpu import trajectories as T
    from quest_tpu.env import batch_bucket

    if buckets is None:
        buckets = default_buckets(engine.max_batch)
    buckets = tuple(dict.fromkeys(int(b) for b in buckets))
    dtype = np.dtype(np.float32 if dtype is None else dtype)
    if key is None:
        key = jax.random.key(0)
    if kind not in (None, "apply", "traj"):
        raise ValueError(
            f"kind must be 'apply', 'traj' or None (infer per "
            f"circuit), got {kind!r}")
    state = getattr(engine, "state", "running")
    if state in ("closed", "failed"):
        # warming a dead engine would compile programs no worker will
        # ever dispatch — reject loudly like submit() does
        from quest_tpu.serve.admission import RejectedError
        raise RejectedError(
            f"Invalid operation: cannot warm a {state} ServeEngine "
            f"(docs/RESILIENCE.md)")
    from quest_tpu import plan as P

    report: Dict[str, float] = {}
    plans: Dict[str, dict] = {}
    stats0 = P.cache_stats()
    t_all = time.perf_counter()
    for i, c in enumerate(circuits):
        if kind is None:
            noisy = any(op.kind == "superop" for op in c.ops)
            c_kind = "traj" if noisy else "apply"
        else:
            c_kind = kind
        # re-price the circuit through the persistent plan cache BEFORE
        # compiling: a warm restart loads every plan from disk (zero
        # searches), a cold one prices and stores for the next start.
        # Loud-not-fatal: an unpriceable circuit (traced operands,
        # dynamic ops) still warms its programs
        if c_kind == "apply":
            try:
                pl = P.autotune(c, state_kind="density" if density
                                else "pure", dtype=dtype)
                plans[f"c{i}"] = {"engine": pl.engine,
                                  "source": pl.source,
                                  "total_ms": pl.cost.get("total_ms")}
            except Exception as e:
                import sys
                print(f"[quest_tpu.serve] warmup could not price "
                      f"circuit c{i}: {e!r}", file=sys.stderr, flush=True)
                plans[f"c{i}"] = {"engine": None, "source": "error",
                                  "total_ms": None}
        else:
            plans[f"c{i}"] = {"engine": None, "source": "unpriced:traj",
                              "total_ms": None}
        n = c.num_qubits * 2 if density else c.num_qubits
        warmed = set()
        for b in buckets:
            # map each declared batch size through the SAME bucket rule
            # the dispatch side uses: apply requests round up to the
            # batch_bucket grid, trajectory dispatch additionally caps
            # down to the largest bucket that fits (engine.
            # traj_dispatch_bucket) — warming batch_bucket(3)=4 for a
            # shots=3 workload would leave the dispatched bucket-2
            # program cold, the exact first-request stall warmup exists
            # to prevent
            if c_kind == "traj":
                from quest_tpu.serve.engine import traj_dispatch_bucket
                b = traj_dispatch_bucket(b, engine.max_batch)
            else:
                b = batch_bucket(b)
            if b in warmed:
                continue
            warmed.add(b)
            t0 = time.perf_counter()
            if c_kind == "traj":
                fn = T._compiled_traj(c, c.num_qubits, b,
                                      q_engine_name(engine, c),
                                      engine.interpret)
                # split preserves the key style, so the traced input
                # (typed key array vs raw uint32 (B, 2)) matches what
                # _dispatch_traj will feed this program
                keys = jax.random.split(key, b)
                planes, draws = fn(keys)
                jax.block_until_ready(planes)
            else:
                fn = c.compiled_batched(b, density=density, donate=False,
                                        interpret=engine.interpret)
                zeros = np.zeros((b, 2, 1 << n), dtype=dtype)
                jax.block_until_ready(fn(zeros))
            report[f"c{i}:b{b}"] = time.perf_counter() - t0
    stats1 = P.cache_stats()
    return {"programs": report,
            "plans": plans,
            "plan_cache": {k: stats1[k] - stats0[k] for k in stats1},
            "total_s": time.perf_counter() - t_all}


def q_engine_name(engine, circuit) -> str:
    """The trajectory engine name this ServeEngine would dispatch
    `circuit` with (the same resolution submit() performs)."""
    from quest_tpu import trajectories as T
    return T._resolve_engine(engine.traj_engine, circuit.num_qubits,
                             engine.interpret)
