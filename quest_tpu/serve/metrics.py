"""Serving observability: counters + latency histograms, zero hot-path deps.

This module deliberately imports NOTHING heavier than the standard
library (no jax, no numpy): the hot path of the serving engine touches
a metric on every submit/dispatch/complete, and observability must
never be the reason a request waits. The process-wide `REGISTRY` is
what `ServeEngine` records into by default and what
`enable_compile_cache`'s hit/miss listener feeds (quest_tpu/precision.py
— the stderr summary lines are DERIVED from these counters, so the
tallies are programmatically readable instead of log-scrape-only).

`snapshot()` returns one JSON-serializable dict — the schema
tests/test_serve.py pins and scripts/serve_stats.py pretty-prints:

    {"counters": {name: int, ...},
     "gauges": {name: float, ...},
     "histograms": {name: {"count": int, "mean": float,
                           "p50": float, "p95": float, "p99": float},
                    ...}}

Gauges are the settable point-in-time values the resilience layer needs
(`serve_breakers_open`: how many program breakers are open RIGHT NOW —
a counter can only ever grow, docs/RESILIENCE.md).

The durable executor (quest_tpu/resilience/durable.py) records here
too: counters `durable_steps_run`, `durable_checkpoints_saved`,
`durable_resumes`, `durable_corrupt_checkpoints_skipped`,
`durable_sentinel_trips`; gauge `durable_last_checkpoint_step`;
histogram `durable_checkpoint_s` (per-cut sentinel+gather+write cost —
the overhead numerator of `bench.py durable`) — a soak's health line
is "corrupt_skipped and sentinel_trips both zero"
(docs/RESILIENCE.md §durable).

Histograms keep a bounded reservoir (the most recent `RESERVOIR`
observations) plus exact count/sum: percentiles are over the recent
window — the figure a serving dashboard wants — while count/mean stay
exact for the whole process lifetime.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

RESERVOIR = 4096   # recent observations kept per histogram


class Counter:
    """A monotonically increasing integer metric (thread-safe)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """A settable point-in-time value (thread-safe): current breaker
    count, queue depth — anything that goes DOWN as well as up."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    """Observation stream with recent-window percentiles (thread-safe).

    count/sum are exact over the process lifetime; p50/p95/p99 are over
    the last `RESERVOIR` observations (sorted on demand at snapshot
    time, never on the record path)."""

    __slots__ = ("name", "_recent", "_count", "_sum", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._recent: deque = deque(maxlen=RESERVOIR)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, x: float) -> None:
        x = float(x)
        with self._lock:
            self._recent.append(x)
            self._count += 1
            self._sum += x

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        """Exact lifetime sum of observations (like `count`): delta
        reads over (count, sum) let a caller derive time-in-phase
        without touching slot internals — bench.py's durable overhead
        fraction reads `durable_checkpoint_s` this way."""
        return self._sum

    def summary(self) -> Dict[str, float]:
        with self._lock:
            data = sorted(self._recent)
            count, total = self._count, self._sum
        if not data:
            return {"count": count, "mean": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}

        def pct(q: float) -> float:
            return data[min(len(data) - 1,
                            max(0, int(round(q * (len(data) - 1)))))]

        return {"count": count, "mean": total / max(count, 1),
                "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99)}


class Registry:
    """A named set of counters and histograms. Metric creation is
    get-or-create by name, so call sites never coordinate; `snapshot()`
    is the one read API (stable schema, JSON-serializable)."""

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.summary()
                           for n, h in sorted(histograms.items())},
        }


# the process-wide default registry: ServeEngine records here unless
# given its own; the compile-cache listener (precision.py) always does
REGISTRY = Registry()


def snapshot(registry: Optional[Registry] = None) -> dict:
    """Snapshot of `registry` (default: the process-wide REGISTRY)."""
    return (registry or REGISTRY).snapshot()
