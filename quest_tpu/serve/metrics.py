"""Serving observability: counters + latency histograms, zero hot-path deps.

This module deliberately imports NOTHING heavier than the standard
library (no jax, no numpy): the hot path of the serving engine touches
a metric on every submit/dispatch/complete, and observability must
never be the reason a request waits. The process-wide `REGISTRY` is
what `ServeEngine` records into by default and what
`enable_compile_cache`'s hit/miss listener feeds (quest_tpu/precision.py
— the stderr summary lines are DERIVED from these counters, so the
tallies are programmatically readable instead of log-scrape-only).

`snapshot()` returns one JSON-serializable dict — the schema
tests/test_serve.py pins and scripts/serve_stats.py pretty-prints:

    {"counters": {name: int, ...},
     "gauges": {name: float, ...},
     "histograms": {name: {"count": int, "mean": float,
                           "p50": float, "p95": float, "p99": float},
                    ...}}

Gauges are the settable point-in-time values the resilience layer needs
(`serve_breakers_open`: how many program breakers are open RIGHT NOW —
a counter can only ever grow, docs/RESILIENCE.md).

The durable executor (quest_tpu/resilience/durable.py) records here
too: counters `durable_steps_run`, `durable_checkpoints_saved`,
`durable_resumes`, `durable_corrupt_checkpoints_skipped`,
`durable_sentinel_trips`; gauge `durable_last_checkpoint_step`;
histogram `durable_checkpoint_s` (per-cut sentinel+gather+write cost —
the overhead numerator of `bench.py durable`) — a soak's health line
is "corrupt_skipped and sentinel_trips both zero"
(docs/RESILIENCE.md §durable).

Histograms keep a bounded reservoir (the most recent `RESERVOIR`
observations) plus exact count/sum: percentiles are over the recent
window — the figure a serving dashboard wants — while count/mean stay
exact for the whole process lifetime.

`Registry.scrape()` renders the same metrics as Prometheus text-format
exposition (histograms as summaries), and
`python -m quest_tpu.serve.metrics --port 9464` serves it at /metrics
for a real scraper; `parse_scrape` round-trips the text back into the
snapshot schema (scripts/serve_stats.py accepts either). The fleet
layer (docs/SERVING.md §fleet) records its fleet_/tenant_/shed_ series
here too.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

RESERVOIR = 4096   # recent observations kept per histogram


class Counter:
    """A monotonically increasing integer metric (thread-safe)."""

    __slots__ = ("name", "_value", "_lock")
    _GUARDED_BY = {"_lock": ("_value",)}

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        # quest-lint: disable=QL005(single int attr load is atomic under the GIL)
        return self._value


class Gauge:
    """A settable point-in-time value (thread-safe): current breaker
    count, queue depth — anything that goes DOWN as well as up."""

    __slots__ = ("name", "_value", "_lock")
    _GUARDED_BY = {"_lock": ("_value",)}

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        # quest-lint: disable=QL005(single float attr load is atomic under the GIL)
        return self._value


class Histogram:
    """Observation stream with recent-window percentiles (thread-safe).

    count/sum are exact over the process lifetime; p50/p95/p99 are over
    the last `RESERVOIR` observations (sorted on demand at snapshot
    time, never on the record path)."""

    __slots__ = ("name", "_recent", "_count", "_sum", "_lock")
    _GUARDED_BY = {"_lock": ("_recent", "_count", "_sum")}

    def __init__(self, name: str):
        self.name = name
        self._recent: deque = deque(maxlen=RESERVOIR)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, x: float) -> None:
        x = float(x)
        with self._lock:
            self._recent.append(x)
            self._count += 1
            self._sum += x

    @property
    def count(self) -> int:
        # quest-lint: disable=QL005(single int attr load is atomic under the GIL)
        return self._count

    @property
    def sum(self) -> float:
        """Exact lifetime sum of observations (like `count`): delta
        reads over (count, sum) let a caller derive time-in-phase
        without touching slot internals — bench.py's durable overhead
        fraction reads `durable_checkpoint_s` this way."""
        # quest-lint: disable=QL005(single float attr load is atomic under the GIL)
        return self._sum

    def summary(self) -> Dict[str, float]:
        with self._lock:
            data = sorted(self._recent)
            count, total = self._count, self._sum
        if not data:
            return {"count": count, "mean": 0.0,
                    "p50": 0.0, "p95": 0.0, "p99": 0.0}

        def pct(q: float) -> float:
            return data[min(len(data) - 1,
                            max(0, int(round(q * (len(data) - 1)))))]

        return {"count": count, "mean": total / max(count, 1),
                "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99)}


class Registry:
    """A named set of counters and histograms. Metric creation is
    get-or-create by name, so call sites never coordinate; `snapshot()`
    is the one read API (stable schema, JSON-serializable)."""

    _GUARDED_BY = {"_lock": ("_counters", "_gauges", "_histograms")}

    def __init__(self):
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.summary()
                           for n, h in sorted(histograms.items())},
        }

    def scrape(self) -> str:
        """Prometheus text-format exposition (format 0.0.4) of every
        metric in this registry — what `python -m quest_tpu.serve.metrics
        --port` serves at /metrics for a real scraper. Counters and
        gauges render as themselves; histograms render as SUMMARIES
        (quantile series over the bounded recent window plus exact
        lifetime `_sum`/`_count`), because the reservoir keeps raw
        recent observations, not cumulative buckets. `parse_scrape`
        round-trips this text back into the snapshot() schema
        (scripts/serve_stats.py accepts either)."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        lines = []
        for n, c in sorted(counters.items()):
            n = _prom_name(n)
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {c.value}")
        for n, g in sorted(gauges.items()):
            n = _prom_name(n)
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {_prom_value(g.value)}")
        for n, h in sorted(histograms.items()):
            s = h.summary()
            n = _prom_name(n)
            lines.append(f"# TYPE {n} summary")
            for q, key in (("0.5", "p50"), ("0.95", "p95"),
                           ("0.99", "p99")):
                lines.append(f'{n}{{quantile="{q}"}} '
                             f"{_prom_value(s[key])}")
            lines.append(f"{n}_sum {_prom_value(h.sum)}")
            lines.append(f"{n}_count {h.count}")
        return "\n".join(lines) + "\n"


# the process-wide default registry: ServeEngine records here unless
# given its own; the compile-cache listener (precision.py) always does
REGISTRY = Registry()


def snapshot(registry: Optional[Registry] = None) -> dict:
    """Snapshot of `registry` (default: the process-wide REGISTRY)."""
    return (registry or REGISTRY).snapshot()


# ---------------------------------------------------------------------------
# Prometheus text format: name/value rendering + the scrape parser
# ---------------------------------------------------------------------------


def _prom_name(name: str) -> str:
    """A valid Prometheus metric name: [a-zA-Z_:][a-zA-Z0-9_:]*. Our
    metric names already conform; tenant-derived names sanitize any
    other byte to '_' so a hostile tenant label cannot corrupt the
    exposition."""
    out = "".join(ch if (ch.isascii() and (ch.isalnum() or ch in "_:"))
                  else "_" for ch in name)
    if not out or not (out[0].isalpha() or out[0] in "_:"):
        out = "_" + out
    return out


def _prom_value(v: float) -> str:
    """repr keeps full float precision; integers render bare (the
    format accepts both, and bare ints keep counter lines exact)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def parse_scrape(text: str) -> dict:
    """Parse Prometheus text-format exposition (as produced by
    `Registry.scrape()`) back into the `snapshot()` schema —
    scripts/serve_stats.py renders scraped input through this, so a
    dashboard dump and a live /metrics response print identically.
    Summaries map back to histograms (mean derived from _sum/_count);
    unknown or untyped series parse as gauges. Raises ValueError on a
    line that is neither a comment nor `name[{labels}] value`."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    summaries: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        # name[{labels}] value [timestamp]
        if "{" in line:
            name, rest = line.split("{", 1)
            labels, rest = rest.split("}", 1)
        else:
            name, _, rest = line.partition(" ")
            labels = ""
        fields = rest.split()
        if not name or not fields:
            raise ValueError(
                f"scrape line {lineno} is not Prometheus text format: "
                f"{line!r}")
        try:
            value = float(fields[0])
        except ValueError:
            raise ValueError(
                f"scrape line {lineno} has a non-numeric value: "
                f"{line!r}")
        name = name.strip()
        base = name
        for suffix in ("_sum", "_count"):
            if name.endswith(suffix) and types.get(name[:-len(suffix)]) \
                    in ("summary", "histogram"):
                base = name[:-len(suffix)]
        kind = types.get(base, types.get(name))
        if kind in ("summary", "histogram"):
            h = summaries.setdefault(
                base, {"count": 0, "mean": 0.0, "p50": 0.0,
                       "p95": 0.0, "p99": 0.0, "_sum": 0.0})
            if name.endswith("_sum"):
                h["_sum"] = value
            elif name.endswith("_count"):
                h["count"] = int(value)
            else:
                q = dict(part.split("=", 1) for part in labels.split(",")
                         if "=" in part).get("quantile", "").strip('"')
                key = {"0.5": "p50", "0.95": "p95", "0.99": "p99"}.get(q)
                if key:
                    h[key] = value
        elif kind == "counter":
            counters[name] = int(value)
        else:
            gauges[name] = value
    histograms = {}
    for name, h in summaries.items():
        total = h.pop("_sum")
        h["mean"] = total / h["count"] if h["count"] else 0.0
        histograms[name] = h
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}


# ---------------------------------------------------------------------------
# multi-registry aggregation (the process fleet's per-replica scrapes)
# ---------------------------------------------------------------------------


def merge_snapshots(snaps) -> dict:
    """Fold several `snapshot()` dicts into one — the process fleet's
    aggregation (docs/SERVING.md §process-fleet): each worker process
    keeps its own Registry and ships snapshots over the heartbeat;
    this merge makes one fleet-wide exposition out of them. Counters
    and gauges SUM across replicas (pending/occupancy gauges are
    additive; a single-writer gauge like fleet_pressure appears in one
    snapshot only, so the sum is the identity). Histogram summaries
    merge as: exact summed `count`, count-weighted `mean`, and the
    WORST replica's quantiles — an upper bound, which is the
    conservative direction for latency alerting (exact cross-process
    quantiles would need the raw reservoirs on the wire every beat)."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, dict] = {}
    for snap in snaps:
        if not snap:
            continue
        for n, v in snap.get("counters", {}).items():
            counters[n] = counters.get(n, 0) + v
        for n, v in snap.get("gauges", {}).items():
            gauges[n] = gauges.get(n, 0.0) + v
        for n, s in snap.get("histograms", {}).items():
            cur = hists.get(n)
            if cur is None:
                hists[n] = dict(s)
                continue
            total = cur["count"] + s["count"]
            if total:
                cur["mean"] = (cur["mean"] * cur["count"]
                               + s["mean"] * s["count"]) / total
            cur["count"] = total
            for q in ("p50", "p95", "p99"):
                cur[q] = max(cur[q], s[q])
    return {"counters": dict(sorted(counters.items())),
            "gauges": dict(sorted(gauges.items())),
            "histograms": dict(sorted(hists.items()))}


def render_snapshot(snap: dict) -> str:
    """Prometheus text exposition of a `snapshot()`-schema dict — the
    same format `Registry.scrape()` emits, so `parse_scrape`
    round-trips it and scripts/serve_stats.py prints it. Histogram
    `_sum` derives from mean*count (snapshots carry mean, not sum)."""
    lines = []
    for n, v in snap.get("counters", {}).items():
        n = _prom_name(n)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_prom_value(v)}")
    for n, v in snap.get("gauges", {}).items():
        n = _prom_name(n)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_prom_value(v)}")
    for n, s in snap.get("histograms", {}).items():
        n = _prom_name(n)
        lines.append(f"# TYPE {n} summary")
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            lines.append(f'{n}{{quantile="{q}"}} '
                         f"{_prom_value(s.get(key, 0.0))}")
        lines.append(f"{n}_sum {_prom_value(s.get('mean', 0.0) * s.get('count', 0))}")
        lines.append(f"{n}_count {int(s.get('count', 0))}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# the scrape endpoint: python -m quest_tpu.serve.metrics --port 9464
# ---------------------------------------------------------------------------


def serve_scrape(registry: Optional[Registry] = None,
                 host: str = "127.0.0.1", port: int = 0):
    """An HTTP server exposing `registry` (default: the process-wide
    REGISTRY) at /metrics in Prometheus text format. `registry` may be
    anything with a `.scrape() -> str` — a Registry, or a process-mode
    ServeFleet whose scrape aggregates its per-replica worker
    snapshots (docs/SERVING.md §process-fleet). Returns the
    ThreadingHTTPServer — callers run `serve_forever()` (the __main__
    below does) or drive it from a daemon thread and `shutdown()` when
    done (tests scrape a real GET this way). port=0 binds an ephemeral
    port, readable from `server.server_address`."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else REGISTRY

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):                      # noqa: N802 - http.server API
            if self.path.split("?")[0] not in ("/", "/metrics"):
                self.send_error(404, "only /metrics is served")
                return
            body = reg.scrape().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):          # quiet: scrapes are periodic
            pass

    return ThreadingHTTPServer((host, port), _Handler)


def _main(argv) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m quest_tpu.serve.metrics",
        description="Serve the process-wide metrics registry at "
                    "/metrics in Prometheus text format "
                    "(docs/SERVING.md §fleet).")
    ap.add_argument("--port", type=int, required=True,
                    help="TCP port to listen on (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny in-process serve workload first so "
                         "the scrape shows real series (imports jax)")
    args = ap.parse_args(argv)
    if args.demo:
        # lazy: the module itself must stay stdlib-only at import time,
        # and the demo must work from ANY install location (no
        # repo-relative script paths)
        import numpy as np

        from quest_tpu.circuit import Circuit
        from quest_tpu.serve.engine import ServeEngine
        from quest_tpu.serve.warmup import warmup

        n = 6
        c = Circuit(n)
        for q in range(n):
            c.h(q)
        c.cnot(0, 1).rz(2, 0.25)
        rng = np.random.default_rng(0)
        states = rng.standard_normal((32, 2, 1 << n)).astype(np.float32)
        states /= np.sqrt((states ** 2).sum(axis=(1, 2), keepdims=True))
        with ServeEngine(max_wait_ms=5, max_batch=8,
                         registry=REGISTRY) as eng:
            warmup(eng, [c], buckets=[8])
            for f in [eng.submit(c, state=s) for s in states]:
                f.result(timeout=300)
    srv = serve_scrape(REGISTRY, host=args.host, port=args.port)
    host, port = srv.server_address[:2]
    print(f"serving /metrics on http://{host}:{port}/metrics "
          f"(Ctrl-C to stop)", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
    return 0


if __name__ == "__main__":                     # pragma: no cover - CLI
    import sys
    raise SystemExit(_main(sys.argv[1:]))
