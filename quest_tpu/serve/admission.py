"""Admission control for the serving engine: loud overflow, deadlines,
cancellation — the robustness half of `quest_tpu.serve` (docs/SERVING.md).

Contracts (tests/test_serve.py pins each):

  * bounded queue — at most `QUEST_SERVE_MAX_QUEUE` requests may be
    pending across the engine's queues; the overflowing submit raises
    `RejectedError` IMMEDIATELY in the caller (loud backpressure, never
    a silent drop or an unbounded queue hiding an overload).
  * deadlines — a request whose relative `deadline_s` elapses while it
    is still queued fails with `DeadlineExceeded` BEFORE dispatch: an
    expired request never occupies a slot in a launch (its caller has
    already given up; spending bucket occupancy on it would tax the
    live requests). A request that was already dispatched when its
    deadline passed completes normally — launches are never aborted.
  * cancellation — `Future.cancel()` succeeds exactly while the request
    is queued (not yet dispatched); the sweep drops cancelled requests
    without charging a launch.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from quest_tpu.validation import QuESTError


class RejectedError(QuESTError):
    """The serving queue is full: the request was REJECTED at submit
    time (bounded queue depth, QUEST_SERVE_MAX_QUEUE). Callers should
    back off and resubmit; the engine never drops silently."""


class DeadlineExceeded(QuESTError):
    """The request's deadline elapsed before dispatch; it was failed
    without occupying a slot in any launch."""


class AdmissionController:
    """Queue-depth accounting and the pre-dispatch expiry/cancel sweep.

    The engine holds one controller; `admit()` runs under the engine
    lock on every submit, `sweep()` under the lock at every worker
    wake. The controller only DECIDES — completing the failed futures
    happens outside the lock (engine code), so user callbacks can never
    deadlock against submit."""

    def __init__(self, max_queue: int):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)

    def admit(self, pending: int) -> None:
        """Raise RejectedError when accepting one more request would
        exceed the bounded queue depth."""
        if pending + 1 > self.max_queue:
            raise RejectedError(
                f"Invalid operation: serve queue is full "
                f"({pending} pending >= QUEST_SERVE_MAX_QUEUE="
                f"{self.max_queue}); the request was rejected — back "
                f"off and resubmit (docs/SERVING.md).")

    @staticmethod
    def expiry_of(deadline_s: Optional[float],
                  now: Optional[float] = None) -> Optional[float]:
        """Absolute monotonic expiry for a relative deadline (None =
        no deadline). deadline_s <= 0 expires immediately — still
        through the normal sweep, so metrics count it as expired."""
        if deadline_s is None:
            return None
        if now is None:
            now = time.monotonic()
        return now + float(deadline_s)

    @staticmethod
    def sweep(requests, now: Optional[float] = None
              ) -> Tuple[List, List, List]:
        """Partition queued requests into (live, expired, cancelled).

        `requests` is any iterable of objects with `.expiry` (absolute
        monotonic or None) and `.future`. Cancelled futures are
        detected via Future.cancel()'s state; expiry wins over
        cancellation only in the sense that an expired-and-cancelled
        request counts as cancelled (the caller already walked away)."""
        if now is None:
            now = time.monotonic()
        live, expired, cancelled = [], [], []
        for r in requests:
            if r.future.cancelled():
                cancelled.append(r)
            elif r.expiry is not None and now >= r.expiry:
                expired.append(r)
            else:
                live.append(r)
        return live, expired, cancelled
