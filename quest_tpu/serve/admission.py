"""Admission control for the serving engine: loud overflow, deadlines,
cancellation — plus the fleet's tenancy policies (quota + priority
shed) — the robustness half of `quest_tpu.serve` (docs/SERVING.md).

Contracts (tests/test_serve.py and tests/test_fleet.py pin each):

  * bounded queue — at most `QUEST_SERVE_MAX_QUEUE` requests may be
    pending across the engine's queues; the overflowing submit raises
    `RejectedError` IMMEDIATELY in the caller (loud backpressure, never
    a silent drop or an unbounded queue hiding an overload).
  * deadlines — a request whose relative `deadline_s` elapses while it
    is still queued fails with `DeadlineExceeded` BEFORE dispatch: an
    expired request never occupies a slot in a launch (its caller has
    already given up; spending bucket occupancy on it would tax the
    live requests). A request that was already dispatched when its
    deadline passed completes normally — launches are never aborted.
  * cancellation — `Future.cancel()` succeeds exactly while the request
    is queued (not yet dispatched); the sweep drops cancelled requests
    without charging a launch.
  * tenant quotas — `TenantQuota` bounds each tenant's PENDING requests
    across the fleet (`QUEST_SERVE_TENANT_QUOTA`): one tenant's burst
    can never occupy the whole bounded queue and starve everyone else;
    the overflowing submit raises `TenantQuotaExceeded` naming the
    tenant and its quota.
  * priority shed — under fleet pressure the LOWEST priority class
    sheds first, with `ShedError` naming the pressure cause; a
    higher-priority submit may evict a queued lower-priority request
    (docs/SERVING.md §fleet — the strictly-before-paying-deadlines
    contract lives in serve/fleet.py, the typed errors live here).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from quest_tpu.validation import QuESTError


class RejectedError(QuESTError):
    """The serving queue is full: the request was REJECTED at submit
    time (bounded queue depth, QUEST_SERVE_MAX_QUEUE). Callers should
    back off and resubmit; the engine never drops silently."""


class DeadlineExceeded(QuESTError):
    """The request's deadline elapsed before dispatch; it was failed
    without occupying a slot in any launch."""


class DispatchTimeout(QuESTError):
    """A serve launch exceeded the dispatch watchdog's deadline
    (QUEST_DISPATCH_TIMEOUT_S): the batch's futures fail with this, the
    program's breaker records the failure, and the supervisor REPLACES
    the wedged worker thread so the engine keeps serving instead of
    drain() hanging forever (docs/RESILIENCE.md §watchdog). The launch
    outcome is unknown — like a crash at dispatch, retrying could
    double-serve, so only durable requests requeue."""


class TenantQuotaExceeded(RejectedError):
    """The submitting tenant already has its quota's worth of pending
    requests in the fleet (QUEST_SERVE_TENANT_QUOTA): the request was
    rejected so one tenant's burst cannot occupy the whole bounded
    queue. A RejectedError subclass — generic backoff handling keeps
    working; the message names the tenant and quota."""


class ShedError(RejectedError):
    """The request was LOAD-SHED: fleet pressure (queue depth + open
    breakers, docs/SERVING.md §fleet) crossed QUEST_SERVE_SHED_THRESHOLD
    and this request sat in the lowest pending priority class. The
    message names the pressure cause. A RejectedError subclass —
    shedding is a rejection, just a prioritized one."""


# the quota every tenant gets when QUEST_SERVE_TENANT_QUOTA names no
# default= entry (and the knob's registered default — env.py reads it
# from here so the two can never drift)
DEFAULT_TENANT_QUOTA = 256


def parse_tenant_quota(raw: str) -> Dict[str, int]:
    """Parse a QUEST_SERVE_TENANT_QUOTA spec (the knob's registered
    parser; raises ValueError on malformed input).

    Grammar: either one integer — the default per-tenant quota for
    every tenant — or a comma list of `tenant=quota` entries with an
    optional `default=` entry (absent: DEFAULT_TENANT_QUOTA, so a spec
    naming only specific tenants still yields a usable table):

        QUEST_SERVE_TENANT_QUOTA="64"
        QUEST_SERVE_TENANT_QUOTA="alice=16,bob=128,default=64"

    Returns {tenant_or_'default': quota}, always carrying 'default'.
    Named quotas may be 0 (that tenant is blocked outright); the
    default must be >= 1 (a fleet that admits nobody is a
    misconfiguration, not a policy)."""
    raw = raw.strip()
    out: Dict[str, int] = {}
    if "=" not in raw:
        out["default"] = _quota_int("default", raw)
        return out
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"QUEST_SERVE_TENANT_QUOTA entry {part!r} is not "
                f"tenant=quota (or a single default integer)")
        name, val = (s.strip() for s in part.split("=", 1))
        if not name:
            raise ValueError(
                f"QUEST_SERVE_TENANT_QUOTA entry {part!r} has an empty "
                f"tenant name")
        if name in out:
            raise ValueError(
                f"QUEST_SERVE_TENANT_QUOTA names tenant {name!r} twice")
        out[name] = _quota_int(name, val)
    if out.get("default", 1) < 1:
        raise ValueError(
            "QUEST_SERVE_TENANT_QUOTA default quota must be >= 1 (a "
            "fleet that admits nobody is a misconfiguration); block "
            "individual tenants with name=0 instead")
    out.setdefault("default", DEFAULT_TENANT_QUOTA)
    return out


def _quota_int(name: str, raw: str) -> int:
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"QUEST_SERVE_TENANT_QUOTA quota for {name!r} must be an "
            f"integer, got {raw!r}")
    if v < 0 or (name == "default" and v < 1):
        raise ValueError(
            f"QUEST_SERVE_TENANT_QUOTA quota for {name!r} must be "
            f">= {1 if name == 'default' else 0}, got {v}")
    return v


class TenantQuota:
    """Per-tenant pending-request bound (the fleet's admission layer).

    `table` is the parse_tenant_quota dict: named quotas win, the
    'default' entry covers everyone else. Like AdmissionController this
    class only DECIDES — the fleet holds the lock and the pending
    counts; `admit()` raises `TenantQuotaExceeded` when one more
    request would take `tenant` over its quota."""

    def __init__(self, table: Dict[str, int]):
        self.table = dict(table)
        self.table.setdefault("default", DEFAULT_TENANT_QUOTA)
        if self.table["default"] < 1:
            raise ValueError(
                f"tenant-quota default must be >= 1, got "
                f"{self.table['default']}")

    def quota_of(self, tenant: str) -> int:
        return self.table.get(tenant, self.table["default"])

    def admit(self, tenant: str, pending: int) -> None:
        quota = self.quota_of(tenant)
        if pending + 1 > quota:
            raise TenantQuotaExceeded(
                f"Invalid operation: tenant {tenant!r} already has "
                f"{pending} pending request(s) >= its quota {quota} "
                f"(QUEST_SERVE_TENANT_QUOTA); the request was rejected "
                f"so one tenant cannot occupy the whole queue — back "
                f"off and resubmit (docs/SERVING.md §fleet).")


class AdmissionController:
    """Queue-depth accounting and the pre-dispatch expiry/cancel sweep.

    The engine holds one controller; `admit()` runs under the engine
    lock on every submit, `sweep()` under the lock at every worker
    wake. The controller only DECIDES — completing the failed futures
    happens outside the lock (engine code), so user callbacks can never
    deadlock against submit."""

    def __init__(self, max_queue: int):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = int(max_queue)

    def admit(self, pending: int) -> None:
        """Raise RejectedError when accepting one more request would
        exceed the bounded queue depth."""
        if pending + 1 > self.max_queue:
            raise RejectedError(
                f"Invalid operation: serve queue is full "
                f"({pending} pending >= QUEST_SERVE_MAX_QUEUE="
                f"{self.max_queue}); the request was rejected — back "
                f"off and resubmit (docs/SERVING.md).")

    @staticmethod
    def expiry_of(deadline_s: Optional[float],
                  now: Optional[float] = None) -> Optional[float]:
        """Absolute monotonic expiry for a relative deadline (None =
        no deadline). deadline_s <= 0 expires immediately — still
        through the normal sweep, so metrics count it as expired."""
        if deadline_s is None:
            return None
        if now is None:
            now = time.monotonic()
        return now + float(deadline_s)

    @staticmethod
    def sweep(requests, now: Optional[float] = None
              ) -> Tuple[List, List, List]:
        """Partition queued requests into (live, expired, cancelled).

        `requests` is any iterable of objects with `.expiry` (absolute
        monotonic or None) and `.future`. Cancelled futures are
        detected via Future.cancel()'s state; expiry wins over
        cancellation only in the sense that an expired-and-cancelled
        request counts as cancelled (the caller already walked away)."""
        if now is None:
            now = time.monotonic()
        live, expired, cancelled = [], [], []
        for r in requests:
            if r.future.cancelled():
                cancelled.append(r)
            elif r.expiry is not None and now >= r.expiry:
                expired.append(r)
            else:
                live.append(r)
        return live, expired, cancelled
