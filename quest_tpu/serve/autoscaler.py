"""Elastic autoscaling for the process fleet: pressure in, replicas out.

PR 18's process-backed `ServeFleet` (docs/SERVING.md §process-fleet)
makes replicas cheap to add and safe to remove: `add_replica()` spawns
a supervised worker process whose warm boot is a plan-cache LOAD, and
`remove_replica()` drains the emptiest replica behind a tombstone so
no in-flight ticket dangles. This module is the control loop that
decides WHEN — the spot-native shape where capacity follows measured
load instead of a static `replicas=` guess.

The policy is deliberately boring (boring is debuggable at 3am):

  * SIGNALS — each `tick()` reads the fleet's own instruments, not
    wall-clock guesses: `stats()["pressure"]` (queued depth plus
    open-breaker backlog over healthy capacity — the same number the
    shed path keys on), the delta of the `shed_requests` counter since
    the previous tick, and the count of FAILED replicas pending
    nothing. A tick is one pure function of (signals, streak state) ->
    one of "up" / "down" / None, so tests drive the loop
    deterministically without threads or sleeps.
  * HYSTERESIS — one hot tick never scales. Pressure must sit at or
    above `high_water` (or any shedding occur) for `up_ticks`
    CONSECUTIVE ticks to grow, and at or below `low_water` for
    `down_ticks` consecutive ticks to shrink; any tick in the neutral
    band resets both streaks. Growing is eager (shed traffic is lost
    revenue), shrinking is lazy (a respawn costs a JAX runtime boot) —
    so `down_ticks` defaults higher than `up_ticks`.
  * COOLDOWN — after any scaling action the loop holds for
    `cooldown_ticks` ticks. A fresh replica takes a few beats to
    absorb backlog; without the hold, the still-high pressure from
    the pre-scale queue would trigger a second spawn for the same
    burst (the classic thrash).
  * BOUNDS — the live replica count stays inside
    [`QUEST_FLEET_MIN_REPLICAS`, `QUEST_FLEET_MAX_REPLICAS`] no matter
    what the signals say. `remove_replica`'s own refusal to drop the
    last live replica is the belt to this suspender.

`tick()` is the unit of behavior; `start()`/`stop()` merely run it on
a daemon-thread metronome for production use. Scaling actions ride the
fleet's counters (`fleet_scale_ups` / `fleet_scale_downs`) and this
module's gauges (`autoscaler_pressure`, `autoscaler_up_streak`,
`autoscaler_down_streak`) so the scrape shows why capacity moved.
"""

from __future__ import annotations

import threading
from typing import Optional


class Autoscaler:
    """The control loop over one `ServeFleet`.

    Thread-safety: `tick()` may be called from tests AND from the
    `start()` thread; `_lock` serializes whole ticks so streak state
    never interleaves. Fleet calls (`stats`, `add_replica`,
    `remove_replica`) happen inside the tick but take no Autoscaler
    state with them — the fleet has its own lock discipline.
    """

    _GUARDED_BY = {
        "_lock": ("_up_streak", "_down_streak", "_cooldown",
                  "_last_shed", "_ticks", "_actions"),
        # the metronome thread handle is touched only by the caller
        # driving start()/stop() — single-owner by contract
        "<owner-thread>": ("_thread",),
    }

    def __init__(self, fleet, *,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 high_water: float = 0.75,
                 low_water: float = 0.15,
                 up_ticks: int = 2,
                 down_ticks: int = 5,
                 cooldown_ticks: int = 3,
                 interval_s: float = 1.0) -> None:
        from quest_tpu.env import knob_value
        if min_replicas is None:
            min_replicas = knob_value("QUEST_FLEET_MIN_REPLICAS")
        if max_replicas is None:
            max_replicas = knob_value("QUEST_FLEET_MAX_REPLICAS")
        min_replicas = int(min_replicas)
        max_replicas = int(max_replicas)
        if min_replicas > max_replicas:
            raise ValueError(
                f"Invalid operation: QUEST_FLEET_MIN_REPLICAS="
                f"{min_replicas} > QUEST_FLEET_MAX_REPLICAS="
                f"{max_replicas} — the autoscaler's bounds must form "
                f"a non-empty range (docs/CONFIG.md).")
        if not (0.0 <= low_water < high_water):
            raise ValueError(
                f"Invalid operation: need 0 <= low_water < high_water, "
                f"got low_water={low_water}, high_water={high_water} — "
                f"an inverted band would scale up and down on the same "
                f"tick (docs/SERVING.md §process-fleet).")
        self.fleet = fleet
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.high_water = float(high_water)
        self.low_water = float(low_water)
        self.up_ticks = max(1, int(up_ticks))
        self.down_ticks = max(1, int(down_ticks))
        self.cooldown_ticks = max(0, int(cooldown_ticks))
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = 0
        self._last_shed = self._shed_total()
        self._ticks = 0
        self._actions: list = []    # (tick, "up"|"down") audit trail
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals -----------------------------------------------------------

    def _shed_total(self) -> int:
        snap = self.fleet.registry.snapshot()
        return int(snap["counters"].get("shed_requests", 0))

    # -- the decision ------------------------------------------------------

    def tick(self) -> Optional[str]:
        """One control-loop step: read signals, update streaks, maybe
        scale. Returns "up" / "down" when a scaling action happened
        this tick, else None — tests assert convergence by driving
        this directly."""
        with self._lock:
            return self._tick_locked()

    def _tick_locked(self) -> Optional[str]:
        self._ticks += 1
        stats = self.fleet.stats()
        pressure = float(stats["pressure"])
        live = [r for r in stats["replicas"] if not r["retired"]]
        shed_now = self._shed_total()
        shed_delta = shed_now - self._last_shed
        self._last_shed = shed_now

        reg = self.fleet.registry
        reg.gauge("autoscaler_pressure").set(pressure)

        hot = pressure >= self.high_water or shed_delta > 0
        cold = pressure <= self.low_water and shed_delta == 0
        self._up_streak = self._up_streak + 1 if hot else 0
        self._down_streak = self._down_streak + 1 if cold else 0
        reg.gauge("autoscaler_up_streak").set(self._up_streak)
        reg.gauge("autoscaler_down_streak").set(self._down_streak)

        if self._cooldown > 0:
            self._cooldown -= 1
            return None

        n = len(live)
        if (self._up_streak >= self.up_ticks and n < self.max_replicas):
            self.fleet.add_replica()
            self._after_action("up")
            return "up"
        if (self._down_streak >= self.down_ticks
                and n > self.min_replicas):
            # a short drain: the victim is the emptiest replica, so
            # this returns fast; a slow drain must not wedge the loop —
            # the fleet rolls an overdue drain back (no accepted work
            # is ever lost to a scale-down) and this tick records no
            # action, so the streak re-arms a later attempt
            try:
                self.fleet.remove_replica(timeout_s=self.interval_s)
            except TimeoutError:
                return None
            self._after_action("down")
            return "down"
        return None

    def _after_action(self, kind: str) -> None:
        self._up_streak = 0
        self._down_streak = 0
        self._cooldown = self.cooldown_ticks
        self._actions.append((self._ticks, kind))

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """The loop's observable state — what an operator (or the
        convergence gate in scripts/check_fleet_golden.py) reads."""
        with self._lock:
            return {
                "ticks": self._ticks,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "cooldown": self._cooldown,
                "actions": list(self._actions),
                "bounds": (self.min_replicas, self.max_replicas),
                "band": (self.low_water, self.high_water),
            }

    # -- the production metronome ------------------------------------------

    def start(self) -> "Autoscaler":
        """Run `tick()` every `interval_s` on a daemon thread until
        `stop()`. Idempotent; returns self so it chains off the
        constructor."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:
                    # a flapping fleet (mid-close, all-FAILED) must not
                    # kill the metronome; the next tick re-reads state
                    continue

        self._thread = threading.Thread(
            target=loop, name="quest-autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(1.0, 2 * self.interval_s))
            self._thread = None

    def __enter__(self) -> "Autoscaler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
