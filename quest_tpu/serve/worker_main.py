"""Process-replica worker entry: one interpreter, one engine, one pipe.

`python -m quest_tpu.serve.worker_main --fd N` is what a
`serve.ipc.ReplicaProxy` execs per replica (docs/SERVING.md
§process-fleet): fd N is the worker end of the proxy's socketpair. The
protocol is deliberately thin — everything hard (coalescing,
supervision of the worker THREAD, breakers, watchdog, durable resume)
is the ordinary in-process `ServeEngine` this module wraps:

  * read the `init` frame (engine kwargs, heartbeat cadence), build a
    ServeEngine over a private Registry, answer `hello` (or `hello`
    with an error string — a boot failure is loud, never a hang).
  * rx loop: `submit` frames rebuild value-keyed circuit descriptors
    (cached by digest, so the on-instance compiled-program cache and
    the shared on-disk plan/XLA caches do their job), feed the engine,
    and ship each result/error back as a `result` frame; `cancel`
    reaps; `drain` round-trips the engine's drain; `close` exits.
  * a heartbeat thread ships engine health (state, pending, open
    breakers, restart budget) plus a full registry snapshot every
    `heartbeat_s` — the proxy's liveness signal AND the fleet's
    per-replica scrape feed in one frame.

Engine-FAILED rejections of queued requests are NOT forwarded: the
heartbeat reports the failed state, the proxy kills/respawns this
process and resubmits — forwarding them would race the fleet's
failover requeue against a proxy that still says 'running'
(serve/ipc.py's loss handler owns that transition).

A parent EOF means the proxy (or its whole process) died: close the
engine briefly and exit — an orphaned worker must never outlive its
fleet. Fault plans arm through the environment (QUEST_FAULT_PLAN is
inherited), so chaos soaks reach inside worker processes with the
same grammar they use in-process.
"""

from __future__ import annotations

import argparse
import os
import socket
import threading
from typing import Optional


def main(argv: Optional[list] = None) -> int:
    ap = argparse.ArgumentParser(
        description="quest_tpu serve fleet worker process (internal: "
                    "spawned by serve.ipc.ReplicaProxy)")
    ap.add_argument("--fd", type=int, required=True,
                    help="inherited socketpair fd to the proxy")
    args = ap.parse_args(argv)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM,
                         fileno=args.fd)

    from quest_tpu.serve.ipc import (decode_key, rebuild_circuit,
                                     recv_frame, send_frame, wire_exc)
    init = recv_frame(sock)
    if init.get("t") != "init":
        return 2
    name = init.get("name", "proc")
    heartbeat_s = float(init.get("heartbeat_s", 0.5))
    wlock = threading.Lock()

    def send(payload: dict) -> None:
        with wlock:
            send_frame(sock, payload)

    try:
        from quest_tpu.serve import metrics as M
        from quest_tpu.serve.admission import (DeadlineExceeded,
                                               RejectedError)
        from quest_tpu.serve.engine import ServeEngine
        reg = M.Registry()
        eng = ServeEngine(registry=reg, name=name,
                          **init.get("engine_kw", {}))
    except BaseException as e:  # noqa: BLE001 - boot must answer
        send({"t": "hello", "pid": os.getpid(),
              "error": f"{type(e).__name__}: {e}"})
        return 1
    send({"t": "hello", "pid": os.getpid(), "error": None})

    stop = threading.Event()

    def hb_main() -> None:
        while not stop.wait(heartbeat_s):
            try:
                hb = {"t": "hb", "snapshot": reg.snapshot()}
                hb.update(eng.health())
                send(hb)
            except OSError:
                return

    threading.Thread(target=hb_main, name="ipc-hb",
                     daemon=True).start()

    circuits: dict = {}     # digest -> rebuilt Circuit (value-keyed)
    inner: dict = {}        # rid -> inner engine Future (for cancel)

    def on_done(rid: int, f) -> None:
        inner.pop(rid, None)
        if f.cancelled():
            return          # proxy-initiated reap: nothing to report
        exc = f.exception()
        try:
            if exc is None:
                import jax
                send({"t": "result", "id": rid, "ok": True,
                      "value": jax.device_get(f.result())})
                return
            if (isinstance(exc, RejectedError)
                    and not isinstance(exc, DeadlineExceeded)
                    and eng.state == "failed"):
                return      # module docstring: the proxy resubmits
            send({"t": "result", "id": rid, "ok": False,
                  "error": wire_exc(exc)})
        except OSError:
            pass            # parent gone; the rx loop will EOF out

    def on_submit(msg: dict) -> None:
        rid = msg["id"]
        circ = circuits.get(msg["digest"])
        if circ is None:
            desc = msg.get("circ")
            if desc is None:
                send({"t": "result", "id": rid, "ok": False,
                      "error": RejectedError(
                          f"Invalid operation: worker {name!r} has no "
                          f"circuit for digest {msg['digest'][:12]}… "
                          f"and the frame carries none (proxy/worker "
                          f"shipping desync — docs/SERVING.md "
                          f"§process-fleet).")})
                return
            circ = circuits[msg["digest"]] = rebuild_circuit(desc)
        try:
            fut = eng.submit(
                circ, state=msg["state"], shots=msg["shots"],
                key=decode_key(msg["key"]),
                deadline_s=msg["deadline_s"],
                observable=msg["observable"], density=msg["density"],
                durable_dir=msg["durable_dir"],
                durable_every=msg["durable_every"])
        except BaseException as e:  # noqa: BLE001 - typed reply
            send({"t": "result", "id": rid, "ok": False,
                  "error": wire_exc(e)})
            return
        inner[rid] = fut
        fut.add_done_callback(lambda f, rid=rid: on_done(rid, f))

    while True:
        try:
            msg = recv_frame(sock)
        except (EOFError, OSError):
            # the proxy died: never outlive the fleet
            stop.set()
            eng.close(timeout_s=5.0)
            return 0
        t = msg.get("t")
        if t == "submit":
            on_submit(msg)
        elif t == "cancel":
            f = inner.get(msg["id"])
            if f is not None and f.cancel():
                eng.reap_cancelled()
        elif t == "drain":
            try:
                eng.drain(timeout_s=msg.get("timeout_s"))
                send({"t": "drained", "id": msg["id"], "ok": True})
            except BaseException as e:  # noqa: BLE001 - typed reply
                send({"t": "drained", "id": msg["id"], "ok": False,
                      "error": wire_exc(e)})
        elif t == "close":
            stop.set()
            try:
                eng.close(timeout_s=msg.get("timeout_s"))
            finally:
                try:
                    send({"t": "closed"})
                except OSError:
                    pass
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
