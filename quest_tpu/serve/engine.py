"""Continuous micro-batching execution service over the batched engines.

PR 4 built bucketed batched kernels — B states riding ONE sweep-fusion
launch (`Circuit.compiled_batched`, `trajectories.run_batched`). This
module is the aggregation runtime in front of them, the shape inference
stacks (and distributed simulators: mpiQulacs arXiv:2203.16044, Q-GEAR
arXiv:2504.03967) converge on: requests from many independent clients
coalesce into full buckets so the hardware never runs a B=1 launch when
B=64 worth of work is queued.

    engine = ServeEngine()                      # knobs: QUEST_SERVE_*
    fut = engine.submit(circuit, state=planes)  # returns immediately
    out = fut.result()                          # the state after circuit

Model (docs/SERVING.md):

  * one daemon WORKER THREAD owns all tracing/dispatch; client threads
    only enqueue numpy payloads and wait on futures (jax tracing stays
    single-threaded by construction).
  * requests queue per PROGRAM IDENTITY — `Circuit.program_key()` /
    `trajectories.program_key()`: same circuit object, register kind,
    dtype and `engine_mode_key()`. Two requests are batch-compatible
    iff their keys are equal; compatible requests stacked and padded to
    the `env.batch_bucket` grid resolve to ONE compiled program per
    bucket (the PR-4 wrapper identity — a mixed stream compiles each
    bucket once, CompileAuditor-pinned in tests/test_serve.py).
  * a queue dispatches when its oldest request has waited
    `QUEST_SERVE_MAX_WAIT_MS`, when `QUEST_SERVE_MAX_BATCH` states are
    pending, or when the engine drains. max_wait_ms=0 is the documented
    NO-COALESCING mode: every request launches alone (the bench's
    baseline column).
  * admission control (serve/admission.py): bounded queue depth with
    loud `RejectedError`, per-request deadlines failing with
    `DeadlineExceeded` strictly BEFORE dispatch, cancellation of
    not-yet-dispatched futures, graceful `drain()`/`close()` flushing
    partial buckets.
  * every hop records into `serve.metrics` (queue-wait, end-to-end
    latency, batch occupancy, counters) — `metrics.snapshot()` is the
    dashboard feed, `scripts/serve_stats.py` the pretty-printer.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from quest_tpu.serve import metrics as M
from quest_tpu.serve.admission import (AdmissionController,
                                       DeadlineExceeded)


class _Request:
    __slots__ = ("future", "kind", "state", "shots", "key", "observable",
                 "expiry", "submit_t", "states")

    def __init__(self, kind, state, shots, key, observable, expiry,
                 submit_t, states):
        self.future: Future = Future()
        self.kind = kind                  # 'apply' | 'traj'
        self.state = state                # numpy planes (apply)
        self.shots = shots                # int (traj)
        self.key = key                    # jax PRNG key (traj)
        self.observable = observable
        self.expiry = expiry              # absolute monotonic or None
        self.submit_t = submit_t
        self.states = states              # slots this request occupies


def traj_dispatch_bucket(total: int, max_batch: int) -> int:
    """The bucket `_dispatch_traj` resolves for a batch of `total` shot
    slots under a `max_batch` bound: `env.batch_bucket` of the bound
    total, capped down to the largest bucket that fits (run_batched's
    chunk=None rule — don't round a partial total up to a 2x launch).
    `warmup` maps declared buckets through THIS function for trajectory
    programs so the warmed grid is exactly the dispatched grid."""
    from quest_tpu.env import batch_bucket
    total = int(total)
    bucket = batch_bucket(min(total, int(max_batch)))
    if bucket > total:
        smaller = batch_bucket(max(1, bucket // 2))
        if smaller < bucket:
            bucket = smaller
    return bucket


class _Queue:
    __slots__ = ("circuit", "kind", "density", "engine", "requests",
                 "pending_states")

    def __init__(self, circuit, kind, density, engine):
        self.circuit = circuit
        self.kind = kind
        self.density = density
        self.engine = engine              # traj engine name or None
        self.requests: Deque[_Request] = deque()
        # sum(r.states) maintained incrementally: the due check runs
        # once per popped batch under the engine lock, and a deep
        # backlog (bench saturation queues thousands of requests)
        # re-summing there turns the pop sweep O(n^2)
        self.pending_states = 0


class ServeEngine:
    """Continuous micro-batcher over `compiled_batched` /
    `trajectories._compiled_traj`. Thread-safe `submit()`; one worker
    thread coalesces, launches, and demuxes. Use as a context manager
    or call `close()` — the worker is a daemon thread, but close()
    flushes partial buckets deterministically.

    Construction keywords override the QUEST_SERVE_* knobs for THIS
    engine (the knobs are runtime-scope: read once here, never inside
    a compiled path): `max_wait_ms`, `max_queue`, `max_batch`.
    `interpret=True` runs Pallas kernels in interpreter mode (CPU
    testing); `traj_engine` pins the trajectory engine
    ('fused'|'banded'|'host', default: resolve by backend);
    `registry` redirects metrics (default: the process-wide one)."""

    def __init__(self, *, max_wait_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 interpret: bool = False,
                 traj_engine: Optional[str] = None,
                 registry: Optional[M.Registry] = None):
        from quest_tpu.env import knob_value
        if max_wait_ms is None:
            max_wait_ms = knob_value("QUEST_SERVE_MAX_WAIT_MS")
        if max_queue is None:
            max_queue = knob_value("QUEST_SERVE_MAX_QUEUE")
        if max_batch is None:
            max_batch = knob_value("QUEST_SERVE_MAX_BATCH")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_batch = int(max_batch)
        self.interpret = bool(interpret)
        self.traj_engine = traj_engine
        self.registry = registry if registry is not None else M.REGISTRY
        self._admission = AdmissionController(max_queue)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[tuple, _Queue] = {}
        self._pending = 0
        self._inflight = 0
        self._drainers = 0                # concurrent drain() calls
        self._closed = False
        self._stop = False
        self._worker = threading.Thread(target=self._run,
                                        name="quest-serve-worker",
                                        daemon=True)
        self._worker.start()

    # -- client API --------------------------------------------------------

    def submit(self, circuit, state=None, shots: Optional[int] = None, *,
               key=None, deadline_s: Optional[float] = None,
               observable: Optional[Callable] = None,
               density: bool = False) -> Future:
        """Enqueue one request; returns a `concurrent.futures.Future`.

        Exactly one of `state` / `shots`:
          * `state` — (2, 2^n) amplitude planes ((2, 4^nq) for
            `density=True`): the circuit applies through the batched
            fused engine; the future resolves to the output planes.
            With `observable=`, the callable reduces the bucket-shaped
            (B, 2, 2^n) planes ON DEVICE (same convention as
            trajectory observables) and the future resolves to this
            request's row of its output.
          * `shots` — that many stochastic trajectories of the
            circuit (`trajectories.run_batched` semantics, including
            the per-shot key chain: `key` defaults to jax.random.key(0)
            and shot i always runs split(key, shots)[i], coalesced or
            not — an uncoalesced request with shots <= max_batch runs
            the IDENTICAL program and chunk sequence as the standalone
            run_batched call; larger or coalesced batches ride a
            different bucket program, whose per-state math is pinned
            batch-size-invariant per engine in the tests). The future
            resolves to (planes, draws) — or (observable(planes),
            draws).

        `deadline_s` is relative: a request still queued when it
        elapses fails with DeadlineExceeded before any launch. Raises
        `RejectedError` when the bounded queue is full and
        RuntimeError after close()."""
        if (state is None) == (shots is None):
            raise ValueError(
                "submit() takes exactly one of state= (apply request) "
                "or shots= (trajectory request)")
        now = time.monotonic()
        if state is not None:
            kind = "apply"
            n = circuit.num_qubits * 2 if density else circuit.num_qubits
            state = np.asarray(state)
            if state.shape != (2, 1 << n):
                raise ValueError(
                    f"state must be (2, {1 << n}) amplitude planes for "
                    f"this circuit, got {state.shape}")
            qkey = circuit.program_key(density=density,
                                       interpret=self.interpret,
                                       dtype=state.dtype)
            req = _Request(kind, state, None, None, observable,
                           self._admission.expiry_of(deadline_s, now),
                           now, 1)
            engine_name = None
        else:
            from quest_tpu import trajectories as T
            if density:
                raise ValueError("trajectory requests are statevector "
                                 "unravelings; density=True is invalid")
            shots = int(shots)
            if shots < 1:
                raise ValueError(f"shots must be >= 1, got {shots}")
            kind = "traj"
            import jax
            import jax.numpy as jnp
            if key is None:
                key = jax.random.key(0)
            engine_name, qkey = T.program_key(circuit,
                                              engine=self.traj_engine,
                                              interpret=self.interpret)
            # the PRNG key STYLE rides the queue key, not the program
            # identity: a typed key (jax.random.key, impl-tagged) and a
            # raw uint32 PRNGKey are different traced inputs, and the
            # dispatch stacks every queued request's key data into one
            # array — coalescing across styles would either fail the
            # concatenate or silently re-wrap one request's key data
            # under the other's impl (different draws than its
            # standalone run_batched).
            if jnp.issubdtype(getattr(key, "dtype", np.uint32),
                              jax.dtypes.prng_key):
                style = ("typed", str(jax.random.key_impl(key)))
            else:
                raw = np.asarray(key)
                style = ("raw", raw.dtype.str, raw.shape)
            qkey = qkey + (style,)
            req = _Request(kind, None, shots, key, observable,
                           self._admission.expiry_of(deadline_s, now),
                           now, shots)

        with self._cond:
            if self._closed:
                raise RuntimeError("submit() after ServeEngine.close()")
            try:
                self._admission.admit(self._pending)
            except Exception:
                self.registry.counter("serve_requests_rejected").inc()
                raise
            q = self._queues.get(qkey)
            if q is None:
                q = self._queues[qkey] = _Queue(circuit, kind, density,
                                                engine_name)
            q.requests.append(req)
            q.pending_states += req.states
            self._pending += 1
            self._cond.notify_all()
        self.registry.counter("serve_requests_submitted").inc()
        return req.future

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Flush every queued request NOW (partial buckets included)
        and block until all launches complete. New submits arriving
        mid-drain are flushed too."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._cond:
            # a COUNT, not a bool: concurrent drains each hold the
            # flush mode open until their own predicate turns true — a
            # bool would let the first drain to finish (or time out)
            # strand another drainer's mid-drain submits in the wait
            # window
            self._drainers += 1
            self._cond.notify_all()
            try:
                while self._pending or self._inflight:
                    t = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
                    if t == 0.0:
                        raise TimeoutError(
                            f"drain() timed out with {self._pending} "
                            f"pending and {self._inflight} in-flight "
                            f"batch(es)")
                    self._cond.wait(t)
            finally:
                self._drainers -= 1

    def close(self, timeout_s: Optional[float] = None) -> None:
        """Reject new submits, drain queued work, stop the worker.
        Idempotent."""
        with self._cond:
            if self._closed and not self._worker.is_alive():
                return
            self._closed = True
        self.drain(timeout_s)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._worker.join(timeout=timeout_s)

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- worker ------------------------------------------------------------

    def _run(self) -> None:
        while True:
            batches: List[Tuple[_Queue, List[_Request]]] = []
            failed: List[Tuple[_Request, BaseException]] = []
            cancelled: List[_Request] = []
            with self._cond:
                while True:
                    if self._stop:
                        return
                    batches, failed, cancelled = self._pop_ready_locked()
                    if batches or failed or cancelled:
                        self._inflight += len(batches)
                        break
                    self._cond.wait(self._next_due_locked())
            # complete failures/cancellations OUTSIDE the lock (user
            # callbacks must not be able to deadlock against submit)
            for r in cancelled:
                self.registry.counter("serve_requests_cancelled").inc()
            for r, exc in failed:
                self.registry.counter("serve_requests_expired").inc()
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(exc)
            if failed or cancelled:
                # wake drain()/close() only AFTER the failed futures
                # are actually completed: a notify from inside the pop
                # (where _pending already reads 0) would let drain()
                # return with a future the caller sees as not-yet-done
                with self._cond:
                    self._cond.notify_all()
            for q, reqs in batches:
                try:
                    self._dispatch(q, reqs)
                except BaseException as e:   # noqa: BLE001 - demuxed
                    for r in reqs:
                        if not r.future.done():
                            r.future.set_exception(e)
                finally:
                    with self._cond:
                        self._inflight -= 1
                        self._cond.notify_all()

    def _pop_ready_locked(self):
        """Sweep expiries/cancellations, then pop every queue that is
        due (oldest request older than max_wait, max_batch states
        pending, draining/closing, or max_wait == 0). Returns
        (batches, failed, cancelled); updates pending counts."""
        now = time.monotonic()
        batches, failed, cancelled = [], [], []
        for qkey in list(self._queues):
            q = self._queues[qkey]
            live, expired, cancd = AdmissionController.sweep(q.requests,
                                                             now)
            if expired or cancd:
                q.requests = deque(live)
                q.pending_states = sum(r.states for r in live)
            self._pending -= len(expired) + len(cancd)
            cancelled.extend(cancd)
            failed.extend((r, DeadlineExceeded(
                "Invalid operation: the request's deadline "
                f"({r.expiry - r.submit_t:.3f}s) elapsed before "
                "dispatch; it was failed without occupying a launch "
                "(docs/SERVING.md).")) for r in expired)
            while q.requests:
                due = (self._drainers or self._closed
                       or self.max_wait_s == 0.0
                       or now - q.requests[0].submit_t >= self.max_wait_s
                       or q.pending_states >= self.max_batch)
                if not due:
                    break
                if self.max_wait_s == 0.0 and not (self._drainers
                                                   or self._closed):
                    # documented no-coalescing mode: one request per
                    # launch — the bench's honest baseline column
                    take = [q.requests.popleft()]
                    filled = take[0].states
                else:
                    take, filled = [], 0
                    while q.requests and (
                            not take
                            or filled + q.requests[0].states
                            <= self.max_batch):
                        r = q.requests.popleft()
                        take.append(r)
                        filled += r.states
                q.pending_states -= filled
                self._pending -= len(take)
                batches.append((q, take))
            if not q.requests:
                del self._queues[qkey]
        # no notify here even when this sweep emptied the engine: the
        # expired/cancelled futures are completed OUTSIDE the lock, so
        # waking drain() now could let it return while a future the
        # caller holds still reads not-done — _run notifies after the
        # completions (and dispatch after every batch)
        return batches, failed, cancelled

    def _next_due_locked(self) -> Optional[float]:
        """Seconds until the next queue becomes due or a deadline
        expires (None: sleep until notified)."""
        now = time.monotonic()
        due = None
        for q in self._queues.values():
            for r in q.requests:
                t = r.submit_t + self.max_wait_s - now
                if r.expiry is not None:
                    t = min(t, r.expiry - now)
                due = t if due is None else min(due, t)
        if due is None:
            return None
        return max(due, 0.0)

    # -- dispatch ----------------------------------------------------------

    def _start(self, reqs: List[_Request]) -> List[_Request]:
        """Transition futures to RUNNING; drops late cancellations."""
        started = []
        for r in reqs:
            if r.future.set_running_or_notify_cancel():
                started.append(r)
            else:
                self.registry.counter("serve_requests_cancelled").inc()
        return started

    def _record_batch(self, reqs, occupancy: float, t_pop: float) -> None:
        self.registry.counter("serve_batches_dispatched").inc()
        self.registry.histogram("serve_batch_occupancy").observe(occupancy)
        qw = self.registry.histogram("serve_queue_wait_s")
        for r in reqs:
            qw.observe(t_pop - r.submit_t)

    def _finish(self, reqs_results) -> None:
        done_t = time.monotonic()
        served = self.registry.counter("serve_requests_served")
        e2e = self.registry.histogram("serve_e2e_latency_s")
        for r, result in reqs_results:
            r.future.set_result(result)
            served.inc()
            e2e.observe(done_t - r.submit_t)

    def _dispatch(self, q: _Queue, reqs: List[_Request]) -> None:
        reqs = self._start(reqs)
        if not reqs:
            return
        if q.kind == "apply":
            self._dispatch_apply(q, reqs)
        else:
            self._dispatch_traj(q, reqs)

    def _dispatch_apply(self, q: _Queue, reqs: List[_Request]) -> None:
        import jax

        t_pop = time.monotonic()
        n = (q.circuit.num_qubits * 2 if q.density
             else q.circuit.num_qubits)
        batch = np.stack([r.state for r in reqs])
        fn = q.circuit.compiled_batched(len(reqs), density=q.density,
                                        donate=False,
                                        interpret=self.interpret)
        if len(reqs) < fn.bucket:
            # pad to the bucket HOST-SIDE: handing the wrapper a partial
            # batch would run its traced zero-pad, and that concatenate
            # is a fresh XLA compile per distinct (b, bucket) pair —
            # measured ~300 ms stalls mid-stream. numpy zeros keep the
            # one-program-per-bucket property literal: the compiled
            # program only ever sees bucket-shaped input.
            batch = np.concatenate(
                [batch, np.zeros((fn.bucket - len(reqs),) + batch.shape[1:],
                                 batch.dtype)])
        out_dev = jax.block_until_ready(fn(batch))
        # AT MOST one device->host materialization for the whole batch:
        # slicing the jax array per request would dispatch an XLA
        # gather per future (measured 0.75 ms/request — it dominated
        # the launch), and observable requests skip the full-planes
        # transfer entirely — like the trajectory path, the observable
        # reduces the CONSTANT bucket-shaped planes ON DEVICE (one
        # compiled reduction per distinct observable per launch) and
        # each request takes its row of the reduced values, so an
        # observable-only batch at 24q ships per-state scalars to the
        # host instead of bucket x 2 x 2^24 planes
        raw_needed = any(r.observable is None for r in reqs)
        out = np.asarray(out_dev) if raw_needed else None
        self._record_batch(reqs, len(reqs) / fn.bucket, t_pop)
        obs_vals: Dict[int, np.ndarray] = {}
        results = []
        for i, r in enumerate(reqs):
            if r.observable is not None:
                vals = obs_vals.get(id(r.observable))
                if vals is None:
                    planes_b = out_dev.reshape(fn.bucket, 2, 1 << n)
                    vals = np.asarray(jax.block_until_ready(
                        r.observable(planes_b)))
                    obs_vals[id(r.observable)] = vals
                results.append((r, vals[i]))
            else:
                results.append((r, out[i].reshape(2, 1 << n)))
        self._finish(results)

    def _dispatch_traj(self, q: _Queue, reqs: List[_Request]) -> None:
        import jax
        import jax.numpy as jnp
        from quest_tpu import trajectories as T

        t_pop = time.monotonic()
        n = q.circuit.num_qubits
        total = sum(r.shots for r in reqs)
        # the per-request key chains match run_batched exactly: shot i
        # of a request with key k runs jax.random.split(k, shots)[i],
        # so a coalesced request reproduces its standalone run. The
        # split stays a jax op (bit-exact parity); concatenation,
        # chunking and padding happen on the raw key DATA in numpy —
        # jnp.concatenate/broadcast_to here would be a fresh XLA
        # compile per distinct (shots..., pad) shape combination, a
        # latency stall on every new mix (same hazard as the apply
        # path's traced zero-pad).
        rows = [jax.random.split(r.key, r.shots) for r in reqs]
        if jnp.issubdtype(rows[0].dtype, jax.dtypes.prng_key):
            impl = jax.random.key_impl(rows[0])
            data = np.concatenate([np.asarray(jax.random.key_data(k))
                                   for k in rows])

            def make_keys(d):
                return jax.random.wrap_key_data(jnp.asarray(d), impl=impl)
        else:
            data = np.concatenate([np.asarray(k) for k in rows])
            make_keys = jnp.asarray
        # run_batched's chunk=None bucket rule (shared helper): beyond
        # the memory rationale, this makes an UNCOALESCED request with
        # shots <= max_batch run the IDENTICAL program + chunk sequence
        # as its standalone run_batched call — bit-identical by
        # construction there, not by cross-program luck. Bigger or
        # coalesced batches necessarily ride a different bucket program
        # (max_batch bounds the launch); their parity rests on the
        # per-state math being batch-size-invariant, pinned per engine
        # in tests/test_batched.py and tests/test_serve.py.
        bucket = traj_dispatch_bucket(total, self.max_batch)
        fn = T._compiled_traj(q.circuit, n, bucket, q.engine,
                              self.interpret)
        spans, lo = [], 0
        for r in reqs:
            spans.append((r, lo, lo + r.shots))
            lo += r.shots
        pieces = [([], []) for _ in reqs]   # (planes|values, draws) chunks
        launches = 0
        for clo in range(0, total, bucket):
            kb = data[clo:clo + bucket]
            pad = bucket - kb.shape[0]
            if pad:
                kb = np.concatenate(
                    [kb, np.broadcast_to(kb[:1], (pad,) + kb.shape[1:])])
            planes, draws = fn(make_keys(kb))
            chi = min(clo + bucket, total)
            draws_np = np.asarray(draws)
            # demux the chunk per request: observable requests reduce
            # ON DEVICE, chunk by chunk, mirroring run_batched's memory
            # contract (no chunk's full planes outlive its reduction —
            # 256 shots at 24q would otherwise materialize 32 GiB on
            # the host) — and like run_batched the observable sees the
            # CONSTANT bucket-shaped chunk, values sliced per request
            # after: reducing a per-request slice would hand XLA a
            # fresh shape per distinct span length, a fresh compile per
            # shot-count mix mid-stream (the same stall hazard as the
            # apply path's traced zero-pad). Requests WITHOUT an
            # observable need their raw planes anyway, so the chunk is
            # materialized ONCE for all of them and sliced in numpy —
            # a device slice per request would dispatch an XLA gather +
            # host transfer per future (the 0.75 ms/request cost the
            # apply path avoids the same way). Pad rows sit past every
            # request's span and are never touched.
            overlaps = []
            raw_needed = False
            for i, (r, rlo, rhi) in enumerate(spans):
                s0, s1 = max(rlo, clo) - clo, min(rhi, chi) - clo
                if s0 >= s1:
                    continue
                overlaps.append((i, r, s0, s1))
                raw_needed = raw_needed or r.observable is None
            planes_np = (np.asarray(jax.block_until_ready(planes))
                         if raw_needed else None)
            obs_vals: Dict[int, np.ndarray] = {}
            for i, r, s0, s1 in overlaps:
                if r.observable is not None:
                    vals = obs_vals.get(id(r.observable))
                    if vals is None:
                        vals = np.asarray(jax.block_until_ready(
                            r.observable(planes)))
                        obs_vals[id(r.observable)] = vals
                    seg = vals[s0:s1]
                else:
                    seg = planes_np[s0:s1]
                pieces[i][0].append(seg)
                pieces[i][1].append(draws_np[s0:s1])
            launches += 1
        self.registry.counter("serve_batches_dispatched").inc(
            launches - 1)                 # _record_batch adds the 1st
        self._record_batch(reqs, total / (launches * bucket), t_pop)
        results = []
        for (r, _, _), (pp, dd) in zip(spans, pieces):
            p = pp[0] if len(pp) == 1 else np.concatenate(pp, axis=0)
            d = dd[0] if len(dd) == 1 else np.concatenate(dd, axis=0)
            results.append((r, (p, d)))
        self._finish(results)
