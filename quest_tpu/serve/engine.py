"""Continuous micro-batching execution service over the batched engines.

PR 4 built bucketed batched kernels — B states riding ONE sweep-fusion
launch (`Circuit.compiled_batched`, `trajectories.run_batched`). This
module is the aggregation runtime in front of them, the shape inference
stacks (and distributed simulators: mpiQulacs arXiv:2203.16044, Q-GEAR
arXiv:2504.03967) converge on: requests from many independent clients
coalesce into full buckets so the hardware never runs a B=1 launch when
B=64 worth of work is queued.

    engine = ServeEngine()                      # knobs: QUEST_SERVE_*
    fut = engine.submit(circuit, state=planes)  # returns immediately
    out = fut.result()                          # the state after circuit

Model (docs/SERVING.md):

  * one daemon WORKER THREAD owns all tracing/dispatch; client threads
    only enqueue numpy payloads and wait on futures (jax tracing stays
    single-threaded by construction).
  * requests queue per PROGRAM IDENTITY — `Circuit.program_key()` /
    `trajectories.program_key()`: same circuit object, register kind,
    dtype and `engine_mode_key()`. Two requests are batch-compatible
    iff their keys are equal; compatible requests stacked and padded to
    the `env.batch_bucket` grid resolve to ONE compiled program per
    bucket (the PR-4 wrapper identity — a mixed stream compiles each
    bucket once, CompileAuditor-pinned in tests/test_serve.py).
  * a queue dispatches when its oldest request has waited
    `QUEST_SERVE_MAX_WAIT_MS`, when `QUEST_SERVE_MAX_BATCH` states are
    pending, or when the engine drains. max_wait_ms=0 is the documented
    NO-COALESCING mode: every request launches alone (the bench's
    baseline column).
  * admission control (serve/admission.py): bounded queue depth with
    loud `RejectedError`, per-request deadlines failing with
    `DeadlineExceeded` strictly BEFORE dispatch, cancellation of
    not-yet-dispatched futures, graceful `drain()`/`close()` flushing
    partial buckets.
  * every hop records into `serve.metrics` (queue-wait, end-to-end
    latency, batch occupancy, counters) — `metrics.snapshot()` is the
    dashboard feed, `scripts/serve_stats.py` the pretty-printer.

Resilience (docs/RESILIENCE.md — the reference's `validate ->
exitWithError` is untenable when one launch carries many clients):

  * SUPERVISION — the worker thread restarts on crash (exponential
    backoff + jitter, `QUEST_SERVE_RESTART_MAX` budget). Queued futures
    survive the restart untouched; popped-but-undispatched requests are
    requeued in order; requests whose launch had already started fail
    with the crash (their outcome is unknown — retrying could
    double-serve). Budget exhausted => the engine goes loudly FAILED:
    every pending future resolves with a typed RejectedError and
    submit() rejects with the cause.
  * POISONED-BATCH ISOLATION — a failing coalesced launch binary-splits
    and retries the halves (bounded depth, per-request retry cap), so
    one bad request gets its own exception while its riders still get
    results; a per-request demux error (bad observable) never touches
    batch-mates at all.
  * DEGRADATION LADDER — a per-program-key circuit breaker: after
    `QUEST_SERVE_BREAKER_THRESHOLD` consecutive primary compile
    failures the program's requests step down fused -> banded -> host
    and keep completing; after a cooldown one half-open probe restores
    the fused path.
  * FAULT INJECTION — every recovery path above is provable end-to-end
    through the named fault sites (`quest_tpu.resilience.faults`,
    `QUEST_FAULT_PLAN`) threaded through this file; all checks are
    host-side and guarded by one module flag, so an empty plan costs
    nothing and retraces nothing.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from quest_tpu.resilience import faults as _F
from quest_tpu.resilience.breaker import (CLOSED, HALF_OPEN, OPEN,
                                          Breaker)
from quest_tpu.resilience.supervisor import Supervisor
from quest_tpu.serve import metrics as M
from quest_tpu.serve.admission import (AdmissionController,
                                       DeadlineExceeded, DispatchTimeout,
                                       RejectedError)

# the full degradation ladder, most capable first (the same engine
# names bench.py's fallback ladder uses): 'fused' is whatever the
# batched compiler resolves as primary, 'banded' the forced vmapped
# banded-XLA program, 'host' the native C++ blocked kernels.
DEFAULT_LADDER = ("fused", "banded", "host")


class _Request:
    __slots__ = ("future", "kind", "state", "shots", "key", "observable",
                 "expiry", "submit_t", "states", "started", "dispatched",
                 "retries", "durable_dir", "durable_every")

    def __init__(self, kind, state, shots, key, observable, expiry,
                 submit_t, states, durable_dir=None):
        self.future: Future = Future()
        self.kind = kind                  # 'apply' | 'traj' | 'durable'
        self.state = state                # numpy planes (apply/durable)
        self.shots = shots                # int (traj)
        self.key = key                    # jax PRNG key (traj)
        self.observable = observable
        self.expiry = expiry              # absolute monotonic or None
        self.submit_t = submit_t
        self.states = states              # slots this request occupies
        self.started = False              # future transitioned RUNNING
        self.dispatched = False           # a launch containing it began
        self.retries = 0                  # failed launch attempts ridden
        self.durable_dir = durable_dir    # checkpoint chain (durable)
        self.durable_every = None         # per-job checkpoint cadence


def traj_dispatch_bucket(total: int, max_batch: int) -> int:
    """The bucket `_dispatch_traj` resolves for a batch of `total` shot
    slots under a `max_batch` bound: `env.batch_bucket` of the bound
    total, capped down to the largest bucket that fits (run_batched's
    chunk=None rule — don't round a partial total up to a 2x launch).
    `warmup` maps declared buckets through THIS function for trajectory
    programs so the warmed grid is exactly the dispatched grid."""
    from quest_tpu.env import batch_bucket
    total = int(total)
    bucket = batch_bucket(min(total, int(max_batch)))
    if bucket > total:
        smaller = batch_bucket(max(1, bucket // 2))
        if smaller < bucket:
            bucket = smaller
    return bucket


class _Queue:
    __slots__ = ("key", "circuit", "kind", "density", "engine", "requests",
                 "pending_states")

    def __init__(self, key, circuit, kind, density, engine):
        self.key = key                    # this queue's program key
        self.circuit = circuit
        self.kind = kind
        self.density = density
        self.engine = engine              # traj engine name or None
        self.requests: Deque[_Request] = deque()
        # sum(r.states) maintained incrementally: the due check runs
        # once per popped batch under the engine lock, and a deep
        # backlog (bench saturation queues thousands of requests)
        # re-summing there turns the pop sweep O(n^2)
        self.pending_states = 0


class ServeEngine:
    """Continuous micro-batcher over `compiled_batched` /
    `trajectories._compiled_traj`. Thread-safe `submit()`; one worker
    thread coalesces, launches, and demuxes. Use as a context manager
    or call `close()` — the worker is a daemon thread, but close()
    flushes partial buckets deterministically.

    Construction keywords override the QUEST_SERVE_* knobs for THIS
    engine (the knobs are runtime-scope: read once here, never inside
    a compiled path): `max_wait_ms`, `max_queue`, `max_batch`,
    `restart_max` (supervisor budget), `breaker_threshold`.
    `interpret=True` runs Pallas kernels in interpreter mode (CPU
    testing); `traj_engine` pins the trajectory engine
    ('fused'|'banded'|'host', default: resolve by backend);
    `registry` redirects metrics (default: the process-wide one);
    `backoff_base_s`/`breaker_cooldown_s` tune the recovery timings
    (tests zero/shrink them); `ladder` overrides the degradation
    ladder (docs/RESILIENCE.md); `name` labels this engine in every
    fault-site context it fires (`ctx["replica"]`) so fleet soaks can
    target one replica deterministically (docs/SERVING.md §fleet)."""

    # the one engine lock: `_cond` wraps `_lock`, so holding either
    # names the same mutex (quest-lint QL005, docs/ANALYSIS.md)
    _GUARDED_BY = {
        "_lock|_cond": ("_queues", "_pending", "_inflight", "_drainers",
                        "_closed", "_stop", "_failure_cause", "_state",
                        "_active", "_active_failed", "_worker_gen",
                        "_worker", "_watch", "_watch_seq", "_watchdog"),
        # the breaker map is worker-generation-owned: only the live
        # worker (or the watchdog superseding a provably-stuck one)
        # touches it, never two threads at once
        "<owner-thread>": ("_breakers",),
    }

    def __init__(self, *, max_wait_ms: Optional[float] = None,
                 max_queue: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 interpret: bool = False,
                 traj_engine: Optional[str] = None,
                 registry: Optional[M.Registry] = None,
                 restart_max: Optional[int] = None,
                 backoff_base_s: float = 0.05,
                 breaker_threshold: Optional[int] = None,
                 breaker_cooldown_s: float = 0.5,
                 ladder: Optional[Tuple[str, ...]] = None,
                 name: Optional[str] = None,
                 dispatch_timeout_s: Optional[float] = None,
                 durable_mesh=None,
                 durable_elastic: Optional[bool] = None):
        from quest_tpu.env import knob_value
        if max_wait_ms is None:
            max_wait_ms = knob_value("QUEST_SERVE_MAX_WAIT_MS")
        if max_queue is None:
            max_queue = knob_value("QUEST_SERVE_MAX_QUEUE")
        if max_batch is None:
            max_batch = knob_value("QUEST_SERVE_MAX_BATCH")
        if restart_max is None:
            restart_max = knob_value("QUEST_SERVE_RESTART_MAX")
        if breaker_threshold is None:
            breaker_threshold = knob_value("QUEST_SERVE_BREAKER_THRESHOLD")
        if dispatch_timeout_s is None:
            dispatch_timeout_s = knob_value("QUEST_DISPATCH_TIMEOUT_S")
        if dispatch_timeout_s < 0:
            raise ValueError(
                f"dispatch_timeout_s must be >= 0, got {dispatch_timeout_s}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if ladder is None:
            ladder = DEFAULT_LADDER
        bad = [e for e in ladder if e not in DEFAULT_LADDER]
        if bad:
            raise ValueError(f"unknown ladder engine(s) {bad}; the rungs "
                             f"are {list(DEFAULT_LADDER)}")
        self.name = name
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_batch = int(max_batch)
        self.interpret = bool(interpret)
        self.traj_engine = traj_engine
        self.registry = registry if registry is not None else M.REGISTRY
        # hot-path metric handles hoisted ONCE: _finish_one runs per
        # RIDER in the demux loop, and a registry lookup there is a
        # locked dict hit per future, contending with client-thread
        # submits (the path the per-request XLA gather was already
        # evicted from)
        self._m_served = self.registry.counter("serve_requests_served")
        self._m_e2e = self.registry.histogram("serve_e2e_latency_s")
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.ladder = tuple(ladder)
        # a split deeper than log2(max_batch) cannot shrink a batch
        # further; +1 headroom for the singleton level
        self._split_depth_cap = max(1, self.max_batch.bit_length() + 1)
        self._retry_cap = self._split_depth_cap + 1
        self._admission = AdmissionController(max_queue)
        self._supervisor = Supervisor(restart_max, base_s=backoff_base_s)
        self._breakers: Dict[tuple, Breaker] = {}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queues: Dict[tuple, _Queue] = {}
        self._pending = 0
        self._inflight = 0
        self._drainers = 0                # concurrent drain() calls
        self._closed = False
        self._stop = False
        self._failure_cause: Optional[BaseException] = None
        self._state = "running"
        # crash-recovery ledger: what the worker holds outside the
        # queues right now (popped batches + popped-expired requests),
        # so supervision can requeue/fail instead of stranding futures
        self._active: List[Tuple[_Queue, List[_Request]]] = []
        self._active_failed: List[Tuple[_Request, BaseException]] = []
        # dispatch watchdog (docs/RESILIENCE.md §watchdog): the worker
        # GENERATION counter supersedes a wedged worker — a stale
        # thread that eventually unsticks sees the bumped generation
        # and exits without touching recovered state
        self.dispatch_timeout_s = float(dispatch_timeout_s)
        self.durable_mesh = durable_mesh
        self.durable_elastic = durable_elastic
        self._worker_gen = 0
        self._watch: Dict[int, Tuple[float, int, _Queue]] = {}
        self._watch_seq = 0
        self._watchdog: Optional[threading.Thread] = None
        _F.install_from_env()             # QUEST_FAULT_PLAN soak arming
        with self._cond:
            self._spawn_worker_locked()
            if self.dispatch_timeout_s > 0:
                self._watchdog = threading.Thread(
                    target=self._watchdog_main,
                    name="quest-serve-watchdog", daemon=True)
                self._watchdog.start()

    def _spawn_worker_locked(self) -> None:
        """Start a fresh worker thread under a NEW generation (callers
        hold the lock). The previous generation — if any thread still
        runs under it — is thereby superseded: its every state mutation
        is generation-guarded."""
        self._worker_gen += 1
        self._worker = threading.Thread(
            target=self._worker_main, args=(self._worker_gen,),
            name="quest-serve-worker", daemon=True)
        self._worker.start()

    # -- client API --------------------------------------------------------

    @property
    def state(self) -> str:
        """'running' | 'failed' (restart budget exhausted) | 'closed'."""
        # quest-lint: disable=QL005(observability fast path: racy flag read, never blocks behind a dispatch)
        if self._closed:
            return "closed"
        # quest-lint: disable=QL005(same racy-read contract as _closed above)
        return self._state

    def health(self) -> dict:
        """One-call liveness summary — state, queued depth, not-CLOSED
        breaker count, restart budget left. This is what a process
        replica's heartbeat frame carries back to its proxy every
        `QUEST_HEARTBEAT_S` (serve/worker_main.py), and what the fleet
        mirrors for routing — kept as a public method so the wire
        contract does not lean on engine privates. Racy reads by
        design, same contract as `state`: a health probe must never
        queue behind a dispatch."""
        from quest_tpu.resilience.breaker import CLOSED as _closed_s
        return {
            "state": self.state,
            "pending": self._pending,  # quest-lint: disable=QL005(observability fast path: racy read, never blocks behind a dispatch)
            "open_breakers": sum(1 for br in list(self._breakers.values())
                                 if br.state != _closed_s),
            "restarts_remaining": self._supervisor.remaining,
        }

    def plan(self, circuit, *, batch: Optional[int] = None,
             density: bool = False, dtype=None):
        """The priced ProgramPlan this engine would dispatch `circuit`
        under (plan.autotune through the persistent plan cache —
        docs/PLANNING.md): pure host introspection, no compile, no
        queue. `batch`/`density`/`dtype` mirror submit's request shape
        (dtype default f32, the submit plane default)."""
        import numpy as np

        from quest_tpu import plan as P
        return P.autotune(circuit,
                          state_kind="density" if density else "pure",
                          dtype=np.float32 if dtype is None else dtype,
                          batch=batch)

    def submit(self, circuit, state=None, shots: Optional[int] = None, *,
               key=None, deadline_s: Optional[float] = None,
               observable: Optional[Callable] = None,
               density: bool = False,
               durable_dir: Optional[str] = None,
               durable_every: Optional[int] = None) -> Future:
        """Enqueue one request; returns a `concurrent.futures.Future`.

        Exactly one of `state` / `shots`:
          * `state` — (2, 2^n) amplitude planes ((2, 4^nq) for
            `density=True`): the circuit applies through the batched
            fused engine; the future resolves to the output planes.
            With `observable=`, the callable reduces the bucket-shaped
            (B, 2, 2^n) planes ON DEVICE (same convention as
            trajectory observables) and the future resolves to this
            request's row of its output. Instead of a callable, an
            `expec.PauliSum` spec (or a bare (codes, coeffs) pair)
            is accepted on BOTH request kinds and resolves to the
            grouped sweep-fused Pauli-sum reduction
            (docs/EXPECTATION.md); equal specs share one compiled
            reduction per launch.
          * `shots` — that many stochastic trajectories of the
            circuit (`trajectories.run_batched` semantics, including
            the per-shot key chain: `key` defaults to jax.random.key(0)
            and shot i always runs split(key, shots)[i], coalesced or
            not — an uncoalesced request with shots <= max_batch runs
            the IDENTICAL program and chunk sequence as the standalone
            run_batched call; larger or coalesced batches ride a
            different bucket program, whose per-state math is pinned
            batch-size-invariant per engine in the tests). The future
            resolves to (planes, draws) — or (observable(planes),
            draws).

        `durable_dir` routes a `state=` request through the durable
        executor at the worker (`resilience.durable.run_durable`,
        docs/RESILIENCE.md §durable): the circuit runs step-by-step at
        the engine's own launch boundaries, checkpointing its planes +
        cursor under `durable_dir` every QUEST_DURABLE_EVERY steps. A
        worker crash or an injected `durable.preempt` kill mid-job
        RESUMES the job from its checkpoint chain instead of failing
        the future (the resume contract makes re-dispatch safe — no
        double-serve is possible when the retry is bit-identical), and
        the future resolves to the final planes exactly like a plain
        apply request. `durable_every` overrides the job's checkpoint
        cadence (default QUEST_DURABLE_EVERY — size it to the job's
        failure rate, not its step count). Durable requests never
        coalesce with batched apply requests and are incompatible with
        `observable=` (the planes ARE the resume payload).

        `deadline_s` is relative: a request still queued when it
        elapses fails with DeadlineExceeded before any launch. Raises
        `RejectedError` when the bounded queue is full, after `close()`
        ("engine closed"), and when the engine is FAILED (the worker
        exhausted its restart budget; the error chains the cause)."""
        if (state is None) == (shots is None):
            raise ValueError(
                "submit() takes exactly one of state= (apply request) "
                "or shots= (trajectory request)")
        if durable_dir is not None:
            if state is None:
                raise ValueError(
                    "durable_dir= requires a state= request: durable "
                    "trajectory serving is not supported — call "
                    "resilience.run_durable_trajectories directly "
                    "(docs/RESILIENCE.md §durable)")
            if observable is not None:
                raise ValueError(
                    "durable_dir= is incompatible with observable=: "
                    "the full planes are the job's resume payload "
                    "(docs/RESILIENCE.md §durable)")
        elif durable_every is not None:
            raise ValueError("durable_every= requires durable_dir=")
        if observable is not None and not callable(observable):
            # a Pauli-sum spec (expec.PauliSum or a (codes, coeffs)
            # pair) resolves HERE — at admission, so a width mismatch
            # rejects the submit, not a batch-mate's demux — to the
            # cached fused batched reducer (docs/EXPECTATION.md).
            # Equal specs resolve to the SAME callable, so the demux's
            # per-identity reduction cache runs one compiled reduction
            # per launch for a batch of like observables.
            from quest_tpu.ops.expec import resolve_observable
            observable = resolve_observable(observable,
                                            circuit.num_qubits,
                                            density=density)
        now = time.monotonic()
        if state is not None:
            kind = "apply"
            n = circuit.num_qubits * 2 if density else circuit.num_qubits
            state = np.asarray(state)
            if state.shape != (2, 1 << n):
                raise ValueError(
                    f"state must be (2, {1 << n}) amplitude planes for "
                    f"this circuit, got {state.shape}")
            qkey = circuit.program_key(density=density,
                                       interpret=self.interpret,
                                       dtype=state.dtype)
            if durable_dir is not None:
                # durable jobs get their own queue family: they run one
                # at a time through run_durable, never through the
                # batched launch path, so they must not coalesce with
                # plain apply requests for the same circuit
                kind = "durable"
                qkey = qkey + ("durable",)
            req = _Request(kind, state, None, None, observable,
                           self._admission.expiry_of(deadline_s, now),
                           now, 1, durable_dir=durable_dir)
            req.durable_every = durable_every
            engine_name = None
        else:
            from quest_tpu import trajectories as T
            if density:
                raise ValueError("trajectory requests are statevector "
                                 "unravelings; density=True is invalid")
            shots = int(shots)
            if shots < 1:
                raise ValueError(f"shots must be >= 1, got {shots}")
            kind = "traj"
            import jax
            import jax.numpy as jnp
            if key is None:
                key = jax.random.key(0)
            engine_name, qkey = T.program_key(circuit,
                                              engine=self.traj_engine,
                                              interpret=self.interpret)
            # the PRNG key STYLE rides the queue key, not the program
            # identity: a typed key (jax.random.key, impl-tagged) and a
            # raw uint32 PRNGKey are different traced inputs, and the
            # dispatch stacks every queued request's key data into one
            # array — coalescing across styles would either fail the
            # concatenate or silently re-wrap one request's key data
            # under the other's impl (different draws than its
            # standalone run_batched).
            if jnp.issubdtype(getattr(key, "dtype", np.uint32),
                              jax.dtypes.prng_key):
                style = ("typed", str(jax.random.key_impl(key)))
            else:
                raw = np.asarray(key)
                style = ("raw", raw.dtype.str, raw.shape)
            qkey = qkey + (style,)
            req = _Request(kind, None, shots, key, observable,
                           self._admission.expiry_of(deadline_s, now),
                           now, shots)

        with self._cond:
            if self._closed:
                self.registry.counter("serve_requests_rejected").inc()
                raise RejectedError(
                    "Invalid operation: engine closed — submit() after "
                    "ServeEngine.close(); create a new engine "
                    "(docs/RESILIENCE.md).")
            if self._state == "failed":
                self.registry.counter("serve_requests_rejected").inc()
                raise RejectedError(
                    f"Invalid operation: ServeEngine is FAILED — its "
                    f"worker exhausted the restart budget "
                    f"(QUEST_SERVE_RESTART_MAX="
                    f"{self._supervisor.max_restarts}); last cause: "
                    f"{self._failure_cause!r}. Create a new engine "
                    f"(docs/RESILIENCE.md).") from self._failure_cause
            try:
                self._admission.admit(self._pending)
            except Exception:
                self.registry.counter("serve_requests_rejected").inc()
                raise
            q = self._queues.get(qkey)
            if q is None:
                q = self._queues[qkey] = _Queue(qkey, circuit, kind,
                                                density, engine_name)
            q.requests.append(req)
            q.pending_states += req.states
            self._pending += 1
            self._cond.notify_all()
        self.registry.counter("serve_requests_submitted").inc()
        return req.future

    def reap_cancelled(self) -> int:
        """Drop CANCELLED requests from the queues NOW, fixing the
        pending accounting (thread-safe). The worker's own sweep does
        this at its next wake; the fleet's shed eviction calls this
        synchronously so the evicted slot is reusable by the evicting
        submit — otherwise, at the hard queue bound, the victim would
        shed while the evictor still saw a full queue and was rejected
        (losing both). Cancelled futures are already resolved, so
        nothing here needs the outside-the-lock completion path; the
        cancel tally happens here so the worker's later sweep cannot
        double-count."""
        dropped = 0
        with self._cond:
            for qkey in list(self._queues):
                q = self._queues[qkey]
                live = [r for r in q.requests
                        if not r.future.cancelled()]
                n = len(q.requests) - len(live)
                if n:
                    q.requests = deque(live)
                    q.pending_states = sum(r.states for r in live)
                    self._pending -= n
                    dropped += n
                    self.registry.counter(
                        "serve_requests_cancelled").inc(n)
                if not q.requests:
                    del self._queues[qkey]
        return dropped

    def drain(self, timeout_s: Optional[float] = None) -> None:
        """Flush every queued request NOW (partial buckets included)
        and block until all launches complete. New submits arriving
        mid-drain are flushed too. After `close()` has stopped the
        worker, drain raises RejectedError deterministically (there is
        no worker left to race); on a FAILED engine it returns
        immediately (failure already resolved every future)."""
        self._drain(timeout_s, _internal=False)

    def _drain(self, timeout_s: Optional[float],
               _internal: bool) -> None:
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        with self._cond:
            if self._stop and not _internal:
                raise RejectedError(
                    "Invalid operation: engine closed — drain() after "
                    "ServeEngine.close() (docs/RESILIENCE.md).")
            # a COUNT, not a bool: concurrent drains each hold the
            # flush mode open until their own predicate turns true — a
            # bool would let the first drain to finish (or time out)
            # strand another drainer's mid-drain submits in the wait
            # window
            self._drainers += 1
            self._cond.notify_all()
            try:
                while self._pending or self._inflight:
                    if self._state == "failed":
                        # the failure transition resolved every future;
                        # nothing further can complete — returning is
                        # the deterministic flush
                        return
                    t = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
                    if t == 0.0:
                        raise TimeoutError(
                            f"drain() timed out with {self._pending} "
                            f"pending and {self._inflight} in-flight "
                            f"batch(es)")
                    self._cond.wait(t)
            finally:
                self._drainers -= 1

    def close(self, timeout_s: Optional[float] = None) -> None:
        """Reject new submits, drain queued work, stop the worker.
        Idempotent."""
        with self._cond:
            if self._closed and not self._worker.is_alive():
                return
            self._closed = True
        self._drain(timeout_s, _internal=True)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
            worker = self._worker   # snapshot: supervision may respawn
        worker.join(timeout=timeout_s)

    def __enter__(self) -> "ServeEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- resilience plumbing -----------------------------------------------

    def _fault(self, site: str, **ctx) -> None:
        """Hot-path fault hook: call sites guard with `if _F.ACTIVE:` so
        an empty plan costs one module-attribute read. A firing site is
        tallied before the error propagates into whichever recovery
        path owns that site. Every context carries `replica` (this
        engine's `name`, None standalone), so a fleet-scoped plan can
        `match` one replica's sites deterministically."""
        try:
            _F.check(site, replica=self.name, **ctx)
        except BaseException:
            self.registry.counter("serve_faults_injected").inc()
            raise

    def _breaker_for(self, q: _Queue) -> Breaker:
        br = self._breakers.get(q.key)
        if br is None:
            opens = self.registry.counter("serve_breaker_opens")
            closes = self.registry.counter("serve_breaker_closes")
            probes = self.registry.counter("serve_breaker_probes")
            gauge = self.registry.gauge("serve_breakers_open")

            def on_transition(old: str, new: str) -> None:
                if new == OPEN and old != OPEN:
                    opens.inc()
                    if old == CLOSED:
                        gauge.inc()
                elif old == OPEN and new == HALF_OPEN:
                    probes.inc()
                elif new == CLOSED:
                    closes.inc()
                    gauge.dec()

            br = self._breakers[q.key] = Breaker(
                self.breaker_threshold, self.breaker_cooldown_s,
                on_transition=on_transition)
        return br

    def _fail_request(self, r: _Request, exc: BaseException,
                      counter: Optional[str] = "serve_requests_failed"
                      ) -> None:
        """Resolve one future with a typed error, tolerating requests
        that were already started (requeued survivors) or cancelled.
        The done()-then-set pair is NOT atomic and two threads may race
        it (the dispatch watchdog failing a batch at the instant its
        superseded worker unsticks and completes the same future) — the
        loser's InvalidStateError means the future was resolved either
        way, so it is swallowed, never allowed to kill the watchdog
        before it spawns the replacement worker."""
        if r.future.done():
            return
        if not r.started:
            if not r.future.set_running_or_notify_cancel():
                self.registry.counter("serve_requests_cancelled").inc()
                return
            r.started = True
        try:
            r.future.set_exception(exc)
        except InvalidStateError:
            return
        if counter:
            self.registry.counter(counter).inc()

    def _requeue_locked(self, q: _Queue, reqs: List[_Request]) -> None:
        """Put popped-but-undispatched requests back at the FRONT of
        their queue, in order (supervised-restart recovery)."""
        live = self._queues.get(q.key)
        if live is None:
            live = self._queues[q.key] = q
            q.requests = deque()
            q.pending_states = 0
        live.requests.extendleft(reversed(reqs))
        live.pending_states += sum(r.states for r in reqs)
        self._pending += len(reqs)

    def _recover_locked(self, exc: BaseException
                        ) -> List[Tuple[_Request, BaseException]]:
        """Crash recovery under the lock: requeue every in-flight
        request that never reached dispatch (it will be retried
        bit-identically), collect the rest for typed failure outside
        the lock (their launch outcome is unknown — retrying could
        double-serve). DURABLE requests requeue even after dispatch:
        run_durable's resume contract makes the retry land on the
        checkpoint chain and finish bit-identical to an uninterrupted
        run, so re-dispatch can never double-serve (docs/RESILIENCE.md
        §durable). Resets the in-flight accounting."""
        doomed: List[Tuple[_Request, BaseException]] = []
        for q, reqs in self._active:
            retry = []
            for r in reqs:
                if r.future.done():
                    continue
                if r.dispatched and r.kind != "durable":
                    doomed.append((r, exc))
                else:
                    r.dispatched = False
                    retry.append(r)
            if retry:
                self._requeue_locked(q, retry)
        doomed.extend(self._active_failed)
        self._active = []
        self._active_failed = []
        self._inflight = 0
        return doomed

    def _evacuate_locked(self) -> List[_Request]:
        """FAILED transition: pull every queued request out so their
        futures can be resolved (typed) outside the lock — a FAILED
        engine never leaves a future hanging."""
        doomed: List[_Request] = []
        for q in self._queues.values():
            doomed.extend(q.requests)
            q.requests.clear()
            q.pending_states = 0
        self._queues.clear()
        self._pending = 0
        return doomed

    # -- worker ------------------------------------------------------------

    def _worker_main(self, my_gen: int) -> None:
        """Supervised outer loop: `_run` only returns on a clean stop;
        anything escaping it is a worker crash, restarted with backoff
        until the budget (`QUEST_SERVE_RESTART_MAX`) is exhausted —
        then the engine transitions to FAILED, resolving EVERY pending
        future with a typed error (docs/RESILIENCE.md). A thread whose
        generation was superseded (the dispatch watchdog replaced it
        while it was wedged) exits silently — the watchdog already ran
        the recovery."""
        while True:
            try:
                self._run(my_gen)
                return
            except BaseException as e:    # noqa: BLE001 - supervised
                with self._cond:
                    if my_gen != self._worker_gen:
                        return
                if not self._handle_worker_failure(e):
                    return
                with self._cond:
                    if my_gen != self._worker_gen:
                        return        # superseded during the backoff

    def _handle_worker_failure(self, e: BaseException) -> bool:
        """Worker-crash bookkeeping, shared by the in-thread supervisor
        loop and the dispatch watchdog: requeue/fail in-flight work,
        FAILED transition when the restart budget is exhausted. Returns
        True when the worker should keep running (the backoff was
        slept), False on FAILED."""
        delay = self._supervisor.next_backoff()
        with self._cond:
            doomed = self._recover_locked(e)
            evacuated = ([] if delay is not None
                         else self._evacuate_locked())
            if delay is None:
                self._failure_cause = e
                self._state = "failed"
        # futures complete OUTSIDE the lock (user callbacks
        # must not be able to deadlock against submit). Popped
        # expiries recovered here keep their normal tally —
        # only requests the crash itself doomed count as failed
        for r, exc in doomed:
            self._fail_request(
                r, exc,
                counter=("serve_requests_expired"
                         if isinstance(exc, DeadlineExceeded)
                         else "serve_requests_failed"))
        if delay is None:
            fail = RejectedError(
                f"Invalid operation: ServeEngine FAILED — its "
                f"worker crashed "
                f"{self._supervisor.total_restarts + 1} time(s) "
                f"and the restart budget is exhausted; last "
                f"cause: {e!r} (docs/RESILIENCE.md).")
            fail.__cause__ = e
            for r in evacuated:
                self._fail_request(r, fail)
        with self._cond:
            self._cond.notify_all()
        if delay is None:
            return False
        self.registry.counter("serve_worker_restarts").inc()
        if delay:
            time.sleep(delay)
        return True

    # -- dispatch watchdog (docs/RESILIENCE.md §watchdog) -------------------

    def _watch_arm(self, q: _Queue) -> Optional[int]:
        """Register the imminent dispatch with the watchdog. Durable
        jobs are exempt: they are legitimately long (the checkpoint
        cadence is their progress signal) and their own retry ladder
        already bounds failures."""
        if self.dispatch_timeout_s <= 0 or q.kind == "durable":
            return None
        with self._cond:
            self._watch_seq += 1
            token = self._watch_seq
            self._watch[token] = (
                time.monotonic() + self.dispatch_timeout_s,
                self._worker_gen, q)
            self._cond.notify_all()
        return token

    def _watch_disarm(self, token: Optional[int]) -> None:
        if token is not None:
            with self._cond:
                self._watch.pop(token, None)

    def _watchdog_main(self) -> None:
        """Monitor thread: when an armed dispatch outlives its
        deadline, the worker is WEDGED (stuck inside a launch it will
        never return from — the failure class the bounded-drain hang
        detector in the tests catches but production could not). The
        watchdog supersedes its generation, fails the batch typed
        DispatchTimeout through the normal crash recovery (durable
        requests requeue, dispatched ones fail — no double-serve),
        records a failure on the program's breaker, and spawns a
        replacement worker under the supervisor's restart budget — so
        drain() completes instead of hanging forever."""
        while True:
            with self._cond:
                if self._stop:
                    return
                now = time.monotonic()
                fire = None
                due = None
                for token, (deadline, gen, q) in self._watch.items():
                    if gen != self._worker_gen:
                        continue      # armed by an already-dead worker
                    if now >= deadline:
                        fire = (token, q)
                        break
                    t = deadline - now
                    due = t if due is None else min(due, t)
                if fire is None:
                    self._cond.wait(due if due is not None else 0.5)
                    continue
                token, q = fire
                del self._watch[token]
                # supersede the wedged worker FIRST: whenever it
                # unsticks, every one of its state mutations is
                # generation-guarded away
                self._worker_gen += 1
                new_gen = self._worker_gen
            e = DispatchTimeout(
                f"Invalid operation: serve launch exceeded the "
                f"dispatch watchdog deadline "
                f"(QUEST_DISPATCH_TIMEOUT_S={self.dispatch_timeout_s}) "
                f"— the worker was wedged and has been replaced; the "
                f"launch outcome is unknown (docs/RESILIENCE.md "
                f"§watchdog).")
            self.registry.counter("serve_dispatch_timeouts").inc()
            # the wedge counts toward the program's breaker: a program
            # that reliably wedges must step down the degradation
            # ladder, not wedge every replacement worker. Safe without
            # the worker lock discipline: the owning worker is stuck
            # inside the launch, and the replacement is not yet spawned.
            br = self._breakers.get(q.key)
            if br is not None:
                br.record_failure()
            if self._handle_worker_failure(e):
                with self._cond:
                    if new_gen == self._worker_gen and not self._stop:
                        self._spawn_worker_locked()

    def _run(self, my_gen: int) -> None:
        while True:
            if _F.ACTIVE:
                self._fault("serve.worker_loop", phase="idle")
            batches: List[Tuple[_Queue, List[_Request]]] = []
            failed: List[Tuple[_Request, BaseException]] = []
            cancelled: List[_Request] = []
            with self._cond:
                while True:
                    if self._stop or my_gen != self._worker_gen:
                        return
                    batches, failed, cancelled = self._pop_ready_locked()
                    if batches or failed or cancelled:
                        self._inflight += len(batches)
                        # ledger for crash recovery: everything the
                        # worker now holds outside the queues
                        self._active = list(batches)
                        self._active_failed = list(failed)
                        break
                    self._cond.wait(self._next_due_locked())
            if _F.ACTIVE and batches:
                self._fault("serve.worker_loop", phase="popped")
            # complete failures OUTSIDE the lock (user callbacks must
            # not be able to deadlock against submit)
            for r, exc in failed:
                self.registry.counter("serve_requests_expired").inc()
                self._fail_request(r, exc, counter=None)
            if failed or cancelled:
                # wake drain()/close() only AFTER the failed futures
                # are actually completed: a notify from inside the pop
                # (where _pending already reads 0) would let drain()
                # return with a future the caller sees as not-yet-done
                with self._cond:
                    self._active_failed = []
                    self._cond.notify_all()
            for q, reqs in batches:
                # raises ONLY for an exhausted durable resume loop
                # (deliberate escalation into the supervised restart);
                # every other failure is split/isolated/typed inside
                token = self._watch_arm(q)
                try:
                    self._dispatch(q, reqs)
                finally:
                    self._watch_disarm(token)
                with self._cond:
                    if my_gen != self._worker_gen:
                        # superseded mid-dispatch by the watchdog: the
                        # recovery already reset the in-flight ledger —
                        # touching it again would corrupt the
                        # replacement worker's accounting
                        return
                    self._inflight -= 1
                    self._active.remove((q, reqs))
                    self._cond.notify_all()
            if batches:
                with self._cond:
                    if my_gen != self._worker_gen:
                        return
                # a fully processed pop cycle is the health signal that
                # refills the restart budget (crash-LOOP bound, not a
                # lifetime quota)
                self._supervisor.record_success()

    def _pop_ready_locked(self):
        """Sweep expiries/cancellations, then pop every queue that is
        due (oldest request older than max_wait, max_batch states
        pending, draining/closing, or max_wait == 0). Returns
        (batches, failed, cancelled); updates pending counts."""
        now = time.monotonic()
        batches, failed, cancelled = [], [], []
        for qkey in list(self._queues):
            q = self._queues[qkey]
            live, expired, cancd = AdmissionController.sweep(q.requests,
                                                             now)
            if expired or cancd:
                q.requests = deque(live)
                q.pending_states = sum(r.states for r in live)
            self._pending -= len(expired) + len(cancd)
            if cancd:
                # tallied HERE (their futures are already cancelled —
                # nothing completes outside the lock), so a crash in
                # the pop-to-completion window can't lose the count
                self.registry.counter("serve_requests_cancelled").inc(
                    len(cancd))
            cancelled.extend(cancd)
            failed.extend((r, DeadlineExceeded(
                "Invalid operation: the request's deadline "
                f"({r.expiry - r.submit_t:.3f}s) elapsed before "
                "dispatch; it was failed without occupying a launch "
                "(docs/SERVING.md).")) for r in expired)
            while q.requests:
                due = (self._drainers or self._closed
                       or self.max_wait_s == 0.0
                       or now - q.requests[0].submit_t >= self.max_wait_s
                       or q.pending_states >= self.max_batch)
                if not due:
                    break
                if self.max_wait_s == 0.0 and not (self._drainers
                                                   or self._closed):
                    # documented no-coalescing mode: one request per
                    # launch — the bench's honest baseline column
                    take = [q.requests.popleft()]
                    filled = take[0].states
                else:
                    take, filled = [], 0
                    while q.requests and (
                            not take
                            or filled + q.requests[0].states
                            <= self.max_batch):
                        r = q.requests.popleft()
                        take.append(r)
                        filled += r.states
                q.pending_states -= filled
                self._pending -= len(take)
                batches.append((q, take))
            if not q.requests:
                del self._queues[qkey]
        # no notify here even when this sweep emptied the engine: the
        # expired/cancelled futures are completed OUTSIDE the lock, so
        # waking drain() now could let it return while a future the
        # caller holds still reads not-done — _run notifies after the
        # completions (and dispatch after every batch)
        return batches, failed, cancelled

    def _next_due_locked(self) -> Optional[float]:
        """Seconds until the next queue becomes due or a deadline
        expires (None: sleep until notified)."""
        now = time.monotonic()
        due = None
        for q in self._queues.values():
            for r in q.requests:
                t = r.submit_t + self.max_wait_s - now
                if r.expiry is not None:
                    t = min(t, r.expiry - now)
                due = t if due is None else min(due, t)
        if due is None:
            return None
        return max(due, 0.0)

    # -- dispatch ----------------------------------------------------------

    def _start(self, reqs: List[_Request]) -> List[_Request]:
        """Transition futures to RUNNING; drops late cancellations.
        Requests surviving a supervised restart are already RUNNING and
        pass straight through."""
        started = []
        for r in reqs:
            if r.started:
                started.append(r)
            elif r.future.set_running_or_notify_cancel():
                r.started = True
                started.append(r)
            else:
                self.registry.counter("serve_requests_cancelled").inc()
        return started

    def _record_batch(self, reqs, occupancy: float, t_pop: float) -> None:
        self.registry.counter("serve_batches_dispatched").inc()
        self.registry.histogram("serve_batch_occupancy").observe(occupancy)
        qw = self.registry.histogram("serve_queue_wait_s")
        for r in reqs:
            qw.observe(t_pop - r.submit_t)

    def _finish_one(self, r: _Request, result) -> None:
        if r.future.done():
            # a watchdog-superseded worker unsticking late: the future
            # was already failed typed DispatchTimeout — the stale
            # result is discarded (the single-engine analogue of the
            # fleet's discarded post-cancel results)
            return
        try:
            r.future.set_result(result)
        except InvalidStateError:
            # lost the done()-then-set race against the watchdog's
            # typed failure — same discard as the done() early-out
            return
        self._m_served.inc()
        self._m_e2e.observe(time.monotonic() - r.submit_t)

    def _dispatch(self, q: _Queue, reqs: List[_Request]) -> None:
        reqs = self._start(reqs)
        if not reqs:
            return
        if q.kind == "durable":
            # durable jobs bypass the splitter: each runs alone through
            # run_durable with its own bounded resume-retry loop, and an
            # exhausted loop RAISES (the one dispatch path that does) to
            # escalate into the supervised-restart machinery — the
            # request stays in the _active ledger and requeues
            self._dispatch_durable(q, reqs)
            return
        self._dispatch_split(q, reqs, depth=0)

    # in-place resume attempts per durable dispatch before the failure
    # escalates to a worker crash (supervised restart -> FAILED ->
    # fleet failover, docs/SERVING.md §fleet); each attempt re-enters
    # run_durable, which resumes from the newest checkpoint
    DURABLE_RETRY_CAP = 3

    def _dispatch_durable(self, q: _Queue, reqs: List[_Request]) -> None:
        """Run each durable request through the durable executor
        (docs/RESILIENCE.md §durable). Failure ladder, cheapest first:

          * typed job errors (DurableError / IntegrityError /
            CheckpointError / OSError / ValueError / TypeError) fail
            ONLY that request's future — retrying a cursor mismatch, a
            tripped sentinel, or an unwritable durable_dir would fail
            identically, and escalating one would crash-loop EVERY
            replica in turn (one tenant's bad path must never become a
            fleet-wide outage; a genuinely transient IO blip is served
            by resubmitting — the chain resumes);
          * anything else (an injected `durable.preempt` kill, a device
            fault) retries IN PLACE up to DURABLE_RETRY_CAP attempts —
            run_durable resumes from the checkpoint chain, so a retry
            is a resume, not a re-run;
          * an exhausted retry loop RAISES, escalating to the
            supervised-restart path: the request requeues (durable
            requests are resume-safe after dispatch, _recover_locked)
            and, once this replica's restart budget is gone, fails over
            to a fleet survivor that resumes the same chain.
        """
        import jax
        import jax.numpy as jnp

        from quest_tpu.checkpoint import CheckpointError
        from quest_tpu.resilience.durable import (DurableError,
                                                  IntegrityError,
                                                  run_durable)
        from quest_tpu.state import Qureg

        t_pop = time.monotonic()
        for r in reqs:
            if r.future.done():
                continue
            r.dispatched = True
            attempts = 0
            while True:
                try:
                    if _F.ACTIVE:
                        self._fault("serve.dispatch", reqs=[r],
                                    durable=True)
                    reg = Qureg(amps=jnp.asarray(r.state),
                                num_qubits=q.circuit.num_qubits,
                                is_density=q.density)
                    # durable_mesh runs the job sharded over this
                    # replica's mesh; durable_elastic lets it RESUME a
                    # chain another (differently-sized) replica left
                    # behind — the fleet failover story for
                    # heterogeneous survivors (docs/RESILIENCE.md
                    # §elastic)
                    out = run_durable(q.circuit, reg, r.durable_dir,
                                      every=r.durable_every,
                                      mesh=self.durable_mesh,
                                      elastic=self.durable_elastic,
                                      interpret=self.interpret,
                                      registry=self.registry)
                    self._record_batch([r], 1.0, t_pop)
                    self.registry.counter("serve_durable_jobs").inc()
                    self._finish_one(r, np.asarray(
                        jax.device_get(out.amps)))
                    break
                except BaseException as e:  # noqa: BLE001 - laddered
                    self.registry.counter("serve_launch_failures").inc()
                    if isinstance(e, (DurableError, IntegrityError,
                                      CheckpointError, OSError,
                                      ValueError, TypeError)):
                        self._fail_request(r, e)
                        break
                    attempts += 1
                    if attempts >= self.DURABLE_RETRY_CAP:
                        raise
                    self.registry.counter(
                        "serve_durable_inplace_resumes").inc()

    def _dispatch_split(self, q: _Queue, reqs: List[_Request],
                        depth: int) -> None:
        """Poisoned-batch isolation (docs/RESILIENCE.md): a failing
        coalesced launch binary-splits and retries the halves, so one
        bad request ends up alone with its own typed exception while
        its riders still get results. Bounded: split depth is capped
        (log2(max_batch)+1 levels) and each request rides at most
        `_retry_cap` failed attempts; a single poisoned rider among B
        wastes at most ceil(log2(B))+1 failing launches (the node path
        containing it) and its riders re-land in ceil(log2(B))
        successful ones."""
        try:
            if q.kind == "apply":
                self._dispatch_apply(q, reqs)
            else:
                self._dispatch_traj(q, reqs)
            return
        except BaseException as e:        # noqa: BLE001 - isolated below
            self.registry.counter("serve_launch_failures").inc()
            err = e
        survivors = [r for r in reqs if not r.future.done()]
        if not survivors:
            return
        if len(survivors) == 1 or depth + 1 >= self._split_depth_cap:
            for r in survivors:
                self._fail_request(r, err)
            return
        retryable = []
        for r in survivors:
            r.retries += 1
            if r.retries >= self._retry_cap:
                self._fail_request(r, err)
            else:
                retryable.append(r)
        if not retryable:
            return
        self.registry.counter("serve_batches_split").inc()
        mid = (len(retryable) + 1) // 2
        self._dispatch_split(q, retryable[:mid], depth + 1)
        if retryable[mid:]:
            self._dispatch_split(q, retryable[mid:], depth + 1)

    # -- program resolution: breaker + degradation ladder ------------------

    def _degraded_rungs(self, primary: str) -> Tuple[str, ...]:
        """Ladder rungs below `primary` in preference order."""
        try:
            i = self.ladder.index(primary)
        except ValueError:
            i = 0
        return self.ladder[i + 1:]

    def _apply_program(self, q: _Queue, b: int, rung: str):
        """One ladder rung's batched apply program (callable with a
        `.bucket`), uniform across rungs so the dispatch below stays
        rung-agnostic."""
        if rung == "fused":
            return q.circuit.compiled_batched(b, density=q.density,
                                              donate=False,
                                              interpret=self.interpret)
        if rung == "banded":
            return q.circuit.compiled_batched(b, density=q.density,
                                              donate=False,
                                              interpret=self.interpret,
                                              engine="banded")
        # host: the native C++ blocked kernels, one state at a time —
        # the floor of the ladder (no jax in the loop at all, so it
        # stays serviceable when the XLA client itself is wedged)
        from quest_tpu import host as H
        n = (q.circuit.num_qubits * 2 if q.density
             else q.circuit.num_qubits)
        step = H.compile_circuit_host(tuple(q.circuit.ops), n, q.density)

        def run(batch_np):
            out = np.array(batch_np)
            for i in range(out.shape[0]):
                step(out[i])
            return out

        run.bucket = b
        return run

    def _traj_program(self, q: _Queue, n: int, bucket: int, rung: str):
        from quest_tpu import trajectories as T
        engine = q.engine if rung == "fused" else rung
        return T._compiled_traj(q.circuit, n, bucket, engine,
                                self.interpret)

    def _resolve_program(self, q: _Queue, compile_primary,
                         compile_rung) -> tuple:
        """Breaker-guarded program resolution: try the primary engine
        when this program's breaker allows it (a breaker coming off
        cooldown makes this call the half-open probe); on compile
        failure — or an open breaker — walk the degradation ladder.
        Returns (fn, primary_used, breaker)."""
        br = self._breaker_for(q)
        primary_err: Optional[BaseException] = None
        if br.allow_primary():
            try:
                if _F.ACTIVE:
                    self._fault("serve.compile", program=q.key)
                return compile_primary(), True, br
            except BaseException as e:   # noqa: BLE001 - ladder below
                br.record_failure()
                primary_err = e
        primary = q.engine if q.kind == "traj" else "fused"
        for rung in self._degraded_rungs(primary or "fused"):
            try:
                fn = compile_rung(rung)
            except BaseException as e:   # noqa: BLE001 - next rung
                primary_err = primary_err or e
                continue
            self.registry.counter("serve_degraded_dispatches").inc()
            return fn, False, br
        raise primary_err if primary_err is not None else RuntimeError(
            "no dispatchable engine rung")

    def _dispatch_apply(self, q: _Queue, reqs: List[_Request]) -> None:
        import jax

        t_pop = time.monotonic()
        # quest-lint: disable=QL005(racy generation read IS the supersession design)
        gen0 = self._worker_gen     # breaker-success guard (watchdog)
        n = (q.circuit.num_qubits * 2 if q.density
             else q.circuit.num_qubits)
        fn, primary, br = self._resolve_program(
            q, lambda: self._apply_program(q, len(reqs), "fused"),
            lambda rung: self._apply_program(q, len(reqs), rung))
        if _F.ACTIVE:
            self._fault("serve.device_put", reqs=reqs)
        batch = np.stack([r.state for r in reqs])
        if len(reqs) < fn.bucket:
            # pad to the bucket HOST-SIDE: handing the wrapper a partial
            # batch would run its traced zero-pad, and that concatenate
            # is a fresh XLA compile per distinct (b, bucket) pair —
            # measured ~300 ms stalls mid-stream. numpy zeros keep the
            # one-program-per-bucket property literal: the compiled
            # program only ever sees bucket-shaped input.
            batch = np.concatenate(
                [batch, np.zeros((fn.bucket - len(reqs),) + batch.shape[1:],
                                 batch.dtype)])
        for r in reqs:
            r.dispatched = True
        if _F.ACTIVE:
            self._fault("serve.dispatch", reqs=reqs)
        out_dev = jax.block_until_ready(fn(batch))
        # quest-lint: disable=QL005(racy generation read IS the supersession design)
        if primary and gen0 == self._worker_gen:
            # generation-guarded like every other stale-worker mutation:
            # a slow-but-not-stuck launch that unsticks AFTER the
            # watchdog fired must not erase the failure it just
            # recorded on this program's breaker
            br.record_success()
        # AT MOST one device->host materialization for the whole batch:
        # slicing the jax array per request would dispatch an XLA
        # gather per future (measured 0.75 ms/request — it dominated
        # the launch), and observable requests skip the full-planes
        # transfer entirely — like the trajectory path, the observable
        # reduces the CONSTANT bucket-shaped planes ON DEVICE (one
        # compiled reduction per distinct observable per launch) and
        # each request takes its row of the reduced values, so an
        # observable-only batch at 24q ships per-state scalars to the
        # host instead of bucket x 2 x 2^24 planes
        raw_needed = any(r.observable is None for r in reqs)
        out = np.asarray(out_dev) if raw_needed else None
        self._record_batch(reqs, len(reqs) / fn.bucket, t_pop)
        obs_vals: Dict[int, np.ndarray] = {}
        for i, r in enumerate(reqs):
            # demux is PER REQUEST from here on: one request's bad
            # observable (wrong shape, a raise inside the callable)
            # fails only its own future — its batch-mates already have
            # correct planes in `out` and must not ride a batch-wide
            # exception (the engine.py:345 whole-batch failure this
            # replaces)
            try:
                if _F.ACTIVE:
                    self._fault("serve.demux", req=r)
                if r.observable is not None:
                    vals = obs_vals.get(id(r.observable))
                    if vals is None:
                        planes_b = out_dev.reshape(fn.bucket, 2, 1 << n)
                        vals = np.asarray(jax.block_until_ready(
                            r.observable(planes_b)))
                        obs_vals[id(r.observable)] = vals
                    self._finish_one(r, vals[i])
                else:
                    self._finish_one(r, out[i].reshape(2, 1 << n))
            except BaseException as e:   # noqa: BLE001 - per-request
                self.registry.counter("serve_demux_failures").inc()
                self._fail_request(r, e)

    def _dispatch_traj(self, q: _Queue, reqs: List[_Request]) -> None:
        import jax
        import jax.numpy as jnp

        t_pop = time.monotonic()
        # quest-lint: disable=QL005(racy generation read IS the supersession design)
        gen0 = self._worker_gen     # breaker-success guard (watchdog)
        n = q.circuit.num_qubits
        total = sum(r.shots for r in reqs)
        # the per-request key chains match run_batched exactly: shot i
        # of a request with key k runs jax.random.split(k, shots)[i],
        # so a coalesced request reproduces its standalone run. The
        # split stays a jax op (bit-exact parity); concatenation,
        # chunking and padding happen on the raw key DATA in numpy —
        # jnp.concatenate/broadcast_to here would be a fresh XLA
        # compile per distinct (shots..., pad) shape combination, a
        # latency stall on every new mix (same hazard as the apply
        # path's traced zero-pad).
        rows = [jax.random.split(r.key, r.shots) for r in reqs]
        if jnp.issubdtype(rows[0].dtype, jax.dtypes.prng_key):
            impl = jax.random.key_impl(rows[0])
            data = np.concatenate([np.asarray(jax.random.key_data(k))
                                   for k in rows])

            def make_keys(d):
                return jax.random.wrap_key_data(jnp.asarray(d), impl=impl)
        else:
            data = np.concatenate([np.asarray(k) for k in rows])
            make_keys = jnp.asarray
        # run_batched's chunk=None bucket rule (shared helper): beyond
        # the memory rationale, this makes an UNCOALESCED request with
        # shots <= max_batch run the IDENTICAL program + chunk sequence
        # as its standalone run_batched call — bit-identical by
        # construction there, not by cross-program luck. Bigger or
        # coalesced batches necessarily ride a different bucket program
        # (max_batch bounds the launch); their parity rests on the
        # per-state math being batch-size-invariant, pinned per engine
        # in tests/test_batched.py and tests/test_serve.py.
        bucket = traj_dispatch_bucket(total, self.max_batch)
        fn, primary, br = self._resolve_program(
            q, lambda: self._traj_program(q, n, bucket, "fused"),
            lambda rung: self._traj_program(q, n, bucket, rung))
        spans, lo = [], 0
        for r in reqs:
            spans.append((r, lo, lo + r.shots))
            lo += r.shots
        pieces = [([], []) for _ in reqs]   # (planes|values, draws) chunks
        dead = set()                        # request indices demux-failed
        launches = 0
        if _F.ACTIVE:
            self._fault("serve.device_put", reqs=reqs)
        for r in reqs:
            r.dispatched = True
        for clo in range(0, total, bucket):
            kb = data[clo:clo + bucket]
            pad = bucket - kb.shape[0]
            if pad:
                kb = np.concatenate(
                    [kb, np.broadcast_to(kb[:1], (pad,) + kb.shape[1:])])
            if _F.ACTIVE:
                self._fault("serve.dispatch", reqs=reqs, chunk=launches)
            planes, draws = fn(make_keys(kb))
            chi = min(clo + bucket, total)
            draws_np = np.asarray(draws)
            # demux the chunk per request: observable requests reduce
            # ON DEVICE, chunk by chunk, mirroring run_batched's memory
            # contract (no chunk's full planes outlive its reduction —
            # 256 shots at 24q would otherwise materialize 32 GiB on
            # the host) — and like run_batched the observable sees the
            # CONSTANT bucket-shaped chunk, values sliced per request
            # after: reducing a per-request slice would hand XLA a
            # fresh shape per distinct span length, a fresh compile per
            # shot-count mix mid-stream (the same stall hazard as the
            # apply path's traced zero-pad). Requests WITHOUT an
            # observable need their raw planes anyway, so the chunk is
            # materialized ONCE for all of them and sliced in numpy —
            # a device slice per request would dispatch an XLA gather +
            # host transfer per future (the 0.75 ms/request cost the
            # apply path avoids the same way). Pad rows sit past every
            # request's span and are never touched. A per-request demux
            # error (bad observable) kills only that request's future.
            overlaps = []
            raw_needed = False
            for i, (r, rlo, rhi) in enumerate(spans):
                s0, s1 = max(rlo, clo) - clo, min(rhi, chi) - clo
                if s0 >= s1 or i in dead:
                    continue
                overlaps.append((i, r, s0, s1))
                raw_needed = raw_needed or r.observable is None
            planes_np = (np.asarray(jax.block_until_ready(planes))
                         if raw_needed else None)
            obs_vals: Dict[int, np.ndarray] = {}
            for i, r, s0, s1 in overlaps:
                try:
                    if _F.ACTIVE:
                        self._fault("serve.demux", req=r)
                    if r.observable is not None:
                        vals = obs_vals.get(id(r.observable))
                        if vals is None:
                            vals = np.asarray(jax.block_until_ready(
                                r.observable(planes)))
                            obs_vals[id(r.observable)] = vals
                        seg = vals[s0:s1]
                    else:
                        seg = planes_np[s0:s1]
                    pieces[i][0].append(seg)
                    pieces[i][1].append(draws_np[s0:s1])
                except BaseException as e:  # noqa: BLE001 - per-request
                    self.registry.counter("serve_demux_failures").inc()
                    dead.add(i)
                    self._fail_request(r, e)
            launches += 1
        # quest-lint: disable=QL005(racy generation read IS the supersession design)
        if primary and gen0 == self._worker_gen:
            # the apply path's stale-worker breaker guard, same rationale
            br.record_success()
        self.registry.counter("serve_batches_dispatched").inc(
            launches - 1)                 # _record_batch adds the 1st
        self._record_batch(reqs, total / (launches * bucket), t_pop)
        for i, ((r, _, _), (pp, dd)) in enumerate(zip(spans, pieces)):
            if i in dead:
                continue
            try:
                p = pp[0] if len(pp) == 1 else np.concatenate(pp, axis=0)
                d = dd[0] if len(dd) == 1 else np.concatenate(dd, axis=0)
            except BaseException as e:   # noqa: BLE001 - per-request
                self.registry.counter("serve_demux_failures").inc()
                self._fail_request(r, e)
                continue
            self._finish_one(r, (p, d))
