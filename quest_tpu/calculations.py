"""Scalar calculations on registers: norms, overlaps, expectations.

Every function here is a reduction over the amplitude planes; when the
array is sharded over a mesh these compile to per-shard partial sums
followed by an XLA all-reduce — the TPU-native form of the reference's
OpenMP `reduction(+:)` + `MPI_Allreduce` pattern
(QuEST_cpu_distributed.c:35-117, 1263-1299).

Complex results are computed as (re, im) float pairs on device and
assembled on the host (complex cannot cross the boundary here — see
quest_tpu.cplx). Reference semantics per function are cited inline.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import precision
from quest_tpu import validation as val
from quest_tpu.ops import gates
from quest_tpu.state import Qureg


@jax.jit
def _sum_sq(amps):
    # ref statevec_calcTotalProb: Kahan-summed sum |a|^2. The TPU-native
    # analogue of the Kahan discipline is an f64 accumulator (the convert
    # fuses into the reduce — no f64-sized buffer exists); at 2^30 f32
    # amplitudes a plain f32 reduction can drift ~1e-4.
    acc = precision.accum_dtype(amps.dtype)
    return jnp.sum(jnp.square(amps.astype(acc)))


@partial(jax.jit, static_argnames=("dim",))
def _total_prob_density(amps, *, dim):
    acc = precision.accum_dtype(amps.dtype)
    return jnp.sum(jnp.diagonal(amps[0].reshape((dim, dim))).astype(acc))


def calc_total_prob(q: Qureg) -> float:
    """Total probability (statevec: sum |a|^2; density: Re trace)."""
    if q.is_density:
        return float(_total_prob_density(q.amps, dim=1 << q.num_qubits))
    return float(_sum_sq(q.amps))


@jax.jit
def _inner(bra, ket):
    """<bra|ket> = sum conj(b) k as a stacked (re, im) pair, accumulated
    in f64 (ref Kahan sums, QuEST_cpu_distributed.c:35-51); result is
    cast back to the plane dtype."""
    acc = precision.accum_dtype(bra.dtype)
    br, bi = bra[0].astype(acc), bra[1].astype(acc)
    kr, ki = ket[0].astype(acc), ket[1].astype(acc)
    return jnp.stack([jnp.sum(br * kr + bi * ki),
                      jnp.sum(br * ki - bi * kr)]).astype(bra.dtype)


def calc_inner_product(bra: Qureg, ket: Qureg) -> complex:
    """<bra|ket> (ref statevec_calcInnerProduct,
    QuEST_cpu_distributed.c:35-51)."""
    val.validate_state_vector(bra)
    val.validate_state_vector(ket)
    val.validate_match(bra, ket)
    pair = np.asarray(jax.device_get(
        _inner(bra.amps, ket.amps.astype(bra.real_dtype))))
    return complex(pair[0], pair[1])


def calc_density_inner_product(rho1: Qureg, rho2: Qureg) -> float:
    """Tr(rho1 rho2) = Re sum conj(a) b for Hermitian args
    (ref densmatr_calcInnerProduct)."""
    val.validate_density_matr(rho1)
    val.validate_density_matr(rho2)
    val.validate_match(rho1, rho2)
    pair = _inner(rho1.amps, rho2.amps.astype(rho1.real_dtype))
    return float(pair[0])


def calc_purity(q: Qureg) -> float:
    """Tr(rho^2) = sum |rho_ij|^2 (ref densmatr_calcPurityLocal)."""
    val.validate_density_matr(q)
    return float(_sum_sq(q.amps))


@partial(jax.jit, static_argnames=("dim",))
def _fidelity_density(rho_amps, psi_amps, *, dim):
    # <psi| rho |psi>: rho flat index = row + col*dim, so the row-major
    # reshape is rho^T; transpose back before the matvec
    hi = jax.lax.Precision.HIGHEST
    rre = rho_amps[0].reshape((dim, dim)).T
    rim = rho_amps[1].reshape((dim, dim)).T
    pre, pim = psi_amps[0], psi_amps[1]
    # (rho psi) as planes
    vr = jnp.matmul(rre, pre, precision=hi) - jnp.matmul(rim, pim, precision=hi)
    vi = jnp.matmul(rre, pim, precision=hi) + jnp.matmul(rim, pre, precision=hi)
    # Re <psi | v>
    return jnp.sum(pre * vr + pim * vi)


def calc_fidelity(q: Qureg, pure: Qureg) -> float:
    """|<psi|phi>|^2 for statevectors; <psi|rho|psi> for a density q
    (ref QuEST_common.c:376-381, densmatr_calcFidelity)."""
    val.validate_pure_state_args(q, pure)
    if q.is_density:
        return float(_fidelity_density(q.amps, pure.amps.astype(q.real_dtype),
                                       dim=1 << q.num_qubits))
    pair = np.asarray(jax.device_get(
        _inner(q.amps, pure.amps.astype(q.real_dtype))))
    return float(pair[0] ** 2 + pair[1] ** 2)


@jax.jit
def _hs_dist_sq(a, b):
    d = (a - b).astype(precision.accum_dtype(a.dtype))
    return jnp.sum(d * d)


def calc_hilbert_schmidt_distance(a: Qureg, b: Qureg) -> float:
    """sqrt(sum |a_ij - b_ij|^2) (ref densmatr_calcHilbertSchmidtDistance)."""
    val.validate_density_matr(a)
    val.validate_density_matr(b)
    val.validate_match(a, b)
    return float(np.sqrt(_hs_dist_sq(a.amps, b.amps.astype(a.real_dtype))))


# ---------------------------------------------------------------------------
# Pauli expectation values (ref QuEST_common.c:464-514)
# ---------------------------------------------------------------------------


def calc_expec_pauli_prod(q: Qureg, targets: Sequence[int],
                          paulis: Sequence[int]) -> float:
    """<q| P |q> (statevec) or Tr(P rho) (density).

    Routes through the grouped fused expectation engine (ops/expec) as
    a one-term sum: one flip-form pass over the state, NO workspace
    register (the reference — and this port until ISSUE 8 — cloned the
    register and paid a full apply plus an inner product,
    QuEST_common.c:464-477). By construction the compiled program IS
    the one-term `calc_expec_pauli_sum` program — program identity
    pinned under CompileAuditor in tests/test_expec.py.
    QUEST_EXPEC_FUSION=0 restores the workspace path."""
    from quest_tpu.ops import expec as E
    val.validate_multi_targets(q, targets)
    val.validate_pauli_targets(targets, paulis)
    val.validate_pauli_codes(paulis)
    if E.fusion_enabled():
        term = [0] * q.num_qubits
        for t, p in zip(targets, paulis):
            term[int(t)] = int(p)
        return E.expec_value(q, np.ones((1,)), (tuple(term),))
    work = gates.apply_pauli_prod(q, targets, paulis)
    if q.is_density:
        return float(_total_prob_density(work.amps, dim=1 << q.num_qubits))
    return float(_inner(work.amps, q.amps)[0])


def _pauli_prod_amps(amps, n, term):
    """P|psi> in one fused flip-form pass (see ops.apply.apply_pauli_string
    — the single home of the Pauli flip/sign/phase algebra)."""
    from quest_tpu.ops import apply as A
    return A.apply_pauli_string(amps, n, term)


@partial(jax.jit, static_argnames=("codes", "n", "density"))
def _expec_pauli_sum(amps, coeffs, *, codes, n, density):
    """sum_t c_t <P_t> as ONE program: every term's Pauli string, overlap
    and the weighted sum compile into a single dispatch (the reference
    loops clone+apply+innerProduct per term, QuEST_common.c:479-491 — one
    workspace pass per term is kept, but without per-term dispatch)."""
    acc = precision.accum_dtype(amps.dtype)
    total = jnp.zeros((), dtype=acc)
    for i, term in enumerate(codes):
        if density:
            term_val = _pauli_term_trace(amps, n // 2, term).astype(acc)
        else:
            w = _pauli_prod_amps(amps, n, term)
            term_val = jnp.sum((amps[0] * w[0]
                                + amps[1] * w[1]).astype(acc))  # Re<q|w>
        total = total + coeffs[i].astype(acc) * term_val
    return total


def _pauli_term_trace(amps, N, term):
    """Re Tr(P rho) reading only the 2^N entries the trace touches.

    Tr(P rho) = sum_k coef(k) rho[k, k^x] with coef(k) =
    i^{ny} (-1)^{parity(k & zy)} — a FLIPPED DIAGONAL of the stored
    matrix, so the whole term costs one strided gather over 2^N entries
    instead of a full 4^N-amplitude pass (the reference clones the 4^N
    register and applies the string factor-by-factor,
    QuEST_common.c:479-491)."""
    from quest_tpu.ops import apply as A

    from quest_tpu.ops.expec import flipped_trace_diag

    x_bits = tuple(q for q, p in enumerate(term) if p in (1, 2))
    zy_bits = tuple(q for q, p in enumerate(term) if p in (2, 3))
    ny = sum(1 for p in term if p == 2)
    # the flipped-diagonal extraction (layout subtleties included) has
    # ONE home: expec.flipped_trace_diag, shared with the grouped path
    rdiag, idiag = flipped_trace_diag(amps, N, x_bits)
    if zy_bits:
        zy_desc = tuple(sorted(zy_bits, reverse=True))
        dims_k, axis_of_k = A.seg_view(N, zy_desc)
        sign = A.parity_sign(len(dims_k), axis_of_k, zy_bits, amps.dtype)
        sign = jnp.broadcast_to(sign, dims_k).reshape(-1)
        rdiag = rdiag * sign
        idiag = idiag * sign
    # Re(i^{ny} * (rdiag + i idiag)): quarter-turn selects the plane
    k = ny % 4
    part = (rdiag, -idiag, -rdiag, idiag)[k]
    return jnp.sum(part)


def calc_expec_pauli_sum(q: Qureg, all_codes, coeffs) -> float:
    """sum_t c_t <P_t>; codes is (numTerms, numQubits) of Pauli codes.

    Default path: the grouped sweep-fused expectation engine
    (quest_tpu/ops/expec.py, docs/EXPECTATION.md) — the whole
    Hamiltonian evaluates in O(#flip-mask-groups) HBM sweeps instead of
    the per-term pass structure (an all-diagonal sum is ONE pass), with
    the coefficient vector a runtime operand so coefficient-only
    changes never retrace. Parsing/validation is memoized by value.
    Sharded statevectors compute per-shard partials + psum.
    QUEST_EXPEC_FUSION=0 restores the legacy per-term program."""
    from quest_tpu.ops import expec as E
    codes_key = E.parse_pauli_sum(all_codes, q.num_qubits)
    coeffs = np.asarray(coeffs, dtype=np.float64).reshape(-1)
    if len(coeffs) != len(codes_key):
        val._err("Invalid Pauli sum: must give exactly one coefficient "
                 "per term.")
    if E.fusion_enabled():
        return E.expec_value(q, coeffs, codes_key)
    cf = jnp.asarray(coeffs, dtype=q.real_dtype)
    return float(_expec_pauli_sum(q.amps, cf, codes=codes_key,
                                  n=q.num_state_qubits,
                                  density=q.is_density))


@partial(jax.jit, static_argnames=("n",))
def _probs_at(amps, samples, *, n):
    re = amps[0][samples]
    im = amps[1][samples]
    return re * re + im * im


def calc_linear_xeb(q: Qureg, samples) -> float:
    """Linear cross-entropy benchmarking fidelity of bitstring `samples`
    against this state: F_XEB = 2^n <p(s)> - 1 (the standard RCS quality
    metric; 1 for perfect sampling from |amps|^2, 0 for uniform noise).
    The reference has no analogue — its RCS workflows stop at measurement.
    Statevector registers only."""
    val.validate_state_vector(q)
    samples = jnp.asarray(samples)
    p = _probs_at(q.amps, samples, n=q.num_state_qubits)
    return float((1 << q.num_state_qubits) * jnp.mean(p) - 1.0)


@partial(jax.jit, static_argnames=("codes", "n"))
def _apply_pauli_sum(amps, coeffs, *, codes, n):
    acc = jnp.zeros_like(amps)
    for i, term in enumerate(codes):
        acc = acc + coeffs[i] * _pauli_prod_amps(amps, n, term)
    return acc


def apply_pauli_sum(q: Qureg, all_codes, coeffs) -> Qureg:
    """Return sum_t c_t P_t |q> (or P_t rho) as a new register — the
    (generally unnormalized) Pauli-sum image (ref statevec_applyPauliSum,
    QuEST_common.c:493-514) — all terms in ONE traced program. Parsing
    and validation share the expectation engine's by-value memo."""
    from quest_tpu.ops import expec as E
    codes_key = E.parse_pauli_sum(all_codes, q.num_qubits)
    coeffs = np.asarray(coeffs, dtype=np.float64).reshape(-1)
    if len(coeffs) != len(codes_key):
        val._err("Invalid Pauli sum: must give exactly one coefficient "
                 "per term.")
    cf = jnp.asarray(coeffs, dtype=q.real_dtype)  # termCoeffs are real
    return q.replace_amps(_apply_pauli_sum(q.amps, cf, codes=codes_key,
                                           n=q.num_state_qubits))
