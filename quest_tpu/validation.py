"""Eager, host-side input validation with the reference's error table.

The reference funnels every user error through a 47-code enum + message
table and an overridable `invalidQuESTInputError` hook that defaults to
exit(1) (QuEST/src/QuEST_validation.c:26-148); its test suite overrides
the hook to throw and asserts on the exact message strings
(tests/test_unitaries.cpp:74-88). Here the codes and messages are
reproduced VERBATIM (ErrorCode / MESSAGES below) so message-matching
tests port 1:1, and the natural Python design raises an exception
eagerly, before any tracing/compilation, so bad inputs never reach XLA.

Numeric tolerances follow the reference's REAL_EPS discipline
(QuEST_precision.h:35,48: 1e-5 single / 1e-13 double): validators take an
optional `eps`; call sites that know the register's dtype pass
`eps_for(qureg)` and standalone calls default to the single-precision
REAL_EPS (the loosest precision the reference ships).
"""

from __future__ import annotations

import enum

import numpy as np


class ErrorCode(enum.Enum):
    """Verbatim reference error codes (QuEST_validation.c:26-79)."""
    E_SUCCESS = 0
    E_INVALID_NUM_RANKS = enum.auto()
    E_INVALID_NUM_CREATE_QUBITS = enum.auto()
    E_INVALID_QUBIT_INDEX = enum.auto()
    E_INVALID_TARGET_QUBIT = enum.auto()
    E_INVALID_CONTROL_QUBIT = enum.auto()
    E_INVALID_STATE_INDEX = enum.auto()
    E_INVALID_AMP_INDEX = enum.auto()
    E_INVALID_NUM_AMPS = enum.auto()
    E_INVALID_OFFSET_NUM_AMPS = enum.auto()
    E_TARGET_IS_CONTROL = enum.auto()
    E_TARGET_IN_CONTROLS = enum.auto()
    E_CONTROL_TARGET_COLLISION = enum.auto()
    E_QUBITS_NOT_UNIQUE = enum.auto()
    E_TARGETS_NOT_UNIQUE = enum.auto()
    E_CONTROLS_NOT_UNIQUE = enum.auto()
    E_INVALID_NUM_QUBITS = enum.auto()
    E_INVALID_NUM_TARGETS = enum.auto()
    E_INVALID_NUM_CONTROLS = enum.auto()
    E_NON_UNITARY_MATRIX = enum.auto()
    E_NON_UNITARY_COMPLEX_PAIR = enum.auto()
    E_ZERO_VECTOR = enum.auto()
    E_SYS_TOO_BIG_TO_PRINT = enum.auto()
    E_COLLAPSE_STATE_ZERO_PROB = enum.auto()
    E_INVALID_QUBIT_OUTCOME = enum.auto()
    E_CANNOT_OPEN_FILE = enum.auto()
    E_SECOND_ARG_MUST_BE_STATEVEC = enum.auto()
    E_MISMATCHING_QUREG_DIMENSIONS = enum.auto()
    E_MISMATCHING_QUREG_TYPES = enum.auto()
    E_DEFINED_ONLY_FOR_STATEVECS = enum.auto()
    E_DEFINED_ONLY_FOR_DENSMATRS = enum.auto()
    E_INVALID_PROB = enum.auto()
    E_UNNORM_PROBS = enum.auto()
    E_INVALID_ONE_QUBIT_DEPHASE_PROB = enum.auto()
    E_INVALID_TWO_QUBIT_DEPHASE_PROB = enum.auto()
    E_INVALID_ONE_QUBIT_DEPOL_PROB = enum.auto()
    E_INVALID_TWO_QUBIT_DEPOL_PROB = enum.auto()
    E_INVALID_ONE_QUBIT_PAULI_PROBS = enum.auto()
    E_INVALID_CONTROLS_BIT_STATE = enum.auto()
    E_INVALID_PAULI_CODE = enum.auto()
    E_INVALID_NUM_SUM_TERMS = enum.auto()
    E_CANNOT_FIT_MULTI_QUBIT_MATRIX = enum.auto()
    E_INVALID_UNITARY_SIZE = enum.auto()
    E_COMPLEX_MATRIX_NOT_INIT = enum.auto()
    E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS = enum.auto()
    E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS = enum.auto()
    E_INVALID_NUM_N_QUBIT_KRAUS_OPS = enum.auto()
    E_INVALID_KRAUS_OPS = enum.auto()
    E_MISMATCHING_NUM_TARGS_KRAUS_SIZE = enum.auto()
    E_DISTRIB_QUREG_TOO_SMALL = enum.auto()
    E_NUM_AMPS_EXCEED_TYPE = enum.auto()


E = ErrorCode

# Verbatim reference message table (QuEST_validation.c:81-131).
MESSAGES = {
    E.E_INVALID_NUM_RANKS: "Invalid number of nodes. Distributed simulation can only make use of a power-of-2 number of node.",
    E.E_INVALID_NUM_CREATE_QUBITS: "Invalid number of qubits. Must create >0.",
    E.E_INVALID_QUBIT_INDEX: "Invalid qubit index. Must be >=0 and <numQubits.",
    E.E_INVALID_TARGET_QUBIT: "Invalid target qubit. Must be >=0 and <numQubits.",
    E.E_INVALID_CONTROL_QUBIT: "Invalid control qubit. Must be >=0 and <numQubits.",
    E.E_INVALID_STATE_INDEX: "Invalid state index. Must be >=0 and <2^numQubits.",
    E.E_INVALID_AMP_INDEX: "Invalid amplitude index. Must be >=0 and <2^numQubits.",
    E.E_INVALID_NUM_AMPS: "Invalid number of amplitudes. Must be >=0 and <=2^numQubits.",
    E.E_INVALID_OFFSET_NUM_AMPS: "More amplitudes given than exist in the statevector from the given starting index.",
    E.E_TARGET_IS_CONTROL: "Control qubit cannot equal target qubit.",
    E.E_TARGET_IN_CONTROLS: "Control qubits cannot include target qubit.",
    E.E_CONTROL_TARGET_COLLISION: "Control and target qubits must be disjoint.",
    E.E_QUBITS_NOT_UNIQUE: "The qubits must be unique.",
    E.E_TARGETS_NOT_UNIQUE: "The target qubits must be unique.",
    E.E_CONTROLS_NOT_UNIQUE: "The control qubits should be unique.",
    E.E_INVALID_NUM_QUBITS: "Invalid number of qubits. Must be >0 and <=numQubits.",
    E.E_INVALID_NUM_TARGETS: "Invalid number of target qubits. Must be >0 and <=numQubits.",
    E.E_INVALID_NUM_CONTROLS: "Invalid number of control qubits. Must be >0 and <numQubits.",
    E.E_NON_UNITARY_MATRIX: "Matrix is not unitary.",
    E.E_NON_UNITARY_COMPLEX_PAIR: "Compact matrix formed by given complex numbers is not unitary.",
    E.E_ZERO_VECTOR: "Invalid axis vector. Must be non-zero.",
    E.E_SYS_TOO_BIG_TO_PRINT: "Invalid system size. Cannot print output for systems greater than 5 qubits.",
    E.E_COLLAPSE_STATE_ZERO_PROB: "Can't collapse to state with zero probability.",
    E.E_INVALID_QUBIT_OUTCOME: "Invalid measurement outcome -- must be either 0 or 1.",
    E.E_CANNOT_OPEN_FILE: "Could not open file.",
    E.E_SECOND_ARG_MUST_BE_STATEVEC: "Second argument must be a state-vector.",
    E.E_MISMATCHING_QUREG_DIMENSIONS: "Dimensions of the qubit registers don't match.",
    E.E_MISMATCHING_QUREG_TYPES: "Registers must both be state-vectors or both be density matrices.",
    E.E_DEFINED_ONLY_FOR_STATEVECS: "Operation valid only for state-vectors.",
    E.E_DEFINED_ONLY_FOR_DENSMATRS: "Operation valid only for density matrices.",
    E.E_INVALID_PROB: "Probabilities must be in [0, 1].",
    E.E_UNNORM_PROBS: "Probabilities must sum to ~1.",
    E.E_INVALID_ONE_QUBIT_DEPHASE_PROB: "The probability of a single qubit dephase error cannot exceed 1/2, which maximally mixes.",
    E.E_INVALID_TWO_QUBIT_DEPHASE_PROB: "The probability of a two-qubit qubit dephase error cannot exceed 3/4, which maximally mixes.",
    E.E_INVALID_ONE_QUBIT_DEPOL_PROB: "The probability of a single qubit depolarising error cannot exceed 3/4, which maximally mixes.",
    E.E_INVALID_TWO_QUBIT_DEPOL_PROB: "The probability of a two-qubit depolarising error cannot exceed 15/16, which maximally mixes.",
    E.E_INVALID_ONE_QUBIT_PAULI_PROBS: "The probability of any X, Y or Z error cannot exceed the probability of no error.",
    E.E_INVALID_CONTROLS_BIT_STATE: "The state of the control qubits must be a bit sequence (0s and 1s).",
    E.E_INVALID_PAULI_CODE: "Invalid Pauli code. Codes must be 0 (or PAULI_I), 1 (PAULI_X), 2 (PAULI_Y) or 3 (PAULI_Z) to indicate the identity, X, Y and Z gates respectively.",
    E.E_INVALID_NUM_SUM_TERMS: "Invalid number of terms in the Pauli sum. The number of terms must be >0.",
    E.E_CANNOT_FIT_MULTI_QUBIT_MATRIX: "The specified matrix targets too many qubits; the batches of amplitudes to modify cannot all fit in a single distributed node's memory allocation.",
    E.E_INVALID_UNITARY_SIZE: "The matrix size does not match the number of target qubits.",
    E.E_COMPLEX_MATRIX_NOT_INIT: "The ComplexMatrixN was not successfully created (possibly insufficient memory available).",
    E.E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS: "At least 1 and at most 4 single qubit Kraus operators may be specified.",
    E.E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS: "At least 1 and at most 16 two-qubit Kraus operators may be specified.",
    E.E_INVALID_NUM_N_QUBIT_KRAUS_OPS: "At least 1 and at most 4*N^2 of N-qubit Kraus operators may be specified.",
    E.E_INVALID_KRAUS_OPS: "The specified Kraus map is not a completely positive, trace preserving map.",
    E.E_MISMATCHING_NUM_TARGS_KRAUS_SIZE: "Every Kraus operator must be of the same number of qubits as the number of targets.",
    E.E_DISTRIB_QUREG_TOO_SMALL: "Too few qubits. The created qureg must have at least one amplitude per node used in distributed simulation.",
    E.E_NUM_AMPS_EXCEED_TYPE: "Too many qubits (max of log2(SIZE_MAX)). Cannot store the number of amplitudes per-node in the size_t type.",
}

# reference REAL_EPS, per precision (QuEST_precision.h:35,48)
REAL_EPS_SINGLE = 1e-5
REAL_EPS_DOUBLE = 1e-13


def eps_for(qureg_or_dtype) -> float:
    """REAL_EPS for a register's (or dtype's) precision."""
    from quest_tpu import precision
    dtype = getattr(qureg_or_dtype, "dtype", qureg_or_dtype)
    return precision.real_eps(dtype)


class QuESTError(ValueError):
    """Raised for any invalid user input (analogue of invalidQuESTInputError)."""

    def __init__(self, msg, code: ErrorCode = None):
        super().__init__(msg)
        self.code = code


def _default_handler(msg: str, func: str = ""):
    # route through the overridable module-level hook (looked up at call
    # time so monkeypatching quest_tpu.api.invalidQuESTInputError works,
    # like redefining the reference's weak symbol, QuEST.h:3163-3190)
    try:
        from quest_tpu import api as _api
        _api.invalidQuESTInputError(msg, func)
    except ImportError:
        pass
    raise QuESTError(msg)


_error_handler = _default_handler


def set_error_handler(handler) -> None:
    """Override the invalid-input hook (the reference's overridable weak
    symbol invalidQuESTInputError, QuEST.h:3163-3190; default raises
    QuESTError). Pass None to restore the default."""
    global _error_handler
    _error_handler = handler if handler is not None else _default_handler


def _err(code, msg: str = None):
    """Report an invalid input: `code` is an ErrorCode (message looked up
    in the verbatim table) or a bare string for checks with no reference
    counterpart."""
    if isinstance(code, ErrorCode):
        msg = MESSAGES[code]
    else:
        code, msg = None, code
    import inspect
    # report the outermost quest_tpu function the USER called (the
    # reference hands __func__ of the public API fn to the hook) — walk
    # out of the validation helpers to the last quest_tpu frame
    func = ""
    frame = inspect.currentframe()
    try:
        f = frame.f_back if frame else None
        while f is not None:
            mod = f.f_globals.get("__name__", "")
            name = f.f_code.co_name
            if mod.startswith("quest_tpu") and not name.startswith("_"):
                func = name
            f = f.f_back
    finally:
        del frame
    _error_handler(msg, func)
    # a non-raising handler must not let execution continue into the op
    raise QuESTError(msg, code)


# -- register construction ---------------------------------------------------

def validate_num_qubits(num_qubits: int):
    if not isinstance(num_qubits, (int, np.integer)) or num_qubits < 1:
        _err(E.E_INVALID_NUM_CREATE_QUBITS)
    if num_qubits > 60:
        _err(E.E_NUM_AMPS_EXCEED_TYPE)


def validate_state_index(qureg, index: int):
    dim = 1 << qureg.num_qubits
    if not (0 <= index < dim):
        _err(E.E_INVALID_STATE_INDEX)


def validate_amp_index(qureg, index: int, dim=None):
    dim = dim if dim is not None else qureg.num_amps
    if not (0 <= index < dim):
        _err(E.E_INVALID_AMP_INDEX)


def validate_num_amps(qureg, start: int, num: int):
    # reference validateNumAmps checks the start index FIRST
    # (QuEST_validation.c validateAmpIndex then the offset sum)
    validate_amp_index(qureg, start)
    if num < 0 or num > qureg.num_amps:
        _err(E.E_INVALID_NUM_AMPS)
    if start + num > qureg.num_amps:
        _err(E.E_INVALID_OFFSET_NUM_AMPS)


def validate_equal_lengths(reals, imags):
    if np.asarray(reals).size != np.asarray(imags).size:
        _err("Invalid number of amplitudes: real and imaginary lists must "
             "have equal length.")


def validate_match(a, b):
    if a.num_qubits != b.num_qubits:
        _err(E.E_MISMATCHING_QUREG_DIMENSIONS)


def validate_matching_types(a, b):
    if a.is_density != b.is_density:
        _err(E.E_MISMATCHING_QUREG_TYPES)


def validate_pure_state_args(qureg, pure):
    if pure.is_density:
        _err(E.E_SECOND_ARG_MUST_BE_STATEVEC)
    if qureg.num_qubits != pure.num_qubits:
        _err(E.E_MISMATCHING_QUREG_DIMENSIONS)


# -- qubit indices -----------------------------------------------------------

def validate_target(qureg, target: int):
    if not (0 <= target < qureg.num_qubits):
        _err(E.E_INVALID_TARGET_QUBIT)


def validate_control(qureg, control: int):
    if not (0 <= control < qureg.num_qubits):
        _err(E.E_INVALID_CONTROL_QUBIT)


def validate_control_target(qureg, control: int, target: int):
    validate_target(qureg, target)
    validate_control(qureg, control)
    if control == target:
        _err(E.E_TARGET_IS_CONTROL)


def validate_unique_targets(qureg, qubit1: int, qubit2: int):
    validate_target(qureg, qubit1)
    validate_target(qureg, qubit2)
    if qubit1 == qubit2:
        _err(E.E_QUBITS_NOT_UNIQUE)


def validate_multi_targets(qureg, targets, num_targets=None):
    targets = list(targets)
    n = len(targets) if num_targets is None else num_targets
    if n < 1 or n > qureg.num_qubits:
        _err(E.E_INVALID_NUM_TARGETS)
    for t in targets:
        validate_target(qureg, t)
    if len(set(targets)) != len(targets):
        _err(E.E_TARGETS_NOT_UNIQUE)


def validate_multi_controls(qureg, controls):
    controls = list(controls)
    if len(controls) >= qureg.num_qubits:
        _err(E.E_INVALID_NUM_CONTROLS)
    for c in controls:
        validate_control(qureg, c)
    if len(set(controls)) != len(controls):
        _err(E.E_CONTROLS_NOT_UNIQUE)


def validate_multi_controls_targets(qureg, controls, targets):
    validate_multi_controls(qureg, controls)
    validate_multi_targets(qureg, targets)
    if set(controls) & set(targets):
        _err(E.E_CONTROL_TARGET_COLLISION)


def validate_control_states(controls, states):
    states = list(states)
    if len(states) != len(list(controls)):
        _err(E.E_INVALID_CONTROLS_BIT_STATE)
    for s in states:
        if s not in (0, 1):
            _err(E.E_INVALID_CONTROLS_BIT_STATE)


def validate_outcome(outcome: int):
    if outcome not in (0, 1):
        _err(E.E_INVALID_QUBIT_OUTCOME)


# -- numeric operator checks -------------------------------------------------

def _as_matrix(m, num_targets=None) -> np.ndarray:
    m = np.asarray(m)
    if m.ndim != 2 or m.shape[0] != m.shape[1]:
        _err(E.E_INVALID_UNITARY_SIZE)
    dim = m.shape[0]
    if dim & (dim - 1) or dim < 2:
        _err(E.E_INVALID_UNITARY_SIZE)
    if num_targets is not None and dim != (1 << num_targets):
        _err(E.E_INVALID_UNITARY_SIZE)
    return m.astype(np.complex128)


def validate_matrix_size(m, num_targets):
    _as_matrix(m, num_targets)


def validate_unitary(m, num_targets=None, eps=REAL_EPS_SINGLE):
    """max |U U+ - I| < eps (ref QuEST_validation.c:166-210; eps is
    REAL_EPS of the register's precision — pass eps_for(qureg))."""
    u = _as_matrix(m, num_targets)
    dev = np.abs(u @ u.conj().T - np.eye(u.shape[0])).max()
    if dev > eps:
        _err(E.E_NON_UNITARY_MATRIX)


def validate_unitary_complex_pair(alpha, beta, eps=REAL_EPS_SINGLE):
    """|alpha|^2+|beta|^2 == 1 (ref validateUnitaryComplexPair)."""
    mag = abs(complex(alpha)) ** 2 + abs(complex(beta)) ** 2
    if abs(mag - 1) > eps:
        _err(E.E_NON_UNITARY_COMPLEX_PAIR)


def validate_vector(v):
    x, y, z = float(v[0]), float(v[1]), float(v[2])
    if x * x + y * y + z * z < REAL_EPS_SINGLE ** 2:
        _err(E.E_ZERO_VECTOR)


def validate_kraus_ops(ops, num_targets, eps=REAL_EPS_SINGLE, max_ops=None):
    """Sum_k K+ K == I, i.e. the map is trace-preserving (CPTP)
    (ref QuEST_validation.c:212-239)."""
    ops = list(ops)
    if max_ops is None:
        max_ops = 1 << (2 * num_targets)
    if len(ops) < 1 or len(ops) > max_ops:
        if num_targets == 1:
            _err(E.E_INVALID_NUM_ONE_QUBIT_KRAUS_OPS)
        elif num_targets == 2:
            _err(E.E_INVALID_NUM_TWO_QUBIT_KRAUS_OPS)
        _err(E.E_INVALID_NUM_N_QUBIT_KRAUS_OPS)
    mats = []
    for op in ops:
        m = np.asarray(op)
        if m.ndim != 2 or m.shape[0] != m.shape[1] or \
                m.shape[0] != (1 << num_targets):
            _err(E.E_MISMATCHING_NUM_TARGS_KRAUS_SIZE)
        mats.append(m.astype(np.complex128))
    dim = 1 << num_targets
    acc = np.zeros((dim, dim), dtype=np.complex128)
    for op in mats:
        acc += op.conj().T @ op
    if np.abs(acc - np.eye(dim)).max() > eps:
        _err(E.E_INVALID_KRAUS_OPS)


# -- probabilities -----------------------------------------------------------

def validate_prob(p: float):
    if not (0 <= p <= 1):
        _err(E.E_INVALID_PROB)


def validate_one_qubit_dephase_prob(p: float):
    validate_prob(p)
    if p > 0.5:
        _err(E.E_INVALID_ONE_QUBIT_DEPHASE_PROB)


def validate_two_qubit_dephase_prob(p: float):
    validate_prob(p)
    if p > 3.0 / 4.0:
        _err(E.E_INVALID_TWO_QUBIT_DEPHASE_PROB)


def validate_one_qubit_depol_prob(p: float):
    validate_prob(p)
    if p > 3.0 / 4.0:
        _err(E.E_INVALID_ONE_QUBIT_DEPOL_PROB)


def validate_two_qubit_depol_prob(p: float):
    validate_prob(p)
    if p > 15.0 / 16.0:
        _err(E.E_INVALID_TWO_QUBIT_DEPOL_PROB)


def validate_one_qubit_damping_prob(p: float):
    validate_prob(p)


def validate_pauli_probs(px: float, py: float, pz: float):
    """Each error prob must not exceed the no-error prob
    (ref QuEST_validation.c:487-496)."""
    for p in (px, py, pz):
        validate_prob(p)
    prob_no_error = 1 - px - py - pz
    if px > prob_no_error or py > prob_no_error or pz > prob_no_error:
        _err(E.E_INVALID_ONE_QUBIT_PAULI_PROBS)


def validate_measurement_prob(p: float, eps: float):
    if p < eps:
        _err(E.E_COLLAPSE_STATE_ZERO_PROB)


def validate_density_matr(qureg):
    if not qureg.is_density:
        _err(E.E_DEFINED_ONLY_FOR_DENSMATRS)


def validate_state_vector(qureg):
    if qureg.is_density:
        _err(E.E_DEFINED_ONLY_FOR_STATEVECS)


def validate_num_pauli_sum_terms(n: int):
    if n < 1:
        _err(E.E_INVALID_NUM_SUM_TERMS)


def validate_pauli_targets(targets, paulis):
    if len(list(targets)) != len(list(paulis)):
        _err(E.E_INVALID_PAULI_CODE)


def validate_pauli_codes(codes):
    for c in np.asarray(codes).reshape(-1):
        if int(c) not in (0, 1, 2, 3):
            _err(E.E_INVALID_PAULI_CODE)
