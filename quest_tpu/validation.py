"""Eager, host-side input validation.

The reference funnels every user error through a 47-code table and an
overridable `invalidQuESTInputError` hook that defaults to exit(1)
(QuEST/src/QuEST_validation.c:26-148); its test suite overrides the hook to
throw. Here the natural design is simply a Python exception, raised eagerly
before any tracing/compilation happens, so bad inputs never reach XLA.

Error message prefixes intentionally mirror the reference's phrasing
("Invalid target qubit", "Invalid number of control qubits", ...) so that
message-matching tests carry over conceptually.
"""

from __future__ import annotations

import numpy as np


class QuESTError(ValueError):
    """Raised for any invalid user input (analogue of invalidQuESTInputError)."""


def _default_handler(msg: str, func: str = ""):
    # route through the overridable module-level hook (looked up at call
    # time so monkeypatching quest_tpu.api.invalidQuESTInputError works,
    # like redefining the reference's weak symbol, QuEST.h:3163-3190)
    try:
        from quest_tpu import api as _api
        _api.invalidQuESTInputError(msg, func)
    except ImportError:
        pass
    raise QuESTError(msg)


_error_handler = _default_handler


def set_error_handler(handler) -> None:
    """Override the invalid-input hook (the reference's overridable weak
    symbol invalidQuESTInputError, QuEST.h:3163-3190; default raises
    QuESTError). Pass None to restore the default."""
    global _error_handler
    _error_handler = handler if handler is not None else _default_handler


def _err(msg: str):
    import inspect
    # report the outermost quest_tpu function the USER called (the
    # reference hands __func__ of the public API fn to the hook) — walk
    # out of the validation helpers to the last quest_tpu frame
    func = ""
    frame = inspect.currentframe()
    try:
        f = frame.f_back if frame else None
        while f is not None:
            mod = f.f_globals.get("__name__", "")
            name = f.f_code.co_name
            if mod.startswith("quest_tpu") and not name.startswith("_"):
                func = name
            f = f.f_back
    finally:
        del frame
    _error_handler(msg, func)
    # a non-raising handler must not let execution continue into the op
    raise QuESTError(msg)


# -- register construction ---------------------------------------------------

def validate_num_qubits(num_qubits: int):
    if not isinstance(num_qubits, (int, np.integer)) or num_qubits < 1:
        _err("Invalid number of qubits: must be a positive integer.")
    if num_qubits > 60:
        _err("Invalid number of qubits: state would overflow the index type.")


def validate_state_index(qureg, index: int):
    dim = 1 << qureg.num_qubits
    if not (0 <= index < dim):
        _err("Invalid state index: must be in [0, 2^numQubits).")


def validate_amp_index(qureg, index: int, dim=None):
    dim = dim if dim is not None else qureg.num_amps
    if not (0 <= index < dim):
        _err("Invalid amplitude index: must be in [0, numAmps).")


def validate_num_amps(qureg, start: int, num: int):
    if start < 0 or num < 0 or start + num > qureg.num_amps:
        _err("Invalid number of amplitudes: slice exceeds the register.")


def validate_equal_lengths(reals, imags):
    if np.asarray(reals).size != np.asarray(imags).size:
        _err("Invalid number of amplitudes: real and imaginary lists must "
             "have equal length.")


def validate_match(a, b):
    if a.num_qubits != b.num_qubits:
        _err("Invalid Qureg pair: dimensions must match.")


def validate_pure_state_args(qureg, pure):
    if pure.is_density:
        _err("Invalid operation: second argument must be a statevector.")
    if qureg.num_qubits != pure.num_qubits:
        _err("Invalid Qureg pair: dimensions must match.")


# -- qubit indices -----------------------------------------------------------

def validate_target(qureg, target: int):
    if not (0 <= target < qureg.num_qubits):
        _err("Invalid target qubit. Must be >=0 and <numQubits.")


def validate_control_target(qureg, control: int, target: int):
    validate_target(qureg, target)
    validate_target(qureg, control)
    if control == target:
        _err("Control qubit cannot equal target qubit.")


def validate_unique_targets(qureg, qubit1: int, qubit2: int):
    validate_target(qureg, qubit1)
    validate_target(qureg, qubit2)
    if qubit1 == qubit2:
        _err("Qubits must be unique.")


def validate_multi_targets(qureg, targets, num_targets=None):
    targets = list(targets)
    n = len(targets) if num_targets is None else num_targets
    if n < 1 or n > qureg.num_qubits:
        _err("Invalid number of target qubits.")
    for t in targets:
        validate_target(qureg, t)
    if len(set(targets)) != len(targets):
        _err("Qubits must be unique.")


def validate_multi_controls(qureg, controls):
    controls = list(controls)
    if len(controls) >= qureg.num_qubits:
        _err("Invalid number of control qubits.")
    for c in controls:
        validate_target(qureg, c)
    if len(set(controls)) != len(controls):
        _err("Qubits must be unique.")


def validate_multi_controls_targets(qureg, controls, targets):
    validate_multi_controls(qureg, controls)
    validate_multi_targets(qureg, targets)
    if set(controls) & set(targets):
        _err("Control and target qubits must be disjoint.")


def validate_control_states(controls, states):
    states = list(states)
    if len(states) != len(list(controls)):
        _err("Invalid control state: must give one state per control qubit.")
    for s in states:
        if s not in (0, 1):
            _err("Invalid control state: each must be 0 or 1.")


def validate_outcome(outcome: int):
    if outcome not in (0, 1):
        _err("Invalid measurement outcome. Must be 0 or 1.")


# -- numeric operator checks -------------------------------------------------

def _as_matrix(m, num_targets=None) -> np.ndarray:
    m = np.asarray(m)
    if m.ndim != 2 or m.shape[0] != m.shape[1]:
        _err("Invalid matrix: must be square.")
    dim = m.shape[0]
    if dim & (dim - 1) or dim < 2:
        _err("Invalid matrix: dimension must be a power of 2.")
    if num_targets is not None and dim != (1 << num_targets):
        _err("Invalid matrix: dimension must be 2^numTargets.")
    return m.astype(np.complex128)


def validate_matrix_size(m, num_targets):
    _as_matrix(m, num_targets)


def validate_unitary(m, num_targets=None, eps=1e-4):
    """||U U+ - I|| elementwise < eps (ref QuEST_validation.c:166-210)."""
    u = _as_matrix(m, num_targets)
    dev = np.abs(u @ u.conj().T - np.eye(u.shape[0])).max()
    if dev > eps:
        _err("Invalid unitary matrix: U U† deviates from the identity.")


def validate_unitary_complex_pair(alpha, beta, eps=1e-4):
    """|alpha|^2+|beta|^2 == 1 (ref validateUnitaryComplexPair)."""
    mag = abs(complex(alpha)) ** 2 + abs(complex(beta)) ** 2
    if abs(mag - 1) > eps:
        _err("Invalid alpha/beta pair: |alpha|^2 + |beta|^2 must equal 1.")


def validate_vector(v):
    x, y, z = float(v[0]), float(v[1]), float(v[2])
    if x * x + y * y + z * z < 1e-24:
        _err("Invalid axis vector: must have non-zero magnitude.")


def validate_kraus_ops(ops, num_targets, eps=1e-4, max_ops=None):
    """Sum_k K+ K == I, i.e. the map is trace-preserving (CPTP)
    (ref QuEST_validation.c:212-239)."""
    ops = [(_as_matrix(op, num_targets)) for op in ops]
    if len(ops) < 1:
        _err("Invalid number of Kraus operators: must give at least one.")
    if max_ops is not None and len(ops) > max_ops:
        _err("Invalid number of Kraus operators: too many for this map size.")
    dim = 1 << num_targets
    acc = np.zeros((dim, dim), dtype=np.complex128)
    for op in ops:
        acc += op.conj().T @ op
    if np.abs(acc - np.eye(dim)).max() > eps:
        _err("Invalid Kraus map: operators do not form a completely "
             "positive trace-preserving map.")


# -- probabilities -----------------------------------------------------------

def validate_prob(p: float):
    if not (0 <= p <= 1):
        _err("Invalid probability: must be in [0, 1].")


def validate_one_qubit_dephase_prob(p: float):
    validate_prob(p)
    if p > 0.5:
        _err("Invalid probability: one-qubit dephasing cannot exceed 1/2.")


def validate_two_qubit_dephase_prob(p: float):
    validate_prob(p)
    if p > 3.0 / 4.0:
        _err("Invalid probability: two-qubit dephasing cannot exceed 3/4.")


def validate_one_qubit_depol_prob(p: float):
    validate_prob(p)
    if p > 3.0 / 4.0:
        _err("Invalid probability: one-qubit depolarising cannot exceed 3/4.")


def validate_two_qubit_depol_prob(p: float):
    validate_prob(p)
    if p > 15.0 / 16.0:
        _err("Invalid probability: two-qubit depolarising cannot exceed 15/16.")


def validate_one_qubit_damping_prob(p: float):
    validate_prob(p)


def validate_pauli_probs(px: float, py: float, pz: float):
    """Each error prob must not exceed the no-error prob
    (ref QuEST_validation.c:487-496)."""
    for p in (px, py, pz):
        validate_prob(p)
    prob_no_error = 1 - px - py - pz
    if px > prob_no_error or py > prob_no_error or pz > prob_no_error:
        _err("Invalid probability: the probability of any X, Y or Z error "
             "cannot exceed the probability of no error.")


def validate_measurement_prob(p: float, eps: float):
    if p < eps:
        _err("Invalid collapse: outcome probability is zero.")


def validate_density_matr(qureg):
    if not qureg.is_density:
        _err("Invalid operation: a density matrix is required.")


def validate_state_vector(qureg):
    if qureg.is_density:
        _err("Invalid operation: a state-vector is required.")


def validate_num_pauli_sum_terms(n: int):
    if n < 1:
        _err("Invalid number of terms in the Pauli sum.")


def validate_pauli_targets(targets, paulis):
    if len(list(targets)) != len(list(paulis)):
        _err("Invalid Pauli code list: must give one code per target qubit.")


def validate_pauli_codes(codes):
    for c in np.asarray(codes).reshape(-1):
        if int(c) not in (0, 1, 2, 3):
            _err("Invalid Pauli code: must be 0 (I), 1 (X), 2 (Y) or 3 (Z).")
