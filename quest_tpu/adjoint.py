"""Adjoint differentiation — O(1)-memory gradients at full width.

The taped reverse pass of `jax.grad` through a variational circuit
holds one residual state per parametric gate: at 30 qubits a 40-layer
ansatz wants ~40 state copies of HBM, so training is pinned at toy
widths. The adjoint method (PennyLane-Lightning's flagship for the
same reason, arXiv:2508.13615) needs THREE live registers total,
independent of parameter count and depth:

    E(theta) = <psi0| U(theta)+ H U(theta) |psi0>

    forward:   psi_L = U_L ... U_1 |psi0>            (one sweep)
    seed:      lambda = H |psi_L>                    (fused Pauli-sum
                                                      operator apply)
    backward, k = L..1 (gradient BEFORE un-apply):
        rotation  U_k = exp(-i s theta/2 P):
                       dE/dtheta_k += w * s * Im <lambda| P |psi>
        projector U_k = exp(+i s theta Proj):
                       dE/dtheta_k += w * s * Im <lambda| Proj |psi>
        psi    <- U_k+ psi        (gates are unitary: the inverse op
        lambda <- U_k+ lambda      stream is exact — circuit.inverse_op)

with w = 1 (rotations) / -2 (projectors) on statevectors and w = 1/2 /
-1 per copy on the doubled density register, where each gate and its
column-space dual (`circuit.dual_of`) SHARE one parameter index and the
dual flips the angle sign per family (`_DUAL_S`).

The per-parameter overlap rides the fused expectation geometry
(ops/expec `_group_view` / `_parity_tables`): the generator of every
parametric family is a signed Pauli-with-projector in flip form
(x/zy/ny + a control mask), so Im<lambda|G|psi> is ONE elementwise
sweep — no generator matrix is ever formed. Constant gate runs between
parameters band-fuse through `fusion.fixed_run_plan` exactly like the
forward engines.

Surface: `value_and_grad(target, hamiltonian)` returns a jitted
`fn(theta) -> (E, dE/dtheta)` built on `jax.custom_vjp`, so optimizer
loops, `variational.sweep` and `jax.vmap` are oblivious. Program-key
discipline: equal specs return the SAME cached callable (value-keyed,
`_GUARDED_BY(_CACHE_LOCK)`), so a rebuilt loop retraces nothing.
Engine selection (`QUEST_ADJOINT` knob, default auto) is priced into
the plan IR — `plan.autotune` grows a grad axis querying
`grad_record()` here, incumbent(taped)-wins-ties (docs/AUTODIFF.md,
docs/PLANNING.md).

Sharded: the same walk runs inside one shard_map body per direction
(forward+energy, backward), the backward op stream riding the exact
kernels of parallel/sharded.py (`_parity_op`, `_butterfly_1q`,
`_apply_gateop`); predicted exchanges are asserted against the lowered
HLO like every other engine (tests/test_adjoint.py).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import circuit as CC
from quest_tpu import precision
from quest_tpu.ops import apply as A
from quest_tpu.ops import expec as E
from quest_tpu.validation import QuESTError


class AdjointError(QuESTError):
    """A target the adjoint engine cannot differentiate — always names
    the offending op/mode. Measurements, noise channels and classical
    control have no inverse stream; traced operands have no concrete
    angle to recover (circuit.as_rotation)."""


# ---------------------------------------------------------------------------
# the program: parametric entries + fused constant runs
# ---------------------------------------------------------------------------


#: generator flip form per rotation family: targets -> (x_bits, zy_bits,
#: ny) of the signed Pauli G in U = exp(-i s theta/2 G)
_ROT_FORMS = {
    "parity": lambda targets: ((), tuple(targets), 0),
    "rx": lambda targets: ((targets[0],), (), 0),
    "ry": lambda targets: ((targets[0],), (targets[0],), 1),
}

#: density column-dual angle sign per family: conj(U(theta)) = U(s*theta)
#: (rx/parity/phase/allones conjugate to the negated angle; ry is real)
_DUAL_S = {"parity": -1.0, "rx": -1.0, "ry": 1.0,
           "phase": -1.0, "allones": -1.0}

_REJECT_KINDS = {"superop": "noise channels",
                 "measure": "measurements",
                 "measure_dm": "measurements",
                 "classical": "classically-controlled gates"}


@dataclasses.dataclass(frozen=True)
class _Param:
    """One parametric gate occurrence. `kind` 'rot' is
    U = exp(-i s theta/2 P_mask (x) G), 'proj' is
    U = exp(+i s theta Proj(mask)); the overlap reads the flip form
    (x/zy/ny) under the (mask_bits, mask_states) control projector."""
    pidx: int
    family: str
    kind: str                    # 'rot' | 'proj'
    targets: Tuple[int, ...]
    controls: Tuple[int, ...]
    cstates: Tuple[int, ...]
    s: float                     # angle sign (column duals flip it)
    w: float                     # overlap weight (register-kind factor)
    x_bits: Tuple[int, ...]
    zy_bits: Tuple[int, ...]
    ny: int
    mask_bits: Tuple[int, ...]
    mask_states: Tuple[int, ...]


@dataclasses.dataclass(frozen=True)
class _Fixed:
    """A constant gate run between parameters: `fwd`/`inv` apply the
    band-fused run (and its exact inverse) to (2, 2^n) planes; `ops` /
    `inv_ops` keep the raw GateOp streams for the sharded walk and the
    comm predictor (None for trotter frame blocks, which are
    single-device)."""
    fwd: Callable
    inv: Callable
    ops: Optional[Tuple] = None
    inv_ops: Optional[Tuple] = None

    def __hash__(self):          # entries live inside hashable programs
        return id(self)


@dataclasses.dataclass(frozen=True)
class _Program:
    n: int                       # register qubits (2N for density)
    density: bool
    entries: Tuple
    num_params: int

    def __hash__(self):
        return id(self)


def _rot_param(pidx, family, targets, controls, cstates, s, w):
    x, zy, ny = _ROT_FORMS[family](targets)
    return _Param(pidx, family, "rot", targets, controls, cstates,
                  s, w, x, zy, ny, controls, cstates)


def _proj_param(pidx, family, targets, controls, cstates, s, w):
    mask_bits = targets + controls
    mask_states = (1,) * len(targets) + cstates
    return _Param(pidx, family, "proj", targets, controls, cstates,
                  s, w, (), (), 0, mask_bits, mask_states)


def _param_entry(op, family, pidx, density, col, N):
    shift = N if col else 0
    targets = tuple(t + shift for t in op.targets)
    controls = tuple(c + shift for c in op.controls)
    cstates = tuple(op.cstates) if op.cstates else (1,) * len(controls)
    s = _DUAL_S[family] if col else 1.0
    if family in _ROT_FORMS:
        w = 0.5 if density else 1.0
        return _rot_param(pidx, family, targets, controls, cstates, s, w)
    w = -1.0 if density else -2.0
    return _proj_param(pidx, family, targets, controls, cstates, s, w)


def _make_fixed(ops, n):
    from quest_tpu.ops import fusion as F
    ops = tuple(ops)
    inv_ops = tuple(CC.inverse_op(op) for op in reversed(ops))
    fwd_items = F.fixed_run_plan(ops, n)
    inv_items = F.fixed_run_plan(inv_ops, n)

    def fwd(amps, _items=tuple(fwd_items), _n=n):
        return CC._apply_banded_items(amps, _n, _items)

    def inv(amps, _items=tuple(inv_items), _n=n):
        return CC._apply_banded_items(amps, _n, _items)

    return _Fixed(fwd=fwd, inv=inv, ops=ops, inv_ops=inv_ops)


def build_circuit_program(circuit, density: bool):
    """(program, theta0) for a Circuit: parametric ops (everything
    `circuit.as_rotation` recovers) become `_Param` entries sharing one
    theta index with their density dual; constant runs band-fuse into
    `_Fixed` blocks. Rejects loudly — typed, naming the op — on
    anything it cannot differentiate."""
    from quest_tpu.ops import fusion as F
    N = circuit.num_qubits
    n = 2 * N if density else N
    entries = []
    theta0 = []
    run = []

    def flush():
        if run:
            entries.append(_make_fixed(run, n))
            run.clear()

    for idx, op in enumerate(circuit.ops):
        if op.kind in _REJECT_KINDS:
            raise AdjointError(
                f"Invalid adjoint target: op {idx} ({_REJECT_KINDS[op.kind]}"
                f") is not differentiable — the backward walk needs an "
                f"exact inverse stream")
        if not F._concrete(op.operand):
            raise AdjointError(
                f"Invalid adjoint target: op {idx} ({op.kind}) carries a "
                f"traced operand; adjoint differentiation recovers angles "
                f"from CONCRETE gates (circuit.as_rotation)")
        rot = CC.as_rotation(op)
        if rot is None:
            run.append(op)
            if density:
                d = CC.dual_of(op, N)
                if d is not None:
                    run.append(d)
            continue
        family, th = rot
        pidx = len(theta0)
        theta0.append(th)
        flush()
        entries.append(_param_entry(op, family, pidx, density, False, N))
        if density:
            entries.append(_param_entry(op, family, pidx, density, True, N))
    flush()
    program = _Program(n=n, density=density, entries=tuple(entries),
                       num_params=len(theta0))
    return program, np.asarray(theta0, dtype=np.float64)


def build_trotter_program(ansatz):
    """(program, angle_meta) for an `evolution.trotter_ansatz` callable:
    the Strang schedule (`evolution.step_schedule`) replays gate-by-gate
    — frame band changes as `_Fixed` blocks, every parity-phase
    occurrence as a `_Param` — so the walk differentiates EXACTLY the
    program `evolve_planes` runs. `angle_meta` = (idx, scale) arrays
    mapping params=(coeffs, dt) onto the per-occurrence theta vector
    theta_e = 2 * dt * coeffs[idx_e] * scale_e (jax chains the VJP of
    that map onto the custom adjoint VJP automatically). Identity terms
    are a global phase — E-invariant, zero gradient — and are skipped."""
    import quest_tpu.evolution as EV
    key = getattr(ansatz, "program_key", None)
    if not (isinstance(key, tuple) and key and key[0] == "trotter_ansatz"):
        raise AdjointError(
            "Invalid adjoint target: expected a Circuit or an "
            "evolution.trotter_ansatz callable (program_key contract)")
    _, codes_key, n, order, steps, imag_time = key
    if imag_time:
        raise AdjointError(
            "Invalid adjoint target: imaginary-time evolution is "
            "non-unitary — the backward walk cannot invert the decay")
    plan = EV._plan_trotter(codes_key)
    sched = EV.step_schedule(plan, order)
    entries = []
    idxs, scales = [], []

    def add_parity(i, scale):
        pidx = len(idxs)
        idxs.append(i)
        scales.append(scale)
        targets = tuple(plan.supports[i])
        entries.append(_rot_param(pidx, "parity", targets, (), (),
                                  1.0, 1.0))

    def band_fixed(bands, forward):
        if forward:
            def go(amps, _b=bands, _n=n):
                for ql, w, fp, _ip in _b:
                    amps = A.apply_band(amps, _n, fp, ql, w, ())
                return amps

            def back(amps, _b=bands, _n=n):
                for ql, w, _fp, ip in reversed(_b):
                    amps = A.apply_band(amps, _n, ip, ql, w, ())
                return amps
        else:
            def go(amps, _b=bands, _n=n):
                for ql, w, _fp, ip in _b:
                    amps = A.apply_band(amps, _n, ip, ql, w, ())
                return amps

            def back(amps, _b=bands, _n=n):
                for ql, w, fp, _ip in reversed(_b):
                    amps = A.apply_band(amps, _n, fp, ql, w, ())
                return amps
        return _Fixed(fwd=go, inv=back)

    for _ in range(int(steps)):
        for (kind, payload), scale in sched:
            if kind == "diag":
                for i in payload:
                    add_parity(i, scale)
            else:
                bands = EV._frame_band_ops(payload.axes, n)
                entries.append(band_fixed(bands, True))
                for i in payload.terms:
                    add_parity(i, scale)
                entries.append(band_fixed(bands, False))
    program = _Program(n=n, density=False, entries=tuple(entries),
                       num_params=len(idxs))
    return program, (np.asarray(idxs, np.int32),
                     np.asarray(scales, np.float64))


# ---------------------------------------------------------------------------
# primitives: the masked Im-overlap and the parametric appliers
# ---------------------------------------------------------------------------


def _control_tables(ranges, bits, states, rdt):
    """[(axis, 0/1 table)] control-projector companion of
    expec._parity_tables: table[v] = 1 iff every listed bit inside the
    axis' chunk matches its required state. Broadcast-multiplied along
    the group view, never 2^n-sized."""
    req = dict(zip(bits, states))
    out = []
    for ax, (lo, w) in enumerate(ranges):
        hit = [(b, req[b]) for b in range(lo, lo + w) if b in req]
        if not hit:
            continue
        idx = np.arange(1 << w)
        m = np.ones(1 << w, dtype=bool)
        for b, want in hit:
            m &= ((idx >> (b - lo)) & 1) == int(want)
        out.append((ax, m.astype(rdt)))
    return out


def _overlap_plane(lam, src_r, src_i, dims, k):
    """The Im((-i)^ny t) integrand plane of t = sum conj(lam)*psi_flip:
    ny even selects t_im, odd t_re; k in (1, 2) negates (the caller
    applies the negation to the reduced scalar)."""
    lr = lam[0].reshape(dims)
    li = lam[1].reshape(dims)
    if k % 2 == 0:
        return lr * src_i - li * src_r
    return lr * src_r + li * src_i


def _im_overlap(lam, psi, n, e: _Param):
    """Im <lambda| G |psi> of entry `e`'s generator (flip form x/zy/ny
    under the mask projector) — one fused elementwise sweep over the
    expec group view; the caller multiplies w*s."""
    dims, axis_of, ranges = E._group_view(n, e.x_bits)
    pr = psi[0].reshape(dims)
    pi = psi[1].reshape(dims)
    if e.x_bits:
        axes = [axis_of[q] for q in e.x_bits]
        pr = jnp.flip(pr, axes)
        pi = jnp.flip(pi, axes)
    k = e.ny % 4
    plane = _overlap_plane(lam, pr, pi, dims, k)
    rdt = np.dtype(plane.dtype)
    tabs = (E._parity_tables(ranges, e.zy_bits, rdt)
            + _control_tables(ranges, e.mask_bits, e.mask_states, rdt))
    plane = E._apply_sign_tables(plane, tabs, len(dims))
    acc = precision.accum_dtype(lam.dtype)
    val = jnp.sum(plane.astype(acc))
    if k in (1, 2):
        val = -val
    return val


def _apply_param(amps, n, e: _Param, ang):
    """Apply entry `e` at (already sign-folded) angle `ang` to (2, 2^n)
    planes — the single-device parametric applier, riding the
    variational gate set so taped and adjoint run the same kernels."""
    from quest_tpu import variational as V
    if e.family == "parity":
        return A.apply_parity_phase(amps, n, e.targets, ang)
    if e.family == "rx":
        return V.rx(amps, n, e.targets[0], ang, e.controls, e.cstates)
    if e.family == "ry":
        return V.ry(amps, n, e.targets[0], ang, e.controls, e.cstates)
    # proj families: e^{i ang} on the mask subspace
    t = jnp.asarray(ang, dtype=amps.dtype)
    q0 = e.mask_bits[0]
    s0 = e.mask_states[0]
    one = jnp.ones((), amps.dtype)
    zero = jnp.zeros((), amps.dtype)
    c, sn = jnp.cos(t), jnp.sin(t)
    dre = jnp.stack([one, c]) if s0 else jnp.stack([c, one])
    dim_ = jnp.stack([zero, sn]) if s0 else jnp.stack([sn, zero])
    return A.apply_diagonal(amps, n, (dre, dim_), (q0,),
                            tuple(e.mask_bits[1:]),
                            tuple(e.mask_states[1:]))


def _density_lambda(amps, cf, eplan):
    """The density bra seed: E = Re<lambda_planes, a_planes> is LINEAR
    in the doubled register, so lambda is exactly the gradient of the
    fused trace at any point — evaluated at zeros, one O(2^n) pass."""
    def f(a):
        return E.expec_traced(a, cf, eplan).astype(a.dtype)
    return jax.grad(f)(jnp.zeros_like(amps))


# ---------------------------------------------------------------------------
# single-device engine
# ---------------------------------------------------------------------------


def _forward_traced(theta, program: _Program, rdt, initial_index):
    from quest_tpu.state import basis_planes
    amps = basis_planes(initial_index, n=program.n, rdt=rdt)
    for e in program.entries:
        if isinstance(e, _Param):
            amps = _apply_param(amps, program.n, e, e.s * theta[e.pidx])
        else:
            amps = e.fwd(amps)
    return amps


def _build_single(program: _Program, eplan, cf0, rdt, initial_index):
    """energy(theta) with the custom adjoint VJP, single device."""
    n = program.n

    def _energy_of(amps):
        cf = jnp.asarray(cf0, dtype=amps.dtype)
        return E.expec_traced(amps, cf, eplan).astype(amps.dtype)

    def _state(theta):
        return _forward_traced(theta, program, rdt, initial_index)

    @jax.custom_vjp
    def energy(theta):
        return _energy_of(_state(theta))

    def energy_fwd(theta):
        amps = _state(theta)
        return _energy_of(amps), (amps, theta)

    def energy_bwd(res, ct):
        amps, theta = res
        cf = jnp.asarray(cf0, dtype=amps.dtype)
        if program.density:
            lam = _density_lambda(amps, cf, eplan)
        else:
            lam = E.apply_pauli_sum_planes(amps, cf, eplan)
        acc = precision.accum_dtype(amps.dtype)
        grads = [jnp.zeros((), dtype=acc)] * program.num_params
        for e in reversed(program.entries):
            if isinstance(e, _Param):
                g = _im_overlap(lam, amps, n, e)
                grads[e.pidx] = grads[e.pidx] + g * (e.w * e.s)
                ia = -e.s * theta[e.pidx]
                amps = _apply_param(amps, n, e, ia)
                lam = _apply_param(lam, n, e, ia)
            else:
                amps = e.inv(amps)
                lam = e.inv(lam)
        if grads:
            g = jnp.stack(grads).astype(theta.dtype) * ct
        else:
            g = jnp.zeros_like(theta)
        return (g,)

    energy.defvjp(energy_fwd, energy_bwd)
    return energy


def _taped_energy(program: _Program, eplan, cf0, rdt, initial_index):
    """The taped twin: the SAME forward trace, differentiated by plain
    jax reverse mode — the baseline adjoint is priced against, and the
    parity oracle in tests (identical parametrization by construction)."""
    def energy(theta):
        amps = _forward_traced(theta, program, rdt, initial_index)
        cf = jnp.asarray(cf0, dtype=amps.dtype)
        return E.expec_traced(amps, cf, eplan).astype(amps.dtype)
    return energy


# ---------------------------------------------------------------------------
# sharded engine (statevector circuits)
# ---------------------------------------------------------------------------


def _im_overlap_sharded(lam, psi, local_n, dev, D, e: _Param):
    """Per-shard Im <lambda| G |psi>: local flip bits flip in-shard, a
    global flip mask is one plain ppermute pair exchange, global zy
    bits fold into the device parity sign and global mask bits into a
    device predicate — the `_group_contrib_sharded` geometry applied to
    the adjoint overlap. Caller psums."""
    from quest_tpu.env import AMP_AXIS
    from quest_tpu.parallel import sharded as S
    lx = tuple(q for q in e.x_bits if q < local_n)
    gxm = 0
    for q in e.x_bits:
        if q >= local_n:
            gxm |= 1 << (q - local_n)
    src = psi
    if gxm:
        src = jax.lax.ppermute(psi, AMP_AXIS,
                               [(d, d ^ gxm) for d in range(D)])
    dims, axis_of, ranges = E._group_view(local_n, lx)
    sr = src[0].reshape(dims)
    si = src[1].reshape(dims)
    if lx:
        axes = [axis_of[q] for q in lx]
        sr = jnp.flip(sr, axes)
        si = jnp.flip(si, axes)
    k = e.ny % 4
    plane = _overlap_plane(lam, sr, si, dims, k)
    rdt = np.dtype(plane.dtype)
    loc = [(b, st) for b, st in zip(e.mask_bits, e.mask_states)
           if b < local_n]
    lzy = tuple(b for b in e.zy_bits if b < local_n)
    tabs = (E._parity_tables(ranges, lzy, rdt)
            + _control_tables(ranges, tuple(b for b, _ in loc),
                              tuple(st for _, st in loc), rdt))
    plane = E._apply_sign_tables(plane, tabs, len(dims))
    acc = precision.accum_dtype(lam.dtype)
    val = jnp.sum(plane.astype(acc))
    gzy = tuple(b - local_n for b in e.zy_bits if b >= local_n)
    if gzy:
        val = val * E._device_parity_sign(dev, gzy, acc)
    glob = [(b - local_n, st) for b, st in zip(e.mask_bits, e.mask_states)
            if b >= local_n]
    pred = S._global_pred(dev, glob)
    if pred is not None:
        val = jnp.where(pred, val, jnp.zeros((), acc))
    if k in (1, 2):
        val = -val
    return val


def _apply_param_sharded(chunk, dev, e: _Param, ang, D, local_n):
    """The sharded parametric applier: parity phases and local-target
    gates never communicate; a global-target rx/ry is one
    `_butterfly_1q` pair exchange with a TRACED 2x2; projectors split
    their mask into a device predicate + a local diagonal."""
    from quest_tpu.parallel import sharded as S
    from quest_tpu import variational as V
    if e.family == "parity":
        return S._parity_op(chunk, dev, local_n=local_n,
                            targets=e.targets, angle=ang)
    if e.family in ("rx", "ry"):
        t = e.targets[0]
        hh = jnp.asarray(ang, chunk.dtype) / 2.0
        c, sn = jnp.cos(hh), jnp.sin(hh)
        if e.family == "rx":
            pair = V._mat2(chunk, (c, None), (None, -sn), (None, -sn),
                           (c, None))
        else:
            pair = V._mat2(chunk, (c, None), (-sn, None), (sn, None),
                           (c, None))
        loc_c, loc_s, glob_c = S._split_controls(e.controls, e.cstates,
                                                 local_n)
        pred = S._global_pred(dev, glob_c)
        if t < local_n:
            new = A.apply_matrix(chunk, local_n, pair, (t,), loc_c, loc_s)
            if pred is not None:
                new = jnp.where(pred, new, chunk)
            return new
        return S._butterfly_1q(chunk, dev, D=D, local_n=local_n,
                               m_pair=pair, gbit=t - local_n,
                               loc_c=loc_c, loc_s=loc_s, pred=pred)
    # proj
    glob = [(b - local_n, st) for b, st in zip(e.mask_bits, e.mask_states)
            if b >= local_n]
    loc = [(b, st) for b, st in zip(e.mask_bits, e.mask_states)
           if b < local_n]
    t = jnp.asarray(ang, chunk.dtype)
    tre, tim = jnp.cos(t), jnp.sin(t)
    pred = S._global_pred(dev, glob)
    if pred is not None:
        tre = jnp.where(pred, tre, jnp.ones((), chunk.dtype))
        tim = jnp.where(pred, tim, jnp.zeros((), chunk.dtype))
    if loc:
        q0, s0 = loc[0]
        one = jnp.ones((), chunk.dtype)
        zero = jnp.zeros((), chunk.dtype)
        dre = jnp.stack([one, tre]) if s0 else jnp.stack([tre, one])
        dim_ = jnp.stack([zero, tim]) if s0 else jnp.stack([tim, zero])
        return A.apply_diagonal(chunk, local_n, (dre, dim_), (q0,),
                                tuple(b for b, _ in loc[1:]),
                                tuple(st for _, st in loc[1:]))
    re, im = chunk[0], chunk[1]
    return jnp.stack([re * tre - im * tim, re * tim + im * tre])


def _build_sharded(program: _Program, eplan, cf0, rdt, initial_index,
                   mesh):
    """energy(theta) with the custom adjoint VJP, one shard_map body per
    direction. The forward body runs the op walk + the fused per-shard
    energy partials (one psum); the backward body seeds lambda through
    `apply_pauli_sum_planes_sharded`, walks the inverse stream on both
    registers through the sharded kernels, and psums the stacked
    per-parameter partials ONCE."""
    from jax.sharding import PartitionSpec as P
    from quest_tpu import compat
    from quest_tpu.env import AMP_AXIS
    from quest_tpu.parallel import sharded as S

    if program.density:
        raise AdjointError(
            "Invalid adjoint target: sharded density registers are not "
            "supported by the adjoint engine (statevector meshes only)")
    D = int(mesh.devices.size)
    gbits = D.bit_length() - 1
    local_n = program.n - gbits
    n = program.n
    idx_local = int(initial_index) & ((1 << local_n) - 1)
    idx_dev = int(initial_index) >> local_n

    def _walk_fixed_ops(chunk, dev, ops):
        for op in ops:
            chunk = S._apply_gateop(chunk, dev, D=D, local_n=local_n,
                                    density=False, op=op)
        return chunk

    def fwd_body(theta):
        dev = jax.lax.axis_index(AMP_AXIS)
        pos = jnp.arange(1 << local_n)
        hit = jnp.equal(dev, idx_dev)
        re = jnp.where(hit & (pos == idx_local),
                       jnp.ones((), rdt), jnp.zeros((), rdt))
        chunk = jnp.stack([re, jnp.zeros_like(re)])
        for e in program.entries:
            if isinstance(e, _Param):
                chunk = _apply_param_sharded(chunk, dev, e,
                                             e.s * theta[e.pidx],
                                             D, local_n)
            else:
                chunk = _walk_fixed_ops(chunk, dev, e.ops)
        cf = jnp.asarray(cf0, dtype=chunk.dtype)
        acc = precision.accum_dtype(chunk.dtype)
        exchanged = {"__D__": D}
        total = jnp.zeros((), dtype=acc)
        for pack in eplan.sweeps:
            flat = None
            for gi in pack:
                c = E._group_contrib_sharded(chunk, cf, local_n, dev,
                                             eplan.groups[gi], exchanged)
                flat = c if flat is None else flat + c
            total = total + jnp.sum(flat.astype(acc))
        val = jax.lax.psum(total, AMP_AXIS).astype(chunk.dtype)
        return val, chunk

    fwd_run = compat.shard_map(fwd_body, mesh, (P(),),
                               (P(), P(None, AMP_AXIS)))

    def bwd_body(theta, chunk, ct):
        dev = jax.lax.axis_index(AMP_AXIS)
        cf = jnp.asarray(cf0, dtype=chunk.dtype)
        exchanged = {"__D__": D}
        lam = E.apply_pauli_sum_planes_sharded(chunk, cf, local_n, dev,
                                               eplan, exchanged)
        acc = precision.accum_dtype(chunk.dtype)
        parts = [jnp.zeros((), dtype=acc)] * program.num_params
        amps = chunk
        for e in reversed(program.entries):
            if isinstance(e, _Param):
                g = _im_overlap_sharded(lam, amps, local_n, dev, D, e)
                parts[e.pidx] = parts[e.pidx] + g * (e.w * e.s)
                ia = -e.s * theta[e.pidx]
                amps = _apply_param_sharded(amps, dev, e, ia, D, local_n)
                lam = _apply_param_sharded(lam, dev, e, ia, D, local_n)
            else:
                amps = _walk_fixed_ops(amps, dev, e.inv_ops)
                lam = _walk_fixed_ops(lam, dev, e.inv_ops)
        if parts:
            g = jax.lax.psum(jnp.stack(parts), AMP_AXIS)
            g = g.astype(theta.dtype) * ct
        else:
            g = jnp.zeros_like(theta)
        return g

    bwd_run = compat.shard_map(bwd_body, mesh,
                               (P(), P(None, AMP_AXIS), P()), P())

    @jax.custom_vjp
    def energy(theta):
        return fwd_run(theta)[0]

    def energy_fwd(theta):
        val, chunk = fwd_run(theta)
        return val, (theta, chunk)

    def energy_bwd(res, ct):
        theta, chunk = res
        return (bwd_run(theta, chunk, jnp.asarray(ct)),)

    energy.defvjp(energy_fwd, energy_bwd)

    def taped(theta):
        return fwd_run(theta)[0]

    return energy, taped


def predict_vjp_collectives(program: _Program, eplan, D: int) -> dict:
    """HOST-side predicted collective counts of ONE jitted
    value-and-grad application on D devices — mirrored 1:1 from the
    dispatch in `_build_sharded` (fixed ops through the same
    comm.gateop_exchanges routing the executor uses, parametric
    butterflies through effective_slices, expectation/seed exchanges one
    plain ppermute per distinct global flip mask) and asserted against
    introspect.parse_collectives of the lowered HLO in
    tests/test_adjoint.py — the no-drift discipline every sharded
    engine carries (docs/PARALLEL.md)."""
    from quest_tpu.parallel import comm as C
    gbits = D.bit_length() - 1
    local_n = program.n - gbits
    topo = C.topology(D)
    ici_b = topo.ici_bits(D) if topo.hierarchical else None
    m = 1 << local_n
    cps = a2as = 0

    def op_exchanges(ops):
        c = a = 0
        for op in ops:
            for kind, _elems, _g in C.gateop_exchanges(op, local_n, ici_b):
                if kind == "cp":
                    c += 1
                else:
                    a += 1
        return c, a

    def param_apply_cps(e):
        if e.family in ("rx", "ry") and e.targets[0] >= local_n:
            gbit = e.targets[0] - local_n
            return C.effective_slices(m, C._link(gbit, ici_b))
        return 0

    def gxm_of(x_bits):
        gxm = 0
        for q in x_bits:
            if q >= local_n:
                gxm |= 1 << (q - local_n)
        return gxm

    emasks = {gxm_of(g.x_bits) for g in eplan.groups} - {0}
    # forward body: the op walk + one exchange per distinct E flip mask
    for e in program.entries:
        if isinstance(e, _Param):
            cps += param_apply_cps(e)
        else:
            c, a = op_exchanges(e.ops)
            cps += c
            a2as += a
    cps += len(emasks)
    # backward body: the lambda seed shares nothing with the forward's
    # exchanges (separate shard_map body), then the walk un-applies
    # every entry to BOTH registers and each global-flip overlap is one
    # plain pair exchange
    cps += len(emasks)
    for e in program.entries:
        if isinstance(e, _Param):
            cps += 2 * param_apply_cps(e)
            if gxm_of(e.x_bits):
                cps += 1
        else:
            c, a = op_exchanges(e.inv_ops)
            cps += 2 * c
            a2as += 2 * a
    return {"collective_permutes": cps, "all_to_alls": a2as,
            "all_reduces": 2 if program.num_params else 1,
            "devices": D}


# ---------------------------------------------------------------------------
# capacity + pricing (the plan IR's grad axis)
# ---------------------------------------------------------------------------


def capacity_stats(n: int, num_params: int, depth: int,
                   dtype=np.float32) -> dict:
    """The grad-engine capacity model: adjoint holds THREE live state
    registers (psi, lambda, the overlap integrand fuses elementwise)
    plus O(masks) sign/control tables; taped reverse-mode holds one
    residual per parametric gate (the constant-gate VJPs are
    state-independent — the circuit is linear in the state) plus primal
    and cotangent. Bytes against the HBM budget (QUEST_HBM_BYTES
    override, else the v5e model) — the same budget every other
    capacity decision prices against (ops/apply.f64_capacity_stats,
    plan.sweep_chunk)."""
    from quest_tpu.env import knob_value
    rdt = precision.real_dtype_of(np.dtype(dtype))
    state_bytes = 2 * (1 << n) * rdt.itemsize
    seg = 1 << E._SEG_BITS
    mask_bytes = 4 * seg * rdt.itemsize * max(1, -(-n // E._SEG_BITS))
    hbm = knob_value("QUEST_HBM_BYTES")
    if hbm is None:
        hbm = A._V5E_HBM_BYTES
    adjoint_peak = 3 * state_bytes + mask_bytes
    taped_peak = (num_params + 2) * state_bytes
    return {
        "state_bytes": int(state_bytes),
        "hbm_bytes": int(hbm),
        "adjoint_peak_bytes": int(adjoint_peak),
        "adjoint_fits": bool(adjoint_peak <= hbm),
        "taped_residual_bytes": int(taped_peak),
        "taped_fits": bool(taped_peak <= hbm),
        "params": int(num_params),
        "depth": int(depth),
    }


def _engine_choice(cap: dict, knob: str) -> str:
    """The priced decision, incumbent-wins-ties: taped (the incumbent
    reverse-mode) keeps every width where its residuals fit; adjoint is
    selected only where taped CANNOT run and adjoint can — a strict
    capability extension, so no existing grad path regresses by
    construction (the plan.autotune `_rank` discipline applied to the
    grad axis)."""
    if knob == "0":
        return "taped"
    if knob == "1":
        return "adjoint"
    if cap["taped_fits"]:
        return "taped"
    if cap["adjoint_fits"]:
        return "adjoint"
    return "taped"


def grad_record(circuit, *, density: bool = False, dtype=np.float32,
                devices: Optional[int] = None) -> Optional[dict]:
    """The plan IR's grad axis for one circuit: parameter count, both
    engines' capacity rows, and the engine the QUEST_ADJOINT knob (or
    the capacity pricing, under 'auto') resolves to. None when the
    circuit has no parametric ops (nothing to differentiate — the grad
    axis stays silent rather than pricing a vacuous choice); a
    non-invertible circuit reports {'supported': False, ...} with the
    taped engine, which differentiates anything jax can trace."""
    from quest_tpu.env import knob_value
    knob = str(knob_value("QUEST_ADJOINT"))
    N = circuit.num_qubits
    n = 2 * N if density else N
    depth = len(circuit.ops)
    try:
        program, _theta0 = build_circuit_program(circuit, density)
    except AdjointError as err:
        num_params = 0
        for op in circuit.ops:
            if op.kind in _REJECT_KINDS:
                continue
            try:
                if CC.as_rotation(op) is not None:
                    num_params += 1
            except Exception:
                pass
        if num_params == 0:
            return None
        cap = capacity_stats(n, num_params, depth, dtype)
        return {"supported": False, "reason": str(err), "engine": "taped",
                "incumbent": "taped", "knob": knob, "params": num_params,
                "depth": depth, "taped": {
                    "residual_bytes": cap["taped_residual_bytes"],
                    "fits": cap["taped_fits"]}}
    if program.num_params == 0:
        return None
    cap = capacity_stats(n, program.num_params, depth, dtype)
    if devices:
        # per-device chunks: every register and residual shards evenly
        shard = max(1, int(devices))
        for key in ("adjoint_peak_bytes", "taped_residual_bytes",
                    "state_bytes"):
            cap[key] = int(cap[key] // shard)
        cap["taped_fits"] = cap["taped_residual_bytes"] <= cap["hbm_bytes"]
        cap["adjoint_fits"] = cap["adjoint_peak_bytes"] <= cap["hbm_bytes"]
    engine = _engine_choice(cap, knob)
    return {
        "supported": True,
        "params": int(program.num_params),
        "depth": depth,
        "engine": engine,
        "incumbent": "taped",
        "knob": knob,
        "taped": {"residual_bytes": cap["taped_residual_bytes"],
                  "fits": cap["taped_fits"]},
        "adjoint": {"peak_bytes": cap["adjoint_peak_bytes"],
                    "fits": cap["adjoint_fits"]},
    }


# ---------------------------------------------------------------------------
# the public surface
# ---------------------------------------------------------------------------


# compiled value-and-grad programs by VALUE (the program-key
# discipline: a rebuilt-but-equal spec returns the SAME callable, so
# optimizer loops retrace nothing). Bounded FIFO — value keys cannot
# be weak.
# _GUARDED_BY(_CACHE_LOCK): _FN_CACHE
_FN_CACHE: "OrderedDict[tuple, Callable]" = OrderedDict()
_CACHE_LOCK = threading.Lock()
_FN_CACHE_MAX = 32


def _resolve_observable(hamiltonian, coeffs, num_qubits):
    if isinstance(hamiltonian, E.PauliSum):
        if coeffs is not None:
            raise ValueError("pass coefficients inside the PauliSum, not "
                             "as a separate coeffs= argument")
        codes_key = E.parse_pauli_sum(np.asarray(hamiltonian.codes),
                                      num_qubits)
        cf = np.asarray(hamiltonian.coeffs, dtype=np.float64)
    else:
        codes_key = E.parse_pauli_sum(hamiltonian, num_qubits)
        cf = np.asarray(coeffs, dtype=np.float64).reshape(-1)
    if len(cf) != len(codes_key):
        from quest_tpu import validation as val
        val._err("Invalid Pauli sum: must give exactly one coefficient "
                 "per term.")
    return codes_key, cf


def _freeze(x):
    if isinstance(x, list):
        return tuple(_freeze(i) for i in x)
    return x


def _circuit_key(circuit):
    from quest_tpu import plan as PL
    fps = []
    for i, op in enumerate(circuit.ops):
        fp = PL._op_fingerprint(op)
        if fp is None:
            raise AdjointError(
                f"Invalid adjoint target: op {i} ({op.kind}) carries a "
                f"traced operand; adjoint differentiation needs concrete "
                f"gates")
        fps.append(_freeze(fp))
    return ("circuit", circuit.num_qubits, tuple(fps))


def value_and_grad(target, hamiltonian, *, coeffs=None,
                   initial_index: int = 0, dtype=np.float32,
                   density: bool = False, mesh=None,
                   engine: Optional[str] = None) -> Callable:
    """`fn(theta) -> (E, dE/dtheta)` for `target` (a Circuit, or an
    `evolution.trotter_ansatz` callable taking params=(coeffs, dt))
    against the Pauli-sum `hamiltonian` — the gradient engine behind it
    resolved by `engine` ('adjoint' | 'taped' | 'auto'; default the
    QUEST_ADJOINT knob). Both engines differentiate the SAME forward
    parametrization, so they agree to numerical precision
    (tests/test_adjoint.py pins parity and the docs/AUTODIFF.md
    contract).

    The returned callable is jitted, cached by VALUE (equal specs —
    ops, observable, dtype, mesh, keyed knobs — return the identical
    object: zero-retrace optimizer loops), carries the
    `variational.sweep` geometry tags (num_qubits/real_dtype/sweep_key)
    and exposes `initial_params` (a Circuit target's recovered angles),
    `engine`, `num_params`, and — sharded — `comm_record`, the
    predicted collective counts of one application."""
    from quest_tpu.env import engine_mode_key, knob_value

    is_circuit = isinstance(target, CC.Circuit)
    if is_circuit:
        nq = target.num_qubits
        tkey = _circuit_key(target)
    else:
        pk = getattr(target, "program_key", None)
        if not (isinstance(pk, tuple) and pk
                and pk[0] == "trotter_ansatz"):
            raise AdjointError(
                "Invalid adjoint target: expected a Circuit or an "
                "evolution.trotter_ansatz callable, got "
                f"{type(target).__name__!r}")
        nq = target.num_qubits
        tkey = pk
    codes_key, cf0 = _resolve_observable(hamiltonian, coeffs, nq)
    rdt = precision.real_dtype_of(np.dtype(dtype))
    if engine not in (None, "auto", "adjoint", "taped"):
        raise ValueError(f"engine must be 'adjoint', 'taped' or 'auto', "
                         f"got {engine!r}")

    devices_key = None
    if mesh is not None:
        devices_key = (int(mesh.devices.size),
                       tuple(str(d) for d in mesh.devices.flat))
    key = (tkey, codes_key, cf0.tobytes(), int(initial_index), rdt.str,
           bool(density), devices_key, engine, engine_mode_key())
    with _CACHE_LOCK:
        fn = _FN_CACHE.get(key)
        if fn is not None:
            return fn

    if is_circuit:
        program, theta0 = build_circuit_program(target, density)
        angle_meta = None
    else:
        if mesh is not None:
            raise AdjointError(
                "Invalid adjoint target: sharded trotter ansatz gradients "
                "are not supported (single-device registers only)")
        if density:
            raise AdjointError(
                "Invalid adjoint target: trotter ansatz gradients run on "
                "statevector registers only")
        program, angle_meta = build_trotter_program(target)
        theta0 = None

    eplan = E.plan_expec(codes_key, nq, density=density)
    # density layout: flat = row + col*2^N, so |i><i| sits at i*(2^N+1)
    init_flat = (int(initial_index) * ((1 << nq) + 1) if density
                 else int(initial_index))

    resolved = engine
    if resolved in (None, "auto"):
        knob = str(knob_value("QUEST_ADJOINT"))
        if knob in ("0", "1"):
            resolved = {"0": "taped", "1": "adjoint"}[knob]
        else:
            cap = capacity_stats(program.n, program.num_params,
                                 len(program.entries), rdt)
            resolved = _engine_choice(cap, "auto")

    comm_record = None
    if mesh is not None and int(mesh.devices.size) > 1:
        adjoint_e, taped_e = _build_sharded(program, eplan, cf0, rdt,
                                            init_flat, mesh)
        if resolved == "adjoint":
            comm_record = predict_vjp_collectives(
                program, eplan, int(mesh.devices.size))
        energy = adjoint_e if resolved == "adjoint" else taped_e
    elif resolved == "adjoint":
        energy = _build_single(program, eplan, cf0, rdt, init_flat)
    else:
        energy = _taped_energy(program, eplan, cf0, rdt, init_flat)

    if is_circuit:
        jitted = jax.jit(jax.value_and_grad(energy))
    else:
        idx_arr, scale_arr = angle_meta

        def param_energy(params):
            cfv, dt = params
            cfv = jnp.asarray(cfv)
            dt = jnp.asarray(dt, cfv.dtype)
            theta = (2.0 * dt * cfv[jnp.asarray(idx_arr)]
                     * jnp.asarray(scale_arr, cfv.dtype))
            return energy(theta.astype(rdt))

        jitted = jax.jit(jax.value_and_grad(param_energy))

    # thin wrapper: jit callables reject attribute assignment, and the
    # sweep/bench surfaces need the geometry tags on the object itself
    def fn(params):
        return jitted(params)

    fn.jitted = jitted               # .lower() access for HLO asserts
    fn.num_qubits = nq
    fn.real_dtype = rdt.str
    fn.engine = resolved
    fn.num_params = program.num_params
    fn.initial_params = theta0
    fn.comm_record = comm_record
    fn.sweep_key = ("adjoint.value_and_grad",) + key
    with _CACHE_LOCK:
        _FN_CACHE[key] = fn
        while len(_FN_CACHE) > _FN_CACHE_MAX:
            _FN_CACHE.popitem(last=False)
    return fn
