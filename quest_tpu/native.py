"""ctypes bindings to the native host runtime (native/quest_host.cpp).

Provides the reference-exact MT19937 RNG (init_by_array seeding +
genrand_real1 draws — for identical seeds the measurement outcome stream
matches the reference binary bit-for-bit) and fast CSV state IO.

The shared library is built lazily with the in-tree Makefile on first use;
if no C++ toolchain is available everything degrades gracefully (callers
check `available()` and fall back to Python implementations).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
# QUEST_NATIVE_LIB overrides the library (e.g. libquest_host_asan.so in
# the ASan CI job, run with LD_PRELOAD=libasan)
from quest_tpu.env import knob_value as _knob_value

_LIB_PATH = (_knob_value("QUEST_NATIVE_LIB")
             or os.path.join(_NATIVE_DIR, "libquest_host.so"))

_lib = None
_lib_tried = False
_lock = threading.Lock()
_degrade_warned = False


def _warn_degrade(reason: str) -> None:
    """One warning per process when the native library is unavailable:
    callers silently fall back to the Python implementations (same
    results, slower), and a silent fallback hid a dead toolchain for a
    whole bench run once — loud ONCE, then quiet (every native.py entry
    point re-checks `_load()` on each call, so repeating it would spam
    a warning per RNG draw)."""
    global _degrade_warned
    if _degrade_warned:
        return
    _degrade_warned = True
    import sys
    print(f"[quest_tpu.native] native host library unavailable "
          f"({reason}); degrading to the pure-Python fallbacks — same "
          f"results, slower (build native/ or set QUEST_NATIVE_LIB)",
          file=sys.stderr, flush=True)


def _build() -> bool:
    try:
        r = subprocess.run(["make", "-C", _NATIVE_DIR],
                           capture_output=True, timeout=120)
        return r.returncode == 0 and os.path.exists(_LIB_PATH)
    except (OSError, subprocess.TimeoutExpired):
        return False


def _isa_ok(lib: ctypes.CDLL) -> bool:
    """Whether this machine supports the ISA extensions the library was
    built with (-march=native makes prebuilt .so files CPU-specific; a
    copied library on an older host would SIGILL with no diagnostics, so
    mismatches trigger a rebuild instead)."""
    try:
        fn = lib.qh_isa_requirements
    except AttributeError:
        return False        # predates the tag: rebuild
    fn.restype = ctypes.c_char_p
    req = fn().decode().split()
    if not req:
        return True
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    have = set(line.split(":", 1)[1].split())
                    return all(r in have for r in req)
    except OSError:
        pass
    return True             # can't introspect the CPU: assume ok


def _try_open() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_LIB_PATH):
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
        _bind(lib)
        return lib
    except (OSError, AttributeError):
        return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        lib = _try_open()
        if lib is None or not _isa_ok(lib):
            # missing, stale (symbol set predates this tree) or built for
            # a different CPU: rebuild once — the Makefile links to a
            # temp name and rename(2)s, so the path gets a NEW inode (an
            # already-mapped old library stays valid) and a fresh dlopen
            # really sees the rebuilt code
            if not _build():
                _warn_degrade("no library and the in-tree build failed")
                return None
            lib = _try_open()
            if lib is None or not _isa_ok(lib):
                # degrade to the Python fallbacks
                _warn_degrade("rebuilt library failed to load")
                return None
        _lib = lib
        return _lib


def load_with(binder) -> Optional[ctypes.CDLL]:
    """The shared load-bind-rebuild dance for extension modules binding
    EXTRA symbols (e.g. quest_tpu/host.py): returns the core library
    with `binder(lib)` applied, rebuilding once if the on-disk library
    predates the symbols the binder needs. One home for the retry logic
    (ADVICE/code-review r5: host.py re-implemented it)."""
    lib = _load()
    if lib is None:
        return None
    try:
        binder(lib)
        return lib
    except AttributeError:
        if not _build():
            return None
        try:
            fresh = ctypes.CDLL(_LIB_PATH)
            _bind(fresh)
            binder(fresh)
            return fresh
        except (OSError, AttributeError):
            return None


def _bind(lib: ctypes.CDLL) -> None:
    lib.qh_init_genrand.argtypes = [ctypes.c_uint32]
    lib.qh_init_by_array.argtypes = [
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_int]
    lib.qh_genrand_int32.restype = ctypes.c_uint32
    lib.qh_genrand_real1.restype = ctypes.c_double
    lib.qh_write_state_csv.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.c_longlong, ctypes.c_int]
    lib.qh_write_state_csv.restype = ctypes.c_int
    lib.qh_append_state_csv.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.c_longlong]
    lib.qh_append_state_csv.restype = ctypes.c_int
    lib.qh_read_state_csv.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_double), ctypes.c_longlong]
    lib.qh_read_state_csv.restype = ctypes.c_longlong

def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# MT19937 (reference mt19937ar.c semantics)
# ---------------------------------------------------------------------------


def init_by_array(seeds) -> bool:
    lib = _load()
    if lib is None:
        return False
    arr = (ctypes.c_uint32 * len(seeds))(
        *[int(s) & 0xFFFFFFFF for s in seeds])
    lib.qh_init_by_array(arr, len(seeds))
    return True


def genrand_real1() -> float:
    lib = _load()
    if lib is None:
        raise RuntimeError("native RNG unavailable")
    return float(lib.qh_genrand_real1())


def genrand_int32() -> int:
    """One full 32-bit word from the reference MT19937 stream."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native RNG unavailable")
    return int(lib.qh_genrand_int32())


# ---------------------------------------------------------------------------
# CSV state IO
# ---------------------------------------------------------------------------


def write_state_csv(path: str, re: np.ndarray, im: np.ndarray,
                    header: bool = True) -> bool:
    lib = _load()
    if lib is None:
        return False
    re = np.ascontiguousarray(re, dtype=np.float64)
    im = np.ascontiguousarray(im, dtype=np.float64)
    rc = lib.qh_write_state_csv(
        path.encode(), re.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        im.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), re.size,
        1 if header else 0)
    return rc == 0


def append_state_csv(path: str, re: np.ndarray, im: np.ndarray) -> bool:
    """Append rows to an existing CSV (bounded-memory streaming of a huge
    register: first chunk via write_state_csv, rest via this)."""
    lib = _load()
    if lib is None:
        return False
    re = np.ascontiguousarray(re, dtype=np.float64)
    im = np.ascontiguousarray(im, dtype=np.float64)
    rc = lib.qh_append_state_csv(
        path.encode(), re.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        im.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), re.size)
    return rc == 0


def read_state_csv(path: str, num_amps: int):
    """Returns (re, im) float64 arrays, or None if the native path is
    unavailable or the file holds fewer rows than requested."""
    lib = _load()
    if lib is None:
        return None
    re = np.empty(num_amps, dtype=np.float64)
    im = np.empty(num_amps, dtype=np.float64)
    got = lib.qh_read_state_csv(
        path.encode(), re.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        im.ctypes.data_as(ctypes.POINTER(ctypes.c_double)), num_amps)
    if got != num_amps:
        return None
    return re, im
