"""QuEST-compatible eager API: every public function of the reference's
QuEST.h (~105 functions in 9 doc groups, QuEST/include/QuEST.h:7-24),
with the reference's camelCase names and imperative calling convention,
over the functional quest_tpu core.

A `Qureg` here is a mutable HANDLE (state + QASM logger); each API call
validates, dispatches to the functional layer, rebinds the handle's state,
and records QASM — the same validate -> dispatch -> record pipeline as the
reference's front-end (QuEST/src/QuEST.c). Reference user code ports
line-for-line:

    C (reference)                         Python (this module)
    ------------------------------------  ------------------------------
    QuESTEnv env = createQuESTEnv();      env = createQuESTEnv()
    Qureg q = createQureg(3, env);        q = createQureg(3, env)
    hadamard(q, 0);                       hadamard(q, 0)
    int m = measure(q, 0);                m = measure(q, 0)
    destroyQureg(q, env);                 destroyQureg(q, env)

Data types map naturally: `Complex` -> python complex, `ComplexMatrix2/4/N`
-> numpy arrays (createComplexMatrixN below), `Vector` -> 3-sequence,
`pauliOpType` -> PAULI_I/X/Y/Z ints. The overridable error hook
`invalidQuESTInputError` (weak symbol in the reference,
QuEST.h:3163-3190) is `set_input_error_handler` here.
"""

from __future__ import annotations

from typing import Optional, Sequence

import os

import numpy as np

from quest_tpu import calculations as _calc
from quest_tpu import env as _env
from quest_tpu import measurement as _meas
from quest_tpu import random_ as _rng
from quest_tpu import state as _state
from quest_tpu import validation as _val
from quest_tpu.ops import channels as _chan
from quest_tpu.ops import gates as _gates
from quest_tpu.qasm import QASMLogger

# pauliOpType (ref QuEST.h:96)
PAULI_I, PAULI_X, PAULI_Y, PAULI_Z = 0, 1, 2, 3

QuESTEnv = _env.QuESTEnv


class Qureg:
    """Mutable register handle: functional state + QASM logger
    (ref Qureg, QuEST.h:160-191)."""

    def __init__(self, state: _state.Qureg, env: Optional[QuESTEnv] = None):
        self.state = state
        self.env = env
        self.qasm = QASMLogger(state.num_qubits)

    # convenience mirrors of the reference's struct fields
    @property
    def numQubitsRepresented(self) -> int:
        return self.state.num_qubits

    @property
    def isDensityMatrix(self) -> bool:
        return self.state.is_density

    @property
    def numAmpsTotal(self) -> int:
        return self.state.num_amps

    def _set(self, new_state: _state.Qureg) -> None:
        self.state = new_state


# ---------------------------------------------------------------------------
# environment (ref QuEST.h "init" group; QuEST_cpu_local.c:170-180)
# ---------------------------------------------------------------------------


def createQuESTEnv(**kwargs) -> QuESTEnv:
    return _env.create_quest_env(**kwargs)


def destroyQuESTEnv(env: QuESTEnv) -> None:
    _env.destroy_quest_env(env)


def syncQuESTEnv(env: QuESTEnv) -> None:
    env.sync()


def syncQuESTSuccess(successCode: int) -> int:
    return _env.sync_quest_success(successCode)


def reportQuESTEnv(env: QuESTEnv) -> None:
    env.report()


def getEnvironmentString(env: QuESTEnv, qureg: "Qureg" = None) -> str:
    # the reference formats qureg.numQubitsInStateVec — the DOUBLED count
    # for density matrices (QuEST_cpu.c:1363), not numQubitsRepresented
    n = qureg.state.num_state_qubits if qureg is not None else None
    return env.get_environment_string(n)


def seedQuEST(seeds: Sequence[int]) -> None:
    _rng.seed_quest(list(seeds))


def seedQuESTDefault() -> None:
    _rng.seed_quest_default()


# ---------------------------------------------------------------------------
# Qureg lifecycle (ref QuEST.c:34-78)
# ---------------------------------------------------------------------------


def createQureg(numQubits: int, env: Optional[QuESTEnv] = None) -> Qureg:
    return Qureg(_state.create_qureg(numQubits, env), env)


def createDensityQureg(numQubits: int, env: Optional[QuESTEnv] = None) -> Qureg:
    return Qureg(_state.create_density_qureg(numQubits, env), env)


def createCloneQureg(qureg: Qureg, env: Optional[QuESTEnv] = None) -> Qureg:
    return Qureg(_state.clone(qureg.state), env if env is not None else qureg.env)


def destroyQureg(qureg: Qureg, env: Optional[QuESTEnv] = None) -> None:
    """Release the handle's device buffer (the functional core is GC'd;
    kept for API parity, ref QuEST.c:74-78)."""
    qureg.state = None


def cloneQureg(targetQureg: Qureg, copyQureg: Qureg) -> None:
    """Overwrite targetQureg's state with a copy of copyQureg's
    (ref cloneQureg, QuEST.c works on matching-dimension registers)."""
    _val.validate_matching_types(targetQureg.state, copyQureg.state)
    _val.validate_match(targetQureg.state, copyQureg.state)
    targetQureg._set(_state.clone(copyQureg.state))


def reportQuregParams(qureg: Qureg) -> None:
    """(ref reportQuregParams, QuEST_common.c:233-242)"""
    n = qureg.state.num_state_qubits
    print("QUBITS:")
    print(f"Number of qubits is {n}.")
    print(f"Number of amps is {1 << n}.")


def getNumQubits(qureg: Qureg) -> int:
    return _state.get_num_qubits(qureg.state)


def getNumAmps(qureg: Qureg) -> int:
    return _state.get_num_amps(qureg.state)


# ---------------------------------------------------------------------------
# state initialisations (ref QuEST.c:109-161)
# ---------------------------------------------------------------------------


def initBlankState(qureg: Qureg) -> None:
    qureg._set(_state.init_blank_state(qureg.state))
    qureg.qasm.record_comment("Initialising state to all-zero amplitudes")


def initZeroState(qureg: Qureg) -> None:
    qureg._set(_state.init_zero_state(qureg.state))
    qureg.qasm.record_init_zero()


def initPlusState(qureg: Qureg) -> None:
    qureg._set(_state.init_plus_state(qureg.state))
    qureg.qasm.record_init_plus()


def initClassicalState(qureg: Qureg, stateInd: int) -> None:
    qureg._set(_state.init_classical_state(qureg.state, stateInd))
    qureg.qasm.record_init_classical(stateInd)


def initPureState(qureg: Qureg, pure: Qureg) -> None:
    qureg._set(_state.init_pure_state(qureg.state, pure.state))
    qureg.qasm.record_comment("Initialising state from purity")


def initDebugState(qureg: Qureg) -> None:
    qureg._set(_state.init_debug_state(qureg.state))
    qureg.qasm.record_comment(
        "Initialising state to debug state (amp[k] = (2k + (2k+1)i)/10)")


def initStateFromAmps(qureg: Qureg, reals, imags) -> None:
    qureg._set(_state.init_state_from_amps(qureg.state, reals, imags))
    qureg.qasm.record_comment("Initialising state from amplitude arrays")


def setAmps(qureg: Qureg, startInd: int, reals, imags, numAmps: int = None) -> None:
    reals = np.asarray(reals).reshape(-1)
    imags = np.asarray(imags).reshape(-1)
    if numAmps is not None:
        reals, imags = reals[:numAmps], imags[:numAmps]
    qureg._set(_state.set_amps(qureg.state, startInd, reals, imags))
    qureg.qasm.record_comment("Setting amplitude slice")


def setWeightedQureg(fac1, qureg1: Qureg, fac2, qureg2: Qureg,
                     facOut, out: Qureg) -> None:
    out._set(_gates.set_weighted_qureg(fac1, qureg1.state, fac2, qureg2.state,
                                       facOut, out.state))
    out.qasm.record_comment("Setting weighted sum of registers")


# ---------------------------------------------------------------------------
# amplitude getters (ref QuEST.c:671-705)
# ---------------------------------------------------------------------------


def getAmp(qureg: Qureg, index: int) -> complex:
    return _state.get_amp(qureg.state, index)


def getRealAmp(qureg: Qureg, index: int) -> float:
    return _state.get_real_amp(qureg.state, index)


def getImagAmp(qureg: Qureg, index: int) -> float:
    return _state.get_imag_amp(qureg.state, index)


def getProbAmp(qureg: Qureg, index: int) -> float:
    return _state.get_prob_amp(qureg.state, index)


def getDensityAmp(qureg: Qureg, row: int, col: int) -> complex:
    return _state.get_density_amp(qureg.state, row, col)


# ---------------------------------------------------------------------------
# ComplexMatrixN (ref QuEST.h:3233-3291, QuEST.c createComplexMatrixN)
# ---------------------------------------------------------------------------


def createComplexMatrixN(numQubits: int) -> np.ndarray:
    """A zeroed (2^n, 2^n) complex matrix (ref createComplexMatrixN)."""
    if numQubits < 1:
        _val._err(
            "Invalid number of qubits: must create a matrix of at least 1 qubit")
    dim = 1 << numQubits
    return np.zeros((dim, dim), dtype=np.complex128)


def destroyComplexMatrixN(matrix) -> None:
    """No-op (numpy GC); kept for API parity."""


def initComplexMatrixN(matrix: np.ndarray, reals, imags) -> None:
    """Overwrite a ComplexMatrixN in place from real/imag 2-D arrays."""
    matrix[...] = np.asarray(reals) + 1j * np.asarray(imags)


def bindArraysToStackComplexMatrixN(numQubits: int, reals, imags,
                                    reStorage=None, imStorage=None) -> np.ndarray:
    """Build a ComplexMatrixN view from row arrays (the stack-allocation
    macro analogue, QuEST.h:3233-3291)."""
    return np.asarray(reals, dtype=np.float64) + \
        1j * np.asarray(imags, dtype=np.float64)


def getStaticComplexMatrixN(numQubits: int, reals, imags) -> np.ndarray:
    return bindArraysToStackComplexMatrixN(numQubits, reals, imags)


# ---------------------------------------------------------------------------
# unitaries (ref QuEST.c:109-520) — validate -> dispatch -> QASM
# ---------------------------------------------------------------------------


def compactUnitary(qureg: Qureg, targetQubit: int, alpha, beta) -> None:
    qureg._set(_gates.compact_unitary(qureg.state, targetQubit, alpha, beta))
    qureg.qasm.record_compact_unitary(alpha, beta, targetQubit)


def controlledCompactUnitary(qureg: Qureg, controlQubit: int,
                             targetQubit: int, alpha, beta) -> None:
    qureg._set(_gates.controlled_compact_unitary(
        qureg.state, controlQubit, targetQubit, alpha, beta))
    qureg.qasm.record_compact_unitary(alpha, beta, targetQubit,
                                      (controlQubit,))


def unitary(qureg: Qureg, targetQubit: int, u) -> None:
    qureg._set(_gates.unitary(qureg.state, targetQubit, u))
    qureg.qasm.record_unitary(u, targetQubit)


def controlledUnitary(qureg: Qureg, controlQubit: int, targetQubit: int, u) -> None:
    qureg._set(_gates.controlled_unitary(qureg.state, controlQubit,
                                         targetQubit, u))
    qureg.qasm.record_unitary(u, targetQubit, (controlQubit,))


def multiControlledUnitary(qureg: Qureg, controlQubits: Sequence[int],
                           numControlQubits: int = None, targetQubit: int = None,
                           u=None) -> None:
    # support both (q, ctrls, nCtrls, targ, u) [C signature] and
    # (q, ctrls, targ, u) [natural Python]
    if u is None:
        u = targetQubit
        targetQubit = numControlQubits
    else:
        controlQubits = list(controlQubits)[:numControlQubits]
    qureg._set(_gates.multi_controlled_unitary(qureg.state, controlQubits,
                                               targetQubit, u))
    qureg.qasm.record_unitary(u, targetQubit, tuple(controlQubits))


def multiStateControlledUnitary(qureg: Qureg, controlQubits: Sequence[int],
                                controlState: Sequence[int],
                                targetQubit: int, u) -> None:
    qureg._set(_gates.multi_state_controlled_unitary(
        qureg.state, controlQubits, controlState, targetQubit, u))
    qureg.qasm.record_multi_state_controlled_unitary(
        u, tuple(controlQubits), tuple(controlState), targetQubit)


def pauliX(qureg: Qureg, targetQubit: int) -> None:
    qureg._set(_gates.pauli_x(qureg.state, targetQubit))
    qureg.qasm.record_gate("x", targetQubit)


def pauliY(qureg: Qureg, targetQubit: int) -> None:
    qureg._set(_gates.pauli_y(qureg.state, targetQubit))
    qureg.qasm.record_gate("y", targetQubit)


def pauliZ(qureg: Qureg, targetQubit: int) -> None:
    qureg._set(_gates.pauli_z(qureg.state, targetQubit))
    qureg.qasm.record_gate("z", targetQubit)


def hadamard(qureg: Qureg, targetQubit: int) -> None:
    qureg._set(_gates.hadamard(qureg.state, targetQubit))
    qureg.qasm.record_gate("h", targetQubit)


def sGate(qureg: Qureg, targetQubit: int) -> None:
    qureg._set(_gates.s_gate(qureg.state, targetQubit))
    qureg.qasm.record_gate("s", targetQubit)


def tGate(qureg: Qureg, targetQubit: int) -> None:
    qureg._set(_gates.t_gate(qureg.state, targetQubit))
    qureg.qasm.record_gate("t", targetQubit)


def phaseShift(qureg: Qureg, targetQubit: int, angle: float) -> None:
    qureg._set(_gates.phase_shift(qureg.state, targetQubit, angle))
    qureg.qasm.record_gate("phase", targetQubit, params=(angle,))


def controlledPhaseShift(qureg: Qureg, idQubit1: int, idQubit2: int,
                         angle: float) -> None:
    qureg._set(_gates.controlled_phase_shift(qureg.state, idQubit1, idQubit2,
                                             angle))
    qureg.qasm.record_gate("phase", idQubit2, (idQubit1,), (angle,))


def multiControlledPhaseShift(qureg: Qureg, controlQubits: Sequence[int],
                              numControlQubits: int = None,
                              angle: float = None) -> None:
    if angle is None:
        angle = numControlQubits
    else:
        controlQubits = list(controlQubits)[:numControlQubits]
    qubits = list(controlQubits)
    qureg._set(_gates.multi_controlled_phase_shift(qureg.state, qubits, angle))
    qureg.qasm.record_gate("phase", qubits[-1], tuple(qubits[:-1]), (angle,))


def controlledPhaseFlip(qureg: Qureg, idQubit1: int, idQubit2: int) -> None:
    qureg._set(_gates.controlled_phase_flip(qureg.state, idQubit1, idQubit2))
    qureg.qasm.record_gate("z", idQubit2, (idQubit1,))


def multiControlledPhaseFlip(qureg: Qureg, controlQubits: Sequence[int],
                             numControlQubits: int = None) -> None:
    if numControlQubits is not None:
        controlQubits = list(controlQubits)[:numControlQubits]
    qubits = list(controlQubits)
    qureg._set(_gates.multi_controlled_phase_flip(qureg.state, qubits))
    qureg.qasm.record_gate("z", qubits[-1], tuple(qubits[:-1]))


def controlledNot(qureg: Qureg, controlQubit: int, targetQubit: int) -> None:
    qureg._set(_gates.controlled_not(qureg.state, controlQubit, targetQubit))
    qureg.qasm.record_gate("x", targetQubit, (controlQubit,))


def controlledPauliY(qureg: Qureg, controlQubit: int, targetQubit: int) -> None:
    qureg._set(_gates.controlled_pauli_y(qureg.state, controlQubit,
                                         targetQubit))
    qureg.qasm.record_gate("y", targetQubit, (controlQubit,))


def rotateX(qureg: Qureg, rotQubit: int, angle: float) -> None:
    qureg._set(_gates.rotate_x(qureg.state, rotQubit, angle))
    qureg.qasm.record_gate("rx", rotQubit, params=(angle,))


def rotateY(qureg: Qureg, rotQubit: int, angle: float) -> None:
    qureg._set(_gates.rotate_y(qureg.state, rotQubit, angle))
    qureg.qasm.record_gate("ry", rotQubit, params=(angle,))


def rotateZ(qureg: Qureg, rotQubit: int, angle: float) -> None:
    qureg._set(_gates.rotate_z(qureg.state, rotQubit, angle))
    qureg.qasm.record_gate("rz", rotQubit, params=(angle,))


def rotateAroundAxis(qureg: Qureg, rotQubit: int, angle: float, axis) -> None:
    axis = _as_axis(axis)
    qureg._set(_gates.rotate_around_axis(qureg.state, rotQubit, angle, axis))
    qureg.qasm.record_axis_rotation(angle, axis, rotQubit)


def controlledRotateX(qureg: Qureg, controlQubit: int, targetQubit: int,
                      angle: float) -> None:
    qureg._set(_gates.controlled_rotate_x(qureg.state, controlQubit,
                                          targetQubit, angle))
    qureg.qasm.record_gate("rx", targetQubit, (controlQubit,), (angle,))


def controlledRotateY(qureg: Qureg, controlQubit: int, targetQubit: int,
                      angle: float) -> None:
    qureg._set(_gates.controlled_rotate_y(qureg.state, controlQubit,
                                          targetQubit, angle))
    qureg.qasm.record_gate("ry", targetQubit, (controlQubit,), (angle,))


def controlledRotateZ(qureg: Qureg, controlQubit: int, targetQubit: int,
                      angle: float) -> None:
    qureg._set(_gates.controlled_rotate_z(qureg.state, controlQubit,
                                          targetQubit, angle))
    qureg.qasm.record_gate("rz", targetQubit, (controlQubit,), (angle,))


def controlledRotateAroundAxis(qureg: Qureg, controlQubit: int,
                               targetQubit: int, angle: float, axis) -> None:
    axis = _as_axis(axis)
    qureg._set(_gates.controlled_rotate_around_axis(
        qureg.state, controlQubit, targetQubit, angle, axis))
    qureg.qasm.record_axis_rotation(angle, axis, targetQubit, (controlQubit,))


def multiRotateZ(qureg: Qureg, qubits: Sequence[int], numQubits: int = None,
                 angle: float = None) -> None:
    if angle is None:
        angle = numQubits
    else:
        qubits = list(qubits)[:numQubits]
    qureg._set(_gates.multi_rotate_z(qureg.state, list(qubits), angle))
    qureg.qasm.record_comment(
        f"Here a multiRotateZ of angle {angle:g} was applied to qubits "
        f"{list(qubits)}")


def multiRotatePauli(qureg: Qureg, targetQubits: Sequence[int],
                     targetPaulis: Sequence[int], numTargets: int = None,
                     angle: float = None) -> None:
    if angle is None:
        angle = numTargets
    else:
        targetQubits = list(targetQubits)[:numTargets]
        targetPaulis = list(targetPaulis)[:numTargets]
    qureg._set(_gates.multi_rotate_pauli(qureg.state, list(targetQubits),
                                         list(targetPaulis), angle))
    qureg.qasm.record_comment(
        f"Here a multiRotatePauli of angle {angle:g} was applied")


def swapGate(qureg: Qureg, qubit1: int, qubit2: int) -> None:
    qureg._set(_gates.swap_gate(qureg.state, qubit1, qubit2))
    qureg.qasm.record_gate("swap", qubit2, (qubit1,))


def sqrtSwapGate(qureg: Qureg, qubit1: int, qubit2: int) -> None:
    qureg._set(_gates.sqrt_swap_gate(qureg.state, qubit1, qubit2))
    qureg.qasm.record_gate("sqrtswap", qubit2, (qubit1,))


def twoQubitUnitary(qureg: Qureg, targetQubit1: int, targetQubit2: int, u) -> None:
    qureg._set(_gates.two_qubit_unitary(qureg.state, targetQubit1,
                                        targetQubit2, u))
    qureg.qasm.record_comment(
        "Here a two-qubit unitary was applied (no QASM equivalent)")


def controlledTwoQubitUnitary(qureg: Qureg, controlQubit: int,
                              targetQubit1: int, targetQubit2: int, u) -> None:
    qureg._set(_gates.controlled_two_qubit_unitary(
        qureg.state, controlQubit, targetQubit1, targetQubit2, u))
    qureg.qasm.record_comment(
        "Here a controlled two-qubit unitary was applied (no QASM equivalent)")


def multiControlledTwoQubitUnitary(qureg: Qureg, controlQubits: Sequence[int],
                                   numControlQubits: int = None,
                                   targetQubit1: int = None,
                                   targetQubit2: int = None, u=None) -> None:
    if u is None:
        u = targetQubit2
        targetQubit2 = targetQubit1
        targetQubit1 = numControlQubits
    else:
        controlQubits = list(controlQubits)[:numControlQubits]
    qureg._set(_gates.multi_controlled_two_qubit_unitary(
        qureg.state, list(controlQubits), targetQubit1, targetQubit2, u))
    qureg.qasm.record_comment(
        "Here a multi-controlled two-qubit unitary was applied "
        "(no QASM equivalent)")


def multiQubitUnitary(qureg: Qureg, targs: Sequence[int],
                      numTargs: int = None, u=None) -> None:
    if u is None:
        u = numTargs
    else:
        targs = list(targs)[:numTargs]
    qureg._set(_gates.multi_qubit_unitary(qureg.state, list(targs), u))
    qureg.qasm.record_comment(
        "Here a multi-qubit unitary was applied (no QASM equivalent)")


def controlledMultiQubitUnitary(qureg: Qureg, ctrl: int, targs: Sequence[int],
                                numTargs: int = None, u=None) -> None:
    if u is None:
        u = numTargs
    else:
        targs = list(targs)[:numTargs]
    qureg._set(_gates.controlled_multi_qubit_unitary(qureg.state, ctrl,
                                                     list(targs), u))
    qureg.qasm.record_comment(
        "Here a controlled multi-qubit unitary was applied "
        "(no QASM equivalent)")


def multiControlledMultiQubitUnitary(qureg: Qureg, ctrls: Sequence[int],
                                     numCtrls: int = None,
                                     targs: Sequence[int] = None,
                                     numTargs: int = None, u=None) -> None:
    if u is None:
        u = targs
        targs = numCtrls
    else:
        ctrls = list(ctrls)[:numCtrls]
        targs = list(targs)[:numTargs]
    qureg._set(_gates.multi_controlled_multi_qubit_unitary(
        qureg.state, list(ctrls), list(targs), u))
    qureg.qasm.record_comment(
        "Here a multi-controlled multi-qubit unitary was applied "
        "(no QASM equivalent)")


def _as_axis(axis):
    if hasattr(axis, "x"):
        return (axis.x, axis.y, axis.z)
    return tuple(axis)


# ---------------------------------------------------------------------------
# decoherence (ref QuEST.c:890-1000)
# ---------------------------------------------------------------------------


def mixDephasing(qureg: Qureg, targetQubit: int, prob: float) -> None:
    qureg._set(_chan.mix_dephasing(qureg.state, targetQubit, prob))
    qureg.qasm.record_comment(
        f"Here, a phase damping of probability {prob:g} was applied")


def mixTwoQubitDephasing(qureg: Qureg, qubit1: int, qubit2: int,
                         prob: float) -> None:
    qureg._set(_chan.mix_two_qubit_dephasing(qureg.state, qubit1, qubit2, prob))
    qureg.qasm.record_comment(
        f"Here, a two-qubit phase damping of probability {prob:g} was applied")


def mixDepolarising(qureg: Qureg, targetQubit: int, prob: float) -> None:
    qureg._set(_chan.mix_depolarising(qureg.state, targetQubit, prob))
    qureg.qasm.record_comment(
        f"Here, a depolarising of probability {prob:g} was applied")


def mixTwoQubitDepolarising(qureg: Qureg, qubit1: int, qubit2: int,
                            prob: float) -> None:
    qureg._set(_chan.mix_two_qubit_depolarising(qureg.state, qubit1, qubit2,
                                                prob))
    qureg.qasm.record_comment(
        f"Here, a two-qubit depolarising of probability {prob:g} was applied")


def mixDamping(qureg: Qureg, targetQubit: int, prob: float) -> None:
    qureg._set(_chan.mix_damping(qureg.state, targetQubit, prob))
    qureg.qasm.record_comment(
        f"Here, an amplitude damping of probability {prob:g} was applied")


def mixPauli(qureg: Qureg, targetQubit: int, probX: float, probY: float,
             probZ: float) -> None:
    qureg._set(_chan.mix_pauli(qureg.state, targetQubit, probX, probY, probZ))
    qureg.qasm.record_comment("Here, a Pauli error channel was applied")


def mixKrausMap(qureg: Qureg, targetQubit: int, ops, numOps: int = None) -> None:
    if numOps is not None:
        ops = list(ops)[:numOps]
    qureg._set(_chan.mix_kraus_map(qureg.state, targetQubit, ops))
    qureg.qasm.record_comment("Here, a Kraus map was applied")


def mixTwoQubitKrausMap(qureg: Qureg, qubit1: int, qubit2: int, ops,
                        numOps: int = None) -> None:
    if numOps is not None:
        ops = list(ops)[:numOps]
    qureg._set(_chan.mix_two_qubit_kraus_map(qureg.state, qubit1, qubit2, ops))
    qureg.qasm.record_comment("Here, a two-qubit Kraus map was applied")


def mixMultiQubitKrausMap(qureg: Qureg, targets: Sequence[int],
                          numTargets: int = None, ops=None,
                          numOps: int = None) -> None:
    if ops is None:
        ops = numTargets
    else:
        targets = list(targets)[:numTargets]
        if numOps is not None:
            ops = list(ops)[:numOps]
    qureg._set(_chan.mix_multi_qubit_kraus_map(qureg.state, list(targets), ops))
    qureg.qasm.record_comment("Here, a multi-qubit Kraus map was applied")


def mixDensityMatrix(combineQureg: Qureg, prob: float, otherQureg: Qureg) -> None:
    combineQureg._set(_chan.mix_density_matrix(combineQureg.state, prob,
                                               otherQureg.state))
    combineQureg.qasm.record_comment(
        f"Here, the register was mixed with probability {prob:g}")


# ---------------------------------------------------------------------------
# calculations (ref QuEST.c:790-887)
# ---------------------------------------------------------------------------


def calcTotalProb(qureg: Qureg) -> float:
    return _calc.calc_total_prob(qureg.state)


def calcInnerProduct(bra: Qureg, ket: Qureg) -> complex:
    return _calc.calc_inner_product(bra.state, ket.state)


def calcDensityInnerProduct(rho1: Qureg, rho2: Qureg) -> float:
    return _calc.calc_density_inner_product(rho1.state, rho2.state)


def calcPurity(qureg: Qureg) -> float:
    return _calc.calc_purity(qureg.state)


def calcFidelity(qureg: Qureg, pureState: Qureg) -> float:
    return _calc.calc_fidelity(qureg.state, pureState.state)


def calcHilbertSchmidtDistance(a: Qureg, b: Qureg) -> float:
    return _calc.calc_hilbert_schmidt_distance(a.state, b.state)


def calcExpecPauliProd(qureg: Qureg, targetQubits: Sequence[int],
                       pauliCodes: Sequence[int], numTargets: int = None,
                       workspace: Qureg = None) -> float:
    if numTargets is not None:
        targetQubits = list(targetQubits)[:numTargets]
        pauliCodes = list(pauliCodes)[:numTargets]
    return _calc.calc_expec_pauli_prod(qureg.state, list(targetQubits),
                                       list(pauliCodes))


def calcExpecPauliSum(qureg: Qureg, allPauliCodes, termCoeffs,
                      numSumTerms: int = None, workspace: Qureg = None) -> float:
    codes = np.asarray(allPauliCodes).reshape(-1)
    coeffs = np.asarray(termCoeffs).reshape(-1)
    if numSumTerms is not None:
        codes = codes[:numSumTerms * qureg.numQubitsRepresented]
        coeffs = coeffs[:numSumTerms]
    return _calc.calc_expec_pauli_sum(qureg.state, codes, coeffs)


def calcProbOfOutcome(qureg: Qureg, measureQubit: int, outcome: int) -> float:
    return _meas.calc_prob_of_outcome(qureg.state, measureQubit, outcome)


def applyPauliSum(inQureg: Qureg, allPauliCodes, termCoeffs,
                  numSumTerms: int = None, outQureg: Qureg = None) -> None:
    codes = np.asarray(allPauliCodes).reshape(-1)
    coeffs = np.asarray(termCoeffs).reshape(-1)
    if numSumTerms is not None:
        codes = codes[:numSumTerms * inQureg.numQubitsRepresented]
        coeffs = coeffs[:numSumTerms]
    result = _calc.apply_pauli_sum(inQureg.state, codes, coeffs)
    if outQureg is None:
        outQureg = inQureg
    outQureg._set(result)


# ---------------------------------------------------------------------------
# gates: measurement (ref QuEST.c:756-777)
# ---------------------------------------------------------------------------


def measure(qureg: Qureg, measureQubit: int) -> int:
    new_state, outcome = _meas.measure(qureg.state, measureQubit)
    qureg._set(new_state)
    qureg.qasm.record_measurement(measureQubit)
    return outcome


def measureWithStats(qureg: Qureg, measureQubit: int):
    """Returns (outcome, outcomeProb) — the C out-param becomes a tuple."""
    new_state, outcome, prob = _meas.measure_with_stats(qureg.state,
                                                        measureQubit)
    qureg._set(new_state)
    qureg.qasm.record_measurement(measureQubit)
    return outcome, prob


def collapseToOutcome(qureg: Qureg, measureQubit: int, outcome: int) -> float:
    new_state, prob = _meas.collapse_to_outcome(qureg.state, measureQubit,
                                                outcome)
    qureg._set(new_state)
    qureg.qasm.record_measurement(measureQubit)
    return prob


# ---------------------------------------------------------------------------
# QASM (ref QuEST.c:85-104)
# ---------------------------------------------------------------------------


def startRecordingQASM(qureg: Qureg) -> None:
    qureg.qasm.start_recording()


def stopRecordingQASM(qureg: Qureg) -> None:
    qureg.qasm.stop_recording()


def clearRecordedQASM(qureg: Qureg) -> None:
    qureg.qasm.clear()


def printRecordedQASM(qureg: Qureg) -> None:
    qureg.qasm.print_recorded()


def writeRecordedQASMToFile(qureg: Qureg, filename: str) -> None:
    if not qureg.qasm.write_recorded_to_file(filename):
        _val._err("Could not open file" + f" \"{filename}\"")


# ---------------------------------------------------------------------------
# device-copy analogues (ref copyStateToGPU/FromGPU, QuEST_gpu.cu:399-418).
# State lives in device HBM permanently here; these synchronize instead.
# ---------------------------------------------------------------------------


def copyStateToGPU(qureg: Qureg) -> None:
    qureg.state.amps.block_until_ready()


def copyStateFromGPU(qureg: Qureg) -> None:
    qureg.state.amps.block_until_ready()


# ---------------------------------------------------------------------------
# debug / reporting (ref QuEST_debug.h, QuEST_common.c:215-242)
# ---------------------------------------------------------------------------


def reportState(qureg: Qureg) -> None:
    """Write all amplitudes to state_rank_0.csv
    (ref reportState, QuEST_common.c:215-231). Uses the native CSV writer
    (native/quest_host.cpp) when built, else pure Python. The register is
    fetched from device in <=2^20-amplitude slices, so host memory stays
    bounded even for a 30q state (a full f64 host copy would be 16 GB)."""
    from quest_tpu import native as _native
    amps = qureg.state.amps
    total = qureg.state.num_amps
    chunk = min(total, 1 << 20)
    path = "state_rank_0.csv"
    use_native = _native.available()
    f = None if use_native else open(path, "w")
    if f is not None:
        f.write("real, imag\n")
    try:
        for lo in range(0, total, chunk):
            hi = min(lo + chunk, total)
            planes = np.asarray(amps[:, lo:hi], dtype=np.float64)
            if use_native:
                ok = (_native.write_state_csv(path, planes[0], planes[1])
                      if lo == 0 else
                      _native.append_state_csv(path, planes[0], planes[1]))
                if not ok:
                    raise OSError(f"native CSV writer failed at offset {lo}")
            else:
                for r, i in zip(planes[0], planes[1]):
                    f.write(f"{r:.12f}, {i:.12f}\n")
    finally:
        if f is not None:
            f.close()


def reportStateToScreen(qureg: Qureg, env: QuESTEnv = None,
                        reportRank: int = 0) -> None:
    """Print amplitudes (<=5 qubits, like the reference's guard,
    QuEST_cpu.c:1334-1357)."""
    print("Reporting state from rank 0:")
    # the reference guards on the full state-vector qubit count, so density
    # registers of >2 represented qubits refuse too (QuEST_cpu.c:1337)
    if qureg.state.num_state_qubits > 5:
        print("(state too large to print)")
        return
    vec = _state.to_dense(qureg.state).reshape(-1, order="F")
    for a in vec:
        print(f"{a.real:.12f}, {a.imag:.12f}")


def initStateDebug(qureg: Qureg) -> None:
    initDebugState(qureg)


def initStateOfSingleQubit(qureg: Qureg, qubitId: int, outcome: int) -> None:
    """Uniform superposition over basis states with bit `qubitId` == outcome
    (ref statevec_initStateOfSingleQubit, QuEST_cpu.c:1513-1555). Device-side
    construction — no 2^n host materialization at 30q."""
    qureg._set(_state.init_state_of_single_qubit(qureg.state, qubitId, outcome))


def initStateFromSingleFile(qureg: Qureg, filename: str,
                            env: QuESTEnv = None) -> bool:
    """Read a state from a CSV of 'real, imag' lines (ref
    statevec_initStateFromSingleFile, QuEST_cpu.c:1593-1642). Uses the
    native CSV reader when built."""
    from quest_tpu import native as _native
    pair = _native.read_state_csv(filename, qureg.state.num_amps) \
        if os.path.exists(filename) else None
    if pair is not None:
        qureg._set(_state.init_state_from_amps(qureg.state, pair[0], pair[1]))
        return True
    reals, imags = [], []
    need = qureg.state.num_amps
    try:
        with open(filename) as f:
            for line in f:
                if len(reals) == need:  # extra rows ignored, like the ref
                    break
                line = line.strip()
                if not line or line.startswith("real"):
                    continue
                parts = line.replace(",", " ").split()
                if len(parts) < 2:
                    continue
                try:  # comment/header lines are legal, skip them
                    r, i = float(parts[0]), float(parts[1])
                except ValueError:
                    continue
                reals.append(r)
                imags.append(i)
    except OSError:
        return False
    if len(reals) != need:
        return False
    qureg._set(_state.init_state_from_amps(qureg.state, reals, imags))
    return True


def setDensityAmps(qureg: Qureg, reals, imags) -> None:
    """Overwrite all density-matrix amplitudes (ref setDensityAmps,
    QuEST_debug.h:44-48)."""
    qureg._set(_state.set_density_amps(qureg.state, 0, 0, reals, imags))


def compareStates(mq1: Qureg, mq2: Qureg, precision: float) -> bool:
    """Amplitude-wise comparison within precision (ref compareStates,
    QuEST_debug.h:30-33)."""
    a = _state.to_dense(mq1.state)
    b = _state.to_dense(mq2.state)
    return bool(np.all(np.abs(a - b) <= precision))


def QuESTPrecision() -> int:
    """1 for f32 planes, 2 for f64 (ref QuEST_debug.h:54)."""
    from quest_tpu import precision as _prec
    return 1 if _prec.get_default_dtype() == np.dtype(np.complex64) else 2


# ---------------------------------------------------------------------------
# error hook (ref invalidQuESTInputError, QuEST.h:3163-3190)
# ---------------------------------------------------------------------------


def set_input_error_handler(handler) -> None:
    """Override what happens on invalid input (the reference's weak-symbol
    invalidQuESTInputError). handler(errMsg, errFunc) may raise or exit."""
    _val.set_error_handler(handler)


def invalidQuESTInputError(errMsg: str, errFunc: str) -> None:
    """The default error hook, invoked (via late lookup, so monkeypatching
    this module attribute overrides it — the analogue of redefining the
    reference's weak symbol, QuEST.h:3163-3190) for every invalid input.
    Default behavior: raise QuESTError with the reference's message shape."""
    raise _val.QuESTError(f"QuEST Error in function {errFunc}: {errMsg}")
