"""One queryable program-plan IR + the stack-wide priced autotuner and
its persistent plan cache (docs/PLANNING.md).

The stack grew seven-plus plan representations — fusion-plan items,
segment/sweep plans with pipeline slot geometry, topology-weighted comm
plans, Trotter frame plans, batch buckets, f64 chunk capacity, serve
program keys — each with its own stats/explain plumbing. `ProgramPlan`
is the ONE typed structure they all roll up into: the scheduled op
stream's counters, the chosen engine, fusion/segment/sweep geometry,
comm events with link attribution, chunk capacity and the pipeline slot
schedule. `Circuit.plan_stats()` now builds this IR and re-emits its
historical dict shape bit-for-bit (`ProgramPlan.stats()`), so every
existing golden keeps gating the same numbers while new consumers query
one object.

`autotune()` generalises `comm.choose_plan` (docs/DISTRIBUTED.md)
stack-wide: enumerate priced alternatives (engine x scheduler stream x
comm strategy x batch/chunk geometry) through each subsystem's OWN cost
model — segment/sweep estimates from the chip-keyed `_estimate_ms`
constants, weighted comm element-bytes from `comm._cost` (via
choose_plan's candidate table), capacity from `apply.f64_capacity_stats`
— and pick the cheapest with INCUMBENT-WINS-TIES: the engine the stack
dispatched before the autotuner existed is always in the candidate set
and only loses to a STRICTLY cheaper plan, so no golden circuit can
regress by construction (the comm planner's tie-break contract,
scripts/check_plan_golden.py).

The chosen plan is PERSISTENT: a content-addressed cache
(sha256 over the op stream's values + register kind + dtype + batch
bucket + mesh/topology + engine_mode_key -> one JSON file, versioned and
self-digested like checkpoints) stored next to the XLA compile cache
(`.jax_cache.plans`), so `serve.warmup` and ServeFleet replica start
re-price from disk: a warm restart is a LOAD, not a search — and a
corrupted or stale-version entry is skipped LOUDLY to a fresh price,
never silently consumed (the checkpoint discipline, quest_tpu/
checkpoint.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
from typing import Any, Dict, List, Optional

import numpy as np

PLAN_FORMAT_VERSION = 3   # 3: transpile axis (raw vs rewritten stream)

# every engine the autotuner can choose between; "pergate" is the
# semantic-oracle XLA chain, the rest are the fusing/sharded families
# (docs/COMPONENTS.md)
ENGINES = ("pergate", "banded", "fused", "sharded-banded", "sharded-fused")

# projected interconnect throughput (GB/s) used to fold the comm
# planner's weighted element-bytes into the same per-application ms
# scale as the fused-engine cost model. RELATIVE, not absolute — like
# _COST_MODELS["v5p"] it only has to rank candidates consistently; the
# ab_silicon.py autotune leg prices the chooser's picks on real silicon.
_COMM_GBPS = 90.0

_CACHE_STATS = {"hits": 0, "misses": 0, "stale": 0, "corrupt": 0,
                "searches": 0, "stores": 0, "unkeyed": 0}


def cache_stats() -> dict:
    """Snapshot of the plan-cache counters: hits/misses (disk lookups),
    searches (full candidate enumerations priced this process), stores,
    and the loud-skip tallies (stale/corrupt) — the observability the
    warm-restart gate pins to zero searches (tests/test_plan.py)."""
    return dict(_CACHE_STATS)


def reset_cache_stats() -> None:
    """Zero the counters (test/bench hook — the cache files stay)."""
    for k in _CACHE_STATS:
        _CACHE_STATS[k] = 0


# ---------------------------------------------------------------------------
# the IR
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ProgramPlan:
    """The one queryable program plan: everything the engines compile
    from and the introspectors report, in JSON-native fields so the
    whole object round-trips through the persistent cache by value
    (tests/test_plan.py pins serialize->load equality)."""
    version: int               # PLAN_FORMAT_VERSION at build time
    key: Optional[str]         # content-addressed identity; None when an
    #                            operand is unrenderable (traced params)
    num_qubits: int
    n: int                     # register qubits (2x num_qubits if density)
    density: bool
    dtype: str                 # numpy dtype str of the real planes
    batch: Optional[int]
    devices: Optional[int]
    engine: str                # chosen engine (ENGINES)
    incumbent: str             # what the stack dispatched pre-autotuner
    source: str                # 'search' | 'cache' | 'build'
    cost: dict                 # chosen candidate's priced record
    candidates: dict           # name -> priced record (advisory included)
    scheduled: bool
    flat_ops: int
    planned_ops: int
    scheduler: dict            # fusion.schedule counters + enabled
    banded: dict               # fusion.plan_stats record
    fused: Optional[dict]      # pallas_band.fused_record (kernel tier only)
    batched: Optional[dict]    # pallas_band.batched_stats (batch= only)
    f64: dict                  # apply.f64_capacity_stats chunk capacity
    comm: Optional[dict]       # predicted collective schedule (devices=)
    extra: dict                # subsystem extensions (Trotter frames ...)
    grad: Optional[dict] = None  # adjoint.grad_record: differentiation
    #                              engine pricing (None: no parameters)
    transpile: Optional[dict] = None  # transpile axis: ops_in/ops_out,
    #                              sweeps_in/sweeps_out, per-pass
    #                              attribution (None: QUEST_TRANSPILE=0)

    def stats(self) -> dict:
        """The historical `Circuit.plan_stats()` dict, bit-compatible:
        same keys, same values, same insertion order as the
        pre-IR per-subsystem assembly (goldens unchanged —
        scripts/check_sweep_golden.py, check_comm_golden.py)."""
        rec = {
            "scheduled": self.scheduled,
            "flat_ops": self.flat_ops,
            "planned_ops": self.planned_ops,
            "scheduler": dict(self.scheduler),
            "banded": dict(self.banded),
        }
        if self.fused is not None:
            rec["fused"] = dict(self.fused)
        if self.batched is not None:
            rec["batched"] = dict(self.batched)
        rec["f64"] = dict(self.f64)
        if self.comm is not None:
            rec["comm"] = dict(self.comm)
        if self.grad is not None:
            rec["grad"] = dict(self.grad)
        if self.transpile is not None:
            rec["transpile"] = dict(self.transpile)
        return rec

    def to_meta(self) -> dict:
        """JSON-native serialisation, self-digested (the digest field
        itself excluded, canonical key order — checkpoint._meta_digest's
        discipline) so one flipped byte on disk is a LOUD skip."""
        meta = dataclasses.asdict(self)
        meta["plan_digest"] = _self_digest(meta)
        return meta

    @classmethod
    def from_meta(cls, meta: dict) -> "ProgramPlan":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in meta.items() if k in fields})

    def line(self) -> str:
        """The one unified plan line `explain()` emits."""
        tot = (self.cost or {}).get("total_ms")
        cost_s = (f"~{tot:.3g} ms/app" if tot is not None else "unpriced")
        src = {"cache": "cache hit", "search": "searched",
               "build": "unsearched"}.get(self.source, self.source)
        grad_s = ""
        if self.grad is not None:
            grad_s = f", grad={self.grad.get('engine', 'taped')}"
        if self.transpile is not None:
            t = self.transpile
            grad_s += (f", transpile={t['ops_in']}->{t['ops_out']} ops"
                       f"{' (chosen)' if t.get('chosen') else ''}")
        return (f"plan: engine={self.engine} {cost_s} "
                f"(incumbent={self.incumbent}{grad_s}, "
                f"{len(self.candidates)} candidate(s), {src}; "
                f"docs/PLANNING.md)")


# ---------------------------------------------------------------------------
# subsystem record assembly (the one home plan_stats reports from)
# ---------------------------------------------------------------------------

def _subsystem_records(circuit, n: int, density: bool,
                       batch: Optional[int],
                       devices: Optional[int]) -> dict:
    """Every subsystem's plan record for one circuit, through each
    subsystem's OWN planner — the single assembly `plan_stats()`,
    `build_plan()` and the autotuner all read, so the reported and the
    priced geometry cannot drift."""
    from quest_tpu.ops import apply as A
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    flat = circuit._flat_ops(n, density)
    enabled = F._schedule_enabled()
    # ONE scheduler run serves the stats, the planned list and pricing
    sched_ops, sstats = F.schedule(flat, n)
    sstats["enabled"] = enabled
    planned = sched_ops if enabled else flat
    rec: Dict[str, Any] = {
        "flat": flat, "sched_ops": sched_ops, "planned": planned,
        "enabled": enabled, "scheduler": sstats,
        "banded": F.plan_stats(F.plan(planned, n)),
        "fused": None, "batched": None, "swept": None,
    }
    if PB.usable(n):
        items = F.plan(planned, n, bands=PB.plan_bands(n))
        parts = PB.segment_plan(items, n)
        swept = PB.maybe_sweep(parts, n)
        rec["swept"] = swept
        rec["fused"] = PB.fused_record(parts, swept, n)
        if batch is not None:
            from quest_tpu.env import batch_bucket
            rec["batched"] = PB.batched_stats(
                swept, int(batch), batch_bucket(batch))
    elif batch is not None:
        # below the kernel tier compiled_batched rides the vmapped
        # banded program: still one dispatch per banded pass for the
        # whole bucket (the documented `batch=` parameter never
        # KeyErrors on small registers)
        from quest_tpu.env import batch_bucket
        bucket = batch_bucket(batch)
        rec["batched"] = {
            "batch": int(batch), "bucket": bucket,
            "states_per_sweep": bucket,
            "hbm_sweeps": rec["banded"]["full_state_passes"],
            "kernel_sweeps": 0, "batched_stages": 0,
        }
    rec["f64"] = A.f64_capacity_stats(n)
    rec["comm"] = None
    if devices is not None:
        from quest_tpu.parallel import sharded as S
        rec["comm"] = S.comm_plan_record(circuit.ops, n, density,
                                         int(devices))
    return rec


def build_plan(circuit, *, density: bool = False,
               batch: Optional[int] = None,
               devices: Optional[int] = None,
               dtype=np.float32) -> ProgramPlan:
    """Assemble the ProgramPlan IR for `circuit` under the CURRENT keyed
    knobs, unpriced (engine = the incumbent route, no candidate search):
    the cheap path `Circuit.plan_stats()` rides on every call. Use
    `autotune()` for the priced search + persistent cache."""
    n = circuit.num_qubits * 2 if density else circuit.num_qubits
    recs = _subsystem_records(circuit, n, density, batch, devices)
    incumbent = _incumbent_engine(len(circuit.ops), devices)
    return ProgramPlan(
        version=PLAN_FORMAT_VERSION,
        key=None, num_qubits=circuit.num_qubits, n=n,
        density=bool(density), dtype=np.dtype(dtype).str,
        batch=None if batch is None else int(batch),
        devices=None if devices is None else int(devices),
        engine=incumbent, incumbent=incumbent, source="build",
        cost={}, candidates={},
        scheduled=recs["enabled"], flat_ops=len(recs["flat"]),
        planned_ops=len(recs["planned"]), scheduler=recs["scheduler"],
        banded=recs["banded"], fused=recs["fused"],
        batched=recs["batched"], f64=recs["f64"], comm=recs["comm"],
        extra=_plan_extra(circuit, density),
        grad=_grad_record(circuit, density, dtype, devices),
        transpile=_transpile_record(circuit, n, density, recs)[0])


def _grad_record(circuit, density: bool, dtype,
                 devices: Optional[int]) -> Optional[dict]:
    """The plan IR's grad axis: adjoint vs taped differentiation-engine
    pricing for this circuit (adjoint.grad_record — capacity rows for
    both engines plus the engine QUEST_ADJOINT resolves to,
    incumbent-wins-ties on 'taped'). None when the circuit carries no
    parametric ops. Imported lazily like every subsystem planner so
    plan.py stays import-light."""
    from quest_tpu import adjoint as AD
    return AD.grad_record(circuit, density=density, dtype=dtype,
                          devices=devices)


_transpile_warned = False


def _transpile_record(circuit, n: int, density: bool, recs: dict):
    """The plan IR's transpile axis: (record, transpiled Circuit | None).
    The record carries the rewrite attribution plus the predicted sweep
    delta under the SAME schedule+fusion pipeline the raw stream was
    priced with; the circuit is returned only when the rewrite changed
    the stream (so autotune can enumerate its candidates). None record
    when QUEST_TRANSPILE=0 — stats() then omits the key entirely, so the
    knob-off record is bit-for-bit the pre-transpiler one
    (scripts/check_transpile_golden.py gates this)."""
    from quest_tpu.env import knob_value
    knob = knob_value("QUEST_TRANSPILE")
    if knob == "0":
        return None, None
    from quest_tpu.ops import fusion as F
    try:
        from quest_tpu import transpile as T
        tc, rep = T.transpile_cached(circuit)
    except Exception as e:             # never fatal to planning
        global _transpile_warned
        if not _transpile_warned:
            _transpile_warned = True
            print(f"[quest_tpu.plan] transpile axis skipped: {e!r}",
                  file=sys.stderr, flush=True)
        return None, None
    sweeps_in = recs["banded"]["full_state_passes"]
    rec = {"knob": knob, "ops_in": rep["ops_in"], "ops_out": rep["ops_out"],
           "sweeps_in": sweeps_in, "sweeps_out": sweeps_in,
           "passes": dict(rep["passes"]), "chosen": False}
    if not rep["changed"]:
        return rec, None
    flat_t = tc._flat_ops(n, density)
    sched_t, _ = F.schedule(flat_t, n)
    planned_t = sched_t if recs["enabled"] else flat_t
    rec["sweeps_out"] = F.plan_stats(F.plan(planned_t, n))[
        "full_state_passes"]
    return rec, tc


def _plan_extra(circuit, density: bool) -> dict:
    fn = getattr(circuit, "_plan_extra", None)
    return dict(fn(density)) if callable(fn) else {}


def _reject_dynamic(circuit, what: str) -> None:
    # mid-circuit measurements have no static plan (the measured
    # engines re-plan per branch) — same loud refusal as plan_stats
    rej = getattr(circuit, "_reject_measure", None)
    if callable(rej):
        rej(what)


def _incumbent_engine(num_ops: int, devices: Optional[int]) -> str:
    """The engine the stack dispatches WITHOUT the autotuner — the
    candidate that wins ties. Sharded registers ride the banded sharded
    engine (explain_sharded's default); unsharded applies ride the
    per-gate oracle below PERGATE_COMPILE_WARN_OPS and the banded
    auto-route above it (QUEST_APPLY_AUTOROUTE, the PR-13 footgun fix;
    0 restores the warn-only per-gate incumbent). `num_ops` is the
    circuit's op count — the same measure Circuit.apply routes on."""
    if devices is not None:
        return "sharded-banded"
    from quest_tpu.circuit import PERGATE_COMPILE_WARN_OPS
    from quest_tpu.env import knob_value
    if (num_ops > PERGATE_COMPILE_WARN_OPS
            and knob_value("QUEST_APPLY_AUTOROUTE")):
        return "banded"
    return "pergate"


# ---------------------------------------------------------------------------
# pricing (each subsystem's own cost model, folded to one ms scale)
# ---------------------------------------------------------------------------

def _pass_scale(n: int, dtype) -> float:
    # _estimate_ms's per-pass DMA constants are calibrated at 30q f32;
    # f64 planes move twice the bytes per full-state pass
    return (1 << n) / (1 << 30) * (np.dtype(dtype).itemsize / 4.0)


def _cost_rec(lo: float, hi: float, passes: int, *, compile_ops: int,
              comm_elem_bytes: float = 0.0, comm_steps: int = 0,
              bytes_per_real: int = 4, selectable: bool = True) -> dict:
    comm_ms = (comm_elem_bytes * bytes_per_real
               / (_COMM_GBPS * (1 << 30)) * 1e3)
    return {"est_ms_lo": round(float(lo), 6),
            "est_ms_hi": round(float(hi), 6),
            "hbm_passes": int(passes),
            "compile_ops": int(compile_ops),
            "comm_elem_bytes": float(comm_elem_bytes),
            "comm_steps": int(comm_steps),
            "comm_ms": round(comm_ms, 6),
            "total_ms": round((float(lo) + float(hi)) / 2 + comm_ms, 6),
            "selectable": bool(selectable)}


def _rank(cost: dict):
    """Total order over priced candidates, cheapest first: estimated
    per-application ms (compute + comm), then HBM passes, then compiled
    program size (the PR-13 pathology axis — the per-gate engine's HLO
    chain length is what compiles in minutes). The incumbent wins ties:
    selection uses STRICT <."""
    return (cost["total_ms"], cost["hbm_passes"], cost["compile_ops"])


def _price_pergate(num_flat: int, n: int, model: dict, dtype) -> dict:
    # one full-state HBM pass per routed op — the per-gate engine's
    # memory model; its compiled size IS its op chain (the footgun axis)
    ms = num_flat * model["base_pass"] * _pass_scale(n, dtype)
    return _cost_rec(ms, ms, num_flat, compile_ops=num_flat)


def _price_banded(banded_stats: dict, n: int, model: dict, dtype,
                  selectable: bool = True, comm_elem_bytes: float = 0.0,
                  comm_steps: int = 0, bytes_per_real: int = 4) -> dict:
    # fusion.plan_stats's pass model: each band/pass/diag-run is one
    # full-state pass; the XLA band einsum moves ~1.8x the state bytes
    # (_estimate_ms's passthrough multiplier)
    passes = banded_stats["full_state_passes"]
    ms = passes * 1.8 * model["base_pass"] * _pass_scale(n, dtype)
    return _cost_rec(ms, ms, passes, compile_ops=passes,
                     comm_elem_bytes=comm_elem_bytes,
                     comm_steps=comm_steps, bytes_per_real=bytes_per_real,
                     selectable=selectable)


def _price_fused(swept, n: int, model: dict, dtype,
                 selectable: bool = True) -> dict:
    # the fused engine's own chip-keyed estimate over the ACTUAL sweep
    # plan (pallas_band.sweep_plan geometry through _estimate_ms)
    from quest_tpu.circuit import _estimate_ms
    lo, hi = _estimate_ms(swept, n, model)
    passes = len(swept)
    segs = sum(1 for p in swept if p[0] == "segment")
    return _cost_rec(lo, hi, passes, compile_ops=passes + segs,
                     selectable=selectable)


def _enumerate_candidates(circuit, n: int, density: bool, dtype,
                          devices: Optional[int], topology,
                          recs: dict) -> dict:
    """Every priced alternative. Advisory candidates (the scheduler
    stream the current knob does NOT execute) are priced with
    selectable=False: the knobs stay user-owned — the autotuner reports
    what a flip would buy (the explain() discipline) but only selects
    among plans the dispatch layer can actually run."""
    from quest_tpu.circuit import _COST_MODELS
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    model = _COST_MODELS["v5e"]   # selection is relative; measured entry
    f32 = np.dtype(dtype).itemsize <= 4
    flat, planned = recs["flat"], recs["planned"]
    cands: Dict[str, dict] = {}
    if devices is None:
        cands["pergate"] = _price_pergate(len(flat), n, model, dtype)
        cands["banded"] = _price_banded(recs["banded"], n, model, dtype)
        if recs["swept"] is not None:
            # the kernels are f32-only: an f64 register rides the banded
            # program (compiled_batched's fallback), so the fused
            # candidate prices but cannot be selected
            cands["fused"] = _price_fused(recs["swept"], n, model, dtype,
                                          selectable=f32)
        # the OTHER scheduler stream, priced but not selectable (flip
        # QUEST_SCHEDULE to execute it)
        other = flat if recs["enabled"] else recs["sched_ops"]
        tag = "nosched" if recs["enabled"] else "sched"
        cands[f"banded:{tag}"] = _price_banded(
            F.plan_stats(F.plan(other, n)), n, model, dtype,
            selectable=False)
        return cands

    # sharded families: local pass pricing on the per-device shard plus
    # the comm planner's weighted element-bytes (comm._cost via
    # choose_plan's candidate table) folded to ms
    from quest_tpu import precision
    from quest_tpu.parallel import comm as C
    from quest_tpu.parallel import sharded as S

    g = devices.bit_length() - 1
    local_n = n - g
    topo = topology if topology is not None else C.topology(devices)
    bands = S._shard_bands(n, local_n)
    chosen, cinfo = C.choose_plan(planned, n, local_n, engine="banded",
                                  bands=bands, topo=topo)
    strategy = cinfo["strategy"]
    comm_cost = cinfo["candidates"][strategy]
    rdt = precision.real_dtype_of(precision.get_default_dtype())
    bpr = np.dtype(rdt).itemsize
    items = cinfo.get("items")
    if items is None:
        items = F.plan(chosen, n, bands=bands)
    bstats = F.plan_stats(items)
    sb = _price_banded(bstats, local_n, model, dtype,
                       comm_elem_bytes=comm_cost["elem_bytes"],
                       comm_steps=comm_cost["exchanges"],
                       bytes_per_real=bpr)
    # every comm strategy the planner priced rides along as an advisory
    # candidate (choose_plan already applied incumbent-wins-ties on
    # this axis — docs/DISTRIBUTED.md)
    for name, cc in cinfo["candidates"].items():
        if name == strategy:
            continue
        cands[f"sharded-banded:comm={name}"] = _price_banded(
            bstats, local_n, model, dtype,
            comm_elem_bytes=cc["elem_bytes"], comm_steps=cc["exchanges"],
            bytes_per_real=bpr, selectable=False)
    cands["sharded-banded"] = sb
    if PB.usable(local_n) and recs["fused"] is not None:
        # projected from the unsharded fused/banded pass ratio on the
        # local shard: the sharded fused engine runs the same segment
        # geometry per shard between the identical exchanges
        ratio = (recs["fused"]["hbm_sweeps"]
                 / max(1, recs["banded"]["full_state_passes"]))
        lo = sb["est_ms_lo"] * ratio
        cands["sharded-fused"] = _cost_rec(
            lo, sb["est_ms_hi"] * ratio,
            max(1, int(round(bstats["full_state_passes"] * ratio))),
            compile_ops=recs["fused"]["hbm_sweeps"],
            comm_elem_bytes=comm_cost["elem_bytes"],
            comm_steps=comm_cost["exchanges"], bytes_per_real=bpr,
            selectable=f32)
    return cands


# ---------------------------------------------------------------------------
# the autotuner
# ---------------------------------------------------------------------------

def autotune(circuit, state_kind: str = "pure", mesh=None, topology=None,
             dtype=np.float32, batch: Optional[int] = None,
             devices: Optional[int] = None,
             persist: Optional[bool] = None) -> ProgramPlan:
    """Price every executable (engine x comm strategy) alternative for
    `circuit` through each subsystem's own cost model and return the
    cheapest as a ProgramPlan — incumbent-wins-ties, so the chosen
    plan's priced cost is NEVER above what the stack dispatched before
    the autotuner existed (scripts/check_plan_golden.py gates this on
    every golden circuit).

    `state_kind` is 'pure' or 'density'; `mesh` (a jax Mesh) or
    `devices` selects the sharded families; `topology` overrides the
    QUEST_COMM_TOPOLOGY resolution for comm pricing. `persist=None`
    follows the QUEST_PLAN_CACHE knob: content-addressed plans load
    from / store to the persistent cache (plan_cache_dir()), so a warm
    restart prices from disk with zero searches. Circuits with
    unrenderable operands (traced parameters) cannot be
    content-addressed and always search."""
    if state_kind not in ("pure", "density"):
        raise ValueError(
            f"state_kind must be 'pure' or 'density', got {state_kind!r}")
    _reject_dynamic(circuit, "plan.autotune")
    density = state_kind == "density"
    if mesh is not None:
        if devices is not None:
            raise ValueError("pass mesh= or devices=, not both")
        devices = int(np.asarray(mesh.devices).size)
    n = circuit.num_qubits * 2 if density else circuit.num_qubits
    if persist is None:
        from quest_tpu.env import knob_value
        persist = bool(knob_value("QUEST_PLAN_CACHE"))
    key = plan_key(circuit, density=density, dtype=dtype, batch=batch,
                   devices=devices, topology=topology)
    if key is None:
        _CACHE_STATS["unkeyed"] += 1
    elif persist:
        cached = load_plan(key)
        if cached is not None:
            _CACHE_STATS["hits"] += 1
            return cached
        _CACHE_STATS["misses"] += 1
    _CACHE_STATS["searches"] += 1
    recs = _subsystem_records(circuit, n, density, batch, devices)
    cands = _enumerate_candidates(circuit, n, density, dtype, devices,
                                  topology, recs)
    incumbent = _incumbent_engine(len(circuit.ops), devices)
    # the transpile axis: price the rewritten stream's candidates
    # alongside the raw ones ("<engine>:transpiled"). Under 'auto' the
    # RAW incumbent stays the tie-winner, so no golden circuit can
    # regress by construction; '1' prefers the transpiled family
    # whenever the rewrite changed the stream.
    tr_rec, tr_c = _transpile_record(circuit, n, density, recs)
    if tr_c is not None:
        recs_t = _subsystem_records(tr_c, n, density, batch, devices)
        for cname, cval in _enumerate_candidates(
                tr_c, n, density, dtype, devices, topology,
                recs_t).items():
            cands[f"{cname}:transpiled"] = cval
    selectable = {k: v for k, v in cands.items() if v["selectable"]}
    assert incumbent in selectable, (incumbent, sorted(cands))
    best = incumbent
    pool = selectable
    if tr_rec is not None and tr_rec["knob"] == "1" and tr_c is not None:
        inc_t = _incumbent_engine(len(tr_c.ops), devices) + ":transpiled"
        pool_t = {k: v for k, v in selectable.items()
                  if k.endswith(":transpiled")}
        if inc_t in pool_t:
            best, pool = inc_t, pool_t
    for name in sorted(pool):
        if _rank(pool[name]) < _rank(pool[best]):
            best = name
    if tr_rec is not None:
        tr_rec["chosen"] = best.endswith(":transpiled")
    plan = ProgramPlan(
        version=PLAN_FORMAT_VERSION,
        key=key, num_qubits=circuit.num_qubits, n=n,
        density=density, dtype=np.dtype(dtype).str,
        batch=None if batch is None else int(batch),
        devices=None if devices is None else int(devices),
        engine=best, incumbent=incumbent, source="search",
        cost=cands[best], candidates=cands,
        scheduled=recs["enabled"], flat_ops=len(recs["flat"]),
        planned_ops=len(recs["planned"]), scheduler=recs["scheduler"],
        banded=recs["banded"], fused=recs["fused"],
        batched=recs["batched"], f64=recs["f64"], comm=recs["comm"],
        extra=_plan_extra(circuit, density),
        grad=_grad_record(circuit, density, dtype, devices),
        transpile=tr_rec)
    if persist and key is not None:
        save_plan(plan)
    return plan


# ---------------------------------------------------------------------------
# content addressing
# ---------------------------------------------------------------------------

def _render_operand(x) -> Optional[list]:
    """JSON-native fingerprint of a gate operand, or None when the
    value cannot be content-addressed (a traced parameter): such
    circuits still autotune, they just never cache."""
    if x is None:
        return ["none"]
    try:
        arr = np.asarray(x)
        if arr.dtype == object:
            return None
        return ["arr", list(arr.shape), arr.dtype.str,
                hashlib.sha256(
                    np.ascontiguousarray(arr).tobytes()).hexdigest()[:16]]
    except Exception:
        return None


def _op_fingerprint(op) -> Optional[list]:
    operand = _render_operand(op.operand)
    if operand is None:
        return None
    return [op.kind, list(op.targets), list(op.controls),
            list(op.cstates or []), operand]


def plan_key(circuit, *, density: bool, dtype, batch: Optional[int],
             devices: Optional[int], topology=None) -> Optional[str]:
    """Content-addressed plan identity: sha256 over the op stream's
    VALUES plus everything the priced answer depends on — register
    kind, plane dtype, batch bucket, device count, the topology model
    and engine_mode_key() (a keyed-knob flip is a different plan, the
    compiled-program cache-key discipline). Returns None when an
    operand is unrenderable (traced parameters) — never a wrong key."""
    from quest_tpu.env import batch_bucket, engine_mode_key
    ops_fp: List[list] = []
    for op in circuit.ops:
        fp = _op_fingerprint(op)
        if fp is None:
            return None
        ops_fp.append(fp)
    topo_desc = None
    if devices is not None:
        from quest_tpu.parallel import comm as C
        topo = topology if topology is not None else C.topology(devices)
        topo_desc = topo.describe(devices)
    ident = {
        "format_version": PLAN_FORMAT_VERSION,
        "num_qubits": circuit.num_qubits,
        "ops": ops_fp,
        "density": bool(density),
        "dtype": np.dtype(dtype).str,
        "bucket": None if batch is None else batch_bucket(int(batch)),
        "devices": devices,
        "topology": topo_desc,
        "mode": [[k, repr(v)] for k, v in engine_mode_key()],
    }
    return hashlib.sha256(json.dumps(
        ident, sort_keys=True, separators=(",", ":")).encode()).hexdigest()


# ---------------------------------------------------------------------------
# the persistent cache (versioned + self-digested, loud-skip on damage)
# ---------------------------------------------------------------------------

def _self_digest(meta: dict) -> str:
    clean = {k: v for k, v in meta.items() if k != "plan_digest"}
    return hashlib.sha256(json.dumps(
        clean, sort_keys=True, separators=(",", ":")).encode()).hexdigest()


def plan_cache_dir(create: bool = True) -> Optional[str]:
    """The plan cache directory: QUEST_PLAN_CACHE_DIR, defaulting to
    `<compile cache>.plans` — literally next to the XLA compile cache
    (precision.enable_compile_cache), so the two warm-restart stores
    travel together. None when the location is unwritable (callers
    fall back to searching, loudly counted)."""
    from quest_tpu.env import knob_value
    path = knob_value("QUEST_PLAN_CACHE_DIR")
    if path is None:
        base = knob_value("QUEST_COMPILE_CACHE_DIR")
        if base is None:
            repo = os.path.dirname(os.path.dirname(os.path.abspath(
                __file__)))
            base = os.path.join(repo, ".jax_cache")
        path = base + ".plans"
    if create:
        try:
            os.makedirs(path, exist_ok=True)
            if not os.access(path, os.W_OK):
                return None
        except OSError:
            return None
    return path


def _loud_skip(path: str, why: str, counter: str) -> None:
    _CACHE_STATS[counter] += 1
    print(f"[quest_tpu.plan] {counter.upper()} plan-cache entry "
          f"{path!r} skipped to a fresh price: {why} (never silently "
          f"consumed — docs/PLANNING.md)", file=sys.stderr, flush=True)


def save_plan(plan: ProgramPlan) -> Optional[str]:
    """Persist a searched plan (atomic tmp+rename; versioned and
    self-digested). Returns the path, or None when the cache directory
    is unavailable."""
    if plan.key is None:
        return None
    d = plan_cache_dir()
    if d is None:
        return None
    path = os.path.join(d, f"plan-{plan.key}.json")
    tmp = path + f".tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(plan.to_meta(), f, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        print(f"[quest_tpu.plan] could not persist plan {path!r}: "
              f"{e!r}", file=sys.stderr, flush=True)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return None
    _CACHE_STATS["stores"] += 1
    return path


def load_plan(key: str) -> Optional[ProgramPlan]:
    """Load a persisted plan by content key. A missing entry returns
    None quietly (a cold cache is normal); a CORRUPTED or
    STALE-VERSION entry returns None LOUDLY (stderr + counter) so the
    caller re-prices — a damaged plan is never silently consumed (the
    checkpoint discipline)."""
    d = plan_cache_dir()
    if d is None:
        return None
    path = os.path.join(d, f"plan-{key}.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        _loud_skip(path, f"unreadable JSON ({e!r})", "corrupt")
        return None
    version = meta.get("version")
    if version != PLAN_FORMAT_VERSION:
        _loud_skip(path, f"format version {version!r} != "
                   f"{PLAN_FORMAT_VERSION}", "stale")
        return None
    digest = meta.get("plan_digest")
    if digest != _self_digest(meta):
        _loud_skip(path, "self-digest mismatch (bytes damaged on disk)",
                   "corrupt")
        return None
    if meta.get("key") != key:
        _loud_skip(path, "content key mismatch (entry filed under the "
                   "wrong identity)", "corrupt")
        return None
    try:
        plan = ProgramPlan.from_meta(meta)
    except TypeError as e:
        _loud_skip(path, f"schema mismatch ({e!r})", "corrupt")
        return None
    return dataclasses.replace(plan, source="cache")


# ---------------------------------------------------------------------------
# geometry helpers for the satellite surfaces
# ---------------------------------------------------------------------------

def sweep_chunk(total: int, num_qubits: int, *, density: bool = False,
                dtype=np.float32) -> int:
    """Priced chunk size for variational.sweep(chunk='auto'): the
    largest batch bucket whose live amplitudes (chunk x both planes x
    2^n at `dtype`, x3 for the ansatz's working set) fit the capacity
    model's HBM budget (apply.f64_capacity_stats — the same chunking
    contract the f64 limb path sizes against), clamped to [1, total]."""
    from quest_tpu.env import batch_bucket
    from quest_tpu.ops import apply as A
    n = num_qubits * 2 if density else num_qubits
    hbm = A.f64_capacity_stats(n)["hbm_bytes"]
    state_bytes = 2 * np.dtype(dtype).itemsize * (1 << n)
    fit = max(1, int(hbm // (3 * state_bytes)))
    chunk = 1
    while chunk * 2 <= min(fit, max(1, int(total))):
        chunk *= 2
    return batch_bucket(min(chunk, max(1, int(total))))
