"""Differentiable parameterized circuits — variational simulation.

A capability the reference architecture cannot express: gate angles are
TRACED inputs, so whole expectation-value evaluations are `jax.jit`-,
`jax.grad`- and `jax.vmap`-able. One compiled program evaluates an
ansatz energy AND its exact gradient (reverse-mode through the
simulation — the classical analogue of parameter-shift at zero extra
engineering), or a whole batch of parameter sets at once. The reference
bakes every operand into an eager per-gate kernel call (QuEST.c
validate->dispatch) and offers no derivatives.

Usage:
    from quest_tpu import variational as V

    def ansatz(amps, params):
        amps = V.ry(amps, n, 0, params[0])
        amps = V.cnot(amps, n, 0, 1)
        amps = V.rz(amps, n, 1, params[1])
        return amps

    energy = V.expectation(ansatz, n, codes, coeffs)  # params -> float
    value, grad = jax.value_and_grad(energy)(params)
    energies = jax.vmap(energy)(param_batch)          # batched ansatz

The gate set covers the parameterized family (rx/ry/rz/phase/crz/
parity strings) plus the fixed Cliffords needed around them; arbitrary
fixed gates pass through `gate`. Statevector registers; f32 planes
(matching the TPU fast path — the gradient of an f32 simulation is
computed in f32).
"""

from __future__ import annotations

import weakref
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import cplx
from quest_tpu.ops import apply as A
from quest_tpu.ops import matrices as M


def _mat2(amps, m00, m01, m10, m11):
    """(2, 2) traced operator from complex-component scalars given as
    (re, im) tuples; returns the (re, im) pair apply_matrix expects."""
    dt = amps.dtype
    z = jnp.zeros((), dtype=dt)

    def part(x):
        return jnp.asarray(x, dtype=dt) if x is not None else z
    re = jnp.stack([jnp.stack([part(m00[0]), part(m01[0])]),
                    jnp.stack([part(m10[0]), part(m11[0])])])
    im = jnp.stack([jnp.stack([part(m00[1]), part(m01[1])]),
                    jnp.stack([part(m10[1]), part(m11[1])])])
    return re, im


def rx(amps, n, target, theta, controls=(), cstates=()):
    """exp(-i theta/2 X) on `target` (ref rotateX, QuEST_common.c:292)."""
    hh = jnp.asarray(theta, dtype=amps.dtype) / 2.0
    c, s = jnp.cos(hh), jnp.sin(hh)
    pair = _mat2(amps, (c, None), (None, -s), (None, -s), (c, None))
    return A.apply_matrix(amps, n, pair, (target,), controls, cstates)


def ry(amps, n, target, theta, controls=(), cstates=()):
    """exp(-i theta/2 Y) on `target` (ref rotateY)."""
    hh = jnp.asarray(theta, dtype=amps.dtype) / 2.0
    c, s = jnp.cos(hh), jnp.sin(hh)
    pair = _mat2(amps, (c, None), (-s, None), (s, None), (c, None))
    return A.apply_matrix(amps, n, pair, (target,), controls, cstates)


def rz(amps, n, target, theta):
    """exp(-i theta/2 Z) on `target` (ref rotateZ) — a parity phase, so
    it lowers to a pure elementwise program."""
    return A.apply_parity_phase(amps, n, (target,), theta)


def parity(amps, n, targets: Sequence[int], theta):
    """exp(-i theta/2 Z...Z) over `targets` (ref multiRotateZ) — the
    Ising-coupling generator of QAOA cost layers."""
    return A.apply_parity_phase(amps, n, tuple(targets), theta)


def phase(amps, n, target, theta, controls=(), cstates=None):
    """diag(1, e^{i theta}) on `target` (ref [controlled]phaseShift).
    `cstates` optionally conditions on zero-controls; the default
    (all-ones) keeps the symmetric phase_on_all_ones fast path."""
    t = jnp.asarray(theta, dtype=amps.dtype)
    if cstates is not None and any(int(s) == 0 for s in cstates):
        dre = jnp.stack([jnp.ones((), amps.dtype), jnp.cos(t)])
        dim = jnp.stack([jnp.zeros((), amps.dtype), jnp.sin(t)])
        return A.apply_diagonal(amps, n, (dre, dim), (target,),
                                tuple(controls), tuple(cstates))
    qubits = (target,) + tuple(controls)
    return A.apply_phase_on_all_ones(amps, n, qubits,
                                     (jnp.cos(t), jnp.sin(t)))


def crz(amps, n, control, target, theta):
    """Controlled rotateZ (ref controlledRotateZ): diag(e^{-it/2},
    e^{it/2}) on `target` where `control` is 1."""
    hh = jnp.asarray(theta, dtype=amps.dtype) / 2.0
    pair = _mat2(amps, (jnp.cos(hh), -jnp.sin(hh)), (None, None),
                 (None, None), (jnp.cos(hh), jnp.sin(hh)))
    return A.apply_matrix(amps, n, pair, (target,), (control,))


def gate(amps, n, matrix, targets, controls=()):
    """Fixed (concrete) k-qubit unitary."""
    return A.apply_matrix(amps, n, cplx.pack(np.asarray(matrix)),
                          tuple(targets), tuple(controls))


def h(amps, n, target):
    return gate(amps, n, M.HADAMARD, (target,))


def x(amps, n, target):
    return gate(amps, n, M.PAULI_X, (target,))


def cnot(amps, n, control, target):
    return gate(amps, n, M.PAULI_X, (target,), (control,))


def cz(amps, n, q1, q2):
    return A.apply_phase_on_all_ones(amps, n, (q1, q2),
                                     (jnp.asarray(-1.0, amps.dtype),
                                      jnp.asarray(0.0, amps.dtype)))


def expectation(ansatz: Callable, n: int, all_codes, coeffs=None,
                initial_index: int = 0, dtype=np.float32) -> Callable:
    """Build `energy(params) -> float`: <psi(params)| H |psi(params)> for
    the Pauli-sum H = sum_t coeffs[t] * P_t (codes as in
    calc_expec_pauli_sum: one 0..3 code per qubit per term), or an
    `expec.PauliSum` spec passed as `all_codes` (coeffs omitted).

    The Hamiltonian evaluates through the grouped sweep-fused
    expectation engine (ops/expec, docs/EXPECTATION.md): terms sharing
    a flip mask share one conj(a)*a_flip pass, so a TFIM-class energy
    is 2 sweeps instead of one workspace pass per term. The returned
    function is pure and traced end-to-end: wrap it in jax.jit,
    differentiate with jax.grad (the fused forward is plain XLA — the
    gradient traces straight through, parity-pinned against the eager
    per-term path in tests/test_expec.py), batch with jax.vmap or
    `sweep`. The ansatz receives ((2, 2^n) planes, params) and returns
    new planes. `dtype` is the real plane dtype (float32 matches the
    TPU fast path; float64 needs jax_enable_x64)."""
    from quest_tpu import validation as val
    from quest_tpu.ops import expec as E
    from quest_tpu.state import basis_planes

    if isinstance(all_codes, E.PauliSum):
        if coeffs is not None:
            raise ValueError("pass coefficients inside the PauliSum, "
                             "not as a separate coeffs= argument")
        if all_codes.num_qubits != n:
            raise ValueError(
                f"PauliSum is over {all_codes.num_qubits} qubits but "
                f"the ansatz register has {n}")
        codes_key = E.parse_pauli_sum(np.asarray(all_codes.codes), n)
        coeffs = np.asarray(all_codes.coeffs, dtype=np.float64)
    else:
        codes_key = E.parse_pauli_sum(all_codes, n)
        coeffs = np.asarray(coeffs, dtype=np.float64).reshape(-1)
    if len(coeffs) != len(codes_key):
        val._err("Invalid Pauli sum: must give exactly one coefficient "
                 "per term.")
    plan = E.plan_expec(codes_key, n, density=False)
    rdt = np.dtype(dtype)

    def energy(params):
        amps = basis_planes(initial_index, n=n, rdt=rdt)
        amps = ansatz(amps, params)
        return E.expec_traced(amps, jnp.asarray(coeffs, amps.dtype),
                              plan).astype(amps.dtype)

    # geometry tags for the priced sweep-chunk helper: sweep(chunk=
    # "auto") sizes its bucket from the capacity model without being
    # handed the register size (quest_tpu/plan.py sweep_chunk)
    energy.num_qubits = n
    energy.real_dtype = rdt.str
    ansatz_key = getattr(ansatz, "program_key", None)
    if ansatz_key is not None:
        # VALUE identity of the whole energy program: an ansatz that
        # declares its program_key (e.g. evolution.trotter_ansatz)
        # promises that equal keys trace identically, so a REBUILT
        # energy over an equal ansatz + equal Pauli sum may share the
        # compiled program. sweep() keys its program cache on this
        # instead of the energy-fn object — without it, an optimizer
        # loop rebuilding the ansatz each step retraced every
        # iteration (tests/test_evolution.py pins the fix by call
        # count and under the CompileAuditor). The BUILD-time
        # engine_mode_key rides the key: the expec plan above is
        # resolved NOW, so two energies built under different keyed
        # knob values are different programs even when everything else
        # matches (_sweep_program adds the TRACE-time mode key on top).
        from quest_tpu.env import engine_mode_key
        energy.sweep_key = ("variational.expectation", ansatz_key,
                            codes_key, coeffs.tobytes(),
                            int(initial_index), rdt.str, n,
                            engine_mode_key())
    return energy


# one jitted vmapped program per energy function (weak: a dropped fn
# frees its trace cache with it)
_SWEEP_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()

# VALUE-keyed companion for energy functions that declare a `sweep_key`
# (expectation() over a program_key-bearing ansatz): rebuilt-but-equal
# functions hit the same compiled program. Bounded FIFO — value keys
# cannot be weak, so the cap bounds held traces
_SWEEP_CACHE_KEYED: dict = {}
_SWEEP_KEYED_MAX = 64


def _sweep_program(fn: Callable):
    """The jitted vmapped program for `fn`: by VALUE when the function
    declares `sweep_key` (the program_key contract — equal keys promise
    identical traces), by object identity (weakly) otherwise. The
    value key additionally carries engine_mode_key(): what a rebuilt
    energy traces depends on the keyed knobs (the expec mask budget,
    matmul precision, the f64 limb scheme), so a mid-process knob flip
    must MISS — the Circuit.program_key discipline; the weak per-object
    path needs no such guard because a flip changes what the NEXT
    built fn closes over, and an already-built fn's trace is its own."""
    key = getattr(fn, "sweep_key", None)
    if key is None:
        batched = _SWEEP_CACHE.get(fn)
        if batched is None:
            batched = jax.jit(jax.vmap(fn))
            _SWEEP_CACHE[fn] = batched
        return batched
    from quest_tpu.env import engine_mode_key
    key = (key, engine_mode_key())
    batched = _SWEEP_CACHE_KEYED.get(key)
    if batched is None:
        batched = jax.jit(jax.vmap(fn))
        _SWEEP_CACHE_KEYED[key] = batched
        while len(_SWEEP_CACHE_KEYED) > _SWEEP_KEYED_MAX:
            _SWEEP_CACHE_KEYED.pop(next(iter(_SWEEP_CACHE_KEYED)))
    return batched


def sweep(fn: Callable, param_batch, chunk: int = None):
    """Evaluate `fn` (an energy/ansatz function of one parameter set)
    over a whole batch of parameter sets — the variational counterpart
    of the batched execution engine (docs/BATCHING.md): ONE compiled
    vmapped program per bucket, re-used across chunks, instead of a
    Python loop of single evaluations. `chunk` bounds live memory
    (each vmapped evaluation holds chunk x 2^n amplitudes);
    chunk='auto' prices it from the capacity model instead
    (plan.sweep_chunk — the largest bucket whose live amplitudes fit
    the HBM budget, docs/PLANNING.md); batch
    sizes BUCKET like Circuit.compiled_batched (env.batch_bucket,
    QUEST_BATCH_BUCKET) so mixed sweep sizes share one jit cache
    entry — the pad evaluations re-run the first parameter set and are
    sliced off. The jitted vmapped program is cached per `fn` (weakly,
    so dropping the energy function frees it) — or by VALUE when `fn`
    declares a `sweep_key` (expectation() over a program_key-bearing
    ansatz such as evolution.trotter_ansatz), so an optimizer loop
    that REBUILDS an equal energy function every iteration still hits
    one compiled program: repeated sweep() calls reuse ONE trace
    instead of rebuilding jax.jit(jax.vmap(fn)) — and with it the
    whole jit cache — each call. `param_batch` is a stacked array (a
    list stacks, as always) or a tuple/dict pytree whose leaves share
    the leading batch axis — the evolved ansatz's (coeffs, dt) pair.
    A tuple whose leaves all share one shape is REJECTED loudly: it
    could mean either stack-or-pytree, and the two disagree silently.
    Traced-parameter circuits cannot pre-compose into the
    fixed-operand sweep kernels (their operands are data), so this is
    the supported fast path for parameter sweeps; fixed circuits batch
    through Circuit.compiled_batched instead."""
    from quest_tpu.env import batch_bucket

    # param sets may be one stacked array (a list of param vectors
    # STACKS, the original sweep contract) OR a tuple/dict pytree of
    # stacked leaves sharing the leading batch axis — e.g. the evolved
    # ansatz's (coeffs, dt) pair (evolution.trotter_ansatz): every
    # leaf is sliced/padded together, vmap maps over axis 0 of each
    if isinstance(param_batch, list):
        param_batch = jnp.asarray(param_batch)
    elif isinstance(param_batch, tuple):
        # a tuple whose leaves all share ONE shape is ambiguous: under
        # the pre-pytree contract jnp.asarray would have STACKED it
        # into the batch axis, under the pytree contract each leaf
        # carries the batch axis — silently picking either gives the
        # other caller wrong results with no error, so refuse loudly
        shapes = {tuple(getattr(v, "shape", np.shape(v)))
                  for v in jax.tree_util.tree_leaves(param_batch)}
        if len(shapes) <= 1:
            raise ValueError(
                "ambiguous tuple param_batch (every leaf has shape "
                f"{shapes or {()}}): pass a LIST to stack parameter "
                "sets into the batch axis, a pre-stacked array, or a "
                "dict / shape-heterogeneous pytree whose leaves share "
                "the leading batch axis")
    params = jax.tree_util.tree_map(jnp.asarray, param_batch)
    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        raise ValueError("param_batch has no array leaves to sweep over")
    total = int(leaves[0].shape[0]) if leaves[0].ndim else 0
    for leaf in leaves:
        if leaf.ndim == 0 or int(leaf.shape[0]) != total:
            raise ValueError(
                "every param_batch leaf must share the leading batch "
                f"axis: got shapes {[tuple(l.shape) for l in leaves]}")
    if chunk == "auto":
        # priced chunk (quest_tpu/plan.py): the largest bucket whose
        # live amplitudes fit the capacity model's HBM budget — opt-in,
        # so chunk=None keeps the one-vmap legacy behavior exactly
        nq = getattr(fn, "num_qubits", None)
        if nq is None:
            raise ValueError(
                "chunk='auto' needs fn.num_qubits (set by "
                "variational.expectation); pass an explicit chunk for "
                "a bare ansatz function")
        from quest_tpu import plan as P
        chunk = P.sweep_chunk(total, int(nq),
                              dtype=getattr(fn, "real_dtype", "f4"))
    per_call = total if chunk is None else max(1, min(int(chunk), total))
    bucket = batch_bucket(per_call)
    if chunk is None and bucket > total:
        # mirror run_batched's implicit-bucket cap: 257 parameter sets
        # sweep as one 256-chunk plus a padded remainder, not one
        # 512-wide vmap doubling peak memory and wasting 255 evals
        smaller = batch_bucket(max(1, bucket // 2))
        if smaller < bucket:
            bucket = smaller
    batched = _sweep_program(fn)

    def _pad(a, pad):
        return jnp.concatenate(
            [a, jnp.broadcast_to(a[:1], (pad,) + a.shape[1:])])

    outs = []
    for lo in range(0, total, bucket):
        pb = jax.tree_util.tree_map(lambda a: a[lo:lo + bucket], params)
        pad = bucket - min(bucket, total - lo)
        if pad:
            pb = jax.tree_util.tree_map(lambda a: _pad(a, pad), pb)
        out = batched(pb)
        outs.append(out[:-pad] if pad else out)
    if len(outs) == 1:
        return outs[0]
    return jnp.concatenate(outs, axis=0)
