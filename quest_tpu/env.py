"""Execution environment: device mesh and sharding policy.

The reference's QuESTEnv is {rank, numRanks} over MPI (QuEST.h:199-203,
QuEST_cpu_distributed.c:129-160, power-of-2 ranks required). The TPU-native
equivalent is a 1-D `jax.sharding.Mesh` over the amplitude axis: a register
whose amplitude count is divisible by the mesh size is laid out with its
top log2(num_devices) qubits "global" (one contiguous chunk per device),
exactly the reference's chunk layout (QuEST_cpu.c:1280-1312) — so gates on
low qubits are embarrassingly local and gates on global qubits lower to XLA
collectives over ICI.

Multi-host pods: pass `distributed=True` to have jax.distributed.initialize
wire up DCN before the mesh is built (the analogue of MPI_Init).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AMP_AXIS = "amp"


# ---------------------------------------------------------------------------
# QUEST_* knob registry — the single source of truth for every runtime
# environment knob (ISSUE 2 satellite; the analogue of the reference's
# one-table validation front-end, QuEST_validation.c). Each entry records
# the validating parser (raises ValueError on malformed input — knobs
# parse LOUDLY), the default, and the knob's compile scope:
#
#   keyed        read at TRACE time inside compiled paths; its effective
#                value is part of engine_mode_key(), so every compiled-
#                program cache (circuit-level engines AND the eager
#                per-gate jit workers) misses when it flips (the
#                stale-program class of ADVICE r4 item 2 / r5 item 2)
#   import_once  resolved once per process (module import or first
#                compile) and deliberately never re-read — stale-proof
#                by construction; mid-process flips are ignored, sweeps
#                go through subprocesses (pallas_band's block knobs)
#   runtime      read outside any compiled path (host tooling, bench,
#                test harness); can never return a stale program
#
# quest-lint enforces the registry statically: QL001 checks that every
# knob read reachable from a jitted/fused/Pallas path is keyed or
# import_once, QL004 that every read routes through knob_value()'s
# validating parser (quest_tpu/analysis/). The knob-flip audit
# (quest_tpu/analysis/audit.py) checks the keyed contract dynamically.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Knob:
    """One registered QUEST_* environment knob."""
    name: str                       # full variable name, e.g. QUEST_SCHEDULE
    parse: Callable[[str], Any]     # raw string -> value; ValueError if bad
    default: Any                    # value when unset (callable = dynamic)
    scope: str                      # "keyed" | "import_once" | "runtime"
    layer: str                      # subsystem: apply|planner|host|kernel|
                                    #            infra|bench|test|build|serve
    doc: str                        # one-liner (docs/CONFIG.md parity)
    malformed: Optional[str] = None     # sample raw value parse() must
                                        # reject (None: every string parses)
    flips: Optional[Tuple[str, str]] = None  # two raw values with distinct
                                             # effective values (flip audit)
    current: Optional[Callable[[], Any]] = None  # effective-value getter
                                                 # override (setter-backed
                                                 # knobs); default reads env


def _bool01(name: str) -> Callable[[str], bool]:
    def parse(raw: str) -> bool:
        if raw not in ("0", "1"):
            raise ValueError(f"{name} must be '0' or '1', got {raw!r}")
        return raw == "1"
    return parse


def _int_range(name: str, lo: Optional[int] = None,
               hi: Optional[int] = None) -> Callable[[str], int]:
    def parse(raw: str) -> int:
        try:
            v = int(raw)
        except ValueError:
            raise ValueError(f"{name} must be an integer, got {raw!r}")
        if (lo is not None and v < lo) or (hi is not None and v > hi):
            raise ValueError(
                f"{name} must be in [{lo}, {'inf' if hi is None else hi}], "
                f"got {v}")
        return v
    return parse


def _parse_f64_chunk(raw: str) -> int:
    try:
        c = int(raw)
    except ValueError:
        raise ValueError(
            f"QUEST_F64_CHUNK must be an integer element count, got {raw!r}")
    if c < 0 or (c and c & (c - 1)):
        raise ValueError(
            f"QUEST_F64_CHUNK must be 0 (chunking off) or a positive "
            f"power of two (state sizes are powers of two, so any other "
            f"chunk cannot divide the row axis), got {c}")
    return c


def _parse_matmul_precision(raw: str):
    table = {"default": jax.lax.Precision.DEFAULT,
             "high": jax.lax.Precision.HIGH,
             "highest": jax.lax.Precision.HIGHEST}
    if raw.lower() not in table:
        raise ValueError(
            f"matmul precision must be one of {sorted(table)} "
            f"(via QUEST_MATMUL_PRECISION or set_matmul_precision), "
            f"got {raw!r}")
    return table[raw.lower()]


def _parse_choice(name: str, choices: Tuple[str, ...]) -> Callable[[str], str]:
    def parse(raw: str) -> str:
        if raw not in choices:
            raise ValueError(f"{name} must be one of {sorted(choices)}, "
                             f"got {raw!r}")
        return raw
    return parse


def _parse_engine_ladder(raw: str) -> Tuple[str, ...]:
    ladder = tuple(raw.split(","))
    bad = [e for e in ladder if e not in ("banded", "fused", "xla", "host")]
    if bad:
        raise ValueError(f"unknown engine(s) in QUEST_BENCH_ENGINES: {bad}")
    return ladder


def _parse_exchange_slices(raw: str) -> int:
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"QUEST_EXCHANGE_SLICES must be an integer, got {raw!r}")
    if v < 1 or v > 1024 or (v & (v - 1)):
        raise ValueError(
            f"QUEST_EXCHANGE_SLICES must be a power of two in [1, 1024] "
            f"(exchange blocks are power-of-two sized, so any other "
            f"slice count cannot divide them), got {v}")
    return v


def _parse_comm_topology(raw: str):
    """QUEST_COMM_TOPOLOGY grammar: '0' (flat — reproduce the PR-8
    planner bit-for-bit) or 'hosts=H[,ici=X][,dci=Y]' — devices grouped
    into H hosts (contiguous, matching jax's host-major device order),
    intra-host links weighted X (default 1) and cross-host links Y
    (default 4). Returns 0 or a (hosts, ici, dci) tuple; comm.topology()
    turns it into the Topology the planner prices with."""
    if raw == "0":
        return 0
    hosts, ici, dci = None, 1.0, 4.0
    for part in raw.split(","):
        if "=" not in part:
            raise ValueError(
                f"QUEST_COMM_TOPOLOGY must be '0' or "
                f"'hosts=H[,ici=X][,dci=Y]', got {raw!r}")
        key, val = part.split("=", 1)
        key = key.strip()
        try:
            if key == "hosts":
                hosts = int(val)
            elif key in ("ici", "dci"):
                v = float(val)
                if not (v > 0):
                    raise ValueError
                if key == "ici":
                    ici = v
                else:
                    dci = v
            else:
                raise KeyError(key)
        except KeyError:
            raise ValueError(
                f"unknown QUEST_COMM_TOPOLOGY key {key!r} in {raw!r} "
                f"(known: hosts, ici, dci)")
        except ValueError:
            raise ValueError(
                f"QUEST_COMM_TOPOLOGY {key}= must be a positive "
                f"{'integer' if key == 'hosts' else 'number'}, "
                f"got {val!r}")
    if hosts is None:
        raise ValueError(
            f"QUEST_COMM_TOPOLOGY must name hosts= (got {raw!r})")
    if hosts < 1 or hosts & (hosts - 1):
        raise ValueError(
            f"QUEST_COMM_TOPOLOGY hosts must be a power of two >= 1 "
            f"(device counts are powers of two, so any other host count "
            f"cannot group them evenly), got {hosts}")
    return (hosts, ici, dci)


def _parse_dci_slices(raw: str) -> int:
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"QUEST_EXCHANGE_SLICES_DCI must be an integer, got {raw!r}")
    if v < 0 or v > 1024 or (v and v & (v - 1)):
        raise ValueError(
            f"QUEST_EXCHANGE_SLICES_DCI must be 0 (follow "
            f"QUEST_EXCHANGE_SLICES) or a power of two in [1, 1024], "
            f"got {v}")
    return v


def _parse_pos_float(name: str) -> Callable[[str], float]:
    def parse(raw: str) -> float:
        try:
            v = float(raw)
        except ValueError:
            raise ValueError(f"{name} must be a float, got {raw!r}")
        if not (v > 0.0):
            raise ValueError(f"{name} must be > 0, got {v}")
        return v
    return parse


def _parse_nonneg_float(name: str) -> Callable[[str], float]:
    def parse(raw: str) -> float:
        try:
            v = float(raw)
        except ValueError:
            raise ValueError(f"{name} must be a float, got {raw!r}")
        if not (v >= 0.0):
            raise ValueError(f"{name} must be >= 0, got {v}")
        return v
    return parse


def _parse_fault_plan(raw: str):
    # the resilience package is stdlib-only at import time, so the lazy
    # import cannot cycle back into env.py's module load
    from quest_tpu.resilience import faults
    return faults.parse_plan(raw)


def _parse_tenant_quota(raw: str):
    # admission.py imports only stdlib + validation (numpy) — the lazy
    # import cannot cycle back into env.py's module load
    from quest_tpu.serve.admission import parse_tenant_quota
    return parse_tenant_quota(raw)


def _default_tenant_quota():
    from quest_tpu.serve.admission import DEFAULT_TENANT_QUOTA
    return {"default": DEFAULT_TENANT_QUOTA}


def _parse_shed_threshold(raw: str) -> float:
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"QUEST_SERVE_SHED_THRESHOLD must be a float, got {raw!r}")
    if not (0.0 < v <= 1.0):
        raise ValueError(
            f"QUEST_SERVE_SHED_THRESHOLD must be in (0, 1] — a fraction "
            f"of fleet queue capacity (1.0 disables shedding below the "
            f"hard queue bound), got {v}")
    return v


def _default_f64_mxu() -> bool:
    # on for TPU backends (native f64 dots are software-emulated there —
    # the measured 9 gates/s @ 26q wall, VERDICT r4), off elsewhere
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:       # pragma: no cover - no backend
        return False


def _current_matmul_precision():
    from quest_tpu import precision
    return precision.matmul_precision()


_KNOB_LIST = (
    Knob("QUEST_MATMUL_PRECISION", _parse_matmul_precision,
         jax.lax.Precision.HIGHEST,
         scope="keyed", layer="apply",
         doc="lax.Precision tier for state-amplitude contractions: "
             "default, high or highest (default: highest — bit-exact f32)",
         malformed="ultra", flips=("highest", "high"),
         current=_current_matmul_precision),
    Knob("QUEST_F64_MXU", _bool01("QUEST_F64_MXU"), _default_f64_mxu,
         scope="keyed", layer="apply",
         doc="f64 band contractions ride the MXU limb scheme: 1/0 "
             "(default: 1 on TPU backends, 0 elsewhere)",
         malformed="yes", flips=("0", "1")),
    Knob("QUEST_F64_CHUNK", _parse_f64_chunk, 1 << 24,
         scope="keyed", layer="apply",
         doc="row-chunk size in elements for the f64 limb path; 0 turns "
             "chunking off (default: 2^24)",
         malformed="1000", flips=(str(1 << 24), str(1 << 12))),
    Knob("QUEST_SCHEDULE", _bool01("QUEST_SCHEDULE"), True,
         scope="keyed", layer="planner",
         doc="commutation-aware gate scheduler in front of the fusing "
             "engines' planners: 1/0 (default: 1)",
         malformed="2", flips=("1", "0")),
    Knob("QUEST_ADJOINT", _parse_choice("QUEST_ADJOINT", ("auto", "0", "1")),
         "auto",
         scope="keyed", layer="planner",
         doc="gradient engine for adjoint.value_and_grad: auto (planner "
             "prices adjoint vs taped per width), 0 = force taped "
             "autodiff, 1 = force the adjoint backward walk "
             "(default: auto)",
         malformed="2", flips=("auto", "1")),
    Knob("QUEST_TRANSPILE",
         _parse_choice("QUEST_TRANSPILE", ("auto", "0", "1")),
         "auto",
         scope="keyed", layer="planner",
         doc="circuit transpiler (docs/TRANSPILE.md): auto (the planner "
             "prices raw vs transpiled per circuit, incumbent-wins-"
             "ties), 0 = never rewrite, 1 = prefer the transpiled "
             "stream whenever it changed (default: auto)",
         malformed="2", flips=("auto", "0")),
    Knob("QUEST_FUSED_SCAN", _bool01("QUEST_FUSED_SCAN"), False,
         scope="keyed", layer="planner",
         doc="lax.scan over repeated-structure kernel segments in the "
             "fused engine (program-size lever): 1/0 (default: 0)",
         malformed="on", flips=("0", "1")),
    Knob("QUEST_SWEEP_FUSION", _bool01("QUEST_SWEEP_FUSION"), True,
         scope="keyed", layer="planner",
         doc="sweep-fusion layer: merge consecutive geometry-compatible "
             "kernel segments (incl. across unrolled iterations) into one "
             "HBM sweep per kernel launch: 1/0 (default: 1)",
         malformed="2", flips=("1", "0")),
    Knob("QUEST_EXPEC_FUSION", _bool01("QUEST_EXPEC_FUSION"), True,
         scope="keyed", layer="planner",
         doc="grouped sweep-fused Pauli-sum expectation engine "
             "(docs/EXPECTATION.md): 1/0 (default: 1; 0 restores the "
             "legacy per-term workspace-pass evaluation)",
         malformed="2", flips=("1", "0")),
    Knob("QUEST_EXPEC_MAX_MASKS",
         _int_range("QUEST_EXPEC_MAX_MASKS", 1), 64,
         scope="keyed", layer="planner",
         doc="max off-diagonal flip-mask groups co-riding one fused "
             "expectation sweep — the expectation engine's stage "
             "budget (default: 64)",
         malformed="0", flips=("64", "1")),
    Knob("QUEST_TROTTER_FUSION", _bool01("QUEST_TROTTER_FUSION"), True,
         scope="keyed", layer="planner",
         doc="pooled Trotter emission + fused-engine dispatch for the "
             "evolution workload (docs/EVOLUTION.md): 1/0 (default: 1; "
             "0 restores the legacy per-term emission dispatched "
             "through the eager per-term workers — one flip-form pass "
             "per term application, the honest bench baseline)",
         malformed="2", flips=("1", "0")),
    Knob("QUEST_COMM_PLAN", _bool01("QUEST_COMM_PLAN"), True,
         scope="keyed", layer="planner",
         doc="communication planner for the sharded engines "
             "(docs/DISTRIBUTED.md): pick the cheapest of plain/"
             "coalesced-reshard/relabel-events/lazy per circuit by "
             "predicted comm_stats bytes: 1/0 (default: 1; 0 restores "
             "the fixed legacy policies)",
         malformed="2", flips=("1", "0")),
    Knob("QUEST_EXCHANGE_SLICES", _parse_exchange_slices, 1,
         scope="keyed", layer="planner",
         doc="collective-permute slices each sharded pair exchange "
             "splits into, so transfer overlaps the consuming compute "
             "on real ICI (default: 1; power of two; NOT "
             "silicon-validated — A/B vs 1 on first chip run)",
         malformed="3", flips=("1", "4")),
    Knob("QUEST_EXCHANGE_SLICES_DCI", _parse_dci_slices, 0,
         scope="keyed", layer="planner",
         doc="collective-permute slices for pair exchanges that CROSS "
             "the host boundary (DCI links under QUEST_COMM_TOPOLOGY); "
             "0 (default) follows QUEST_EXCHANGE_SLICES — slower links "
             "want finer slicing so transfer overlaps compute longer "
             "(power of two; NOT silicon-validated — A/B on first "
             "multi-host run, scripts/ab_silicon.py)",
         malformed="3", flips=("0", "4")),
    Knob("QUEST_COMM_TOPOLOGY", _parse_comm_topology, None,
         scope="keyed", layer="planner",
         doc="hierarchical interconnect model for the comm planner "
             "(docs/DISTRIBUTED.md §topology): 'hosts=H[,ici=X][,dci=Y]' "
             "groups the mesh into H hosts with per-link cost weights "
             "(defaults ici=1, dci=4); 0 forces the flat single-tier "
             "model (bit-for-bit the PR-8 planner); unset auto-derives "
             "host grouping from jax.devices() process ids",
         malformed="hosts=three", flips=("0", "hosts=2")),
    Knob("QUEST_BATCH_BUCKET",
         _parse_choice("QUEST_BATCH_BUCKET", ("pow2", "off")), "pow2",
         scope="keyed", layer="planner",
         doc="batch-size bucketing for the batched engines: pow2 rounds a "
             "requested batch B up to the next power of two so mixed batch "
             "sizes share one compiled program; off compiles exact sizes "
             "(default: pow2)",
         malformed="4", flips=("pow2", "off")),
    Knob("QUEST_APPLY_AUTOROUTE", _bool01("QUEST_APPLY_AUTOROUTE"), True,
         scope="keyed", layer="planner",
         doc="Circuit.apply auto-routes through the banded engine above "
             "PERGATE_COMPILE_WARN_OPS flat ops (the per-gate XLA chain "
             "compiles pathologically slowly there — docs/PLANNING.md): "
             "1/0 (default: 1; 0 restores the legacy warn-only per-gate "
             "dispatch)",
         malformed="2", flips=("1", "0")),
    Knob("QUEST_PLAN_CACHE", _bool01("QUEST_PLAN_CACHE"), True,
         scope="runtime", layer="infra",
         doc="persistent content-addressed plan cache for plan.autotune "
             "(docs/PLANNING.md): 1/0 (default: 1; 0 prices every "
             "autotune call fresh — host-side planning only, never "
             "inside a traced program)"),
    Knob("QUEST_PLAN_CACHE_DIR", str, None,
         scope="runtime", layer="infra",
         doc="plan-cache directory for plan.autotune (default: the "
             "compile cache path + '.plans' — next to the XLA compile "
             "cache)"),
    Knob("QUEST_COMPILE_CACHE_DIR", str, None,
         scope="runtime", layer="infra",
         doc="persistent XLA compile-cache directory for "
             "enable_compile_cache (default: .jax_cache under the repo)"),
    Knob("QUEST_HOST_BLOCK", _int_range("QUEST_HOST_BLOCK", 1, 30), 17,
         scope="keyed", layer="host",
         doc="log2 amplitudes per cache block of the native host engine "
             "(default: 17 = 1 MiB blocks)",
         malformed="big", flips=("17", "15")),
    Knob("QUEST_FUSED_NBUF", _int_range("QUEST_FUSED_NBUF", 2, 8), 3,
         scope="import_once", layer="kernel",
         doc="VMEM slot buffers in the manually pipelined Pallas driver "
             "(default: 3); malformed values warn and fall back",
         malformed="9"),
    Knob("QUEST_FUSED_PIPELINE", _bool01("QUEST_FUSED_PIPELINE"), True,
         scope="keyed", layer="kernel",
         doc="decoupled multi-buffer sweep pipeline in the manually "
             "pipelined Pallas driver: separate in-slot and out-slot "
             "rings with independent DMA semaphore chains, so the HBM "
             "read stream, the stage chain and the HBM write stream "
             "each run a full step ahead (docs/SWEEPS.md): 1/0 "
             "(default: 1; 0 restores the legacy in-place NBUF slot "
             "driver for the silicon A/B)",
         malformed="2", flips=("1", "0")),
    Knob("QUEST_ROWS_EFF_BITS", _int_range("QUEST_ROWS_EFF_BITS", 3), None,
         scope="import_once", layer="kernel",
         doc="log2 block rows per Pallas kernel step (default: auto from "
             "VMEM); upper bound checked at first compile",
         malformed="x"),
    Knob("QUEST_FUSED_DRIVER",
         _parse_choice("QUEST_FUSED_DRIVER", ("pipelined", "grid")),
         "pipelined",
         scope="import_once", layer="kernel",
         doc="Pallas segment driver: pipelined (manual slot DMA, default) "
             "or grid (automatic BlockSpec pipeline)",
         malformed="turbo"),
    Knob("QUEST_AXON_PORT", _int_range("QUEST_AXON_PORT", 0), 8093,
         scope="runtime", layer="infra",
         doc="local TCP relay port probed before the tunneled-backend "
             "liveness check; 0 disables the port probe",
         malformed="abc"),
    Knob("QUEST_NATIVE_LIB", str, None,
         scope="runtime", layer="host",
         doc="override path of the native host-engine shared library "
             "(e.g. the ASan build in CI)"),
    Knob("QUEST_HBM_BYTES", _int_range("QUEST_HBM_BYTES", 1), None,
         scope="runtime", layer="bench",
         doc="per-device HBM capacity in bytes for the bench's OOM gate "
             "when the device hides memory stats",
         malformed="16G"),
    Knob("QUEST_BENCH_ENGINES", _parse_engine_ladder, None,
         scope="runtime", layer="bench",
         doc="comma-separated engine fallback ladder for bench.py "
             "(default: fused,banded,xla on TPU; host,banded,xla off it)",
         malformed="warp,xla"),
    Knob("QUEST_TEST_PLATFORM", str, "cpu",
         scope="runtime", layer="test",
         doc="JAX platform the test suite pins before importing jax "
             "(conftest.py; tpu_pod_tests.sh sets the chip platform)"),
    Knob("QUEST_SLOW_TESTS", _bool01("QUEST_SLOW_TESTS"), False,
         scope="runtime", layer="test",
         doc="opt into multi-minute subprocess tests (16-device dryrun)",
         malformed="yes"),
    Knob("QUEST_METRICS_FILE", str, "/tmp/tpu_smoke_metrics.log",
         scope="runtime", layer="test",
         doc="file collecting on-chip smoke-test measurement lines "
             "(pytest capture swallows stderr of passing tests)"),
    Knob("QUEST_TUNNEL_POLL_S", _int_range("QUEST_TUNNEL_POLL_S", 1), 30,
         scope="runtime", layer="infra",
         doc="poll interval of scripts/tunnel_watch.sh (shell-only)"),
    Knob("QUEST_MEMCHECK", _bool01("QUEST_MEMCHECK"), False,
         scope="runtime", layer="build",
         doc="build the native host engine under AddressSanitizer "
             "(native/Makefile, CI job; shell-only)",
         malformed="on"),
    Knob("QUEST_SERVE_MAX_WAIT_MS",
         _int_range("QUEST_SERVE_MAX_WAIT_MS", 0), 5,
         scope="runtime", layer="serve",
         doc="max milliseconds a serve request may wait for its bucket "
             "to fill before the partial batch launches (default: 5); "
             "0 = no coalescing, every request launches alone (the "
             "bench baseline mode)",
         malformed="-1"),
    Knob("QUEST_SERVE_MAX_QUEUE",
         _int_range("QUEST_SERVE_MAX_QUEUE", 1), 1024,
         scope="runtime", layer="serve",
         doc="bounded pending-request depth of ServeEngine; the "
             "overflowing submit raises RejectedError — loud "
             "backpressure, never a silent drop (default: 1024)",
         malformed="0"),
    Knob("QUEST_SERVE_MAX_BATCH",
         _int_range("QUEST_SERVE_MAX_BATCH", 1), 64,
         scope="runtime", layer="serve",
         doc="max states coalesced into one serve launch; a queue "
             "reaching this many pending states dispatches immediately "
             "(default: 64)",
         malformed="0"),
    Knob("QUEST_SERVE_RESTART_MAX",
         _int_range("QUEST_SERVE_RESTART_MAX", 0), 3,
         scope="runtime", layer="serve",
         doc="consecutive worker-crash restarts ServeEngine's "
             "supervisor allows (exponential backoff + jitter) before "
             "the engine transitions to FAILED and rejects submits "
             "(default: 3; docs/RESILIENCE.md)",
         malformed="-1"),
    Knob("QUEST_SERVE_BREAKER_THRESHOLD",
         _int_range("QUEST_SERVE_BREAKER_THRESHOLD", 1), 3,
         scope="runtime", layer="serve",
         doc="consecutive primary-engine failures of one program before "
             "its circuit breaker opens and requests step down the "
             "fused->banded->host degradation ladder (default: 3; "
             "docs/RESILIENCE.md)",
         malformed="0"),
    Knob("QUEST_SERVE_REPLICAS",
         _int_range("QUEST_SERVE_REPLICAS", 1), 2,
         scope="runtime", layer="serve",
         doc="ServeEngine replicas a ServeFleet owns (program-key "
             "affinity routing, fleet-level failover; default: 2; "
             "docs/SERVING.md §fleet)",
         malformed="0"),
    Knob("QUEST_FLEET_PROC", _bool01("QUEST_FLEET_PROC"), False,
         scope="runtime", layer="serve",
         doc="ServeFleet replica backend: 1 = supervised worker "
             "PROCESSES behind the serve.ipc dispatch boundary (own "
             "interpreter + JAX runtime per replica — req/s scales "
             "with cores), 0 = in-process worker threads (default; "
             "docs/SERVING.md §process-fleet)",
         malformed="2"),
    Knob("QUEST_FLEET_MIN_REPLICAS",
         _int_range("QUEST_FLEET_MIN_REPLICAS", 1), 1,
         scope="runtime", layer="serve",
         doc="elastic-autoscaler floor: the fleet never scales below "
             "this many live replicas (serve/autoscaler.py; default: "
             "1; docs/SERVING.md §process-fleet)",
         malformed="0"),
    Knob("QUEST_FLEET_MAX_REPLICAS",
         _int_range("QUEST_FLEET_MAX_REPLICAS", 1), 4,
         scope="runtime", layer="serve",
         doc="elastic-autoscaler ceiling: the fleet never scales above "
             "this many live replicas (serve/autoscaler.py; default: "
             "4; docs/SERVING.md §process-fleet)",
         malformed="0"),
    Knob("QUEST_HEARTBEAT_S", _parse_pos_float("QUEST_HEARTBEAT_S"),
         0.25,
         scope="runtime", layer="serve",
         doc="process-replica heartbeat cadence in seconds: each "
             "worker ships health + a registry snapshot per beat, and "
             "the proxy declares the worker LOST (kill + respawn "
             "under the restart budget) after 4 missed beats "
             "(serve/ipc.py; default: 0.25; docs/SERVING.md "
             "§process-fleet)",
         malformed="0"),
    Knob("QUEST_SERVE_TENANT_QUOTA", _parse_tenant_quota,
         _default_tenant_quota,
         scope="runtime", layer="serve",
         doc="per-tenant pending-request quota for ServeFleet "
             "admission: one integer (every tenant) or "
             "'tenant=quota,...' with an optional default= entry "
             "(default: 256; docs/SERVING.md §fleet)",
         malformed="alice=lots"),
    Knob("QUEST_SERVE_SHED_THRESHOLD", _parse_shed_threshold, 0.75,
         scope="runtime", layer="serve",
         doc="fleet pressure (queued fraction of healthy capacity + "
             "open-breaker weight) above which the lowest priority "
             "class load-sheds with typed ShedError (default: 0.75; "
             "1.0 = shed only at the hard queue bound; "
             "docs/SERVING.md §fleet)",
         malformed="0"),
    Knob("QUEST_SERVE_PRIORITIES",
         _int_range("QUEST_SERVE_PRIORITIES", 1), 2,
         scope="runtime", layer="serve",
         doc="priority classes a ServeFleet accepts (submit priority= "
             "in [0, N); higher sheds later — default: 2, a free/paying "
             "pair; docs/SERVING.md §fleet)",
         malformed="0"),
    Knob("QUEST_FAULT_PLAN", _parse_fault_plan, None,
         scope="runtime", layer="serve",
         doc="deterministic fault-injection plan armed at engine "
             "construction for soak runs: 'site[:key=value]...[;...]' "
             "over the docs/RESILIENCE.md site catalog (keys: error, "
             "after, every, times, p, seed); unset = no injection, "
             "zero hot-path cost",
         malformed="serve.not_a_site"),
    Knob("QUEST_DURABLE_EVERY", _int_range("QUEST_DURABLE_EVERY", 1), 8,
         scope="runtime", layer="serve",
         doc="sweep-plan steps between checkpoints of the durable "
             "executor (resilience/durable.py, docs/RESILIENCE.md "
             "§durable; default: 8)",
         malformed="0"),
    Knob("QUEST_INTEGRITY", _bool01("QUEST_INTEGRITY"), True,
         scope="runtime", layer="serve",
         doc="in-flight corruption sentinels at checkpoint cadence "
             "(statevector norm / density trace+hermiticity drift vs "
             "the run's baseline): 1/0 (default: 1; a trip raises "
             "IntegrityError and refuses to stamp the checkpoint)",
         malformed="2"),
    Knob("QUEST_INTEGRITY_TOL", _parse_pos_float("QUEST_INTEGRITY_TOL"),
         1e-3,
         scope="runtime", layer="serve",
         doc="relative drift budget of the durable integrity sentinels "
             "(absolute for unit-scale invariants; default: 1e-3 — "
             "orders above honest f32 rounding drift, orders below "
             "real corruption)",
         malformed="-1"),
    Knob("QUEST_CHECKPOINT_KEEP",
         _int_range("QUEST_CHECKPOINT_KEEP", 1), 2,
         scope="runtime", layer="serve",
         doc="versioned checkpoints retained per durable run "
             "(checkpoint.prune_steps keep-last-K; default: 2 — a "
             "corrupt newest checkpoint always leaves a valid "
             "predecessor to resume from)",
         malformed="0"),
    Knob("QUEST_DURABLE_ELASTIC", _bool01("QUEST_DURABLE_ELASTIC"),
         False,
         scope="runtime", layer="serve",
         doc="default for run_durable(elastic=): 1 makes durable "
             "resume MESH-INDEPENDENT — a checkpoint chain written by "
             "D devices across H hosts re-enters any mesh that holds "
             "the amplitudes, re-verifying digests and re-deriving the "
             "comm plan (default: 0 — mesh mismatch rejects typed; "
             "docs/RESILIENCE.md §elastic)",
         malformed="yes"),
    Knob("QUEST_DISPATCH_TIMEOUT_S",
         _parse_nonneg_float("QUEST_DISPATCH_TIMEOUT_S"), 0.0,
         scope="runtime", layer="serve",
         doc="serve dispatch watchdog deadline in seconds: a launch "
             "exceeding it fails typed DispatchTimeout, counts toward "
             "the program's breaker, and the supervisor replaces the "
             "wedged worker thread instead of letting drain() hang "
             "(default: 0 = watchdog off; docs/RESILIENCE.md "
             "§watchdog)",
         malformed="-1"),
    Knob("_QUEST_DRYRUN_BOOTSTRAPPED", _parse_choice(
         "_QUEST_DRYRUN_BOOTSTRAPPED", ("1",)), None,
         scope="runtime", layer="infra",
         doc="internal sentinel marking the virtual-mesh bootstrap child "
             "of the driver dryrun / 16-device test (not user-facing)",
         malformed="0"),
)

KNOBS = {k.name: k for k in _KNOB_LIST}


def knob_value(name: str):
    """Effective value of a registered knob: the validating parse of the
    environment when set (raises ValueError on malformed input — knobs
    parse loudly), else the registered default. The ONE read path for
    QUEST_* knobs in package code (quest-lint QL004 flags direct
    os.environ reads)."""
    k = KNOBS[name]
    raw = os.environ.get(name)
    if raw is None:
        return k.default() if callable(k.default) else k.default
    return k.parse(raw)


def batch_bucket(b: int) -> int:
    """Effective COMPILED batch size for a requested batch of `b` states
    (the batched engines' bucketing policy, docs/BATCHING.md): under
    QUEST_BATCH_BUCKET=pow2 (default) `b` rounds UP to the next power of
    two, so serving mixed batch sizes hits one compiled program per
    bucket instead of retracing per size (B=5 and B=8 share the B=8
    program; the caller pads and slices). 'off' compiles exact sizes —
    every distinct B pays its own compile. The knob is keyed: it changes
    which program a batched call resolves to, so engine_mode_key()
    carries it (flip-audited in tests/test_lint.py)."""
    b = int(b)
    if b < 1:
        raise ValueError(f"batch size must be >= 1, got {b}")
    if knob_value("QUEST_BATCH_BUCKET") == "off":
        return b
    return 1 << (b - 1).bit_length()


def knob_current(name: str):
    """Like knob_value, but honoring setter-backed effective values
    (e.g. set_matmul_precision beats the env var once called)."""
    k = KNOBS[name]
    if k.current is not None:
        return k.current()
    return knob_value(name)


# keyed-knob sublists per layer, computed once: the registry is
# immutable and engine_mode_key sits on the eager per-gate dispatch
# path (ops/gates.py feeds A.mode_key() to every worker call), so only
# the knob_current() reads belong in the per-call cost
_KEYED_SORTED = tuple(sorted((k for k in _KNOB_LIST if k.scope == "keyed"),
                             key=lambda k: k.name))
_KEYED_BY_LAYER = {None: _KEYED_SORTED}
for _k in _KEYED_SORTED:
    _KEYED_BY_LAYER.setdefault(_k.layer, ())
    _KEYED_BY_LAYER[_k.layer] += (_k,)
del _k


def engine_mode_key(layer: Optional[str] = None) -> Tuple:
    """The trace-time mode-flag tuple every compiled-program cache key
    must carry, DERIVED from the registry: every keyed knob's effective
    value, sorted by name (omitting any would return stale programs when
    a user flips the knob mid-process — the cache-key discipline of
    ADVICE r4 item 2 / r5 item 2). `layer` restricts to one subsystem's
    knobs: the eager per-gate jit workers carry layer='apply' (all that
    their traces read), the circuit-level engines carry the full key."""
    return tuple((k.name, knob_current(k.name))
                 for k in _KEYED_BY_LAYER.get(layer, ()))


class QuESTEnv:
    """Device environment; analogue of the reference's QuESTEnv."""

    def __init__(self, devices: Optional[Sequence] = None,
                 distributed: bool = False):
        if distributed and jax.process_count() == 1:
            jax.distributed.initialize()
        if devices is None:
            devices = jax.devices()
        # amplitude sharding needs a power-of-2 device count
        # (ref validateNumRanks, QuEST_validation.c:81)
        count = 1 << (len(devices).bit_length() - 1)
        self.devices = list(devices)[:count]
        self.mesh = Mesh(np.array(self.devices), (AMP_AXIS,))

    @property
    def num_ranks(self) -> int:
        return len(self.devices)

    @property
    def rank(self) -> int:
        return jax.process_index()

    def sharding_for(self, num_state_qubits: int):
        """NamedSharding for a (2**n,) amplitude array, or None if the
        register is too small to shard. The floor is TWO amplitudes per
        device — the same local_n >= 1 bound the shard_map engines
        enforce (E_DISTRIB_QUREG_TOO_SMALL): a one-amp-per-device layout
        buys nothing AND miscompiles under GSPMD on this runtime
        (measured: the eager all-ones phase on a 3-qubit register over
        8 devices returned 4x-scaled amplitudes — the seed-red
        test_tutorial_circuit_exact; jax 0.4.37 XLA-CPU reshape of
        fully-degenerate shards)."""
        if (self.num_ranks == 1
                or (1 << num_state_qubits) < 2 * self.num_ranks):
            return None
        return NamedSharding(self.mesh, P(None, AMP_AXIS))

    def sync(self) -> None:
        """Block until all queued device work completes (ref syncQuESTEnv)."""
        jax.effects_barrier()

    def get_environment_string(self, num_state_qubits: int = None) -> str:
        """Benchmark-label tag in the reference's documented format
        "{n}qubits_{PLATFORM}_{r}ranksx{t}threads" (getEnvironmentString,
        QuEST_cpu.c:1358-1364; platform replaces "CPU", device count plays
        the rank role, 1 thread per device core)."""
        plat = self.devices[0].platform.upper() if self.devices else "CPU"
        tag = f"{plat}_{self.num_ranks}ranksx1threads"
        if num_state_qubits is not None:
            tag = f"{num_state_qubits}qubits_{tag}"
        return tag

    def report(self) -> str:
        s = (f"EXECUTION ENVIRONMENT:\nRunning distributed (MPI) version: "
             f"{'yes' if self.num_ranks > 1 else 'no'}\n"
             f"Number of devices: {self.num_ranks}\n"
             f"Platform: {self.devices[0].platform if self.devices else '?'}")
        print(s)
        return s


def create_quest_env(**kwargs) -> QuESTEnv:
    return QuESTEnv(**kwargs)


def destroy_quest_env(env: QuESTEnv) -> None:
    """No resources to free in the functional design; kept for API parity."""


def ensure_live_backend(timeout_s: int = 240) -> str:
    """Probe the default JAX backend in a SUBPROCESS and return its
    platform name, falling back to the host CPU when it is unreachable.

    The tunneled TPU backend can drop for hours (observed in round 2);
    an in-process jax.devices() then hangs indefinitely and would wedge
    whatever called it — the benchmark, the driver's dryrun. Probing in
    a subprocess bounds the wait; on failure the CURRENT process is
    switched to the CPU platform (jax.config, the only override that
    works after the container's sitecustomize pre-captures env vars) so
    callers still produce a result.

    ORDERING CONTRACT: call this BEFORE anything that initializes the JAX
    backend (jax.devices(), any jit execution, device_put). Once this
    process has committed to a backend the probe can neither time-bound
    the hang (the in-process jax.devices() below IS the risky call) nor
    rebind jax_platforms — the already-initialized branch exists only to
    make late calls harmless, not useful. Current call sites honoring the
    contract: bench.py:main (first call), __graft_entry__.entry/
    dryrun_multichip (before any mesh/array work), scripts/*."""
    import sys
    from jax._src import xla_bridge as _xb
    try:
        already = bool(_xb._backends)
    except Exception:
        already = False
    if already:
        # This process has committed to a backend: a probe child would
        # deadlock against OUR device lock, and jax_platforms cannot be
        # rebound after init — nothing useful to do but report.
        return jax.devices()[0].platform

    # A cpu-FIRST in-process platform config (tests' conftest, CPU
    # cross-check scripts via jax.config.update) beats any probe: the
    # container's sitecustomize re-forces JAX_PLATFORMS=axon in child
    # processes, so a subprocess probe reports the tunnel's platform
    # even when THIS process is pinned to cpu — entry() would then hand
    # back a Pallas program a cpu backend cannot run (caught round 3 by
    # the graft-entry suite test). The default config ('axon,cpu',
    # mirroring the env) is not cpu-first and still probes.
    try:
        cfg_first = (jax.config.jax_platforms or "").split(",")[0]
    except Exception:
        cfg_first = ""
    if cfg_first == "cpu":
        return "cpu"

    import os

    # Tunneled (axon) backends ride a local TCP relay; when its port is
    # not even listening the full-length probe below just burns its whole
    # timeout (observed mid-round-3: the relay died between revalidation
    # stages and two 240 s probes were wasted). The port answering does
    # not prove the chip works, and the port NOT answering could be a
    # nonstandard relay port — so the check only shortens the probe
    # timeout, it never skips the probe. QUEST_AXON_PORT=0 disables.
    if "axon" in os.environ.get("JAX_PLATFORMS", ""):
        try:
            port = knob_value("QUEST_AXON_PORT")
        except ValueError as e:
            # unparseable value must not break the fallback path — warn
            # and use the registry default (knobs parse loudly, but THIS
            # caller's job is to keep the process alive)
            print(f"[quest_tpu] {e}; using default port "
                  f"{KNOBS['QUEST_AXON_PORT'].default}",
                  file=sys.stderr, flush=True)
            port = KNOBS["QUEST_AXON_PORT"].default
        if port and not _tcp_port_open("127.0.0.1", port):
            timeout_s = min(timeout_s, 45)
            print(f"[quest_tpu] axon relay port {port} not listening; "
                  f"probe timeout shortened to {timeout_s}s",
                  file=sys.stderr, flush=True)

    platform, last_err = _probe_subprocess(
        "import jax; print(jax.devices()[0].platform)", timeout_s)
    if platform is not None:
        return platform
    print(f"[quest_tpu] default backend unavailable, falling back to host "
          f"CPU. Last probe error: {last_err}", file=sys.stderr, flush=True)
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def _probe_subprocess(code: str, timeout_s: float, attempts: int = 3,
                      retry_sleep_s: float = 20.0, *, _run=None,
                      _sleep=None):
    """Run the backend-probe `code` in a subprocess with bounded
    retries; returns (platform | None, last_err). A FAST nonzero exit is
    often another process holding the device's exclusive lock — that can
    clear, so it retries (sleeping `retry_sleep_s`) before downgrading;
    a TIMEOUT means a hung init that rarely clears quickly, so it breaks
    immediately instead of tripling the wait. `_run`/`_sleep` are
    injectable so tests/test_resilience.py can pin the contention path
    without spawning processes (the retry-before-downgrade contract)."""
    import subprocess
    import sys
    import time as _time
    if _run is None:
        _run = subprocess.run
    if _sleep is None:
        _sleep = _time.sleep
    last_err = ""
    for attempt in range(attempts):
        try:
            out = _run([sys.executable, "-c", code],
                       timeout=timeout_s, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {timeout_s}s (tunnel down?)"
            break   # a hung init rarely clears quickly; don't triple the wait
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip().splitlines()[-1], ""
        # fast nonzero exit: often another process holds the device's
        # exclusive lock — that can clear, so retry before downgrading
        last_err = (out.stderr or "").strip()[-500:]
        if attempt < attempts - 1:
            _sleep(retry_sleep_s)
    return None, last_err


def _tcp_port_open(host: str, port: int, timeout_s: float = 3.0) -> bool:
    import socket
    try:
        with socket.create_connection((host, port), timeout=timeout_s):
            return True
    except OSError:
        return False


def sync_array(x) -> None:
    """Block until `x` (and the queued computation chain behind it) has
    ACTUALLY executed, by materializing one 4-element slice on the host.
    The one place this idiom lives: on the tunneled axon platform
    jax.block_until_ready returns before queued steps run (measured in
    round 2 — it timed a 30q step chain at 4M gates/s), and fetching
    ravel()[:k] would relayout-copy the whole state (8 GB at 30q); a tiny
    leading slice forces true completion at zero cost."""
    np.asarray(x[(0,) * (x.ndim - 1) + (slice(0, 4),)])


def sync_quest_success(success_code: int = 1) -> int:
    """AND a success code across processes (ref syncQuESTSuccess,
    QuEST_cpu_distributed.c:166-170). Single-process: identity."""
    return int(bool(success_code))
