"""Execution environment: device mesh and sharding policy.

The reference's QuESTEnv is {rank, numRanks} over MPI (QuEST.h:199-203,
QuEST_cpu_distributed.c:129-160, power-of-2 ranks required). The TPU-native
equivalent is a 1-D `jax.sharding.Mesh` over the amplitude axis: a register
whose amplitude count is divisible by the mesh size is laid out with its
top log2(num_devices) qubits "global" (one contiguous chunk per device),
exactly the reference's chunk layout (QuEST_cpu.c:1280-1312) — so gates on
low qubits are embarrassingly local and gates on global qubits lower to XLA
collectives over ICI.

Multi-host pods: pass `distributed=True` to have jax.distributed.initialize
wire up DCN before the mesh is built (the analogue of MPI_Init).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AMP_AXIS = "amp"


class QuESTEnv:
    """Device environment; analogue of the reference's QuESTEnv."""

    def __init__(self, devices: Optional[Sequence] = None,
                 distributed: bool = False):
        if distributed and jax.process_count() == 1:
            jax.distributed.initialize()
        if devices is None:
            devices = jax.devices()
        # amplitude sharding needs a power-of-2 device count
        # (ref validateNumRanks, QuEST_validation.c:81)
        count = 1 << (len(devices).bit_length() - 1)
        self.devices = list(devices)[:count]
        self.mesh = Mesh(np.array(self.devices), (AMP_AXIS,))

    @property
    def num_ranks(self) -> int:
        return len(self.devices)

    @property
    def rank(self) -> int:
        return jax.process_index()

    def sharding_for(self, num_state_qubits: int):
        """NamedSharding for a (2**n,) amplitude array, or None if the
        register is too small to shard. The floor is TWO amplitudes per
        device — the same local_n >= 1 bound the shard_map engines
        enforce (E_DISTRIB_QUREG_TOO_SMALL): a one-amp-per-device layout
        buys nothing AND miscompiles under GSPMD on this runtime
        (measured: the eager all-ones phase on a 3-qubit register over
        8 devices returned 4x-scaled amplitudes — the seed-red
        test_tutorial_circuit_exact; jax 0.4.37 XLA-CPU reshape of
        fully-degenerate shards)."""
        if (self.num_ranks == 1
                or (1 << num_state_qubits) < 2 * self.num_ranks):
            return None
        return NamedSharding(self.mesh, P(None, AMP_AXIS))

    def sync(self) -> None:
        """Block until all queued device work completes (ref syncQuESTEnv)."""
        jax.effects_barrier()

    def get_environment_string(self, num_state_qubits: int = None) -> str:
        """Benchmark-label tag in the reference's documented format
        "{n}qubits_{PLATFORM}_{r}ranksx{t}threads" (getEnvironmentString,
        QuEST_cpu.c:1358-1364; platform replaces "CPU", device count plays
        the rank role, 1 thread per device core)."""
        plat = self.devices[0].platform.upper() if self.devices else "CPU"
        tag = f"{plat}_{self.num_ranks}ranksx1threads"
        if num_state_qubits is not None:
            tag = f"{num_state_qubits}qubits_{tag}"
        return tag

    def report(self) -> str:
        s = (f"EXECUTION ENVIRONMENT:\nRunning distributed (MPI) version: "
             f"{'yes' if self.num_ranks > 1 else 'no'}\n"
             f"Number of devices: {self.num_ranks}\n"
             f"Platform: {self.devices[0].platform if self.devices else '?'}")
        print(s)
        return s


def create_quest_env(**kwargs) -> QuESTEnv:
    return QuESTEnv(**kwargs)


def destroy_quest_env(env: QuESTEnv) -> None:
    """No resources to free in the functional design; kept for API parity."""


def ensure_live_backend(timeout_s: int = 240) -> str:
    """Probe the default JAX backend in a SUBPROCESS and return its
    platform name, falling back to the host CPU when it is unreachable.

    The tunneled TPU backend can drop for hours (observed in round 2);
    an in-process jax.devices() then hangs indefinitely and would wedge
    whatever called it — the benchmark, the driver's dryrun. Probing in
    a subprocess bounds the wait; on failure the CURRENT process is
    switched to the CPU platform (jax.config, the only override that
    works after the container's sitecustomize pre-captures env vars) so
    callers still produce a result.

    ORDERING CONTRACT: call this BEFORE anything that initializes the JAX
    backend (jax.devices(), any jit execution, device_put). Once this
    process has committed to a backend the probe can neither time-bound
    the hang (the in-process jax.devices() below IS the risky call) nor
    rebind jax_platforms — the already-initialized branch exists only to
    make late calls harmless, not useful. Current call sites honoring the
    contract: bench.py:main (first call), __graft_entry__.entry/
    dryrun_multichip (before any mesh/array work), scripts/*."""
    import subprocess
    import sys
    import time as _time
    from jax._src import xla_bridge as _xb
    try:
        already = bool(_xb._backends)
    except Exception:
        already = False
    if already:
        # This process has committed to a backend: a probe child would
        # deadlock against OUR device lock, and jax_platforms cannot be
        # rebound after init — nothing useful to do but report.
        return jax.devices()[0].platform

    # A cpu-FIRST in-process platform config (tests' conftest, CPU
    # cross-check scripts via jax.config.update) beats any probe: the
    # container's sitecustomize re-forces JAX_PLATFORMS=axon in child
    # processes, so a subprocess probe reports the tunnel's platform
    # even when THIS process is pinned to cpu — entry() would then hand
    # back a Pallas program a cpu backend cannot run (caught round 3 by
    # the graft-entry suite test). The default config ('axon,cpu',
    # mirroring the env) is not cpu-first and still probes.
    try:
        cfg_first = (jax.config.jax_platforms or "").split(",")[0]
    except Exception:
        cfg_first = ""
    if cfg_first == "cpu":
        return "cpu"

    import os

    # Tunneled (axon) backends ride a local TCP relay; when its port is
    # not even listening the full-length probe below just burns its whole
    # timeout (observed mid-round-3: the relay died between revalidation
    # stages and two 240 s probes were wasted). The port answering does
    # not prove the chip works, and the port NOT answering could be a
    # nonstandard relay port — so the check only shortens the probe
    # timeout, it never skips the probe. QUEST_AXON_PORT=0 disables.
    if "axon" in os.environ.get("JAX_PLATFORMS", ""):
        try:
            port = int(os.environ.get("QUEST_AXON_PORT") or "8093")
        except ValueError:
            port = 8093   # unparseable value must not break the fallback path
        if port and not _tcp_port_open("127.0.0.1", port):
            timeout_s = min(timeout_s, 45)
            print(f"[quest_tpu] axon relay port {port} not listening; "
                  f"probe timeout shortened to {timeout_s}s",
                  file=sys.stderr, flush=True)

    code = "import jax; print(jax.devices()[0].platform)"
    last_err = ""
    attempts = 3
    for attempt in range(attempts):
        try:
            out = subprocess.run([sys.executable, "-c", code],
                                 timeout=timeout_s, capture_output=True,
                                 text=True)
        except subprocess.TimeoutExpired:
            last_err = f"probe timed out after {timeout_s}s (tunnel down?)"
            break   # a hung init rarely clears quickly; don't triple the wait
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip().splitlines()[-1]
        # fast nonzero exit: often another process holds the device's
        # exclusive lock — that can clear, so retry before downgrading
        last_err = (out.stderr or "").strip()[-500:]
        if attempt < attempts - 1:
            _time.sleep(20)
    print(f"[quest_tpu] default backend unavailable, falling back to host "
          f"CPU. Last probe error: {last_err}", file=sys.stderr, flush=True)
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def _tcp_port_open(host: str, port: int, timeout_s: float = 3.0) -> bool:
    import socket
    try:
        with socket.create_connection((host, port), timeout=timeout_s):
            return True
    except OSError:
        return False


def sync_array(x) -> None:
    """Block until `x` (and the queued computation chain behind it) has
    ACTUALLY executed, by materializing one 4-element slice on the host.
    The one place this idiom lives: on the tunneled axon platform
    jax.block_until_ready returns before queued steps run (measured in
    round 2 — it timed a 30q step chain at 4M gates/s), and fetching
    ravel()[:k] would relayout-copy the whole state (8 GB at 30q); a tiny
    leading slice forces true completion at zero cost."""
    np.asarray(x[(0,) * (x.ndim - 1) + (slice(0, 4),)])


def sync_quest_success(success_code: int = 1) -> int:
    """AND a success code across processes (ref syncQuESTSuccess,
    QuEST_cpu_distributed.c:166-170). Single-process: identity."""
    return int(bool(success_code))
