"""The simulation state: a functional `Qureg` pytree.

The reference's Qureg (QuEST/include/QuEST.h:160-191) is a mutable pair of
real/imag C arrays plus chunk metadata. Here the state is an immutable
pytree holding ONE real jax.Array of shape (2, 2^N): plane 0 the real
parts, plane 1 the imaginary parts — the same split-storage layout the
reference uses (QuEST.h ComplexArray), chosen on TPU for speed (measured
2.3x over interleaved complex64 on the memory-bound butterflies) and
because complex buffers cannot cross the host<->device boundary on this
platform (see quest_tpu.cplx).

For a density matrix, rho_{r,c} lives at flat index r + c*2^N: an N-qubit
density matrix IS a 2N-qubit statevector under the Choi isomorphism,
exactly as the reference stores it (QuEST/src/QuEST.c:48-60). Qubit indices
are little-endian: qubit q is bit q of the flat amplitude index.

Distribution metadata (the reference's chunkId/numChunks) is carried by the
array's sharding, not by the pytree: a sharded Qureg is simply one whose
amplitude axis is laid out over a Mesh (see quest_tpu.parallel).

The logical `dtype` of a Qureg remains complex64/complex128 at the API
surface; the planes are the matching real dtype.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import precision
from quest_tpu import validation


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Qureg:
    """Functional quantum register: statevector or density matrix.

    amps: (2, 2**num_state_qubits) real array — [0] real, [1] imag planes.
          For a density matrix over N qubits, num_state_qubits = 2N and
          plane[:, r + c*2**N] holds rho[r, c].
    """

    amps: jax.Array
    num_qubits: int = dataclasses.field(metadata=dict(static=True))
    is_density: bool = dataclasses.field(metadata=dict(static=True))

    @property
    def num_state_qubits(self) -> int:
        return 2 * self.num_qubits if self.is_density else self.num_qubits

    @property
    def num_amps(self) -> int:
        return 1 << self.num_state_qubits

    @property
    def dtype(self):
        """Logical (complex) amplitude dtype."""
        return precision.complex_dtype_of(self.amps.dtype)

    @property
    def real_dtype(self):
        return np.dtype(self.amps.dtype)

    def replace_amps(self, amps: jax.Array) -> "Qureg":
        return dataclasses.replace(self, amps=amps)


def _planes(num_state_qubits: int, rdt):
    return jnp.zeros((2, 1 << num_state_qubits), dtype=rdt)


@partial(jax.jit, static_argnames=("n", "rdt", "shape"))
def _basis_planes_hl(hi, lo, *, n, rdt, shape=None):
    """Planes of a computational-basis state built in ONE fused buffer
    (zeros().at[...].set() briefly materializes TWO full-state buffers —
    at 30 qubits that is 16 GB and exhausts the chip's HBM). The target
    index arrives split as (index >> 20, index & 0xFFFFF) so every iota
    stays within int32 regardless of jax_enable_x64 (int64 iotas silently
    truncate when x64 is off). `shape` builds the buffer directly in a
    caller-chosen view of (2, 2^n) — reshaping OUTSIDE the jit would
    relayout-copy the whole state (another 8 GB at 30q)."""
    lo_bits = min(n, 20)
    view = (2, 1 << (n - lo_bits), 1 << lo_bits)
    ih = jax.lax.broadcasted_iota(jnp.int32, view, 1)
    il = jax.lax.broadcasted_iota(jnp.int32, view, 2)
    plane = jax.lax.broadcasted_iota(jnp.int32, view, 0)
    hit = (ih == hi) & (il == lo) & (plane == 0)
    out = jnp.where(hit, 1.0, 0.0).astype(rdt)
    return out.reshape(shape if shape is not None else (2, 1 << n))


def _basis_planes(flat_index, *, n, rdt, shape=None):
    lo_bits = min(n, 20)
    return _basis_planes_hl(int(flat_index) >> lo_bits,
                            int(flat_index) & ((1 << lo_bits) - 1),
                            n=n, rdt=rdt, shape=shape)


def basis_planes(flat_index, *, n, rdt=np.float32, shape=None):
    """PUBLIC: the (2, 2^n) re/im planes of computational-basis state
    |flat_index>, built in one fused device buffer, optionally directly
    in a caller-chosen view `shape` (see fused_state_shape — building in
    the target layout avoids an out-of-jit relayout copy, 8 GB at 30q).
    Benchmarks and scripts should use this instead of allocating
    zeros().at[...].set(...)."""
    return _basis_planes(flat_index, n=n, rdt=rdt, shape=shape)


def fused_state_shape(n: int):
    """The fused (Pallas band-segment) engine's native state view for an
    n-qubit register: (2, 2^(n-7), 128) — same physical (8, 128) tiling
    as the kernel blocks, so engine-boundary reshapes are free bitcasts.
    The ONE place this layout constant lives for out-of-package callers
    (compiled_fused callers, bench.py, benchmarks/run.py)."""
    from quest_tpu.ops.pallas_band import LANE_QUBITS, LANES, usable
    if not usable(n):
        raise ValueError(
            f"the fused engine needs n >= {LANE_QUBITS + 3} qubits "
            f"(one (8, 128) f32 tile per block), got n={n}")
    return (2, 1 << (n - LANE_QUBITS), LANES)


def _make(num_qubits: int, is_density: bool, dtype, sharding=None) -> Qureg:
    validation.validate_num_qubits(num_qubits)
    dtype = np.dtype(dtype) if dtype is not None else precision.get_default_dtype()
    n = 2 * num_qubits if is_density else num_qubits
    rdt = precision.real_dtype_of(dtype)
    amps = _basis_planes(0, n=n, rdt=rdt)
    if sharding is not None:
        amps = jax.device_put(amps, sharding)
    return Qureg(amps=amps, num_qubits=num_qubits, is_density=is_density)


def create_qureg(num_qubits: int, env=None, dtype=None) -> Qureg:
    """Statevector register initialized to |0...0> (ref: QuEST.c:34-46)."""
    sharding = env.sharding_for(num_qubits) if env is not None else None
    return _make(num_qubits, False, dtype, sharding)


def create_density_qureg(num_qubits: int, env=None, dtype=None) -> Qureg:
    """Density-matrix register initialized to |0..0><0..0| (ref: QuEST.c:48-60)."""
    sharding = env.sharding_for(2 * num_qubits) if env is not None else None
    return _make(num_qubits, True, dtype, sharding)


@jax.jit
def _device_copy(x):
    return x + jnp.zeros((), dtype=x.dtype)


def clone(qureg: Qureg) -> Qureg:
    """Deep copy (ref createCloneQureg, QuEST.c:62-72) — a fresh device
    buffer, so later donation of either register cannot invalidate the
    other."""
    return qureg.replace_amps(_device_copy(qureg.amps))


# ---------------------------------------------------------------------------
# State initializers (ref: QuEST_cpu.c:1366-1655 init kernels)
# ---------------------------------------------------------------------------



def _init_amps(qureg: Qureg, amps) -> Qureg:
    """Install freshly built planes, PRESERVING the register's sharding.
    Every init_* builds a new array (functional design), which would
    otherwise land on the default device and silently de-shard a
    mesh-sharded register — after which every downstream op compiles as
    a single-device program (measured: GSPMD gathers the full state).
    The ONE place init results are committed."""
    sh = getattr(qureg.amps, "sharding", None)
    if getattr(sh, "mesh", None) is not None:
        amps = jax.device_put(amps, sh)
    return qureg.replace_amps(amps)


def init_blank_state(qureg: Qureg) -> Qureg:
    """All amplitudes zero (an unnormalized, unphysical state)."""
    return _init_amps(qureg,
                      _planes(qureg.num_state_qubits, qureg.real_dtype))


def init_zero_state(qureg: Qureg) -> Qureg:
    """|0...0> or |0..0><0..0|."""
    return _init_amps(qureg, _basis_planes(
        0, n=qureg.num_state_qubits, rdt=qureg.real_dtype))


def init_plus_state(qureg: Qureg) -> Qureg:
    """|+>^N; density: uniform matrix 1/2^N (ref QuEST_cpu.c:1406-1473)."""
    n = qureg.num_qubits
    if qureg.is_density:
        val = 1.0 / (1 << n)
    else:
        val = 1.0 / np.sqrt(1 << n)
    rdt = qureg.real_dtype
    re = jnp.full((qureg.num_amps,), val, dtype=rdt)
    im = jnp.zeros((qureg.num_amps,), dtype=rdt)
    return _init_amps(qureg, jnp.stack([re, im]))


def init_classical_state(qureg: Qureg, state_index: int) -> Qureg:
    """Basis state |k> or |k><k| (ref QuEST_cpu.c:1475-1539)."""
    validation.validate_state_index(qureg, state_index)
    if qureg.is_density:
        flat = state_index + (state_index << qureg.num_qubits)
    else:
        flat = state_index
    return _init_amps(qureg, _basis_planes(
        flat, n=qureg.num_state_qubits, rdt=qureg.real_dtype))


def init_debug_state(qureg: Qureg) -> Qureg:
    """Deterministic unphysical state: amp[k] = (2k + i(2k+1))/10.

    Matches the reference's initDebugState exactly (QuEST_cpu.c:1559-1590),
    which the whole test strategy leans on.
    """
    rdt = qureg.real_dtype
    k = jnp.arange(qureg.num_amps, dtype=rdt)
    return _init_amps(qureg,
                      jnp.stack([(2.0 * k) / 10.0, (2.0 * k + 1.0) / 10.0]))


@partial(jax.jit, static_argnames=("n", "qubit", "outcome", "rdt"))
def _single_qubit_outcome_planes(*, n, qubit, outcome, rdt):
    # scatter value must carry the register dtype: a bare Python float is
    # f64 under x64 and JAX is hardening the implicit down-cast to an error
    norm = jnp.asarray(1.0 / np.sqrt(1 << (n - 1)), dtype=rdt)
    pre, post = 1 << (n - 1 - qubit), 1 << qubit
    re = jnp.zeros((pre, 2, post), dtype=rdt).at[:, outcome, :].set(norm)
    return jnp.stack([re.reshape(-1), jnp.zeros((1 << n,), dtype=rdt)])


def init_state_of_single_qubit(qureg: Qureg, qubit: int, outcome: int) -> Qureg:
    """Uniform superposition over basis states whose bit `qubit` equals
    `outcome` (ref statevec_initStateOfSingleQubit, QuEST_cpu.c:1513-1555).
    Built ON DEVICE in one fused buffer — the whole point at 30q, where a
    host-side arange/where would materialize 2^n indices in host RAM."""
    validation.validate_state_vector(qureg)
    validation.validate_target(qureg, qubit)
    validation.validate_outcome(outcome)
    return _init_amps(qureg, _single_qubit_outcome_planes(
        n=qureg.num_state_qubits, qubit=qubit, outcome=outcome,
        rdt=qureg.real_dtype))


def init_pure_state(qureg: Qureg, pure: Qureg) -> Qureg:
    """Set qureg to the pure state |psi> (statevec copy) or |psi><psi|
    (ref densmatr_initPureState, QuEST_cpu.c / QuEST.c:139-146)."""
    validation.validate_pure_state_args(qureg, pure)
    rdt = qureg.real_dtype
    if not qureg.is_density:
        return _init_amps(qureg, pure.amps.astype(rdt))
    re, im = pure.amps[0].astype(rdt), pure.amps[1].astype(rdt)
    # rho[r, c] = psi_r conj(psi_c); flat index r + c*2^N = column-major,
    # i.e. row-major of rho^T
    rho_re = jnp.outer(re, re) + jnp.outer(im, im)
    rho_im = jnp.outer(im, re) - jnp.outer(re, im)
    return _init_amps(qureg,
                      jnp.stack([rho_re.T.reshape(-1), rho_im.T.reshape(-1)]))


def _host_pair(reals, imags, rdt):
    reals = np.asarray(reals, dtype=rdt).reshape(-1)
    imags = np.asarray(imags, dtype=rdt).reshape(-1)
    return np.stack([reals, imags])


def init_state_from_amps(qureg: Qureg, reals, imags) -> Qureg:
    """Overwrite all amplitudes from real/imag arrays (ref QuEST.c:155-161)."""
    reals = np.asarray(reals).reshape(-1)
    imags = np.asarray(imags).reshape(-1)
    validation.validate_equal_lengths(reals, imags)
    validation.validate_num_amps(qureg, 0, reals.size)
    if reals.size != qureg.num_amps:
        raise validation.QuESTError(
            "Invalid number of amplitudes: must match the register size")
    return _init_amps(qureg,
                      jnp.asarray(_host_pair(reals, imags, qureg.real_dtype)))


def set_amps(qureg: Qureg, start_index: int, reals, imags) -> Qureg:
    """Overwrite a contiguous slice of amplitudes (ref QuEST.c:779-786)."""
    validation.validate_state_vector(qureg)
    reals = np.asarray(reals).reshape(-1)
    imags = np.asarray(imags).reshape(-1)
    validation.validate_equal_lengths(reals, imags)
    validation.validate_num_amps(qureg, start_index, reals.size)
    vals = jnp.asarray(_host_pair(reals, imags, qureg.real_dtype))
    amps = jax.lax.dynamic_update_slice(qureg.amps, vals, (0, start_index))
    return qureg.replace_amps(amps)


def set_density_amps(qureg: Qureg, start_row: int, start_col: int, reals, imags) -> Qureg:
    """Debug-grade density amplitude writer (ref QuEST_debug.h:44-48).

    Writes a flat run of amplitudes starting at rho[start_row, start_col] in
    the column-major flat ordering.
    """
    if not qureg.is_density:
        raise validation.QuESTError(
            "Invalid operation: setDensityAmps requires a density matrix")
    reals = np.asarray(reals).reshape(-1)
    imags = np.asarray(imags).reshape(-1)
    validation.validate_equal_lengths(reals, imags)
    dim = 1 << qureg.num_qubits
    validation.validate_amp_index(qureg, start_row, dim=dim)
    validation.validate_amp_index(qureg, start_col, dim=dim)
    start = start_row + (start_col << qureg.num_qubits)
    validation.validate_num_amps(qureg, start, reals.size)
    vals = jnp.asarray(_host_pair(reals, imags, qureg.real_dtype))
    amps = jax.lax.dynamic_update_slice(qureg.amps, vals, (0, start))
    return qureg.replace_amps(amps)


# ---------------------------------------------------------------------------
# Amplitude getters (ref QuEST.c:671-705)
# ---------------------------------------------------------------------------


def _fetch_amp(qureg: Qureg, flat: int) -> complex:
    pair = np.asarray(jax.device_get(qureg.amps[:, flat]))
    return complex(pair[0], pair[1])


def get_amp(qureg: Qureg, index: int) -> complex:
    validation.validate_amp_index(qureg, index)
    validation.validate_state_vector(qureg)
    return _fetch_amp(qureg, index)


def get_real_amp(qureg: Qureg, index: int) -> float:
    return get_amp(qureg, index).real


def get_imag_amp(qureg: Qureg, index: int) -> float:
    return get_amp(qureg, index).imag


def get_prob_amp(qureg: Qureg, index: int) -> float:
    a = get_amp(qureg, index)
    return a.real * a.real + a.imag * a.imag


def get_density_amp(qureg: Qureg, row: int, col: int) -> complex:
    if not qureg.is_density:
        raise validation.QuESTError(
            "Invalid operation: getDensityAmp requires a density matrix")
    validation.validate_amp_index(qureg, row, dim=1 << qureg.num_qubits)
    validation.validate_amp_index(qureg, col, dim=1 << qureg.num_qubits)
    return _fetch_amp(qureg, row + (col << qureg.num_qubits))


def get_num_qubits(qureg: Qureg) -> int:
    return qureg.num_qubits


def get_num_amps(qureg: Qureg) -> int:
    """Statevector amplitude count (ref getNumAmps requires a statevector)."""
    validation.validate_state_vector(qureg)
    return qureg.num_amps


def to_dense(qureg: Qureg) -> np.ndarray:
    """Fetch the full state to host: (2^N,) complex vector or (2^N, 2^N)
    complex matrix."""
    planes = np.asarray(jax.device_get(qureg.amps))
    arr = planes[0] + 1j * planes[1]
    if qureg.is_density:
        dim = 1 << qureg.num_qubits
        return arr.reshape(dim, dim, order="F")
    return arr
