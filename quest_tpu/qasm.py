"""OPENQASM 2.0 circuit logger.

Feature-equivalent to the reference's QASM logger (QuEST/src/QuEST_qasm.c):
a per-register growable text buffer seeded with the OPENQASM header
(qasm_setup, QuEST_qasm.c:60-84), recording named gates, parameterized
gates, (multi-)controlled gates, ZYZ-decomposed general unitaries with
global-phase restoration comments, measurements, state initialisations,
and comments for operations QASM cannot express (QuEST_qasm.c:120-504).

The buffer is a Python list of lines (no manual growth logic needed); the
emitted text matches the reference's format: `U(rz2,ry,rz1)` for general
unitaries, `Ctrl-` prefixes per control, `q`/`c` register labels.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import numpy as np

QUREG_LABEL = "q"
MESREG_LABEL = "c"
CTRL_LABEL_PREF = "Ctrl-"
MEASURE_CMD = "measure"
INIT_ZERO_CMD = "reset"
COMMENT_PREF = "//"

GATE_LABELS = {
    "x": "x", "y": "y", "z": "z", "t": "t", "s": "s", "h": "h",
    "rx": "Rx", "ry": "Ry", "rz": "Rz", "u": "U", "phase": "Rz",
    "swap": "swap", "sqrtswap": "sqrtswap",
}


def zyz_angles_from_complex_pair(alpha: complex, beta: complex):
    """(rz2, ry, rz1) Euler angles of U(alpha, beta)
    (ref getZYZRotAnglesFromComplexPair, QuEST_common.c:123-132)."""
    alpha_mag = abs(alpha)
    ry = 2.0 * math.acos(min(1.0, alpha_mag))
    alpha_phase = math.atan2(alpha.imag, alpha.real)
    beta_phase = math.atan2(beta.imag, beta.real)
    return (-alpha_phase + beta_phase, ry, -alpha_phase - beta_phase)


def complex_pair_and_phase_from_unitary(u):
    """Map a 2x2 unitary to exp(i phase) U(alpha, beta)
    (ref getComplexPairAndPhaseFromUnitary, QuEST_common.c:135-147)."""
    u = np.asarray(u, dtype=np.complex128)
    phase = (math.atan2(u[0, 0].imag, u[0, 0].real)
             + math.atan2(u[1, 1].imag, u[1, 1].real)) / 2.0
    rot = complex(math.cos(phase), -math.sin(phase))
    return u[0, 0] * rot, u[1, 0] * rot, phase


def _fmt(x: float) -> str:
    return f"{x:g}"


class QASMLogger:
    """Per-register QASM recorder (ref QASMLogger, QuEST.h:62-69)."""

    def __init__(self, num_qubits: int):
        self.num_qubits = num_qubits
        self.is_logging = False
        self._lines: list[str] = []
        self._header = (f"OPENQASM 2.0;\n"
                        f"qreg {QUREG_LABEL}[{num_qubits}];\n"
                        f"creg {MESREG_LABEL}[{num_qubits}];\n")

    # -- low-level emission --------------------------------------------------

    def _add(self, text: str) -> None:
        self._lines.append(text)

    def _add_gate(self, gate: str, controls: Sequence[int], target: int,
                  params: Sequence[float]) -> None:
        line = CTRL_LABEL_PREF * len(controls) + GATE_LABELS[gate]
        if params:
            line += "(" + ",".join(_fmt(p) for p in params) + ")"
        line += " "
        for c in controls:
            line += f"{QUREG_LABEL}[{c}],"
        line += f"{QUREG_LABEL}[{target}];\n"
        self._add(line)

    # -- recording API (mirrors qasm_record*, QuEST_qasm.h:43-84) ------------

    def record_comment(self, comment: str) -> None:
        if not self.is_logging:
            return
        self._add(f"{COMMENT_PREF} {comment}\n")

    def record_gate(self, gate: str, target: int,
                    controls: Sequence[int] = (), params: Sequence[float] = ()
                    ) -> None:
        if not self.is_logging:
            return
        self._add_gate(gate, tuple(controls), target, tuple(params))
        # restore the global phase of controlled phase shifts
        # (ref qasm_recordControlledParamGate, QuEST_qasm.c:252-258)
        if gate == "phase" and controls:
            self.record_comment("Restoring the discarded global phase of "
                                "the previous controlled phase gate")
            self._add_gate("rz", (), target, (params[0] / 2.0,))

    def record_compact_unitary(self, alpha, beta, target: int,
                               controls: Sequence[int] = ()) -> None:
        if not self.is_logging:
            return
        self._add_gate("u", tuple(controls), target,
                       zyz_angles_from_complex_pair(alpha, beta))

    def record_unitary(self, u, target: int,
                       controls: Sequence[int] = ()) -> None:
        if not self.is_logging:
            return
        alpha, beta, phase = complex_pair_and_phase_from_unitary(u)
        self._add_gate("u", tuple(controls), target,
                       zyz_angles_from_complex_pair(alpha, beta))
        if controls:
            # global phase matters once controlled
            # (ref qasm_recordControlledUnitary, QuEST_qasm.c:282-303)
            self.record_comment("Restoring the discarded global phase of "
                                "the previous controlled unitary")
            self._add_gate("rz", (), target, (phase,))

    def record_axis_rotation(self, angle, axis, target: int,
                             controls: Sequence[int] = ()) -> None:
        if not self.is_logging:
            return
        from quest_tpu.ops.matrices import rotation_pair
        alpha, beta = rotation_pair(angle, axis)
        self._add_gate("u", tuple(controls), target,
                       zyz_angles_from_complex_pair(alpha, beta))

    def record_multi_state_controlled_unitary(
            self, u, controls: Sequence[int], control_states: Sequence[int],
            target: int) -> None:
        if not self.is_logging:
            return
        self.record_comment("NOTing some gates so that the subsequent "
                            "unitary is controlled-on-0")
        for c, s in zip(controls, control_states):
            if s == 0:
                self._add_gate("x", (), c, ())
        self.record_unitary(u, target, tuple(controls))
        self.record_comment("Undoing the NOTing of the controlled-on-0 "
                            "qubits of the previous unitary")
        for c, s in zip(controls, control_states):
            if s == 0:
                self._add_gate("x", (), c, ())

    def record_measurement(self, qubit: int) -> None:
        if not self.is_logging:
            return
        self._add(f"{MEASURE_CMD} {QUREG_LABEL}[{qubit}] -> "
                  f"{MESREG_LABEL}[{qubit}];\n")

    def record_init_zero(self) -> None:
        if not self.is_logging:
            return
        self._add(f"{INIT_ZERO_CMD} {QUREG_LABEL};\n")

    def record_init_plus(self) -> None:
        if not self.is_logging:
            return
        self.record_comment("Initialising state |+>")
        self.record_init_zero()
        self._add(f"h {QUREG_LABEL};\n")

    def record_init_classical(self, state_index: int) -> None:
        if not self.is_logging:
            return
        self.record_comment(f"Initialising state |{state_index}>")
        self.record_init_zero()
        for q in range(self.num_qubits):
            if (state_index >> q) & 1:
                self._add_gate("x", (), q, ())

    # -- control (ref QuEST.c:85-104) ----------------------------------------

    def start_recording(self) -> None:
        self.is_logging = True

    def stop_recording(self) -> None:
        self.is_logging = False

    def clear(self) -> None:
        self._lines.clear()

    def recorded(self) -> str:
        return self._header + "".join(self._lines)

    def print_recorded(self) -> None:
        print(self.recorded(), end="")

    def write_recorded_to_file(self, filename: str) -> bool:
        try:
            with open(filename, "w") as f:
                f.write(self.recorded())
            return True
        except OSError:
            return False
