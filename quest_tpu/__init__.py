"""quest_tpu — a TPU-native exact quantum circuit simulation framework.

A brand-new framework with the capabilities of QuEST (the Quantum Exact
Simulation Toolkit): dense state-vector and density-matrix simulation of
universal quantum circuits — the full gate set (arbitrary multi-controlled
multi-qubit unitaries, rotations, phase gates), decoherence channels
(dephasing, depolarising, damping, general Kraus maps), measurement and
collapse, inner-product / fidelity / purity / Pauli-expectation calculations,
and QASM logging.

Architecture (TPU-first, not a C port):
  - state:   functional `Qureg` pytree of 2^N complex amplitudes
             (2^2N for density matrices, via the Choi isomorphism,
             cf. reference QuEST/src/QuEST.c:8-10)
  - ops:     gates as tensor contractions on the (2,)*N view of the state;
             whole circuits trace into ONE XLA program so adjacent gates fuse
  - parallel: amplitudes sharded over a `jax.sharding.Mesh`; the reference's
             MPI_Sendrecv pair exchange (QuEST_cpu_distributed.c:481-509)
             becomes `lax.ppermute` over ICI, MPI_Allreduce becomes `lax.psum`
  - api:     a QuEST-compatible eager layer exposing the reference's ~106
             public functions (QuEST/include/QuEST.h) over the functional core
"""

from quest_tpu.precision import (
    get_default_dtype,
    set_default_dtype,
    real_eps,
    real_dtype_of,
)
from quest_tpu.state import (
    Qureg,
    create_qureg,
    create_density_qureg,
    init_blank_state,
    init_zero_state,
    init_plus_state,
    init_classical_state,
    init_debug_state,
    init_pure_state,
    init_state_from_amps,
    set_amps,
    set_density_amps,
    clone,
    get_amp,
    get_density_amp,
)
from quest_tpu.env import QuESTEnv, create_quest_env
from quest_tpu.validation import QuESTError

from quest_tpu.ops import gates
from quest_tpu import calculations
from quest_tpu import measurement
from quest_tpu.calculations import (
    calc_expec_pauli_prod,
    calc_expec_pauli_sum,
    calc_fidelity,
    calc_inner_product,
    calc_purity,
    calc_total_prob,
)
from quest_tpu.measurement import (
    calc_prob_of_outcome,
    collapse_to_outcome,
    measure,
    measure_with_stats,
    sample,
)
from quest_tpu.circuit import Circuit
from quest_tpu.ops.expec import PauliSum
from quest_tpu import qasm
from quest_tpu import api
from quest_tpu import checkpoint
from quest_tpu import profiling
from quest_tpu import variational
from quest_tpu import trajectories
from quest_tpu import evolution

__version__ = "0.1.0"
