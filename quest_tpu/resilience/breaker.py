"""Per-program circuit breaker driving the serve degradation ladder.

When ONE circuit's fused program reliably fails to compile or dispatch
(a Mosaic regression on that geometry, an operand-budget edge), retrying
it on every batch taxes every OTHER program's latency and spams the
failure path. The classic serving answer is a circuit breaker per
failure domain — here per `program_key`: after
`QUEST_SERVE_BREAKER_THRESHOLD` consecutive primary-engine failures the
breaker OPENS and the engine stops even attempting the fused program,
stepping requests down the degradation ladder (fused -> banded -> host,
the same engine ladder bench.py falls down) so riders keep getting
results. After `cooldown_s` the breaker lets ONE probe through
(HALF_OPEN); a healthy probe CLOSES it and fused service resumes, a
failing probe re-opens it for another cooldown (docs/RESILIENCE.md).

State machine:

    CLOSED --record_failure x threshold--> OPEN
    OPEN --cooldown elapsed (next allow_primary)--> HALF_OPEN (probe)
    HALF_OPEN --record_success--> CLOSED
    HALF_OPEN --record_failure--> OPEN (cooldown restarts)

Single-owner discipline: the serve worker thread is the only caller, so
there is no internal locking (the engine serializes every dispatch).
Stdlib-only.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class Breaker:
    """One failure domain's breaker (the engine keys them by
    program_key). `on_transition(old, new)` fires on every state change
    — the engine hangs its metrics (breaker_opens/closes counters, the
    breakers-open gauge) off it."""

    def __init__(self, threshold: int, cooldown_s: float = 0.5,
                 on_transition: Optional[Callable[[str, str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if threshold < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.state = CLOSED
        self.failures = 0           # consecutive primary failures
        self.opened_at: Optional[float] = None
        self._on_transition = on_transition
        self._clock = clock

    def _transition(self, new: str) -> None:
        old, self.state = self.state, new
        if old != new and self._on_transition is not None:
            self._on_transition(old, new)

    def allow_primary(self) -> bool:
        """Whether THIS dispatch may try the primary (fused) engine.
        CLOSED: yes. OPEN: only once the cooldown has elapsed — that
        call IS the half-open probe (the single-owner worker resolves
        it via record_success/record_failure before asking again)."""
        if self.state == CLOSED:
            return True
        if self.state == OPEN:
            if self._clock() - self.opened_at >= self.cooldown_s:
                self._transition(HALF_OPEN)
                return True
            return False
        return True                 # HALF_OPEN: the probe in progress

    def record_success(self) -> None:
        self.failures = 0
        if self.state != CLOSED:
            self._transition(CLOSED)

    def record_failure(self) -> None:
        self.failures += 1
        if self.state == HALF_OPEN or (self.state == CLOSED
                                       and self.failures >= self.threshold):
            self.opened_at = self._clock()
            self._transition(OPEN)
