"""Worker supervision policy: bounded restarts with backoff + jitter.

The ServeEngine owns ONE worker thread; before this layer a crash
escaping its loop silently stranded every queued future (the process
kept running, the futures never resolved — the worst failure mode a
serving system has). `Supervisor` is the policy half of the fix: it
decides, per crash, whether the worker restarts (and after how long) or
the engine gives up and transitions to its loud FAILED state
(docs/RESILIENCE.md). The mechanism half — requeueing undispatched
in-flight requests, failing dispatched ones, completing every future on
give-up — lives in the engine (`ServeEngine._worker_main`).

The policy is deliberately mechanism-agnostic: `serve.ipc.ReplicaProxy`
applies the SAME class to a supervised worker PROCESS (heartbeat loss or
pipe EOF is its "crash"; respawn+resubmit its "restart"; budget
exhaustion its transition to FAILED, which hands the proxy's incomplete
requests to the fleet's failover requeue — docs/SERVING.md
§process-fleet). One restart-budget story covers both boundaries.

Exponential backoff with deterministic jitter: restart k sleeps
`base * 2^(k-1)` capped at `cap`, plus a seeded-uniform jitter slice so
a crash-looping worker neither hot-spins nor thunders in lockstep with
anything else. Stdlib-only.
"""

from __future__ import annotations

import random
from typing import Optional


class Supervisor:
    """Restart budget + backoff schedule for one supervised worker.

    `next_backoff()` is called once per crash: it returns the seconds to
    sleep before the restart, or None when the budget
    (`QUEST_SERVE_RESTART_MAX`) is exhausted and the owner must fail
    loudly instead of restarting. `record_success()` (called after a
    healthy stretch, e.g. a completed dispatch) refills the budget —
    restarts are a CRASH-LOOP bound, not a lifetime quota."""

    def __init__(self, max_restarts: int, base_s: float = 0.05,
                 cap_s: float = 2.0, jitter_frac: float = 0.25,
                 seed: int = 0):
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts}")
        self.max_restarts = int(max_restarts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.jitter_frac = float(jitter_frac)
        self.restarts = 0           # consecutive crashes since success
        self.total_restarts = 0
        self._rng = random.Random(seed)

    def next_backoff(self) -> Optional[float]:
        """Seconds to sleep before the next restart, or None when the
        consecutive-crash budget is exhausted."""
        if self.restarts >= self.max_restarts:
            return None
        self.restarts += 1
        self.total_restarts += 1
        delay = min(self.cap_s, self.base_s * (2 ** (self.restarts - 1)))
        if delay <= 0.0:
            return 0.0
        return delay + self._rng.uniform(0.0, self.jitter_frac * delay)

    @property
    def remaining(self) -> int:
        """Restarts left in the consecutive-crash budget right now —
        the per-replica health figure ServeFleet.stats() surfaces so an
        operator can see which replica is one crash from FAILED."""
        return max(0, self.max_restarts - self.restarts)

    def record_success(self) -> None:
        """A healthy work cycle completed: reset the consecutive-crash
        count so one crash per hour never exhausts a budget meant to
        stop crash LOOPS."""
        self.restarts = 0
