"""Deterministic fault injection for the serving hot path.

The reference QuEST treats every backend failure as fatal
(`validate -> exitWithError`, SURVEY.md L5). A serving system cannot:
one Mosaic compile failure or device hiccup inside a coalesced launch
would take down every rider in the batch, and nothing short of killing
the process exercises the recovery paths. This module is the OTHER half
of that story: a registry of named FAULT SITES threaded through the hot
path (quest_tpu/serve/engine.py, quest_tpu/parallel/sharded.py) and a
`FaultPlan` that makes a chosen site raise a chosen error
DETERMINISTICALLY — so every recovery path (supervised restart, batch
splitting, breaker degradation) is provable end-to-end in tests and
soak runs instead of waiting for real hardware to misbehave
(docs/RESILIENCE.md; the single-host analogue of the node-failure
operations mpiQulacs-class distributed simulators plan for,
arXiv:2203.16044).

Zero-cost when empty: every call site is guarded by the ONE module
flag `ACTIVE` (`if faults.ACTIVE: faults.check(site)`), so an
uninstrumented process pays a single attribute read per site and the
compiled programs never see any of this (the checks live strictly on
the host side of every launch — the empty-plan zero-retrace pin in
tests/test_resilience.py).

Usage — tests install a plan directly:

    plan = FaultPlan()
    plan.inject("serve.compile", error=RuntimeError("mosaic"), times=3)
    with faults.active(plan):
        ...

Soak runs set the `QUEST_FAULT_PLAN` knob (grammar below);
`install_from_env()` (called once per ServeEngine construction) parses
and installs it process-wide.

This module imports ONLY the standard library (the fault checks sit on
paths that must not drag jax in, and env.py's knob parser imports it).
"""

from __future__ import annotations

import contextlib
import random
import threading
from typing import Callable, Dict, List, Optional

# the ONE hot-path guard: call sites read `faults.ACTIVE` before calling
# check(). False whenever no plan (or an empty plan) is installed.
ACTIVE = False

# the fault-site catalog (docs/RESILIENCE.md). inject() validates
# against it so a typo'd site fails loudly at plan-build time instead of
# silently never firing.
SITES = (
    "serve.worker_loop",    # ServeEngine worker iteration (phase=idle
                            # before the pop, phase=popped with batches
                            # in hand but none dispatched)
    "serve.compile",        # primary-engine program compile/resolution
    "serve.device_put",     # host->device staging of a coalesced batch
    "serve.dispatch",       # the batched launch itself (ctx carries the
                            # batch's requests — match= emulates one
                            # poisoned rider failing its whole launch)
    "serve.demux",          # per-request result demux (ctx carries the
                            # single request)
    "sharded.dispatch",     # apply_circuit_sharded's mesh dispatch
    "checkpoint.save",      # checkpoint commit point (temp files
                            # written, rename pending) — an injected
                            # error emulates a crash MID-SAVE; the
                            # previous checkpoint must stay loadable
    "checkpoint.load",      # checkpoint read path (load/load_arrays) —
                            # emulates IO failures; the durable resume
                            # chain must skip to an older checkpoint
    "checkpoint.load_gang", # gang/elastic reassembly read path
                            # (checkpoint.load_step_gang — every
                            # multi-host resume and every elastic
                            # re-entry of a gang chain): chaos plans
                            # can fail the reassembly on one host; the
                            # gang scanner must skip to an older
                            # committed step on EVERY host (validity is
                            # a pure function of the shared dir)
    "durable.step",         # durable executor, before each sweep-plan
                            # step (ctx carries the step index)
    "durable.preempt",      # the durable KILL site: same cut points as
                            # durable.step, reserved for preemption
                            # plans so soaks can kill a run at seeded
                            # boundaries without disturbing step-fault
                            # rules (docs/RESILIENCE.md §durable)
    "fleet.route",          # ServeFleet routing decision (ctx carries
                            # program key, chosen replica, tenant,
                            # priority) — an armed error surfaces in
                            # the submitter, so soaks can fail routing
                            # deterministically
    "fleet.failover",       # fleet-level failover requeue of a dead
                            # replica's request onto a survivor (ctx:
                            # replica, target) — an armed error fails
                            # that request's future typed
    "fleet.shed",           # the shed decision point: fires when
                            # pressure crosses the threshold and a
                            # victim is about to shed (ctx: pressure,
                            # priority, evict) — soaks can force the
                            # decision path deterministically
    "fleet.requeue",        # failover REQUEUE hop: fires as a dead
                            # replica's ticket is re-submitted to its
                            # chosen survivor (ctx: replica, target,
                            # hops, durable) — distinct from
                            # fleet.failover (the decision point), so
                            # chaos plans can fail the hop itself, e.g.
                            # mid-durable-failover
    "fleet.spawn",          # process-replica spawn (serve.ipc — both
                            # the initial boot and every supervised
                            # respawn; ctx: replica, respawn) — an
                            # armed error emulates exec/fork failure so
                            # soaks can prove spawn loss burns the
                            # process supervisor budget and fails over
    "ipc.send",             # one framed message leaving the proxy for
                            # its worker process (ctx: replica, type) —
                            # an armed error emulates a broken pipe
                            # mid-submit; the proxy must fail the
                            # request typed, never strand its future
    "ipc.recv",             # one framed message arriving from the
                            # worker process (ctx: replica, type) — an
                            # armed error emulates a torn/poisoned
                            # frame; the proxy treats it as worker loss
                            # (kill + respawn under budget)
)


class InjectedFault(RuntimeError):
    """Default error an armed fault site raises (a stand-in for the real
    failure class: Mosaic compile error, device OOM, transfer fault)."""


class _Rule:
    """One armed site: deterministic hit counting, bounded firing."""

    __slots__ = ("site", "error", "after_n", "every_n", "times", "p",
                 "match", "hits", "fired", "_rng")

    def __init__(self, site: str, error, after_n: int, every_n,
                 times, p, match, seed: int):
        self.site = site
        self.error = error
        self.after_n = int(after_n)
        self.every_n = None if every_n is None else int(every_n)
        self.times = None if times is None else int(times)
        self.p = None if p is None else float(p)
        self.match = match
        self.hits = 0
        self.fired = 0
        # per-rule PRNG seeded by (site, seed): a probabilistic rule
        # fires the same hit sequence on every run of the same plan
        self._rng = random.Random(f"{site}:{seed}")

    def consider(self, ctx: dict) -> None:
        if self.match is not None and not self.match(ctx):
            return
        self.hits += 1
        if self.hits <= self.after_n:
            return
        if self.times is not None and self.fired >= self.times:
            return
        if (self.every_n is not None
                and (self.hits - self.after_n) % self.every_n != 0):
            return
        if self.p is not None and self._rng.random() >= self.p:
            return
        self.fired += 1
        err = self.error
        if isinstance(err, type):
            err = err(f"injected fault at {self.site!r} "
                      f"(hit {self.hits}, fire {self.fired})")
        raise err


class FaultPlan:
    """A deterministic set of armed fault sites.

    `inject(site, ...)` arms one site; every keyword is optional:

      error    exception INSTANCE or CLASS to raise (default
               InjectedFault — classes get a descriptive message built
               per fire, instances raise as-is)
      after_n  skip the first N hits of the site (default 0)
      every_n  then fire every Nth remaining hit (default: every hit)
      times    cap total fires (default: unlimited)
      p        fire with probability p per eligible hit, from a PRNG
               seeded by (site, seed) — deterministic per plan replay
      match    callable(ctx) -> bool; the hit only COUNTS when the
               site's context matches (e.g. lambda ctx: bad_future in
               [r.future for r in ctx["reqs"]] — emulates a poisoned
               request that fails any launch containing it)
      seed     PRNG seed for `p` (default 0)

    Thread-safe: hit counters mutate under one lock (client threads hit
    sharded.dispatch while the serve worker hits the serve.* sites)."""

    _GUARDED_BY = {"_lock": ("_rules",)}

    def __init__(self):
        self._rules: Dict[str, List[_Rule]] = {}
        self._lock = threading.Lock()

    def inject(self, site: str, error=InjectedFault, after_n: int = 0,
               every_n: Optional[int] = None, times: Optional[int] = None,
               p: Optional[float] = None,
               match: Optional[Callable[[dict], bool]] = None,
               seed: int = 0) -> "FaultPlan":
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r}; the catalog is "
                f"{sorted(SITES)} (docs/RESILIENCE.md)")
        if after_n < 0:
            raise ValueError(f"after_n must be >= 0, got {after_n}")
        if every_n is not None and every_n < 1:
            raise ValueError(f"every_n must be >= 1, got {every_n}")
        if times is not None and times < 1:
            raise ValueError(f"times must be >= 1, got {times}")
        if p is not None and not (0.0 <= p <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {p}")
        with self._lock:
            self._rules.setdefault(site, []).append(
                _Rule(site, error, after_n, every_n, times, p, match, seed))
        return self

    @property
    def empty(self) -> bool:
        # quest-lint: disable=QL005(truthiness of a dict is one atomic read)
        return not self._rules

    def fired(self, site: Optional[str] = None) -> int:
        """Total fires (or one site's) — test/soak introspection."""
        with self._lock:
            rules = (self._rules.get(site, ()) if site is not None
                     else [r for rs in self._rules.values() for r in rs])
            return sum(r.fired for r in rules)

    def check(self, site: str, ctx: dict) -> None:
        # quest-lint: disable=QL005(lock-free fast path: dict.get is atomic, plans arm before workers start)
        rules = self._rules.get(site)
        if not rules:
            return
        with self._lock:
            for rule in rules:
                rule.consider(ctx)


# ---------------------------------------------------------------------------
# process-wide installation
# ---------------------------------------------------------------------------

_PLAN: Optional[FaultPlan] = None
_ENV_INSTALLED = False


def install(plan: Optional[FaultPlan]) -> None:
    """Install `plan` process-wide (None clears). `ACTIVE` flips with
    it, so an empty/absent plan keeps every call site on the one-flag
    fast path."""
    global _PLAN, ACTIVE
    _PLAN = plan
    ACTIVE = bool(plan is not None and not plan.empty)


def clear() -> None:
    install(None)


def current() -> Optional[FaultPlan]:
    return _PLAN


@contextlib.contextmanager
def active(plan: FaultPlan):
    """Scoped install: the previous plan is restored on exit (tests)."""
    prev = _PLAN
    install(plan)
    try:
        yield plan
    finally:
        install(prev)


def check(site: str, **ctx) -> None:
    """Raise if the installed plan arms `site` for this hit. Call sites
    guard with `if faults.ACTIVE:` so the empty case never gets here."""
    plan = _PLAN
    if plan is not None:
        plan.check(site, ctx)


# ---------------------------------------------------------------------------
# QUEST_FAULT_PLAN: the soak-run knob
# ---------------------------------------------------------------------------
#
# Grammar (validated loudly — env.knob_value raises ValueError on any
# malformed spec):
#
#     QUEST_FAULT_PLAN="site[:key=value]...[;site[:key=value]...]..."
#
# e.g. "serve.dispatch:error=RuntimeError:after=10:every=25;
#       serve.worker_loop:p=0.01:seed=7:times=2"
#
# keys: error (builtin exception name or 'fault' = InjectedFault),
# after, every, times, p, seed — the inject() parameters; match= is
# API-only (it takes a callable).


def parse_plan(spec: str) -> FaultPlan:
    """Parse a QUEST_FAULT_PLAN spec string into a FaultPlan (the knob's
    registered parser; raises ValueError on malformed input)."""
    plan = FaultPlan()
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        site, kw = fields[0].strip(), {}
        for f in fields[1:]:
            if "=" not in f:
                raise ValueError(
                    f"QUEST_FAULT_PLAN field {f!r} is not key=value "
                    f"(in {part!r})")
            k, v = (s.strip() for s in f.split("=", 1))
            if k == "error":
                if v == "fault":
                    kw["error"] = InjectedFault
                else:
                    import builtins
                    err = getattr(builtins, v, None)
                    if not (isinstance(err, type)
                            and issubclass(err, Exception)):
                        raise ValueError(
                            f"QUEST_FAULT_PLAN error={v!r} is not a "
                            f"builtin exception name (or 'fault')")
                    kw["error"] = err
            elif k in ("after", "after_n"):
                kw["after_n"] = _parse_int(k, v, lo=0)
            elif k in ("every", "every_n"):
                kw["every_n"] = _parse_int(k, v, lo=1)
            elif k == "times":
                kw["times"] = _parse_int(k, v, lo=1)
            elif k == "seed":
                kw["seed"] = _parse_int(k, v)
            elif k == "p":
                try:
                    kw["p"] = float(v)
                except ValueError:
                    raise ValueError(
                        f"QUEST_FAULT_PLAN p={v!r} is not a float")
            else:
                raise ValueError(
                    f"unknown QUEST_FAULT_PLAN key {k!r} (in {part!r}); "
                    f"keys: error, after, every, times, p, seed")
        plan.inject(site, **kw)
    return plan


def _parse_int(key: str, raw: str, lo: Optional[int] = None) -> int:
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"QUEST_FAULT_PLAN {key}={raw!r} is not an int")
    if lo is not None and v < lo:
        raise ValueError(f"QUEST_FAULT_PLAN {key} must be >= {lo}, got {v}")
    return v


def install_from_env() -> None:
    """Install the QUEST_FAULT_PLAN knob's plan once per process (no-op
    when the knob is unset or a plan was already installed explicitly).
    ServeEngine construction calls this, so soak runs arm the sites by
    exporting the knob — no code change."""
    global _ENV_INSTALLED
    if _ENV_INSTALLED or _PLAN is not None:
        return
    _ENV_INSTALLED = True
    from quest_tpu.env import knob_value
    plan = knob_value("QUEST_FAULT_PLAN")
    if plan is not None:
        install(plan)
