"""Durable execution: mid-circuit checkpointing, preemption-tolerant
resume, and corruption sentinels (docs/RESILIENCE.md §durable).

The reference can only restart a run from gate 0 and trusts every
amplitude blindly; on preemptible pods a 30q+ job measured in hours
makes "lose the run" the dominant failure cost (the TPU brute-force
paper's operating regime, arXiv:2111.10466). `run_durable` executes a
circuit in STEPS cut at the engines' own launch boundaries — the
sweep-plan parts of the fused engine, fusion-plan items of the banded
and sharded engines, shot chunks of the trajectory engine; NEVER
mid-kernel — and checkpoints the amplitude planes plus a cursor every
`QUEST_DURABLE_EVERY` steps through quest_tpu.checkpoint's atomic
versioned chain:

  * RESUME: a rerun of the same call finds the newest VALID checkpoint
    under `directory`, verifies its cursor against the re-derived plan
    (engine, step count, keyed-knob mode key, and — on the sharded
    engine — the relabel `_PermTracker` permutation at the cut), and
    continues from the cut. Interrupted and uninterrupted runs execute
    the IDENTICAL per-step program sequence, so the final amplitudes
    are BIT-IDENTICAL (pinned per engine in tests/test_durable.py).
  * CORRUPTION ON DISK: every checkpoint's per-plane SHA-256 digests
    are verified at load (checkpoint.py format 3); a corrupt checkpoint
    is skipped LOUDLY (stderr + `durable_corrupt_checkpoints_skipped`)
    in favor of the previous valid one — never silently consumed.
  * CORRUPTION IN FLIGHT: cheap on-device sentinel reductions run at
    checkpoint cadence — statevector norm drift vs the run's baseline,
    density trace + hermiticity residual (`QUEST_INTEGRITY`, budget
    `QUEST_INTEGRITY_TOL`). A trip raises typed `IntegrityError` and
    REFUSES to stamp the checkpoint, so a NaN'd or drifted state can
    never poison the resume chain.

Fault sites `durable.step` / `durable.preempt` (plus `checkpoint.save`
/ `checkpoint.load`) make every path provable: a seeded FaultPlan kills
a deep run K times at random boundaries — including mid-save — and the
chaos soak pins that it still completes with the exact uninterrupted
amplitudes (tests/test_durable.py).

Metrics (serve.metrics.REGISTRY): counters `durable_steps_run`,
`durable_checkpoints_saved`, `durable_resumes`,
`durable_corrupt_checkpoints_skipped`, `durable_sentinel_trips`; gauge
`durable_last_checkpoint_step`.

This module imports jax and is therefore loaded LAZILY by
quest_tpu.resilience.__getattr__ — the rest of the resilience package
stays stdlib-only (env.py's knob parser imports it).
"""

from __future__ import annotations

import hashlib
import os
import sys
import time as _time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import checkpoint as ckpt
from quest_tpu import validation
from quest_tpu.resilience import faults
from quest_tpu.serve import metrics as _metrics
from quest_tpu.state import Qureg


class DurableError(validation.QuESTError):
    """A durable resume could not be reconciled with the re-derived
    plan: the cursor's engine/step-count/mode-key/permutation disagrees
    with what this process would execute (a keyed-knob flip or circuit
    edit between save and resume). The message names the field and the
    expected/got values — resuming anyway would execute the wrong
    program suffix over the checkpointed amplitudes."""


class IntegrityError(validation.QuESTError):
    """An in-flight corruption sentinel tripped: the state's cheap
    invariant (statevector norm / density trace+hermiticity) drifted
    beyond QUEST_INTEGRITY_TOL from the run's baseline — NaN poisoning,
    a silently corrupt plane, or a non-CPTP evolution. The checkpoint
    at this cut was NOT stamped (docs/RESILIENCE.md §durable)."""


def _registry_of(registry: Optional[_metrics.Registry]
                 ) -> _metrics.Registry:
    return registry if registry is not None else _metrics.REGISTRY


def _counter(name: str, registry: Optional[_metrics.Registry] = None):
    return _registry_of(registry).counter(name)


def _ops_sha(ops) -> str:
    """Value fingerprint of a circuit's op stream — kinds, qubits AND
    operand bytes. The cursor's op COUNT alone cannot catch an edited
    rotation angle (same count, same plan shape, different program);
    resuming across one would splice two different circuits' amplitude
    prefixes silently."""
    h = hashlib.sha256()
    for op in ops:
        h.update(repr((op.kind, op.targets, op.controls,
                       op.cstates)).encode())
        if op.operand is not None:
            try:
                h.update(np.asarray(op.operand).tobytes())
            except Exception:       # nested structures (classical ops)
                h.update(repr(op.operand).encode())
    return h.hexdigest()[:32]


def _state_fingerprint(state: Qureg) -> str:
    """Cheap value fingerprint of the INITIAL register, stored in the
    cursor and re-derived at resume from the caller's own argument: a
    rerun that passes a different initial state (or dtype) must fail
    typed, not splice prefixes. Small registers hash every amplitude;
    huge ones hash shape/dtype plus a leading slice — a full host
    gather per run is the cost this executor exists to avoid."""
    amps = state.amps
    h = hashlib.sha256()
    h.update(repr((tuple(amps.shape), str(amps.dtype))).encode())
    if amps.size <= (1 << 22):
        payload = np.asarray(jax.device_get(amps))
    else:
        payload = np.asarray(jax.device_get(amps[:, :4096]))
    h.update(memoryview(np.ascontiguousarray(payload)).cast("B"))
    return h.hexdigest()[:32]


_ELASTIC_FP_FNS: dict = {}


def _state_fingerprint_elastic(state: Qureg) -> str:
    """MESH-INDEPENDENT exact fingerprint of the initial register, for
    the elastic cursor (docs/RESILIENCE.md §elastic): the float-sum
    fingerprints above round differently per mesh (a psum of shard
    partials reassociates), so an elastic resume on a different device
    or host count could never match them. This one reduces the raw
    amplitude BITS with modular uint32 arithmetic — a plain bit-sum and
    an index-weighted bit-sum, both wraparound-exact and fully
    associative/commutative — so the value is BIT-EQUAL on any mesh
    that holds the same amplitudes (and across hosts of a gang, where
    the cursor must agree byte-for-byte)."""
    amps = state.amps
    key = (tuple(amps.shape), str(amps.dtype))
    fn = _ELASTIC_FP_FNS.get(key)
    if fn is None:
        def f(a):
            bits = jax.lax.bitcast_convert_type(a, jnp.uint32).reshape(-1)
            idx = jax.lax.iota(jnp.uint32, bits.shape[0])
            s1 = jnp.sum(bits, dtype=jnp.uint32)
            # +1 gives every position a DISTINCT nonzero weight (mod
            # 2^32), so moving one amplitude between positions changes
            # the weighted sum even though the plain sum is unchanged
            s2 = jnp.sum(bits * (idx + jnp.uint32(1)), dtype=jnp.uint32)
            return s1, s2
        fn = _ELASTIC_FP_FNS[key] = jax.jit(f)
    vals = [int(v) for v in fn(amps)]
    h = hashlib.sha256()
    h.update(repr((key, vals)).encode())
    return h.hexdigest()[:32]


_GANG_FP_FNS: dict = {}


def _state_fingerprint_gang(state: Qureg) -> str:
    """Fingerprint of a MULTI-HOST register: the gang cursor must be
    IDENTICAL on every host (load_step_gang rejects torn saves), so the
    per-host byte hash above cannot ride it — no host can read its
    peers' shards without a gather. Instead: shape/dtype plus three
    replicated global reductions (sum, sum of squares, max magnitude),
    computed by the SAME SPMD program on every host and therefore
    bit-equal across them; a different initial state or dtype still
    fails typed at resume."""
    amps = state.amps
    key = (tuple(amps.shape), str(amps.dtype))
    fn = _GANG_FP_FNS.get(key)
    if fn is None:
        def f(a):
            return (jnp.sum(a), jnp.sum(a * a), jnp.max(jnp.abs(a)))
        fn = _GANG_FP_FNS[key] = jax.jit(f)
    vals = [float(v) for v in fn(amps)]
    h = hashlib.sha256()
    h.update(repr((key, vals)).encode())
    return h.hexdigest()[:32]


# ---------------------------------------------------------------------------
# step plans: the circuit cut at launch boundaries, per engine
# ---------------------------------------------------------------------------


def _resolve_state_engine(engine, n: int, is_f32: bool, mesh) -> str:
    from quest_tpu.ops import pallas_band as PB
    if mesh is not None:
        if engine not in (None, "sharded"):
            raise ValueError(
                f"engine {engine!r} does not take a mesh; pass "
                f"engine='sharded' (or None) with mesh=")
        return "sharded"
    if engine == "sharded":
        raise ValueError("engine='sharded' requires mesh=")
    if engine not in (None, "fused", "banded"):
        raise ValueError(
            f"engine must be None, 'fused', 'banded' or 'sharded', "
            f"got {engine!r}")
    if engine in (None, "fused") and PB.usable(n) and is_f32:
        return "fused"
    # compiled_fused's own fallback: f64 planes and sub-kernel-tier
    # registers ride the banded XLA program
    return "banded"


def _build_steps(circuit, n: int, density: bool, engine: str,
                 interpret: bool, mesh) -> Tuple[List, dict]:
    """(steps, info) for one engine's durable plan: `steps` is the list
    of independently-jitted per-launch programs (cached on the circuit,
    so a resume in a warm process retraces NOTHING — the zero-retrace
    pin), `info` the plan fingerprint the cursor validates against.
    Cuts reuse the engines' own planners — pallas_band.segment_plan /
    sweep_plan for the fused engine, fusion.plan items for banded and
    sharded — so a cut can never land mid-kernel."""
    from quest_tpu.circuit import (_apply_banded_items, _engine_mode_key,
                                   _xla_part_applier)
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    key = ("durable", engine, n, density, interpret,
           mesh if mesh is not None else None, _engine_mode_key())
    cached = circuit._compiled.get(key)
    if cached is not None:
        return cached

    perm_ops = None
    devices = 1
    if engine == "fused":
        flat = circuit._planned_flat(n, density)
        item_attr: list = []
        items = F.plan(flat, n, bands=PB.plan_bands(n), attr=item_attr)
        seg_attr: list = []
        seg_parts = PB.segment_plan(items, n, attr=seg_attr)
        if PB.sweep_enabled():
            part_attr: list = []
            parts = PB.sweep_plan(seg_parts, n, attr=part_attr,
                                  part_attrs=seg_attr)
        else:
            parts, part_attr = list(seg_parts), list(seg_attr)
        # per-STEP flat-op attribution: parts index items, items index
        # flat ops
        step_attr = [frozenset().union(*(item_attr[i] for i in pa))
                     if pa else frozenset() for pa in part_attr]
        seg_cache: dict = {}
        steps = []
        for part in parts:
            if part[0] == "segment":
                seg = PB.compile_segment_cached(
                    seg_cache, tuple(part[1]), n, interpret=interpret)
                fn = (lambda a, seg=seg, arrays=part[2]: seg(a, arrays))
            else:
                fn = _xla_part_applier(part, n)
            steps.append(jax.jit(fn))
        layout = "fused"
        flat_used, exec_items = flat, None
    elif engine == "banded":
        flat = circuit._planned_flat(n, density)
        item_attr = []
        items = F.plan(flat, n, attr=item_attr)
        steps = [jax.jit(lambda a, it=it: _apply_banded_items(a, n, (it,)))
                 for it in items]
        layout = "flat"
        step_attr = item_attr
        flat_used, exec_items = flat, items
    else:                                   # sharded
        import math
        from quest_tpu.parallel import sharded as S
        devices = int(mesh.devices.size)
        local_n = n - int(math.log2(devices))
        bands = S._shard_bands(n, local_n)
        cinfo: dict = {}
        flat_r = S.engine_flat(circuit.ops, n, density, local_n,
                               bands=bands, comm_info=cinfo)
        item_attr = []
        planned = F.plan(flat_r, n, bands=bands, attr=item_attr)
        items = cinfo.get("items")
        if items is None:
            items = planned
        elif not _plans_align(items, planned):
            # the comm planner handed back a plan the deterministic
            # re-plan does not reproduce — attribution would be
            # misaligned (a mis-mapped boundary would double-apply an
            # op on elastic resume), so the elastic boundary map
            # degrades to "no portable boundaries" (strict resume is
            # untouched)
            item_attr = None
        steps = [S.compile_plan_items_sharded((it,), n, mesh)
                 for it in items]
        layout = "sharded"
        step_attr = item_attr
        flat_used, exec_items = flat_r, items
        # the relabel-permutation trajectory at every cut: perm_ops[k]
        # is the GateOp stream behind items[:k] that replay_perm
        # fingerprints (band-composed ops expose no op; relabel events
        # and explicit SWAPs do — see relabel.replay_perm)
        perm_ops = []
        acc: list = []
        for it in items:
            op = getattr(it, "op", None)
            perm_ops.append(tuple(acc))
            if op is not None:
                acc.append(op)
        perm_ops.append(tuple(acc))

    sched = circuit._planned_flat(n, density)
    ops_done_at = _boundary_ops_done(flat_used, step_attr, exec_items,
                                     len(steps))
    info = {
        "engine": engine,
        "n": n,
        "density": density,
        "num_steps": len(steps),
        "mode_key": repr(_engine_mode_key()),
        "circuit_ops": len(circuit.ops),
        "layout": layout,
        "devices": devices,
        "mesh": mesh,
        "perm_ops": perm_ops,
        # elastic boundary bookkeeping (docs/RESILIENCE.md §elastic):
        # the SCHEDULED canonical op stream is mesh-independent (the
        # relabel rewrites only remap/insert), so a cut that consumed
        # exactly its first m ops can re-enter any other mesh's plan at
        # a boundary with the same count
        "sched_sha": _ops_sha(sched),
        "ops_total": len(sched),
        "ops_done_at": ops_done_at,
    }
    circuit._compiled[key] = (steps, info)
    return steps, info


def _plans_align(items, planned) -> bool:
    """STRUCTURAL equality of the comm planner's item list and the
    attribution re-plan — length alone could mask a same-length plan
    that composes ops differently (under-counting ops_done by one and
    double-applying a gate on elastic resume). Both lists wrap the SAME
    flat-stream op objects, so exposed ops compare by identity; band
    items compare by geometry + the qubit sets that drove composition."""
    if len(items) != len(planned):
        return False
    for a, b in zip(items, planned):
        if type(a) is not type(b):
            return False
        if getattr(a, "op", None) is not getattr(b, "op", None):
            return False
        if (getattr(a, "ql", None) != getattr(b, "ql", None)
                or getattr(a, "w", None) != getattr(b, "w", None)
                or getattr(a, "nondiag", None) != getattr(b, "nondiag",
                                                          None)
                or getattr(a, "touched", None) != getattr(b, "touched",
                                                          None)):
            return False
    return True


def _boundary_ops_done(flat_used, step_attr, exec_items,
                       num_steps: int) -> List[Optional[int]]:
    """ops_done_at[b] for every step boundary b in [0, num_steps]: the
    number of CANONICAL (scheduled-stream) ops fully consumed by steps
    [0, b) when that boundary is PORTABLE — the consumed ops form an
    exact prefix of the canonical stream, nothing straddles the cut,
    and every relabel-pass-inserted layout op before it is VISIBLE to
    the perm replay (an inserted SWAP the planner composed into a band
    operator moves data replay_perm cannot see — canonicalization would
    be wrong from that step on) — else None. Boundary 0 is always
    portable (restart from op 0). `step_attr` is the per-step flat-op
    attribution (None = attribution unavailable: only boundary 0
    stays portable)."""
    from quest_tpu.parallel import relabel as R

    out: List[Optional[int]] = [0]
    if step_attr is None:
        return out + [None] * num_steps
    nflat = len(flat_used)
    canon_of: List[Optional[int]] = []
    m = 0
    for op in flat_used:
        if R.is_inserted_layout_op(op):
            canon_of.append(None)
        else:
            canon_of.append(m)
            m += 1
    first = [num_steps] * nflat
    last = [-1] * nflat
    poison = num_steps + 1
    for k, srcs in enumerate(step_attr):
        for p in srcs:
            first[p] = min(first[p], k)
            last[p] = max(last[p], k)
            if canon_of[p] is None and exec_items is not None:
                # layout ops must ride op-exposing items (PassOp for
                # relabel events, DiagItem never): a band-composed one
                # is invisible to the perm replay — poison every
                # boundary past its item
                if getattr(exec_items[k], "op", None) is not flat_used[p]:
                    poison = min(poison, k)
    canon_total = m
    for b in range(1, num_steps + 1):
        if b > poison:
            out.append(None)
            continue
        done = 0
        hi = -1
        ok = True
        for p in range(nflat):
            consumed = last[p] < b and last[p] >= 0
            touched = first[p] < b
            if consumed != touched:
                ok = False          # an op straddles the cut
                break
            if consumed and canon_of[p] is not None:
                done += 1
                hi = max(hi, canon_of[p])
        # prefix check: the consumed canonical ops must be exactly
        # 0..done-1 of the scheduled stream
        if ok and hi == done - 1:
            out.append(done)
        else:
            out.append(None)
    # a fully-consumed plan must land on the full canonical count —
    # anything else means attribution lost ops; degrade loudly-safe
    if out[num_steps] is not None and out[num_steps] != canon_total:
        out[num_steps] = None
    return out


def _cut_perm(info: dict, step: int) -> Optional[List[int]]:
    """The relabel `_PermTracker` permutation at cut `step` (sharded
    engine only): which logical qubit sits at which physical position
    when the first `step` plan items have executed."""
    if info["engine"] != "sharded":
        return None
    import math
    from quest_tpu.parallel import relabel as R
    local_n = info["n"] - int(math.log2(info["devices"]))
    return R.replay_perm(info["perm_ops"][step], info["n"], local_n)


# ---------------------------------------------------------------------------
# layouts: each engine's native amplitude view <-> the (2, 2^n) planes
# ---------------------------------------------------------------------------


def _to_layout(amps, info: dict):
    from quest_tpu.ops import pallas_band as PB
    if info["layout"] == "fused":
        return jnp.asarray(amps).reshape(2, -1, PB.LANES)
    if info["layout"] == "sharded":
        from quest_tpu.parallel.mesh import amp_sharding
        sharding = amp_sharding(info["mesh"])
        if jax.process_count() > 1:
            # multi-host: the caller's register is already a global
            # array (pass it through); a resume's reassembled host
            # planes must enter via make_array_from_callback — a
            # device_put cannot target non-addressable devices
            if isinstance(amps, jax.Array) \
                    and not amps.is_fully_addressable:
                return amps.reshape(2, -1)
            arr = np.asarray(amps).reshape(2, -1)
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx])
        return jax.device_put(jnp.asarray(amps).reshape(2, -1),
                              sharding)
    return jnp.asarray(amps).reshape(2, -1)


def _from_layout(amps, info: dict):
    return amps.reshape(2, -1)


# ---------------------------------------------------------------------------
# corruption sentinels: cheap on-device invariants at checkpoint cadence
# ---------------------------------------------------------------------------

_SENTINEL_FNS: dict = {}


def _sentinel_values(amps, info: dict) -> dict:
    """The state's cheap integrity invariants, as host floats: one
    reduction pass for a statevector (norm), trace + hermiticity
    residual for a density register — orders cheaper than a sweep, and
    NaN anywhere fails every comparison (NaN <= tol is False)."""
    density = info["density"]
    key = ("dm" if density else "sv", info["n"], amps.shape,
           str(amps.dtype))
    fn = _SENTINEL_FNS.get(key)
    if fn is None:
        if density:
            nq = info["n"] // 2          # rho is 2^nq x 2^nq

            def f(a):
                v = a.reshape(2, 1 << nq, 1 << nq)
                # flat index r + c*2^nq => v[plane, c, r]
                tr_re = jnp.trace(v[0])
                tr_im = jnp.trace(v[1])
                herm = jnp.maximum(
                    jnp.max(jnp.abs(v[0] - v[0].T)),
                    jnp.max(jnp.abs(v[1] + v[1].T)))
                return tr_re, tr_im, herm
        else:
            def f(a):
                return (jnp.sum(a.astype(jnp.float32) ** 2),)
        fn = _SENTINEL_FNS[key] = jax.jit(f)
    vals = [float(v) for v in fn(amps)]
    if density:
        return {"trace_re": vals[0], "trace_im": vals[1],
                "herm_residual": vals[2]}
    return {"norm": vals[0]}


def _check_integrity(vals: dict, baseline: dict, tol: float,
                     step, registry=None) -> None:
    for name, got in vals.items():
        ref = float(baseline.get(name, 0.0))
        # relative drift with a floor of 1: registers need not be
        # normalized (init_debug_state is not), so the budget scales
        # with the invariant's own magnitude and becomes absolute for
        # unit-scale invariants (norm/trace of normalized states)
        drift = abs(got - ref) / max(1.0, abs(ref))
        if not (drift <= tol):           # NaN-safe: NaN fails the <=
            _counter("durable_sentinel_trips", registry).inc()
            raise IntegrityError(
                f"Integrity sentinel tripped at step {step}: {name} = "
                f"{got!r}, baseline {ref!r}, drift beyond the "
                f"QUEST_INTEGRITY_TOL budget {tol} — the state is "
                f"corrupt (NaN poisoning or a bad plane); REFUSING to "
                f"stamp a checkpoint from it (docs/RESILIENCE.md "
                f"§durable)")


# ---------------------------------------------------------------------------
# cursor + resume chain
# ---------------------------------------------------------------------------


def _validate_cursor(cursor: dict, want: dict, path: str) -> None:
    """Every field of the re-derived plan must match the checkpointed
    cursor — resuming across a drifted plan would run the wrong program
    suffix over the cut amplitudes. Raises DurableError naming the
    first mismatching field."""
    for field, expect in want.items():
        got = cursor.get(field)
        if got != expect:
            raise DurableError(
                f"Invalid durable resume: checkpoint {path!r} was cut "
                f"under {field}={got!r}, but this process would execute "
                f"{field}={expect!r} — a keyed knob flip or circuit "
                f"change between save and resume; finish the run under "
                f"the original configuration (or clear the checkpoint "
                f"directory to restart from op 0)")


def _latest_valid(directory: str, kind: str, registry=None):
    """Newest checkpoint under `directory` that loads AND digests
    cleanly, scanning newest -> oldest: corrupt or unreadable entries
    are skipped LOUDLY (stderr + counter) in favor of older ones —
    never silently consumed. Returns (meta, arrays, cursor, path) or
    None when no valid checkpoint exists (the run restarts from op
    0). A GANG-format step (written by a multi-host run) is a typed
    mesh mismatch, not corruption: restarting from op 0 over a valid
    multi-host chain would silently discard it."""
    for step, path in reversed(ckpt.step_dirs(directory)):
        if ckpt.is_gang_step(path):
            raise DurableError(
                f"Invalid durable resume: checkpoint {path!r} was "
                f"written by a multi-host gang run; resume it on the "
                f"same mesh, or pass elastic=True to re-enter it on "
                f"this one (docs/RESILIENCE.md §elastic)")
        try:
            meta, arrays = ckpt.load_arrays(path, require=("planes",))
            cursor = meta.get("extra")
            if not isinstance(cursor, dict) or cursor.get("kind") != kind:
                raise ckpt.CheckpointError(
                    f"Invalid checkpoint: {path!r} carries no "
                    f"{kind!r} durable cursor")
            # belt to the meta self-digest's suspenders: the cursor's
            # cut index must agree with the committed directory name (a
            # save-side bug writing the wrong step would pass digests)
            cut = cursor.get("step", cursor.get("shots_done"))
            if int(cut) != step:
                raise ckpt.CheckpointError(
                    f"Invalid checkpoint: {path!r} carries cursor cut "
                    f"{cut!r}, directory name says {step}")
        except (ckpt.CheckpointError, OSError, TypeError, ValueError,
                faults.InjectedFault) as e:
            # TypeError/ValueError: a parseable-but-malformed cursor
            # (e.g. no 'step' field) is corruption, not a crash — the
            # scan's contract is skip-loudly-to-older
            # InjectedFault: the checkpoint.load site's default error —
            # its documented contract is that the resume chain SKIPS to
            # an older checkpoint, so the injected failure must prove
            # the fallback, not take the run down
            _counter("durable_corrupt_checkpoints_skipped",
                     registry).inc()
            print(f"[durable] SKIPPING corrupt checkpoint {path!r} "
                  f"({e}); falling back to the previous one",
                  file=sys.stderr, flush=True)
            continue
        return meta, arrays, cursor, path
    return None


def _latest_valid_gang(directory: str, kind: str, registry=None):
    """Gang counterpart of _latest_valid: newest COMMITTED gang
    checkpoint whose every shard digests cleanly and whose per-host
    cursors agree. Validity is a pure function of the shared directory
    (load_step_gang verifies ALL shards on every host), so every host
    independently lands on the SAME checkpoint — a mid-save kill left
    its step uncommitted, and corruption anywhere skips the whole gang
    to the same older cut. Returns (cursor, planes, path) or None."""
    for step, path in reversed(ckpt.step_dirs(directory)):
        if os.path.exists(os.path.join(path, "qureg_meta.json")):
            raise DurableError(
                f"Invalid durable resume: checkpoint {path!r} was "
                f"written by a single-process run, but this is a "
                f"multi-host gang resume; resume it on the writing "
                f"mesh, or pass elastic=True to re-enter it on this "
                f"one (docs/RESILIENCE.md §elastic)")
        try:
            metas, planes = ckpt.load_step_gang(path, kind_extra=kind)
            cursor = metas[0].get("extra")
            cut = cursor.get("step")
            if int(cut) != step:
                raise ckpt.CheckpointError(
                    f"Invalid checkpoint: {path!r} carries cursor cut "
                    f"{cut!r}, directory name says {step}")
        except (ckpt.CheckpointError, OSError, TypeError, ValueError,
                faults.InjectedFault) as e:
            # TypeError/ValueError: a parseable-but-malformed cursor
            # (e.g. no 'step' field) is corruption, not a crash — the
            # scan's contract is skip-loudly-to-older
            _counter("durable_corrupt_checkpoints_skipped",
                     registry).inc()
            print(f"[durable] SKIPPING corrupt gang checkpoint "
                  f"{path!r} ({e}); falling back to the previous one",
                  file=sys.stderr, flush=True)
            continue
        return cursor, planes, path
    return None


def _iter_valid_elastic(directory: str, registry=None):
    """Format-agnostic scan for ELASTIC resume (docs/RESILIENCE.md
    §elastic): yields every step checkpoint — plain single-process
    (canonical or legacy physical layout) or multi-host gang — that
    loads and digests cleanly, newest first, in CANONICAL LOGICAL ORDER
    via checkpoint.load_step_elastic. Corrupt/unreadable entries skip
    loudly to older ones, exactly like the strict scanners; the caller
    advances past entries the target mesh cannot re-enter. Yields
    (cursor, canonical_planes, path)."""
    for step, path in reversed(ckpt.step_dirs(directory)):
        try:
            cursor, planes = ckpt.load_step_elastic(path)
            cut = cursor.get("step")
            if int(cut) != step:
                raise ckpt.CheckpointError(
                    f"Invalid checkpoint: {path!r} carries cursor cut "
                    f"{cut!r}, directory name says {step}")
        except (ckpt.CheckpointError, OSError, TypeError, ValueError,
                faults.InjectedFault) as e:
            # TypeError/ValueError: a parseable-but-malformed cursor
            # (e.g. no 'step' field) is corruption, not a crash — the
            # scan's contract is skip-loudly-to-older
            _counter("durable_corrupt_checkpoints_skipped",
                     registry).inc()
            print(f"[durable] SKIPPING corrupt checkpoint {path!r} "
                  f"({e}); falling back to the previous one",
                  file=sys.stderr, flush=True)
            continue
        yield cursor, planes, path


def _enter_elastic(want, elastic_want, cursor_extra, info, state,
                   directory: str, registry=None):
    """Elastic re-entry (docs/RESILIENCE.md §elastic): walk the chain
    newest->oldest and re-enter the first checkpoint THIS plan can
    continue. Per checkpoint:

      * a mismatched sched_sha / state_efp / dtype / density / ops_total
        (or cursor_extra descriptor) raises typed DurableError — elastic
        never relaxes WHAT is computed, only where;
      * a pre-elastic cursor (no sched_sha) falls back to the STRICT
        field validation: on the writing mesh it resumes tolerantly, on
        a changed mesh it rejects typed (old checkpoints never resume
        wrong);
      * a cut this mesh's plan has no matching portable boundary for
        (ops_done is None, or the target compositions straddle that
        count) skips LOUDLY to an older checkpoint — op 0 is always
        portable, so the walk terminates correctly.

    Returns (start_step, layouted_amps, baseline) or None (no usable
    checkpoint: start from op 0)."""
    from quest_tpu.parallel import relabel as R

    for cursor, canon, path in _iter_valid_elastic(directory, registry):
        if "sched_sha" not in cursor:
            _validate_cursor(cursor, want, path)
            step = int(cursor["step"])
            perm = _cut_perm(info, step)
            _validate_cursor(cursor, {"perm": perm}, path)
            b = step
        else:
            _validate_cursor(cursor, elastic_want, path)
            if cursor_extra:
                _validate_cursor(cursor, cursor_extra, path)
            m = cursor.get("ops_done")
            b = (info["ops_done_at"].index(m)
                 if m is not None and m in info["ops_done_at"] else None)
            if b is None:
                print(f"[durable] checkpoint {path!r} cut at canonical "
                      f"op {m!r} has no portable boundary in this "
                      f"mesh's plan; falling back to an older "
                      f"checkpoint (docs/RESILIENCE.md §elastic)",
                      file=sys.stderr, flush=True)
                continue
            perm = _cut_perm(info, b)
        if canon.shape != state.amps.shape:
            raise DurableError(
                f"Invalid durable resume: checkpoint {path!r} holds "
                f"planes of shape {tuple(canon.shape)}, register "
                f"expects {tuple(state.amps.shape)}")
        planes = np.asarray(canon).astype(state.real_dtype)
        if perm:
            planes = R.physicalize_planes(planes, perm)
        _counter("durable_resumes", registry).inc()
        if (cursor.get("devices") != info["devices"]
                or cursor.get("engine") != info["engine"]):
            _counter("durable_elastic_resumes", registry).inc()
        return b, _to_layout(planes, info), cursor.get("baseline")
    return None


def _clear_chain(directory: str) -> None:
    """A COMPLETED run consumes its resume chain: the checkpoints exist
    to finish this run, and leaving them would make a later run over
    the same directory resume mid-circuit with a different initial
    state."""
    import shutil
    for _, path in ckpt.step_dirs(directory):
        shutil.rmtree(path, ignore_errors=True)
    ckpt.sweep_stale(directory)


# ---------------------------------------------------------------------------
# the durable executor: state engines
# ---------------------------------------------------------------------------


def run_durable(circuit, state: Qureg, directory: str, *,
                every: int = None, engine: str = None, mesh=None,
                interpret: bool = False, keep: int = None,
                elastic: Optional[bool] = None,
                cursor_extra: Optional[dict] = None,
                registry: Optional[_metrics.Registry] = None) -> Qureg:
    """Apply `circuit` to `state` durably: execute the engine's own
    launch plan step by step, checkpoint planes + cursor every `every`
    steps (default QUEST_DURABLE_EVERY) under `directory`, and — when a
    valid checkpoint already exists there — RESUME from it instead of
    op 0. The final register is bit-identical to an uninterrupted run
    whatever mix of preemptions, mid-save crashes and on-disk
    corruption happened in between, because interrupted and
    uninterrupted runs execute the identical per-step program sequence
    and a corrupt checkpoint is never consumed (tests/test_durable.py;
    docs/RESILIENCE.md §durable).

    engine: None auto-resolves like apply_fused (Pallas kernels on the
    kernel tier at f32, banded XLA otherwise); 'fused' / 'banded' pin
    it; mesh= selects the sharded banded engine (its relabel
    permutation rides the cursor and is re-verified at resume). On a
    MULTI-HOST mesh (jax.process_count() > 1) checkpointing is
    GANG-CONSISTENT: every cursor step writes one shared checkpoint
    through checkpoint.save_step_gang's two-phase commit — each host
    stamps its shard, the last stamp commits atomically, a host killed
    mid-save leaves the step uncommitted on EVERY host — and resume
    validity is a pure function of the shared directory, so all hosts
    independently resume the same cut, bit-identical to an
    uninterrupted run (tests/test_gang.py; docs/RESILIENCE.md
    §gang-consistent durable). Noise
    channels run through the density engines as usual; for trajectory
    unraveling use run_durable_trajectories. Integrity sentinels run at
    checkpoint cadence (QUEST_INTEGRITY / QUEST_INTEGRITY_TOL); a
    completed run removes its own checkpoint chain. `registry` redirects
    the durable_* metrics (default: the process-wide
    serve.metrics.REGISTRY) — the serve fleet's replicas pass their own
    registry so a fleet soak's durable tallies ride the same snapshot
    as its fleet_* metrics. `cursor_extra` adds workload-descriptor
    fields (JSON-serializable) to every cursor, VALIDATED at resume
    like the plan fields — quest_tpu.evolution's deep quenches stamp
    their Trotter steps/order/dt through it (docs/EVOLUTION.md).

    `elastic` (default: the QUEST_DURABLE_ELASTIC knob, off) makes the
    resume MESH-INDEPENDENT (docs/RESILIENCE.md §elastic): a checkpoint
    chain written by D devices across H hosts — including a gang chain
    — re-enters THIS call's mesh (any D'/H', including single-device
    and single->sharded) by reassembling the planes in canonical
    logical order, re-verifying every source digest, matching the
    cursor's canonical op count against this plan's portable step
    boundaries, and re-deriving the comm plan / relabel permutation for
    the new mesh. What still rejects typed: a different circuit or
    scheduled stream (sched_sha), a different initial state (the exact
    bit-sum state_efp), a different dtype, and cursor_extra mismatches
    — elastic relaxes only WHERE the run executes, never WHAT it
    computes. A checkpoint whose cut is not portable to this mesh
    skips LOUDLY to an older one (op 0 is always portable). Without
    elastic, a mesh mismatch rejects typed exactly as before."""
    from quest_tpu.env import knob_value

    if circuit.num_qubits != state.num_qubits:
        raise ValueError("circuit/register size mismatch")
    circuit._reject_measure("run_durable")
    n = state.num_state_qubits
    density = state.is_density
    every = int(every) if every is not None else knob_value(
        "QUEST_DURABLE_EVERY")
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    is_f32 = state.real_dtype == np.dtype(np.float32)
    engine = _resolve_state_engine(engine, n, is_f32, mesh)
    steps, info = _build_steps(circuit, n, density, engine, interpret,
                               mesh)
    integrity = knob_value("QUEST_INTEGRITY")
    tol = knob_value("QUEST_INTEGRITY_TOL")
    if elastic is None:
        elastic = bool(knob_value("QUEST_DURABLE_ELASTIC"))
    # multi-host gang mode: one gang-consistent checkpoint per cursor
    # step (two-phase commit across the mesh's processes — all hosts
    # stamp or none do, checkpoint.save_step_gang), cursor fields
    # computed so they are bit-equal on every host
    gang = mesh is not None and jax.process_count() > 1

    want = {
        "engine": engine,
        # interpret-mode kernels round differently from compiled ones,
        # and a different mesh width changes the shard layout: both
        # must match the save-side plan exactly, like every other field
        "interpret": bool(interpret),
        "devices": info["devices"],
        "num_steps": info["num_steps"],
        "mode_key": info["mode_key"],
        "circuit_ops": info["circuit_ops"],
        "plan_sha": _ops_sha(circuit.ops),
        "state_fp": (_state_fingerprint_gang(state) if gang
                     else _state_fingerprint(state)),
    }
    # mesh-independent cursor fields: every state cursor carries them
    # (whether or not THIS run is elastic), so any chain can later be
    # picked up by an elastic resume on different hardware
    # (docs/RESILIENCE.md §elastic)
    elastic_want = {
        "sched_sha": info["sched_sha"],
        "ops_total": info["ops_total"],
        "state_efp": _state_fingerprint_elastic(state),
        "dtype": str(state.real_dtype),
        "density": density,
    }
    if cursor_extra:
        # workload-level descriptor fields (e.g. the Trotter
        # steps/order/dt of quest_tpu.evolution's deep quenches): they
        # ride EVERY cursor and are VALIDATED at resume exactly like
        # the plan fields — a rerun under a different workload
        # descriptor fails typed instead of splicing prefixes. Values
        # must be JSON-serializable (the checkpoint meta self-digest
        # canonicalizes them).
        reserved = (set(want) | set(elastic_want)
                    | {"kind", "step", "perm", "baseline", "layout",
                       "ops_done"})
        overlap = set(cursor_extra) & reserved
        if overlap:
            raise ValueError(
                f"cursor_extra may not shadow reserved cursor fields "
                f"{sorted(overlap)}")
        want.update(cursor_extra)
    start, baseline = 0, None
    if elastic:
        resume = _enter_elastic(want, elastic_want, cursor_extra,
                                info, state, directory, registry)
        if resume is not None:
            start, amps, baseline = resume
        else:
            amps = _to_layout(state.amps, info)
    else:
        if gang:
            found = _latest_valid_gang(directory, "state", registry)
        else:
            found = _latest_valid(directory, "state", registry)
        if found is not None:
            if gang:
                cursor, planes, path = found
            else:
                meta, arrays, cursor, path = found
                planes = arrays["planes"]
            _validate_cursor(cursor, want, path)
            step = int(cursor["step"])
            perm = _cut_perm(info, step)
            _validate_cursor(cursor, {"perm": perm}, path)
            if planes.shape != state.amps.shape:
                raise DurableError(
                    f"Invalid durable resume: checkpoint {path!r} holds "
                    f"planes of shape {tuple(planes.shape)}, register "
                    f"expects {tuple(state.amps.shape)}")
            if cursor.get("layout") == "canonical" and perm:
                # canonical-order checkpoint (the save-side normalizes,
                # docs/RESILIENCE.md §elastic): re-enter the validated
                # cut's physical layout — an exact index permutation,
                # so the strict round trip stays bit-identical
                from quest_tpu.parallel import relabel as R
                planes = R.physicalize_planes(np.asarray(planes), perm)
            amps = _to_layout(planes.astype(state.real_dtype), info)
            start = step
            baseline = cursor.get("baseline")
            _counter("durable_resumes", registry).inc()
        else:
            amps = _to_layout(state.amps, info)
    if baseline is None and integrity:
        baseline = _sentinel_values(amps, info)

    for i in range(start, len(steps)):
        if faults.ACTIVE:
            faults.check("durable.step", step=i, engine=engine)
            faults.check("durable.preempt", step=i, engine=engine)
        amps = steps[i](amps)
        _counter("durable_steps_run", registry).inc()
        done = i + 1
        if done % every == 0 and done < len(steps):
            # drain the async step queue BEFORE the checkpoint timer:
            # the first sync point would otherwise absorb the pending
            # steps' compute into the measured checkpoint cost
            if gang:
                # sync_array's tiny host slice is not addressable on
                # every host of a multi-controller mesh
                jax.block_until_ready(amps)
            else:
                from quest_tpu.env import sync_array
                sync_array(amps)
            t0 = _time.perf_counter()
            if integrity:
                _check_integrity(_sentinel_values(amps, info), baseline,
                                 tol, done, registry)
            perm_cut = _cut_perm(info, done)
            cursor = dict(want, **elastic_want, kind="state", step=done,
                          perm=perm_cut, baseline=baseline,
                          ops_done=info["ops_done_at"][done],
                          layout="physical" if gang else "canonical")
            stamped = True
            if gang:
                # gang shards stay in the PHYSICAL layout (no host
                # holds its peers' canonical columns without a
                # collective); the perm in the digested cursor makes
                # the checkpoint's meaning writer-independent — the
                # elastic loader normalizes at reassembly
                # (checkpoint.load_step_elastic)
                committed = ckpt.save_step_gang(
                    directory, done,
                    qureg=state.replace_amps(_from_layout(amps, info)),
                    extra=cursor, keep=keep)
                # the commit may land on any host; count a saved
                # checkpoint only when the committed dir is actually
                # observable — a gang save a killed peer never stamped
                # must not advance the metric (a slower peer
                # committing later is counted by THAT host)
                stamped = (committed is not None
                           or os.path.isdir(ckpt.step_path(directory,
                                                           done)))
            else:
                # normalize to CANONICAL LOGICAL ORDER before digesting
                # (docs/RESILIENCE.md §elastic): the shard file's
                # meaning no longer depends on the writer's relabel
                # history — an exact index permutation, undone at
                # strict resume bit-identically
                planes_np = np.asarray(
                    jax.device_get(_from_layout(amps, info)))
                if perm_cut:
                    from quest_tpu.parallel import relabel as R
                    planes_np = R.canonicalize_planes(planes_np,
                                                      perm_cut)
                ckpt.save_step(directory, done,
                               qureg=Qureg(amps=planes_np,
                                           num_qubits=state.num_qubits,
                                           is_density=state.is_density),
                               extra=cursor, keep=keep)
            if stamped:
                _counter("durable_checkpoints_saved", registry).inc()
                _registry_of(registry).gauge(
                    "durable_last_checkpoint_step").set(done)
            # per-cut cost (sentinel + host gather + atomic write):
            # bench.py's durable scenario derives its overhead fraction
            # from this histogram — one instrumented run instead of a
            # noisy wall-clock A/B difference
            _registry_of(registry).histogram("durable_checkpoint_s").observe(
                _time.perf_counter() - t0)
    if integrity:
        # the run's exit gate: a durable run must never RETURN a
        # corrupt state silently either — same sentinel, same budget
        _check_integrity(_sentinel_values(amps, info), baseline, tol,
                         "final", registry)
    out = state.replace_amps(_from_layout(amps, info))
    _clear_chain(directory)
    return out


# ---------------------------------------------------------------------------
# the durable executor: trajectory engine
# ---------------------------------------------------------------------------


def _key_fingerprint(key) -> str:
    try:
        data = jax.random.key_data(key)
    except Exception:
        data = key
    return hashlib.sha256(
        np.ascontiguousarray(jax.device_get(data)).tobytes()
    ).hexdigest()[:32]


def run_durable_trajectories(circuit, key, shots: int, directory: str, *,
                             every: int = None, chunk: int = None,
                             engine: str = None, interpret: bool = False,
                             keep: int = None,
                             registry: Optional[_metrics.Registry] = None):
    """Durable counterpart of trajectories.run_batched: run `shots`
    stochastic trajectories of a noisy Circuit in the SAME bucket-sized
    chunks run_batched would dispatch (trajectories._bucket_for), and
    checkpoint the accumulated (shots_done, 2, 2^n) planes + (shots_done,
    C) draws plus a cursor every `every` chunks. The cursor carries the
    root key's fingerprint, so a resumed run provably continues the
    exact `split(key, shots)` chain — completed shots load from the
    checkpoint, remaining shots re-dispatch from their own keys, and
    the result is bit-identical to an uninterrupted run (and to
    run_batched at the same chunking). Per-shot norm sentinels run at
    checkpoint cadence (every trajectory is a normalized statevector by
    construction). Returns (planes, draws) exactly like run_batched;
    `observable=` reductions are deliberately unsupported here — the
    planes ARE the resume payload.

    COST NOTE: each checkpoint stores the FULL accumulated payload
    (delta-chained checkpoints would break keep-last-K retention — the
    corrupt-skip fallback needs every surviving checkpoint to be
    self-contained), so total checkpoint bytes grow quadratically in
    shot count at fixed cadence. Size `every` to the failure rate, not
    the chunk count; shot counts whose planes don't comfortably fit in
    host memory should reduce with run_batched(observable=) instead of
    running durably."""
    from quest_tpu import trajectories as T
    from quest_tpu.circuit import _engine_mode_key
    from quest_tpu.env import knob_value

    n = circuit.num_qubits
    shots = int(shots)
    if shots < 1:
        raise ValueError(f"shots must be >= 1, got {shots}")
    every = int(every) if every is not None else knob_value(
        "QUEST_DURABLE_EVERY")
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    integrity = knob_value("QUEST_INTEGRITY")
    tol = knob_value("QUEST_INTEGRITY_TOL")
    engine = T._resolve_engine(engine, n, interpret)
    bucket = T._bucket_for(shots, chunk)
    fn = T._compiled_traj(circuit, n, bucket, engine, interpret)
    keys = jax.random.split(key, shots)
    want = {
        "engine": engine,
        "interpret": bool(interpret),
        "bucket": bucket,
        "shots": shots,
        "mode_key": repr(_engine_mode_key()),
        "circuit_ops": len(circuit.ops),
        "plan_sha": _ops_sha(circuit.ops),
        "key_fp": _key_fingerprint(key),
    }

    planes_acc: list = []
    draws_acc: list = []
    shots_done = 0
    found = _latest_valid(directory, "traj", registry)
    if found is not None:
        meta, arrays, cursor, path = found
        _validate_cursor(cursor, want, path)
        shots_done = int(cursor["shots_done"])
        planes_acc.append(np.asarray(arrays["planes"]))
        draws_acc.append(np.asarray(arrays["draws"]))
        _counter("durable_resumes", registry).inc()

    chunks_done = 0
    for lo in range(shots_done, shots, bucket):
        if faults.ACTIVE:
            faults.check("durable.step", shot=lo, engine=engine)
            faults.check("durable.preempt", shot=lo, engine=engine)
        # the SAME chunk dispatch (slice/pad/unpad) run_batched uses —
        # the bit-identity pin depends on the loops never diverging
        planes, draws = T._dispatch_chunk(fn, keys, lo, bucket)
        planes_acc.append(np.asarray(planes))
        draws_acc.append(np.asarray(draws))
        _counter("durable_steps_run", registry).inc()
        shots_done = min(lo + bucket, shots)
        chunks_done += 1
        if chunks_done % every == 0 and shots_done < shots:
            t0 = _time.perf_counter()
            all_planes = np.concatenate(planes_acc, axis=0)
            all_draws = np.concatenate(draws_acc, axis=0)
            planes_acc, draws_acc = [all_planes], [all_draws]
            if integrity:
                norms = np.sum(all_planes.astype(np.float32) ** 2,
                               axis=(1, 2))
                worst = int(np.argmax(np.abs(norms - 1.0)))
                _check_integrity(
                    {"norm": float(norms[worst])}, {"norm": 1.0}, tol,
                    f"shot {worst} (of {shots_done} done)", registry)
            cursor = dict(want, kind="traj", shots_done=shots_done)
            ckpt.save_step(directory, shots_done,
                           arrays={"planes": all_planes,
                                   "draws": all_draws},
                           extra=cursor, keep=keep)
            _counter("durable_checkpoints_saved", registry).inc()
            _registry_of(registry).gauge("durable_last_checkpoint_step").set(
                shots_done)
            _registry_of(registry).histogram("durable_checkpoint_s").observe(
                _time.perf_counter() - t0)
    planes = (planes_acc[0] if len(planes_acc) == 1
              else np.concatenate(planes_acc, axis=0))
    draws = (draws_acc[0] if len(draws_acc) == 1
             else np.concatenate(draws_acc, axis=0))
    if integrity:
        # exit gate: every trajectory is a normalized statevector by
        # construction — a NaN'd or drifted shot must fail loudly
        norms = np.sum(planes.astype(np.float32) ** 2, axis=(1, 2))
        worst = int(np.argmax(np.abs(norms - 1.0)))
        _check_integrity({"norm": float(norms[worst])}, {"norm": 1.0},
                         tol, f"final (shot {worst})", registry)
    _clear_chain(directory)
    return jnp.asarray(planes), jnp.asarray(draws)
