"""quest_tpu.resilience — fault injection, supervision, degradation.

The robustness layer under the serving runtime (docs/RESILIENCE.md):

  * `faults` — deterministic fault injection at named hot-path sites
    (`FaultPlan`, the `QUEST_FAULT_PLAN` knob); zero-cost when empty.
  * `supervisor` — bounded-restart backoff policy for the serve worker.
  * `breaker` — per-program circuit breaker driving the fused -> banded
    -> host degradation ladder.

Everything here is standard-library-only at import time: these modules
sit UNDER the serving engine and inside env.py's knob parser, so they
must never drag jax in.
"""

from quest_tpu.resilience import faults  # noqa: F401
from quest_tpu.resilience.breaker import Breaker  # noqa: F401
from quest_tpu.resilience.faults import FaultPlan, InjectedFault  # noqa: F401
from quest_tpu.resilience.supervisor import Supervisor  # noqa: F401

__all__ = ["faults", "FaultPlan", "InjectedFault", "Breaker", "Supervisor"]
