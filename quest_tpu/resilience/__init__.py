"""quest_tpu.resilience — fault injection, supervision, degradation,
durable execution.

The robustness layer under the serving runtime (docs/RESILIENCE.md):

  * `faults` — deterministic fault injection at named hot-path sites
    (`FaultPlan`, the `QUEST_FAULT_PLAN` knob); zero-cost when empty.
  * `supervisor` — bounded-restart backoff policy for the serve worker.
  * `breaker` — per-program circuit breaker driving the fused -> banded
    -> host degradation ladder.
  * `durable` — mid-circuit checkpointing + preemption-tolerant resume
    + corruption sentinels (`run_durable`, `run_durable_trajectories`;
    docs/RESILIENCE.md §durable).

faults/supervisor/breaker are standard-library-only at import time:
they sit UNDER the serving engine and inside env.py's knob parser, so
they must never drag jax in. `durable` DOES import jax (it drives the
compiled engines), so it loads lazily through this namespace — the
package import stays stdlib-only.
"""

from quest_tpu.resilience import faults  # noqa: F401
from quest_tpu.resilience.breaker import Breaker  # noqa: F401
from quest_tpu.resilience.faults import FaultPlan, InjectedFault  # noqa: F401
from quest_tpu.resilience.supervisor import Supervisor  # noqa: F401

_LAZY = {
    "durable": ("quest_tpu.resilience.durable", None),
    "run_durable": ("quest_tpu.resilience.durable", "run_durable"),
    "run_durable_trajectories": ("quest_tpu.resilience.durable",
                                 "run_durable_trajectories"),
    "DurableError": ("quest_tpu.resilience.durable", "DurableError"),
    "IntegrityError": ("quest_tpu.resilience.durable", "IntegrityError"),
}

__all__ = ["faults", "FaultPlan", "InjectedFault", "Breaker",
           "Supervisor"] + sorted(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module 'quest_tpu.resilience' has no "
                             f"attribute {name!r}") from None
    import importlib
    mod = importlib.import_module(mod_name)
    value = mod if attr is None else getattr(mod, attr)
    globals()[name] = value
    return value
