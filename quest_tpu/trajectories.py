"""Quantum-trajectory noise simulation: stochastic Kraus unraveling.

The reference simulates noise ONLY via density matrices — 2^{2N}
amplitudes (`QuEST.c` mixDamping/mixKrausMap on the doubled register),
which caps noisy registers at half the qubit count of pure states. The
trajectory method unravels a channel into a stochastic choice of Kraus
branch per shot: each trajectory is a STATEVECTOR (2^N), and averaging
|psi><psi| over shots converges to the channel's density matrix. On TPU
the method is a natural fit: a trajectory is a pure traced function of a
`jax.random` key, so `jax.vmap` runs a whole batch of shots as one
compiled program, and every gate inside rides the same engines as
noiseless simulation.

    key = jax.random.key(0)
    def shot(k):
        amps = state.basis_planes(0, n=n, rdt=jnp.float32)
        amps = V.h(amps, n, 0)
        amps, k, _ = T.damping(amps, k, n, 0, 0.3)
        amps, k, _ = T.depolarising(amps, k, n, 1, 0.1)
        return amps
    batch = jax.vmap(shot)(jax.random.split(key, 4096))  # (shots, 2, 2^n)

Averages of observables over the batch estimate the open-system result
to O(1/sqrt(shots)); `tests/test_trajectories.py` pins the estimator
against the exact density-matrix engine.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import cplx
from quest_tpu.ops import apply as A
from quest_tpu.ops import matrices as M


def _targets_tuple(targets):
    return (targets,) if np.isscalar(targets) else tuple(targets)


def kraus(amps, key, n, targets, ops: Sequence) -> Tuple:
    """One stochastic application of the Kraus map {K_k} to `targets`:
    branch k is drawn with Born probability p_k = ||K_k psi||^2 and the
    state renormalizes to K_k psi / sqrt(p_k). Returns
    (new_amps, next_key, branch_index).

    All branches are evaluated (their norms are needed for the
    probabilities anyway) and the draw selects via a one-hot weighted
    sum — branch-free, so the whole thing jits and vmaps cleanly."""
    targets = _targets_tuple(targets)
    ops = [np.asarray(K, dtype=np.complex128) for K in ops]
    # same CPTP check as the density engine's mix_kraus_map: a
    # mis-normalized set would otherwise converge silently to a
    # DIFFERENT channel (categorical renormalizes the probabilities)
    from quest_tpu import validation as val
    val.validate_kraus_ops(ops, len(targets))
    key, sub = jax.random.split(key)
    ws = [A.apply_matrix(amps, n, cplx.pack(K), targets) for K in ops]
    ps = jnp.stack([jnp.sum(w[0] * w[0] + w[1] * w[1]) for w in ws])
    # zero-probability branches are masked OUT (-inf logit), not
    # epsilon-floored: a flat epsilon could still draw an impossible
    # branch (p exactly 0) with probability ~eps*k
    logits = jnp.where(ps > 0, jnp.log(jnp.maximum(ps, jnp.finfo(ps.dtype).tiny)),
                       -jnp.inf)
    k = jax.random.categorical(sub, logits)
    onehot = jax.nn.one_hot(k, len(ops), dtype=amps.dtype)
    w = ws[0] * onehot[0]
    for i in range(1, len(ops)):
        w = w + ws[i] * onehot[i]
    return w / jnp.sqrt(ps[k]), key, k


def unitary_mixture(amps, key, n, targets, probs, unitaries) -> Tuple:
    """Stochastic application of a UNITARY mixture sum_k p_k U . U+:
    the branch probabilities are state-independent, so the draw happens
    first and only the chosen branch applies (lax.switch) — one gate
    per shot instead of one per branch. This covers every unital Pauli
    channel (dephasing/depolarising/pauli); general Kraus maps need
    `kraus` (state-dependent Born probabilities)."""
    targets = _targets_tuple(targets)
    probs = np.asarray(probs, dtype=np.float64)
    key, sub = jax.random.split(key)
    logits = np.where(probs > 0, np.log(np.maximum(probs, 1e-300)), -np.inf)
    k = jax.random.categorical(sub, jnp.asarray(logits))
    branches = [
        (lambda a, U=np.asarray(U, dtype=np.complex128):
         A.apply_matrix(a, n, cplx.pack(U), targets))
        for U in unitaries]
    return jax.lax.switch(k, branches, amps), key, k


def _validate_channel_prob(p: float, what: str) -> float:
    """Trajectory channels accept the full CPTP range 0 <= p <= 1 —
    wider than the density API's maximal-mixing caps (1/2, 3/4, ...,
    QuEST_validation.c:113-117), which encode a convention, not
    validity. Out-of-range still fails loudly instead of unraveling to
    an all-NaN state."""
    from quest_tpu.validation import QuESTError
    p = float(p)
    if not (0.0 <= p <= 1.0):
        raise QuESTError(
            f"Invalid probability: the {what} probability must be in "
            f"[0, 1] for a trajectory unraveling, got {p}")
    return p


def damping(amps, key, n, target, prob):
    """Amplitude damping as a trajectory branch (ref mixDamping
    semantics, QuEST_cpu.c:48-130 — here at statevector cost)."""
    p = _validate_channel_prob(prob, "damping")
    return kraus(amps, key, n, target, M.damping_kraus(p))


def dephasing(amps, key, n, target, prob):
    """Phase damping (ref mixDephasing) — a unitary mixture, so only
    the drawn branch applies."""
    p = _validate_channel_prob(prob, "dephasing")
    return unitary_mixture(amps, key, n, target, [1.0 - p, p],
                           [M.PAULI_I, M.PAULI_Z])


def depolarising(amps, key, n, target, prob):
    """Depolarising channel (ref mixDepolarising) — unitary mixture."""
    p = _validate_channel_prob(prob, "depolarising")
    return unitary_mixture(amps, key, n, target,
                           [1.0 - p, p / 3.0, p / 3.0, p / 3.0],
                           list(M.PAULIS))


def pauli(amps, key, n, target, px, py, pz):
    """Probabilistic Pauli error (ref mixPauli) — unitary mixture."""
    px = _validate_channel_prob(px, "Pauli-X")
    py = _validate_channel_prob(py, "Pauli-Y")
    pz = _validate_channel_prob(pz, "Pauli-Z")
    _validate_channel_prob(px + py + pz, "total Pauli error")
    return unitary_mixture(amps, key, n, target,
                           [1.0 - px - py - pz, px, py, pz],
                           list(M.PAULIS))


def average_density(batch) -> jax.Array:
    """Dense (2^n, 2^n) estimator: mean over the shot axis of
    |psi><psi|. For validation at small n — real workloads should
    average observables instead (O(shots * 2^n), not O(shots * 4^n))."""
    re, im = batch[:, 0, :], batch[:, 1, :]
    psi = re + 1j * im
    return jnp.einsum("sa,sb->ab", psi, psi.conj()) / psi.shape[0]
