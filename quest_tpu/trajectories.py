"""Quantum-trajectory noise simulation: stochastic Kraus unraveling.

The reference simulates noise ONLY via density matrices — 2^{2N}
amplitudes (`QuEST.c` mixDamping/mixKrausMap on the doubled register),
which caps noisy registers at half the qubit count of pure states. The
trajectory method unravels a channel into a stochastic choice of Kraus
branch per shot: each trajectory is a STATEVECTOR (2^N), and averaging
|psi><psi| over shots converges to the channel's density matrix. On TPU
the method is a natural fit: a trajectory is a pure traced function of a
`jax.random` key, so `jax.vmap` runs a whole batch of shots as one
compiled program, and every gate inside rides the same engines as
noiseless simulation.

    key = jax.random.key(0)
    def shot(k):
        amps = state.basis_planes(0, n=n, rdt=jnp.float32)
        amps = V.h(amps, n, 0)
        amps, k, _ = T.damping(amps, k, n, 0, 0.3)
        amps, k, _ = T.depolarising(amps, k, n, 1, 0.1)
        return amps
    batch = jax.vmap(shot)(jax.random.split(key, 4096))  # (shots, 2, 2^n)

Averages of observables over the batch estimate the open-system result
to O(1/sqrt(shots)); `tests/test_trajectories.py` pins the estimator
against the exact density-matrix engine.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from quest_tpu import cplx
from quest_tpu.ops import apply as A
from quest_tpu.ops import matrices as M


def _targets_tuple(targets):
    return (targets,) if np.isscalar(targets) else tuple(targets)


_VALIDATED_KRAUS: set = set()


def _validate_kraus_once(ops, num_targets: int) -> None:
    """validate_kraus_ops, memoized BY VALUE: the CPTP check is O(m d^3)
    host math, and a per-shot Python loop (or every retrace of a vmapped
    shot) would re-run it for the SAME channel thousands of times. One
    validation per distinct (target count, operator values) channel per
    process; the batched engine validates at plan time through the same
    memo (regression-pinned in tests/test_batched.py)."""
    key = (num_targets, tuple((K.shape, K.tobytes()) for K in ops))
    if key in _VALIDATED_KRAUS:
        return
    from quest_tpu import validation as val
    val.validate_kraus_ops(ops, num_targets)
    _VALIDATED_KRAUS.add(key)


def kraus(amps, key, n, targets, ops: Sequence) -> Tuple:
    """One stochastic application of the Kraus map {K_k} to `targets`:
    branch k is drawn with Born probability p_k = ||K_k psi||^2 and the
    state renormalizes to K_k psi / sqrt(p_k). Returns
    (new_amps, next_key, branch_index).

    All branches are evaluated (their norms are needed for the
    probabilities anyway) and the draw selects via a one-hot weighted
    sum — branch-free, so the whole thing jits and vmaps cleanly."""
    targets = _targets_tuple(targets)
    ops = [np.asarray(K, dtype=np.complex128) for K in ops]
    # same CPTP check as the density engine's mix_kraus_map: a
    # mis-normalized set would otherwise converge silently to a
    # DIFFERENT channel (categorical renormalizes the probabilities).
    # Memoized by value — one validation per distinct channel per
    # process, however many shots call through here
    _validate_kraus_once(ops, len(targets))
    key, sub = jax.random.split(key)
    ws = [A.apply_matrix(amps, n, cplx.pack(K), targets) for K in ops]
    ps = jnp.stack([jnp.sum(w[0] * w[0] + w[1] * w[1]) for w in ws])
    # zero-probability branches are masked OUT (-inf logit), not
    # epsilon-floored: a flat epsilon could still draw an impossible
    # branch (p exactly 0) with probability ~eps*k
    logits = jnp.where(ps > 0, jnp.log(jnp.maximum(ps, jnp.finfo(ps.dtype).tiny)),
                       -jnp.inf)
    k = jax.random.categorical(sub, logits)
    onehot = jax.nn.one_hot(k, len(ops), dtype=amps.dtype)
    w = ws[0] * onehot[0]
    for i in range(1, len(ops)):
        w = w + ws[i] * onehot[i]
    return w / jnp.sqrt(ps[k]), key, k


def unitary_mixture(amps, key, n, targets, probs, unitaries) -> Tuple:
    """Stochastic application of a UNITARY mixture sum_k p_k U . U+:
    the branch probabilities are state-independent, so the draw happens
    first and only the chosen branch applies (lax.switch) — one gate
    per shot instead of one per branch. This covers every unital Pauli
    channel (dephasing/depolarising/pauli); general Kraus maps need
    `kraus` (state-dependent Born probabilities)."""
    targets = _targets_tuple(targets)
    probs = np.asarray(probs, dtype=np.float64)
    key, sub = jax.random.split(key)
    logits = np.where(probs > 0, np.log(np.maximum(probs, 1e-300)), -np.inf)
    k = jax.random.categorical(sub, jnp.asarray(logits))
    branches = [
        (lambda a, U=np.asarray(U, dtype=np.complex128):
         A.apply_matrix(a, n, cplx.pack(U), targets))
        for U in unitaries]
    return jax.lax.switch(k, branches, amps), key, k


def _validate_channel_prob(p: float, what: str) -> float:
    """Trajectory channels accept the full CPTP range 0 <= p <= 1 —
    wider than the density API's maximal-mixing caps (1/2, 3/4, ...,
    QuEST_validation.c:113-117), which encode a convention, not
    validity. Out-of-range still fails loudly instead of unraveling to
    an all-NaN state."""
    from quest_tpu.validation import QuESTError
    p = float(p)
    if not (0.0 <= p <= 1.0):
        raise QuESTError(
            f"Invalid probability: the {what} probability must be in "
            f"[0, 1] for a trajectory unraveling, got {p}")
    return p


def damping(amps, key, n, target, prob):
    """Amplitude damping as a trajectory branch (ref mixDamping
    semantics, QuEST_cpu.c:48-130 — here at statevector cost)."""
    p = _validate_channel_prob(prob, "damping")
    return kraus(amps, key, n, target, M.damping_kraus(p))


def dephasing(amps, key, n, target, prob):
    """Phase damping (ref mixDephasing) — a unitary mixture, so only
    the drawn branch applies."""
    p = _validate_channel_prob(prob, "dephasing")
    return unitary_mixture(amps, key, n, target, [1.0 - p, p],
                           [M.PAULI_I, M.PAULI_Z])


def depolarising(amps, key, n, target, prob):
    """Depolarising channel (ref mixDepolarising) — unitary mixture."""
    p = _validate_channel_prob(prob, "depolarising")
    return unitary_mixture(amps, key, n, target,
                           [1.0 - p, p / 3.0, p / 3.0, p / 3.0],
                           list(M.PAULIS))


def pauli(amps, key, n, target, px, py, pz):
    """Probabilistic Pauli error (ref mixPauli) — unitary mixture."""
    px = _validate_channel_prob(px, "Pauli-X")
    py = _validate_channel_prob(py, "Pauli-Y")
    pz = _validate_channel_prob(pz, "Pauli-Z")
    _validate_channel_prob(px + py + pz, "total Pauli error")
    return unitary_mixture(amps, key, n, target,
                           [1.0 - px - py - pz, px, py, pz],
                           list(M.PAULIS))


def average_density(batch) -> jax.Array:
    """Dense (2^n, 2^n) estimator: mean over the shot axis of
    |psi><psi|. For validation at small n — real workloads should
    average observables instead (O(shots * 2^n), not O(shots * 4^n))."""
    re, im = batch[:, 0, :], batch[:, 1, :]
    psi = re + 1j * im
    return jnp.einsum("sa,sb->ab", psi, psi.conj()) / psi.shape[0]


# ---------------------------------------------------------------------------
# batched execution engine: B trajectories through ONE sweep launch
# ---------------------------------------------------------------------------
#
# jax.vmap over the eager per-gate workers (the module docstring's
# pattern) batches the SHOTS but keeps the per-gate pass structure: a
# B-shot workload pays B x the per-gate HBM traffic and launch count the
# sweep-fusion layer (PR 3) just eliminated for single states. The
# engine below instead rides the whole unitary structure of a NOISY
# Circuit through the batched sweep kernels — a leading batch grid
# dimension streams B states per launch — and turns each stochastic
# channel application into a per-state ONE-HOT SELECT:
#
#   * the channel's Kraus branches are classified at plan time:
#     UNITARY MIXTURES (every K_k proportional to a unitary —
#     dephasing, depolarising, Pauli) have state-independent Born
#     probabilities, so their draws depend only on the per-shot keys
#     and the selected branch fuses ANYWHERE in a sweep;
#   * GENERAL KRAUS channels (damping) need the pre-channel state: the
#     per-branch probabilities p_k = <psi|K_k^+ K_k|psi> come from the
#     targets' reduced density matrix (ONE batched reduction pass —
#     cheaper than the eager path's apply-every-branch-and-norm), the
#     draw one-hot-selects K_k, and the 1/sqrt(p_k) renormalization is
#     folded into the selected operator. The stage is a LAUNCH BARRIER
#     before (its operand reads the state between launches) but fuses
#     with everything after it.
#
# Either way the selected 2x2 rides as a (B, 8) kernel operand row per
# state (pallas_band.BatchSelStage) — the launch count of the whole
# noisy program is the UNBATCHED plan's, independent of B
# (plan_stats below; scripts/check_batch_golden.py holds the golden).
# Off-TPU (or engine="banded") the same plan executes as one vmapped
# banded-XLA program — still one compiled dispatch for the batch, with
# the band-composed pass structure instead of per-gate passes.


@dataclasses.dataclass(frozen=True)
class _XlaChannel:
    """Plan marker for a channel the kernels do not inline (multi-qubit
    Kraus, sub-kernel-tier registers): applied between sweeps as a
    vmapped XLA matrix op; segment_plan passes it through as an ("xla",
    item) part, which is also a sweep barrier."""
    index: int

    def qubits(self):
        return ()


def _mixture_probs(kraus_ops):
    """(p_k,) when every K_k is PROPORTIONAL to a unitary (K^+K = p I —
    the Born probabilities are then state-independent), else None."""
    probs = []
    for K in kraus_ops:
        d = K.shape[0]
        KK = K.conj().T @ K
        p = float(np.real(np.trace(KK)) / d)
        if not np.allclose(KK, p * np.eye(d), atol=1e-10):
            return None
        probs.append(p)
    return np.asarray(probs, dtype=np.float64)


def _traj_channels_and_items(circuit, n: int, use_kernels: bool):
    """Split a noisy Circuit into the batched engine's plan stream:
    fusion-plan items for the unitary stretches, interleaved with
    ChannelItem (kernel-inlined 1q channels) / _XlaChannel markers.
    Returns (items, channels) where channels[i] holds the static
    per-channel data (targets, Kraus stacks, mixture probabilities)."""
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB
    from quest_tpu.validation import QuESTError

    bands = PB.plan_bands(n) if use_kernels else None
    items: list = []
    channels: list = []
    stretch: list = []

    def close():
        nonlocal stretch
        if stretch:
            flat = F.maybe_schedule(
                flatten_ops(tuple(stretch), n, False), n)
            items.extend(F.plan(flat, n, bands=bands))
            stretch = []

    for op in circuit.ops:
        if op.kind == "superop":
            meta = op.meta
            if not (isinstance(meta, tuple) and meta
                    and meta[0] == "kraus"):
                raise QuESTError(
                    "Invalid operation: this channel op carries no raw "
                    "Kraus metadata; build channels through the Circuit "
                    "noise builders (kraus/damping/depolarising/"
                    "dephasing) for trajectory unraveling.")
            kraus_ops = [np.asarray(K, dtype=np.complex128)
                         for K in meta[1]]
            # plan-time validation (build-time validation already ran
            # for Circuit-built channels; the memo makes this free)
            _validate_kraus_once(kraus_ops, len(op.targets))
            probs = _mixture_probs(kraus_ops)
            idx = len(channels)
            inline = use_kernels and len(op.targets) == 1
            channels.append({
                "index": idx,
                "targets": tuple(op.targets),
                "ops": kraus_ops,
                "mixture_probs": probs,
                "inline": inline,
            })
            close()
            if inline:
                items.append(PB.ChannelItem(op.targets[0], idx,
                                            barrier=probs is None))
            else:
                items.append(_XlaChannel(idx))
            continue
        if op.kind in ("measure", "classical"):
            raise QuESTError(
                "Invalid operation: run_batched does not thread "
                "mid-circuit measurement outcomes; use "
                "compiled_measured per shot for dynamic circuits.")
        stretch.append(op)
    close()
    return items, channels


def _reduced_density(flat_b, n: int, targets):
    """(B, 2^k, 2^k) complex reduced density matrix of `targets` for a
    (B, 2, 2^n) batch of planes — ONE pass over the batch, serving the
    Born probabilities of every branch at once (tr(K^+K rho))."""
    psi = flat_b[:, 0, :] + 1j * flat_b[:, 1, :]
    b = psi.shape[0]
    k = len(targets)
    if k == 1:
        # the common case, transpose-free: expose the target bit by
        # reshape alone (a moveaxis over the (2,)*n view materializes a
        # full-state transpose — measured ~100x this path's cost)
        q = targets[0]
        pre, post = 1 << (n - 1 - q), 1 << q
        v = psi.reshape(b, pre, 2, post)
        return jnp.einsum("bpir,bpjr->bij", v, jnp.conj(v))
    v = psi.reshape((b,) + (2,) * n)
    # axis of qubit q in the (b, 2, ..., 2) view; index bit j of the
    # merged target axis must equal targets[j], so the MSB-most moved
    # axis is targets[k-1]
    order = [1 + (n - 1 - q) for q in reversed(targets)]
    v = jnp.moveaxis(v, order, range(1, 1 + k))
    v = v.reshape(b, 1 << k, -1)
    return jnp.einsum("bir,bjr->bij", v, jnp.conj(v))


def _channel_select(ch, subkeys_b, flat_b, n: int):
    """Draw each state's branch for channel `ch` and build the selected
    (renormalized) operators: (draw (B,) i32, op_re (B, d, d) f32,
    op_im (B, d, d) f32). `flat_b` is only read for general Kraus
    channels (state-dependent probabilities)."""
    ops = ch["ops"]
    m = len(ops)
    kre = np.stack([K.real for K in ops]).astype(np.float32)
    kim = np.stack([K.imag for K in ops]).astype(np.float32)
    tiny = jnp.finfo(jnp.float32).tiny
    if ch["mixture_probs"] is not None:
        probs = ch["mixture_probs"]
        # logits constructed EXACTLY like unitary_mixture's (ambient
        # dtype, same masking): categorical's gumbel bits depend on the
        # logits dtype, so any deviation here would make batched draws
        # diverge from the eager path's on identical keys
        logits = jnp.asarray(np.where(probs > 0,
                                      np.log(np.maximum(probs, 1e-300)),
                                      -np.inf))
        draw = jax.vmap(
            lambda kk: jax.random.categorical(kk, logits))(subkeys_b)
        psel = jnp.asarray(probs, dtype=jnp.float32)[draw]
    else:
        mkm = np.stack([(K.conj().T @ K) for K in ops])
        rho = _reduced_density(flat_b, n, ch["targets"])
        ps = jnp.real(jnp.einsum("mij,bji->bm",
                                 jnp.asarray(mkm, rho.dtype), rho))
        logits = jnp.where(ps > 0,
                           jnp.log(jnp.maximum(ps, tiny)), -jnp.inf)
        draw = jax.vmap(jax.random.categorical)(subkeys_b, logits)
        psel = jnp.take_along_axis(ps, draw[:, None], axis=1)[:, 0]
    onehot = jax.nn.one_hot(draw, m, dtype=jnp.float32)
    inv = jax.lax.rsqrt(jnp.maximum(psel, tiny))[:, None, None]
    op_re = jnp.einsum("bm,mij->bij", onehot, jnp.asarray(kre)) * inv
    op_im = jnp.einsum("bm,mij->bij", onehot, jnp.asarray(kim)) * inv
    return draw.astype(jnp.int32), op_re, op_im


def _pack_rows(op_re, op_im):
    """(B, 2, 2) re/im pairs -> the (B, 8) BatchSelStage operand rows
    [g00re, g00im, g01re, g01im, g10re, g10im, g11re, g11im]."""
    return jnp.stack([op_re[:, 0, 0], op_im[:, 0, 0],
                      op_re[:, 0, 1], op_im[:, 0, 1],
                      op_re[:, 1, 0], op_im[:, 1, 0],
                      op_re[:, 1, 1], op_im[:, 1, 1]], axis=1)


def _resolve_engine(engine, n: int, interpret: bool) -> str:
    from quest_tpu.ops import pallas_band as PB
    if engine is not None:
        if engine not in ("fused", "banded", "host"):
            raise ValueError(f"engine must be 'fused', 'banded' or "
                             f"'host', got {engine!r}")
        return engine
    if interpret:
        return "fused" if PB.usable(n) else "banded"
    try:
        on_tpu = jax.default_backend() in ("tpu", "axon")
    except Exception:           # pragma: no cover - no backend
        on_tpu = False
    if on_tpu:
        return "fused" if PB.usable(n) else "banded"
    # off-chip the XLA banded path trades memory passes for 50x the
    # FLOPs (band matmuls the MXU would eat for free): the native host
    # engine is the honest CPU fast path, exactly like the bench ladder
    from quest_tpu import host as H
    return "host" if H.available() else "banded"


def _apply_2x2_native(planes, q, op_re, op_im):
    """Per-state 2x2 on qubit `q` of (B, 2, 2^n) float planes through
    the NATIVE host engine's blocked butterfly, in place — per-call
    re-encode of the tiny one-gate program is microseconds, the
    butterfly itself runs at the native engine's memory rate (measured
    ~6x this host's numpy elementwise rate, which is allocation-bound)."""
    from quest_tpu import host as H
    from quest_tpu.circuit import GateOp

    n = planes.shape[-1].bit_length() - 1
    for s in range(planes.shape[0]):
        k = (op_re[s] + 1j * op_im[s]).astype(np.complex128)
        step = H.compile_circuit_host(
            (GateOp("matrix", (q,), operand=k),), n, False)
        step(planes[s])


_vmapped_categorical = None


def _draw_categorical(subkeys_b, logits_b):
    """One process-wide jitted vmap(categorical) — the host path's only
    per-channel jax work (the per-state logits ride in as data, so every
    channel of a given (B, m) shape shares one compiled draw)."""
    global _vmapped_categorical
    if _vmapped_categorical is None:
        _vmapped_categorical = jax.jit(jax.vmap(jax.random.categorical))
    return _vmapped_categorical(subkeys_b, logits_b)


def _host_channel_select(ch, subkeys_b, planes):
    """The host engine's channel select — numpy throughout except the
    (B, m) categorical draw, which stays jax so identically-keyed shots
    take the SAME branches as the jax engines. For a 1q general-Kraus
    channel the Born probabilities come from a transpose-free numpy
    reduced density (one pass over the chunk); mixtures never read the
    state. Returns (draw (B,), op_re (B, d, d), op_im (B, d, d))."""
    ops = ch["ops"]
    m = len(ops)
    b = planes.shape[0]
    tiny = np.finfo(np.float32).tiny
    if ch["mixture_probs"] is not None:
        probs = ch["mixture_probs"]
        logits = jnp.asarray(np.where(probs > 0,
                                      np.log(np.maximum(probs, 1e-300)),
                                      -np.inf))
        draw = np.asarray(_draw_categorical(
            subkeys_b, jnp.broadcast_to(logits, (b,) + logits.shape)))
        psel = np.asarray(probs, dtype=np.float32)[draw]
    else:
        nq = planes.shape[-1].bit_length() - 1
        q = ch["targets"][0]
        pre, post = 1 << (nq - 1 - q), 1 << q
        # reduced density from strided REAL views via einsum reductions
        # — no complex/full-state temporaries (numpy elementwise with
        # fresh allocations runs allocation-bound on small hosts)
        r = planes[:, 0].reshape(b, pre, 2, post)
        i = planes[:, 1].reshape(b, pre, 2, post)
        r0, r1, i0, i1 = r[:, :, 0], r[:, :, 1], i[:, :, 0], i[:, :, 1]

        def dot(x, y):
            return np.einsum("bpr,bpr->b", x, y)

        rho = np.empty((b, 2, 2), dtype=np.complex64)
        rho[:, 0, 0] = dot(r0, r0) + dot(i0, i0)
        rho[:, 1, 1] = dot(r1, r1) + dot(i1, i1)
        re01 = dot(r0, r1) + dot(i0, i1)
        im01 = dot(i0, r1) - dot(r0, i1)
        rho[:, 0, 1] = re01 + 1j * im01
        rho[:, 1, 0] = re01 - 1j * im01
        mkm = np.stack([(K.conj().T @ K) for K in ops])
        ps = np.real(np.einsum("mij,bji->bm", mkm, rho)).astype(
            np.float32)
        logits = np.where(ps > 0,
                          np.log(np.maximum(ps, tiny)),
                          -np.inf).astype(np.float32)
        draw = np.asarray(_draw_categorical(subkeys_b,
                                            jnp.asarray(logits)))
        psel = np.take_along_axis(ps, draw[:, None], axis=1)[:, 0]
    kre = np.stack([K.real for K in ops]).astype(np.float32)
    kim = np.stack([K.imag for K in ops]).astype(np.float32)
    inv = (1.0 / np.sqrt(np.maximum(psel, tiny)))[:, None, None]
    onehot = np.eye(m, dtype=np.float32)[draw]
    op_re = np.einsum("bm,mij->bij", onehot, kre) * inv
    op_im = np.einsum("bm,mij->bij", onehot, kim) * inv
    return draw.astype(np.int32), op_re, op_im


def _compiled_traj_host(circuit, n: int, bucket: int, key_, channels):
    """The CPU fast path: unitary stretches run through the NATIVE host
    engine's cache-blocked C++ kernels per state (quest_tpu/host.py —
    the off-chip rung of the bench ladder, ~20x the XLA-CPU banded
    path's gate rate), channels as vectorized numpy butterflies of the
    per-state selected branch. Draws reuse the SAME jax key chain and
    _channel_select math as the jax engines, so identically-keyed shots
    take identical branches whatever the engine. Returns a plain Python
    fn(keys (B, ...)) -> (planes (B, 2, 2^n) numpy, draws (B, C));
    raises host.HostEngineUnsupported when the native library or an
    op's kernel is unavailable (the caller falls back loudly)."""
    from quest_tpu import host as H

    num_chan = len(channels)
    # ("hstep", step) | ("chan", idx) | ("mixrun", [idx, ...]) — a
    # mixrun is a maximal run of CONSECUTIVE 1q mixture channels (the
    # per-qubit noise layer of a NISQ model): their draws are
    # state-independent, so each state's selected 2x2s apply as ONE
    # native program — the blocked engine sweeps the state once for
    # the whole layer instead of once per channel
    program = []
    stretch: list = []
    chan_count = 0

    def close():
        nonlocal stretch
        if stretch:
            program.append(
                ("hstep", H.compile_circuit_host(tuple(stretch), n,
                                                 False)))
            stretch = []

    for op in circuit.ops:
        if op.kind == "superop":
            close()
            idx = chan_count
            chan_count += 1
            ch = channels[idx]
            if (ch["mixture_probs"] is not None
                    and len(ch["targets"]) == 1
                    and program and program[-1][0] == "mixrun"):
                program[-1][1].append(idx)
            elif (ch["mixture_probs"] is not None
                    and len(ch["targets"]) == 1):
                program.append(("mixrun", [idx]))
            else:
                program.append(("chan", idx))
        else:
            stretch.append(op)
    close()

    def chain(k):
        subs = []
        for _ in range(num_chan):
            k, s = jax.random.split(k)
            subs.append(s)
        return jnp.stack(subs)

    # ONE jitted prelude per chunk computes everything that does not
    # read the state: the per-state key chain AND every mixture
    # channel's draw + selected operator (state-independent Born
    # probabilities) — per-channel eager dispatches would otherwise
    # dominate dense noise models (a per-qubit-per-layer circuit has
    # ~n*depth channels, each a host<->device round trip)
    mix_idx = [i for i, ch in enumerate(channels)
               if ch["mixture_probs"] is not None]

    def prelude(keys_b):
        subkeys = jax.vmap(chain)(keys_b)
        mix = {i: _channel_select(channels[i], subkeys[:, i], None, n)
               for i in mix_idx}
        return subkeys, mix
    prelude_j = jax.jit(prelude) if num_chan else None

    def fn(keys_b):
        b = keys_b.shape[0]
        if num_chan:
            subkeys, mix = prelude_j(keys_b)
            mix = {i: tuple(np.asarray(x) for x in v)
                   for i, v in mix.items()}
        planes = np.zeros((b, 2, 1 << n), dtype=np.float32)
        planes[:, 0, 0] = 1.0
        draws: dict = {}
        for el in program:
            if el[0] == "hstep":
                for s in range(b):
                    el[1](planes[s])          # native, in place
                continue
            if el[0] == "mixrun":
                from quest_tpu.circuit import GateOp
                sel = {}
                for idx in el[1]:
                    draw, op_re, op_im = mix[idx]
                    draws[idx] = np.asarray(draw).astype(np.int32)
                    sel[idx] = (np.asarray(op_re), np.asarray(op_im))
                for s in range(b):
                    ops_s = tuple(
                        GateOp("matrix", channels[idx]["targets"],
                               operand=(sel[idx][0][s]
                                        + 1j * sel[idx][1][s]
                                        ).astype(np.complex128))
                        for idx in el[1])
                    H.compile_circuit_host(ops_s, n, False)(planes[s])
                continue
            idx = el[1]
            ch = channels[idx]
            if idx in mix:
                draw, op_re, op_im = mix[idx]
                draw = draw.astype(np.int32)
            elif len(ch["targets"]) == 1:
                draw, op_re, op_im = _host_channel_select(
                    ch, subkeys[:, idx], planes)
            else:
                draw, op_re, op_im = _channel_select(
                    ch, subkeys[:, idx], jnp.asarray(planes), n)
                draw = np.asarray(draw)
            draws[idx] = draw
            if len(ch["targets"]) == 1:
                _apply_2x2_native(planes, ch["targets"][0],
                                  np.asarray(op_re), np.asarray(op_im))
            else:
                out = jax.vmap(
                    lambda a, re_, im_: A.apply_matrix(
                        a, n, (re_, im_), ch["targets"]))(
                    jnp.asarray(planes), jnp.asarray(op_re),
                    jnp.asarray(op_im))
                planes = np.asarray(out)
        if num_chan:
            out_draws = np.stack([draws[i] for i in range(num_chan)],
                                 axis=1).astype(np.int32)
        else:
            out_draws = np.zeros((b, 0), dtype=np.int32)
        return planes, out_draws

    circuit._compiled[key_] = fn
    return fn


def _compiled_traj(circuit, n: int, bucket: int, engine: str,
                   interpret: bool):
    """One jitted program fn(keys (B, ...)) -> (planes (B, 2, 2^n),
    draws (B, C) i32) running `bucket` trajectories of a noisy Circuit
    from |0...0>. Cached on the Circuit per (bucket, engine, mode)."""
    from quest_tpu.circuit import _engine_mode_key, _xla_part_applier
    from quest_tpu.ops import pallas_band as PB

    key_ = ("traj-batched", n, bucket, engine, interpret,
            _engine_mode_key())
    fn = circuit._compiled.get(key_)
    if fn is not None:
        return fn

    if engine == "host":
        from quest_tpu import host as H
        _, channels = _traj_channels_and_items(circuit, n, False)
        try:
            return _compiled_traj_host(circuit, n, bucket, key_,
                                       channels)
        except H.HostEngineUnsupported as e:
            import sys
            print(f"[trajectories] host engine unavailable ({e}); "
                  f"falling back to the banded engine", file=sys.stderr)
            engine = "banded"
            key_ = ("traj-batched", n, bucket, engine, interpret,
                    _engine_mode_key())
            fn = circuit._compiled.get(key_)
            if fn is not None:
                return fn

    use_kernels = engine == "fused" and PB.usable(n)
    items, channels = _traj_channels_and_items(circuit, n, use_kernels)
    num_chan = len(channels)

    if use_kernels:
        parts = PB.maybe_sweep(
            PB.segment_plan(items, n, batch=bucket), n)
        seg_cache: dict = {}
        program = []
        for part in parts:
            if part[0] == "segment":
                # planner invariant the operand computation leans on: a
                # barrier (general-Kraus) stage reads the state at its
                # LAUNCH boundary, so it must lead its sweep
                # (segment_plan flushes before it; sweep_plan never
                # merges its segment backward)
                for j, st in enumerate(part[1]):
                    assert not (isinstance(st, PB.BatchSelStage)
                                and st.barrier and j != 0), part[1]
                seg = PB.compile_segment_cached(
                    seg_cache, tuple(part[1]), n, interpret=interpret,
                    batch=bucket)
                program.append(("sweep", seg, part[1], part[2]))
            elif isinstance(part[1], _XlaChannel):
                program.append(("chan_xla", part[1].index))
            else:
                program.append(
                    ("xla", jax.vmap(_xla_part_applier(part, n))))
    else:
        # banded program: stretches of plan items between channels,
        # each one vmapped application over the batch
        program = []
        run: list = []
        for it in items:
            if isinstance(it, (PB.ChannelItem, _XlaChannel)):
                if run:
                    program.append(("stretch", tuple(run)))
                    run = []
                program.append(("chan_xla", it.index))
            else:
                run.append(it)
        if run:
            program.append(("stretch", tuple(run)))

    def apply_chan_xla(flat_b, idx, subkeys_b, draws):
        ch = channels[idx]
        draw, op_re, op_im = _channel_select(ch, subkeys_b, flat_b, n)
        draws[idx] = draw
        out = jax.vmap(
            lambda a, re_, im_: A.apply_matrix(a, n, (re_, im_),
                                               ch["targets"]))(
            flat_b, op_re, op_im)
        return out

    def run_program(keys_b):
        flat_b = jnp.zeros((bucket, 2, 1 << n), dtype=jnp.float32)
        flat_b = flat_b.at[:, 0, 0].set(1.0)

        # per-channel subkeys, chained per state exactly like the eager
        # path (key, sub = split(key) at each channel in program order)
        def chain(k):
            subs = []
            for _ in range(num_chan):
                k, s = jax.random.split(k)
                subs.append(s)
            return jnp.stack(subs)
        subkeys = jax.vmap(chain)(keys_b) if num_chan else None
        draws: dict = {}

        if use_kernels:
            a = flat_b.reshape(bucket, 2, -1, PB.LANES)
            for el in program:
                if el[0] == "sweep":
                    _, seg, stages, arrays = el
                    call_arrays = []
                    for st, arr in zip(stages, arrays):
                        if isinstance(st, PB.BatchSelStage):
                            ch = channels[st.index]
                            draw, op_re, op_im = _channel_select(
                                ch, subkeys[:, st.index],
                                a.reshape(bucket, 2, -1), n)
                            draws[st.index] = draw
                            call_arrays.append(_pack_rows(op_re, op_im))
                        else:
                            call_arrays.append(arr)
                    a = seg(a, call_arrays)
                elif el[0] == "chan_xla":
                    flat = a.reshape(bucket, 2, -1)
                    flat = apply_chan_xla(flat, el[1],
                                          subkeys[:, el[1]], draws)
                    a = flat.reshape(bucket, 2, -1, PB.LANES)
                else:
                    a = el[1](a)
            flat_b = a.reshape(bucket, 2, -1)
        else:
            from quest_tpu.circuit import _apply_banded_items
            for el in program:
                if el[0] == "stretch":
                    flat_b = jax.vmap(
                        lambda s, its=el[1]: _apply_banded_items(
                            s, n, its))(flat_b)
                else:
                    flat_b = apply_chan_xla(flat_b, el[1],
                                            subkeys[:, el[1]], draws)

        if num_chan:
            out_draws = jnp.stack([draws[i] for i in range(num_chan)],
                                  axis=1)
        else:
            out_draws = jnp.zeros((bucket, 0), dtype=jnp.int32)
        return flat_b, out_draws

    fn = jax.jit(run_program)
    circuit._compiled[key_] = fn
    return fn


def _bucket_for(shots: int, chunk: int = None) -> int:
    """The compiled bucket size a `shots`-trajectory run dispatches
    (docs/BATCHING.md): chunk=None caps the implicit whole-run bucket at
    the largest bucket <= shots (257 shots = one 256-chunk + a padded
    remainder, not a 512-state launch doubling peak memory); an explicit
    chunk buckets itself. The ONE home of this rule — run_batched,
    plan_stats and the durable trajectory executor
    (resilience/durable.py) all chunk through it, so an interrupted and
    an uninterrupted run dispatch the identical program sequence."""
    from quest_tpu.env import batch_bucket
    per_call = shots if chunk is None else max(1, min(int(chunk), shots))
    bucket = batch_bucket(per_call)
    if chunk is None and bucket > shots:
        smaller = batch_bucket(max(1, bucket // 2))
        if smaller < bucket:
            bucket = smaller
    return bucket


def _dispatch_chunk(fn, keys, lo: int, bucket: int):
    """One bucket-sized dispatch of shots [lo, lo+bucket): slice the
    key chain, pad the tail chunk by re-running key 0 of the chunk
    (broadcast — sliced off after), launch, unpad. The ONE home of the
    pad rule, shared by run_batched and the durable trajectory
    executor (resilience/durable.py) — their bit-identity pin depends
    on the two dispatch loops staying byte-equivalent."""
    kb = keys[lo:lo + bucket]
    pad = bucket - kb.shape[0]
    if pad:
        kb = jnp.concatenate(
            [kb, jnp.broadcast_to(kb[:1], (pad,) + kb.shape[1:])])
    planes, draws = fn(kb)
    if pad:
        planes, draws = planes[:-pad], draws[:-pad]
    return planes, draws


def program_key(circuit, engine: str = None, interpret: bool = False):
    """(resolved engine name, hashable PROGRAM IDENTITY) of the batched
    trajectory program family `run_batched` would execute for this
    circuit — the serving layer's batch-compatibility rule for
    trajectory requests (quest_tpu.serve, docs/SERVING.md): two shot
    requests may coalesce into one launch iff their identities are
    EQUAL. Mirrors Circuit.program_key: the circuit OBJECT (identity,
    kept alive by the key), op count, register size, the resolved
    engine, the interpret flag and engine_mode_key(). Bucket size is
    not part of the identity (all buckets share the plan; the compiled
    per-bucket programs cache on the circuit)."""
    from quest_tpu.circuit import _engine_mode_key

    n = circuit.num_qubits
    engine = _resolve_engine(engine, n, interpret)
    return engine, ("traj-batched", circuit, len(circuit.ops), n, engine,
                    interpret, _engine_mode_key())


def run_batched(circuit, key, shots: int, *, engine: str = None,
                interpret: bool = False, chunk: int = None,
                observable=None):
    """Run `shots` stochastic trajectories of a NOISY Circuit (channels
    built via the Circuit noise builders: kraus/damping/depolarising/
    dephasing) as batched statevector unravelings from |0...0>.
    Returns (planes, draws): planes (shots, 2, 2^n) f32 — average
    |psi><psi| (average_density) or observables over the shot axis to
    estimate the open-system result — and draws (shots, C) i32, the
    branch index every channel took in every shot (C channels in
    program order).

    THE fast path for noisy sampling: where jax.vmap of the eager
    per-gate workers pays B x the per-gate launch and HBM-pass count,
    this engine plans the circuit ONCE and rides all B states through
    the batched sweep kernels — launches do not scale with B
    (plan_stats; docs/BATCHING.md). Channel draws become per-state
    one-hot selects inside the kernels (pallas_band.BatchSelStage).

    shots are independent, keyed by jax.random.split(key, shots) —
    identical keys reproduce identical trajectories, batched or not.
    Batch sizes BUCKET like compiled_batched (env.batch_bucket,
    QUEST_BATCH_BUCKET): the compiled program serves any shot count in
    its bucket (the pad shots re-run the first key and are sliced off).
    `chunk` bounds live memory: at most bucket_of(chunk) states are
    resident at once, sequential chunks reuse the ONE compiled program.
    engine: 'fused' (batched Pallas kernels; interpret=True for CPU
    testing), 'banded' (vmapped banded XLA), or 'host' (native
    cache-blocked C++ kernels for the unitary stretches + numpy channel
    butterflies — the off-chip default, ~20x the XLA-CPU banded gate
    rate; falls back to 'banded' loudly without the native library);
    None picks by backend. Draws are engine-independent up to Born-prob
    rounding: mixture-channel draws use constant probabilities and are
    exactly reproducible across engines; general-Kraus (state-dependent)
    probabilities are computed by a different f32 route per engine
    (full-state norms / reduced-density trace / numpy einsum, agreeing
    to ~1e-7 relative), so a draw can differ between engines only when
    the key lands within that margin of a branch boundary.

    `observable` keeps LARGE runs statevector-free on the host: a
    callable mapping a (b, 2, 2^n) chunk of final planes to per-shot
    values (leading axis preserved); the return becomes
    (values (shots, ...), draws) and no chunk's states outlive its
    reduction — 256 shots at 24 qubits would otherwise materialize
    32 GiB of output planes."""
    n = circuit.num_qubits
    shots = int(shots)
    if shots < 1:
        raise ValueError(f"shots must be >= 1, got {shots}")
    engine = _resolve_engine(engine, n, interpret)
    bucket = _bucket_for(shots, chunk)
    fn = _compiled_traj(circuit, n, bucket, engine, interpret)

    keys = jax.random.split(key, shots)
    dispatch = fn
    if observable is not None:
        # reduce the padded bucket BEFORE the unpad slice: the
        # constant-bucket-shaped reduction is the memory contract (no
        # full planes leave the device), so the observable wraps fn
        # rather than riding _dispatch_chunk's sliced output
        def dispatch(kb, fn=fn):
            planes, draws = fn(kb)
            return observable(planes), draws
    planes_out, draws_out = [], []
    for lo in range(0, shots, bucket):
        planes, draws = _dispatch_chunk(dispatch, keys, lo, bucket)
        planes_out.append(planes)
        draws_out.append(draws)
    if len(planes_out) == 1:
        return planes_out[0], draws_out[0]
    return (jnp.concatenate(planes_out, axis=0),
            jnp.concatenate(draws_out, axis=0))


def plan_stats(circuit, shots: int) -> dict:
    """CPU-assertable batched-trajectory plan statistics (no compile,
    no chip): how many HBM sweeps one application of the noisy circuit
    costs — INDEPENDENT of the shot count, the batched engine's whole
    point (`hbm_sweeps` here equals the shots=1 plan's; the golden gate
    is scripts/check_batch_golden.py) — plus the channel mix (inlined
    BatchSelStage channels vs XLA-applied ones)."""
    from quest_tpu.ops import pallas_band as PB

    n = circuit.num_qubits
    bucket = _bucket_for(shots)   # run_batched's chunk=None cap rule
    use_kernels = PB.usable(n)
    items, channels = _traj_channels_and_items(circuit, n, use_kernels)
    if use_kernels:
        parts = PB.maybe_sweep(
            PB.segment_plan(items, n, batch=bucket), n)
        rec = PB.batched_stats(parts, shots, bucket)
    else:
        rec = {"batch": int(shots), "bucket": bucket,
               "states_per_sweep": bucket,
               "hbm_sweeps": len(items), "kernel_sweeps": 0,
               "batched_stages": 0}
    rec["channels"] = len(channels)
    rec["inline_channels"] = sum(1 for ch in channels if ch["inline"])
    rec["mixture_channels"] = sum(
        1 for ch in channels if ch["mixture_probs"] is not None)
    return rec
