"""Profiling / tracing hooks.

The reference has NO profiling support (SURVEY.md §5: the only
introspection is reportQuregParams/getEnvironmentString). On TPU the
platform tooling is first-class; this module packages it:

  * `trace(dir)` — context manager capturing a profiler trace viewable in
    TensorBoard / Perfetto (wraps jax.profiler).
  * `annotate(name)` — named region that shows up on the trace timeline.
  * `op_metrics(fn, *args)` — compile a function and return its XLA cost
    analysis (flops, bytes accessed) — the quick "is this memory-bound?"
    check used to tune the engines.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace: `with profiling.trace("/tmp/trace"): ...`"""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region: `with profiling.annotate("qft"): ...`"""
    return jax.profiler.TraceAnnotation(name)


def op_metrics(fn, *args, **kwargs) -> dict:
    """Lower+compile `fn(*args)` and return XLA's cost analysis
    (flops / bytes accessed / estimated seconds where available)."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # backend without cost analysis
        return {}
    if isinstance(analysis, list):  # some versions return [dict]
        analysis = analysis[0] if analysis else {}
    return dict(analysis)


# ---------------------------------------------------------------------------
# self-auditing stage report (VERDICT r3/r4 carried item: the measured
# stage costs behind the cost model must be reproducible by a SHIPPED
# command, not ad-hoc probe scripts)
# ---------------------------------------------------------------------------


def _single_segment(ops, n):
    """(stages, arrays) of the ONE kernel segment a tiny circuit plans
    into — the report measures real planner output, not hand-built
    stages, so it cannot drift from what the engine runs."""
    from quest_tpu.circuit import flatten_ops
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    items = F.plan(flatten_ops(ops, n, False), n, bands=PB.plan_bands(n))
    parts = PB.segment_plan(items, n)
    segs = [p for p in parts if p[0] == "segment"]
    if len(segs) != 1:
        raise RuntimeError(
            f"probe circuit planned into {len(segs)} segments (want 1)")
    return segs[0][1], segs[0][2]


def _stage_cases(n):
    """Probe circuits, one per stage family of docs/KERNELS.md: a lone
    phase (the DMA floor — its compute adder is tiny, so steady time ~
    one HBM pass), and full-width band operators in each band position
    (b0 lanes / b1 sublanes / scb scattered tiles), plus the width-1
    remainder band (sc) when this n has one."""
    from quest_tpu.circuit import Circuit
    from quest_tpu.ops import pallas_band as PB

    rng_angles = [0.3 + 0.1 * i for i in range(7)]

    def rot_band(ql, w):
        c = Circuit(n)
        for i in range(w):
            c.rx(ql + i, rng_angles[i % 7])
        return c

    cases = [("phase (DMA floor)", Circuit(n).cphase(0.37, 0, 1))]
    bands = PB.plan_bands(n)
    kinds = {0: "b0", 1: "b1"}
    for bi, (ql, w) in enumerate(bands):
        label = kinds.get(bi, "sc" if w == 1 else "scb")
        if label in dict(cases):
            continue
        cases.append((label, rot_band(ql, w)))
    return cases


def stage_report(n: int = None, reps: int = 5, out=None) -> dict:
    """Measure the kernel tier's per-stage costs ON THE ATTACHED BACKEND
    and print the comparison against the chip cost model's constants
    (quest_tpu.circuit._COST_MODELS) — the shipped, repeatable form of
    the round-3/4 probe scripts behind docs/KERNELS.md. Returns the
    record {case: {"measured_ms", "model_lo_ms", "model_hi_ms"}, ...}.

    On a TPU the numbers ARE the cost-model audit (run at n=30 to
    compare against the calibration constants directly). On a CPU host
    the kernels run in the Pallas interpreter — the command still
    exercises the whole path (CI smoke), but the times say nothing
    about chip constants and the report says so loudly.

    CLI: python -m quest_tpu.profiling [--n N] [--reps R]"""
    import sys
    import time

    import jax.numpy as jnp
    import numpy as np

    from quest_tpu.circuit import (_COST_MODELS, _cost_model_for,
                                   _estimate_ms)
    from quest_tpu.ops import pallas_band as PB
    from quest_tpu.state import basis_planes, fused_state_shape

    out = out or sys.stdout
    # bounded backend probe FIRST: an in-process jax.devices() with the
    # axon tunnel down hangs indefinitely (env.py; the same guard
    # explain() takes)
    from quest_tpu.env import ensure_live_backend
    ensure_live_backend()
    platform = jax.devices()[0].platform
    on_tpu = platform in ("tpu", "axon")
    if n is None:
        n = 30 if on_tpu else 12
    if not PB.usable(n):
        raise ValueError(f"n={n} is below the kernel tier's minimum")
    interpret = not on_tpu
    kind = str(getattr(jax.devices()[0], "device_kind", "?"))
    model, matched = _cost_model_for(kind)
    chip = "v5p" if model is _COST_MODELS["v5p"] else "v5e"
    print(f"[stage_report] backend={platform} device_kind={kind!r} "
          f"n={n} reps={reps} model={chip} "
          f"({model['provenance']})", file=out)
    if interpret:
        print("[stage_report] CAUTION: CPU host — kernels run in the "
              "Pallas INTERPRETER; times exercise the path but are NOT "
              "chip constants. Run on the TPU for the real audit.",
              file=out)

    rec = {}
    for label, circ in _stage_cases(n):
        stages, arrays = _single_segment(circ.ops, n)
        fn = PB.compile_segment(stages, n, interpret=interpret)
        arrays = [jnp.asarray(a) for a in arrays]
        jfn = jax.jit(lambda a: fn(a, arrays), donate_argnums=(0,))
        amps = basis_planes(0, n=n, rdt=jnp.float32,
                            shape=fused_state_shape(n))
        amps = jfn(amps)
        _ = np.asarray(amps[0, 0, :4])          # true completion
        t0 = time.perf_counter()
        for _ in range(reps):
            amps = jfn(amps)
        _ = np.asarray(amps[0, 0, :4])
        ms = (time.perf_counter() - t0) / reps * 1e3
        del amps    # free this case's state BEFORE the next case
                    # allocates its own — two live 30q states (8 GiB
                    # each) exceed v5e HBM (seen as ResourceExhausted
                    # while the next jit baked its operand constants)
        lo, hi = _estimate_ms([("segment", stages, arrays)], n, model)
        rec[label] = {"measured_ms": round(ms, 2),
                      "model_lo_ms": round(lo, 2),
                      "model_hi_ms": round(hi, 2),
                      "stages": [type(s).__name__ for s in stages]}
        verdict = ("OK" if lo * 0.8 <= ms <= hi * 1.3 else "DRIFT")
        if interpret:
            verdict = "n/a (interpreter)"
        print(f"[stage_report] {label:<18} measured {ms:8.2f} ms   "
              f"model [{lo:.1f}, {hi:.1f}] ms   {verdict}", file=out)

    # DMA vs MXU split: the phase case is ~pure DMA; a band case's
    # compute adder is (measured - DMA floor)
    if "phase (DMA floor)" in rec:
        dma = rec["phase (DMA floor)"]["measured_ms"]
        for label, r in rec.items():
            if label != "phase (DMA floor)":
                r["compute_adder_ms"] = round(max(0.0, r["measured_ms"]
                                                  - dma), 2)
        print(f"[stage_report] DMA floor {dma:.2f} ms; per-stage compute "
              f"adders: "
              + ", ".join(f"{k}={v['compute_adder_ms']:.1f}"
                          for k, v in rec.items()
                          if "compute_adder_ms" in v), file=out)
    return rec


def sweep_dma_report(n: int = None, reps: int = 5, circuit=None,
                     iters: int = None, out=None) -> dict:
    """Per-sweep DMA-stream vs compute-time split of a fused plan ON
    THE ATTACHED BACKEND — the host-side half of the pipeline's stall
    attribution (ISSUE 11 profiling hook). For each kernel sweep of
    the plan it measures

      * the full sweep launch (stage chain under the decoupled
        multi-buffer pipeline), and
      * ONE stage-free copy kernel — the same slot/semaphore schedule
        streaming the same state bytes with an empty stage chain: the
        plan's raw HBM in+out DMA floor —

    and reports per sweep `total_ms`, the shared `dma_ms` floor and
    `compute_adder_ms = total - dma`. A sweep whose adder is ~0 is
    DMA-bound (the pipeline hides its compute entirely); a large adder
    says the MXU chain overruns the stream and is where the residual
    stall lives. The IN-KERNEL attribution rides the named-scope
    labels the decoupled driver wraps its DMA waits in
    ('quest:dma_in_wait' / 'quest:dma_out_wait' / 'quest:stages',
    pallas_band._decoupled_kernel) — capture with profiling.trace()
    and the regions land on the chip timeline directly.

    Defaults: the bench headline step (bench._build_circuit) unrolled
    `iters` = INNER_STEPS applications, n = 30 on TPU / 12 on a CPU
    host (where kernels run in the Pallas INTERPRETER — the command
    exercises the path, the times are not chip constants; the report
    says so loudly, like stage_report).

    CLI: python -m quest_tpu.profiling --sweeps [--n N] [--reps R]"""
    import sys
    import time

    import jax.numpy as jnp
    import numpy as np

    from quest_tpu.env import ensure_live_backend
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB
    from quest_tpu.state import basis_planes, fused_state_shape

    out = out or sys.stdout
    ensure_live_backend()
    platform = jax.devices()[0].platform
    on_tpu = platform in ("tpu", "axon")
    if n is None:
        n = 30 if on_tpu else 12
    if not PB.usable(n):
        raise ValueError(f"n={n} is below the kernel tier's minimum")
    interpret = not on_tpu
    if circuit is None:
        import bench
        circuit = bench._build_circuit(n)
        if iters is None:
            iters = bench.INNER_STEPS
    iters = iters or 1
    print(f"[sweep_dma_report] backend={platform} n={n} reps={reps} "
          f"iters={iters} pipeline="
          f"{'decoupled' if PB.decoupled_active() else 'legacy'}",
          file=out)
    if interpret:
        print("[sweep_dma_report] CAUTION: CPU host — kernels run in "
              "the Pallas INTERPRETER; the split exercises the path "
              "but the times are NOT chip constants.", file=out)

    items = F.plan(circuit._planned_flat(n, False), n,
                   bands=PB.plan_bands(n))
    parts = PB.maybe_sweep(PB.segment_plan(items, n) * iters, n)

    def time_launch(stages, arrays):
        fn = PB.compile_segment(list(stages), n, interpret=interpret)
        arrays = [jnp.asarray(a) for a in arrays]
        jfn = jax.jit(lambda a: fn(a, arrays), donate_argnums=(0,))
        amps = basis_planes(0, n=n, rdt=jnp.float32,
                            shape=fused_state_shape(n))
        amps = jfn(amps)
        _ = np.asarray(amps[0, 0, :4])
        t0 = time.perf_counter()
        for _ in range(reps):
            amps = jfn(amps)
        _ = np.asarray(amps[0, 0, :4])
        ms = (time.perf_counter() - t0) / reps * 1e3
        del amps                 # one live full state at a time
        return ms

    # the plan's DMA floor: the identical slot schedule with an empty
    # stage chain — same state bytes through the same rings. Measured
    # once (block geometry differences between sweeps move the DMA
    # stream second-order; the bytes are the whole state either way).
    dma_ms = time_launch((), ())
    rec = {"platform": platform, "n": n, "dma_ms": round(dma_ms, 2),
           "sweeps": []}
    print(f"[sweep_dma_report] DMA floor (stage-free copy kernel): "
          f"{dma_ms:.2f} ms", file=out)
    for i, part in enumerate(parts):
        if part[0] != "segment":
            rec["sweeps"].append({"sweep": i, "kind": "xla_passthrough"})
            print(f"[sweep_dma_report] sweep {i}: XLA passthrough "
                  f"(not a kernel launch)", file=out)
            continue
        ms = time_launch(part[1], part[2])
        adder = max(0.0, ms - dma_ms)
        rec["sweeps"].append({
            "sweep": i, "kind": "kernel", "stages": len(part[1]),
            "total_ms": round(ms, 2),
            "compute_adder_ms": round(adder, 2),
            # interpreter timings are not chip constants: the record
            # mirrors the printed verdict and refuses a verdict off-chip
            "dma_bound": None if interpret
            else bool(adder <= 0.15 * dma_ms),
        })
        verdict = "DMA-bound" if adder <= 0.15 * dma_ms else \
            f"compute overruns stream by {adder:.1f} ms"
        if interpret:
            verdict = "n/a (interpreter)"
        print(f"[sweep_dma_report] sweep {i}: {len(part[1])} stages, "
              f"{ms:8.2f} ms total, compute adder {adder:6.2f} ms   "
              f"{verdict}", file=out)
    return rec


def _main():
    import argparse

    ap = argparse.ArgumentParser(description=stage_report.__doc__)
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--sweeps", action="store_true",
                    help="per-sweep DMA-vs-compute split "
                         "(sweep_dma_report) instead of the per-stage "
                         "cost-model audit")
    args = ap.parse_args()
    from quest_tpu.env import ensure_live_backend
    ensure_live_backend()
    if args.sweeps:
        sweep_dma_report(n=args.n, reps=args.reps)
    else:
        stage_report(n=args.n, reps=args.reps)


if __name__ == "__main__":
    _main()
