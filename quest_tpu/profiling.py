"""Profiling / tracing hooks.

The reference has NO profiling support (SURVEY.md §5: the only
introspection is reportQuregParams/getEnvironmentString). On TPU the
platform tooling is first-class; this module packages it:

  * `trace(dir)` — context manager capturing a profiler trace viewable in
    TensorBoard / Perfetto (wraps jax.profiler).
  * `annotate(name)` — named region that shows up on the trace timeline.
  * `op_metrics(fn, *args)` — compile a function and return its XLA cost
    analysis (flops, bytes accessed) — the quick "is this memory-bound?"
    check used to tune the engines.
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device trace: `with profiling.trace("/tmp/trace"): ...`"""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named trace region: `with profiling.annotate("qft"): ...`"""
    return jax.profiler.TraceAnnotation(name)


def op_metrics(fn, *args, **kwargs) -> dict:
    """Lower+compile `fn(*args)` and return XLA's cost analysis
    (flops / bytes accessed / estimated seconds where available)."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    try:
        analysis = compiled.cost_analysis()
    except Exception:  # backend without cost analysis
        return {}
    if isinstance(analysis, list):  # some versions return [dict]
        analysis = analysis[0] if analysis else {}
    return dict(analysis)
