"""Noisy random-circuit sampling via quantum trajectories + linear XEB.

The full pipeline the reference cannot run at statevector cost: simulate
an RCS experiment with per-qubit depolarising noise using trajectory
unraveling (quest_tpu/trajectories.py — 2^n memory per shot, the whole
shot batch one vmapped program), sample a bitstring from every noisy
shot, and score the samples against the IDEAL circuit with linear
cross-entropy benchmarking (calculations.calc_linear_xeb). The measured
fidelity decays with circuit volume toward the digital-error-model
reference curve (1 - p)^{n_channels} — a lower bound at shallow depth,
where errors are not yet fully decorrelating.

Run: python examples/noisy_rcs_trajectories.py
"""

import numpy as np


if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401

import jax
import jax.numpy as jnp

import quest_tpu as qt
from quest_tpu import trajectories as T
from quest_tpu import variational as V
from quest_tpu.calculations import calc_linear_xeb
from quest_tpu.circuit import Circuit
from quest_tpu.state import basis_planes

N = 10
P_DEPOL = 0.01
SHOTS = 1024


def layers(depth, seed=3):
    """Shared gate plan: (kind, qubit, angle) rotations + CZ bricks."""
    rng = np.random.default_rng(seed)
    plan = []
    for d in range(depth):
        rots = [(int(rng.integers(0, 3)), q,
                 float(rng.uniform(0, 2 * np.pi))) for q in range(N)]
        brick = [(q, q + 1) for q in range(d % 2, N - 1, 2)]
        plan.append((rots, brick))
    return plan


def ideal_state(plan):
    c = Circuit(N)
    for rots, brick in plan:
        for kind, q, ang in rots:
            (c.rx, c.ry, c.rz)[kind](q, ang)
        for a, b in brick:
            c.cz(a, b)
    return c.apply(qt.create_qureg(N))


def sampler(plan, p_noise):
    """One trajectory: the circuit with depolarising noise p_noise after
    every layer, then one bitstring sampled from the final state."""
    def shot(key):
        amps = basis_planes(0, n=N, rdt=jnp.float32)
        for rots, brick in plan:
            for kind, q, ang in rots:
                amps = (V.rx, V.ry, V.rz)[kind](amps, N, q, ang)
            for a, b in brick:
                amps = V.cz(amps, N, a, b)
            if p_noise:
                for q in range(N):
                    amps, key, _ = T.depolarising(amps, key, N, q, p_noise)
        key, sub = jax.random.split(key)
        probs = amps[0] ** 2 + amps[1] ** 2
        return jax.random.categorical(sub, jnp.log(probs + 1e-30))
    return shot


def main():
    print(f"{N}-qubit RCS, depolarising p={P_DEPOL} per qubit per layer, "
          f"{SHOTS} trajectories per depth")
    print("fidelity = XEB(noisy samples) / XEB(ideal samples) — the raw "
          "XEB exceeds 1 at shallow depth (not yet Porter-Thomas), so "
          "the ideal sampler's own score is the correct normalizer")
    print(f"{'depth':>5} {'fidelity':>9} {'(1-p)^channels':>15}")
    for depth in (2, 4, 6, 8):
        plan = layers(depth)
        ideal = ideal_state(plan)

        def xeb_of(p_noise, seed):
            keys = jax.random.split(jax.random.key(seed), SHOTS)
            samples = jax.jit(jax.vmap(sampler(plan, p_noise)))(keys)
            return calc_linear_xeb(ideal, samples)

        fidelity = xeb_of(P_DEPOL, depth) / xeb_of(0.0, 1000 + depth)
        predict = (1.0 - P_DEPOL) ** (N * depth)
        print(f"{depth:>5} {fidelity:>9.3f} {predict:>15.3f}")


if __name__ == "__main__":
    main()
