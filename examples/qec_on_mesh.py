"""Repetition-code QEC cycles ON A DEVICE MESH — the dynamic sharded engine.

The round-5 capability this demonstrates: a dynamic circuit (gates +
mid-circuit syndrome measurements + classical feedback corrections)
compiled as ONE shard_map program over a multi-device mesh, where the
measurement-free stretches get the full static-engine treatment —
band-fusion, and the layer-amortized relabel pass per stretch
(quest_tpu/parallel/sharded.py compile_circuit_sharded_measured,
engine='banded'). The reference must host-round-trip AND MPI-broadcast
per measurement, and its measurement path communicates per-gate and
fuses nothing (QuEST_cpu_distributed.c:1244-1319).

The program: a 3-qubit bit-flip code with two syndrome ancillas runs
TWO full noise->syndrome->correct cycles, with deterministic injected
X errors (a different single data qubit each cycle). Self-checking:
every trajectory must decode back to the exact encoded state, the
syndrome outcomes must match the injected error pattern, and the
8-device trajectory must equal the single-device dynamic engine's for
the same key.

Run: python examples/qec_on_mesh.py     (bootstraps an 8-virtual-device
CPU mesh when fewer real devices are attached, like __graft_entry__)
"""

import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

THETA = 1.1


def build_cycle_circuit():
    """Qubits 0-2 data, 3-4 ancillas; two QEC cycles with X(0) injected
    in cycle 1 and X(2) in cycle 2. Outcome indices: cycle k uses
    4 measurements (syndrome a3, a4, then ancilla resets via
    measure+x_if)."""
    import numpy as np
    from quest_tpu.circuit import Circuit
    from quest_tpu.ops.matrices import PAULI_X

    c = Circuit(5)
    c.ry(0, THETA)
    c.cnot(0, 1)
    c.cnot(0, 2)

    out = 0
    for cycle, bad in enumerate((0, 2)):
        c.gate(PAULI_X, (bad,))           # deterministic injected error
        c.cnot(0, 3)
        c.cnot(1, 3)                      # a3 = q0 XOR q1
        c.cnot(1, 4)
        c.cnot(2, 4)                      # a4 = q1 XOR q2
        c.measure(3)                      # outcome out+0
        c.measure(4)                      # outcome out+1
        # decode: (1,0)->X on q0, (1,1)->X on q1, (0,1)->X on q2
        c.gate_if(PAULI_X, (0,), [(out, 1), (out + 1, 0)])
        c.gate_if(PAULI_X, (1,), [(out, 1), (out + 1, 1)])
        c.gate_if(PAULI_X, (2,), [(out, 0), (out + 1, 1)])
        # reset ancillas for the next cycle (measure + conditional flip)
        c.reset(3)                        # outcome out+2
        c.reset(4)                        # outcome out+3
        out += 4
    return c


def main():
    import jax
    import numpy as np

    if not os.environ.get("_QEC_MESH_BOOTSTRAPPED"):
        # bounded probe FIRST: an in-process jax.devices() with the
        # axon tunnel down hangs indefinitely (quest_tpu/env.py; the
        # same guard __graft_entry__.dryrun_multichip takes)
        from quest_tpu.env import ensure_live_backend
        ensure_live_backend()

    if len(jax.devices()) < 8:
        if os.environ.get("_QEC_MESH_BOOTSTRAPPED"):
            raise RuntimeError("virtual mesh bootstrap failed")
        env = dict(os.environ)
        env["_QEC_MESH_BOOTSTRAPPED"] = "1"
        env["JAX_PLATFORMS"] = "cpu"
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith(
                     "--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=8")
        env["XLA_FLAGS"] = " ".join(flags)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                "jax.config.update('jax_enable_x64', True); "
                "import examples.qec_on_mesh as m; m.main()")
        raise SystemExit(subprocess.run(
            [sys.executable, "-c", code], env=env, cwd=repo).returncode)

    jax.config.update("jax_enable_x64", True)   # 5 qubits: exactness over speed

    import quest_tpu as qt
    from quest_tpu.parallel import make_amp_mesh
    from quest_tpu.state import to_dense

    mesh = make_amp_mesh(8)
    c = build_cycle_circuit()

    # the exact encoded state the cycles must restore
    want = np.zeros(32, dtype=complex)
    want[0b00000] = np.cos(THETA / 2)
    want[0b00111] = np.sin(THETA / 2)

    print(c.explain_sharded(mesh, engine="banded"))

    for s in range(6):
        key = jax.random.PRNGKey(s)
        q = qt.create_qureg(5, dtype=np.complex128)
        r, outs = c.apply_sharded_measured(q, key, mesh, engine="banded")
        outs = np.asarray(outs)
        # syndromes must finger the injected errors: X(0) -> (1,0),
        # X(2) -> (0,1)
        assert (outs[0], outs[1]) == (1, 0), outs
        assert (outs[4], outs[5]) == (0, 1), outs
        v = to_dense(r)
        fidelity = abs(np.vdot(want, v)) ** 2
        assert fidelity > 1 - 1e-10, (s, fidelity)
        # the mesh trajectory equals the single-device dynamic engine's
        q1 = qt.create_qureg(5, dtype=np.complex128)
        r1, o1 = c.apply_measured(q1, key)
        assert np.array_equal(np.asarray(o1), outs)
        np.testing.assert_allclose(to_dense(r1), v, atol=1e-11, rtol=0)
    print("qec_on_mesh: 6/6 trajectories decoded exactly on the "
          "8-device mesh (and match the single-device engine per key)")


if __name__ == "__main__":
    main()
