"""Bounded backend probe for running examples as scripts — import for
the side effect. A dead TPU tunnel must not hang an example
(quest_tpu/env.py ensure_live_backend: subprocess probe with timeout,
loud fallback to the host CPU). One home for the probe behavior; every
example imports this under ``if __name__ == "__main__"`` so the test
suite's imports stay no-ops."""

from quest_tpu.env import ensure_live_backend

ensure_live_backend()
