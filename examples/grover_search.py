"""Grover's search: find a marked basis state in sqrt(2^n) iterations.

Builds the whole search as ONE traced circuit (oracle = all-ones phase
flip conjugated by X on the 0-bits of the marked string; diffusion =
all-ones phase flip conjugated by H and X), runs it through the
band-fusion engine, and verifies the analytic success probability

    p(k) = sin^2((2k + 1) * asin(1/sqrt(N)))

at the optimal iteration count — a self-checking example with no
reference analogue (the reference ships tutorial/BV/damping examples
only; see docs/api_parity.md for the API surface this drives).

Run: python examples/grover_search.py
"""

import numpy as np

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


N_QUBITS = 12
MARKED = 0b101101110010 & ((1 << N_QUBITS) - 1)


def grover_circuit(n, marked, iters):
    from quest_tpu.circuit import Circuit

    c = Circuit(n)
    for q in range(n):
        c.h(q)
    all_q = tuple(range(n))
    zero_bits = [q for q in range(n) if not (marked >> q) & 1]
    for _ in range(iters):
        # oracle: flip the phase of |marked>
        for q in zero_bits:
            c.x(q)
        c.cphase(np.pi, *all_q)          # all-ones phase flip (-1)
        for q in zero_bits:
            c.x(q)
        # diffusion: 2|s><s| - 1
        for q in range(n):
            c.h(q)
        for q in range(n):
            c.x(q)
        c.cphase(np.pi, *all_q)
        for q in range(n):
            c.x(q)
        for q in range(n):
            c.h(q)
    return c


def main():
    import jax

    import quest_tpu as qt
    from quest_tpu import measurement as meas

    n = N_QUBITS
    dim = 1 << n
    theta = np.arcsin(1.0 / np.sqrt(dim))
    k_opt = int(np.round(np.pi / (4 * theta) - 0.5))
    p_want = np.sin((2 * k_opt + 1) * theta) ** 2

    q = qt.create_qureg(n)
    q = grover_circuit(n, MARKED, k_opt).apply_banded(q)

    amp_re = float(q.amps[0, MARKED])
    amp_im = float(q.amps[1, MARKED])
    p_got = amp_re ** 2 + amp_im ** 2
    print(f"n={n}, marked=|{MARKED:0{n}b}>, optimal iterations k={k_opt}")
    print(f"success probability: got {p_got:.6f}, analytic {p_want:.6f}")
    assert abs(p_got - p_want) < 1e-4, "Grover amplitude off the analytic value"

    shots = np.asarray(meas.sample(q, 32, jax.random.PRNGKey(7)))
    frac = float((shots == MARKED).mean())
    print(f"32 measurement shots hit the marked state {frac:.0%} of the time")
    assert frac > 0.9
    print("OK")


if __name__ == "__main__":
    main()
