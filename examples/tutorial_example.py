"""Port of the reference tutorial (examples/tutorial_example.c) using the
QuEST-compatible API — every call maps 1:1 onto the reference's.

Expected output (matches the reference binary):
  Probability amplitude of |111>: 0.112422
  Probability of qubit 2 being in state 1: 0.749178
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


import numpy as np

from quest_tpu.api import (
    createQuESTEnv, createQureg, destroyQureg, destroyQuESTEnv,
    reportQuregParams, reportQuESTEnv, startRecordingQASM, printRecordedQASM,
    hadamard, controlledNot, rotateY, multiControlledPhaseFlip, unitary,
    compactUnitary, rotateAroundAxis, controlledCompactUnitary,
    multiControlledUnitary, multiQubitUnitary, createComplexMatrixN,
    getProbAmp, calcProbOfOutcome, measure, measureWithStats,
)


def main():
    # prepare our environment and register (ref tutorial_example.c:19-37)
    env = createQuESTEnv()
    qubits = createQureg(3, env)

    print("\nThis is our environment:")
    reportQuregParams(qubits)
    reportQuESTEnv(env)

    startRecordingQASM(qubits)

    # apply circuit (ref tutorial_example.c:50-82)
    hadamard(qubits, 0)
    controlledNot(qubits, 0, 1)
    rotateY(qubits, 2, 0.1)

    multiControlledPhaseFlip(qubits, [0, 1, 2])

    u = np.array([[0.5 + 0.5j, 0.5 - 0.5j],
                  [0.5 - 0.5j, 0.5 + 0.5j]])
    unitary(qubits, 0, u)

    a = 0.5 + 0.5j
    b = 0.5 - 0.5j
    compactUnitary(qubits, 1, a, b)

    v = (1.0, 0.0, 0.0)
    rotateAroundAxis(qubits, 2, 3.14 / 2, v)

    controlledCompactUnitary(qubits, 0, 1, a, b)

    multiControlledUnitary(qubits, [0, 1], 2, u)

    toff = createComplexMatrixN(3)
    toff[6, 7] = 1
    toff[7, 6] = 1
    for i in range(6):
        toff[i, i] = 1
    multiQubitUnitary(qubits, [0, 1, 2], toff)

    # study the quantum state (ref tutorial_example.c:89-105)
    print("\nCircuit output:")

    prob = getProbAmp(qubits, 7)
    print(f"Probability amplitude of |111>: {prob:g}")

    prob = calcProbOfOutcome(qubits, 2, 1)
    print(f"Probability of qubit 2 being in state 1: {prob:g}")

    outcome = measure(qubits, 0)
    print(f"Qubit 0 was measured in state {outcome}")

    outcome, prob = measureWithStats(qubits, 2)
    print(f"Qubit 2 collapsed to {outcome} with probability {prob:g}")

    print("\nRecorded QASM:")
    printRecordedQASM(qubits)

    destroyQureg(qubits, env)
    destroyQuESTEnv(env)


if __name__ == "__main__":
    main()
