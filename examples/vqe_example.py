"""Variational quantum eigensolver on a transverse-field Ising chain.

Demonstrates the differentiable layer (quest_tpu/variational.py) — a
capability with no analogue in the reference: the full energy
<psi(theta)| H |psi(theta)> is one traced JAX function, so jax.grad
yields EXACT reverse-mode gradients through the simulation and the
optimization loop runs entirely on device-compiled programs.

H = -J sum_i Z_i Z_{i+1} - h sum_i X_i   (J = 1, h = 0.75, N = 6)

Run: python examples/vqe_example.py
"""

import numpy as np


if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401

import jax
import jax.numpy as jnp

from quest_tpu import variational as V

N = 6
J, HF = 1.0, 0.75
LAYERS = 3


def hamiltonian():
    codes, coeffs = [], []
    for i in range(N - 1):           # -J Z_i Z_{i+1}
        term = [0] * N
        term[i] = term[i + 1] = 3
        codes.append(term)
        coeffs.append(-J)
    for i in range(N):               # -h X_i
        term = [0] * N
        term[i] = 1
        codes.append(term)
        coeffs.append(-HF)
    return codes, coeffs


def ansatz(amps, params):
    """Hardware-efficient ansatz: ry layers + cz entangler bricks."""
    p = params.reshape(LAYERS, N)
    for l in range(LAYERS):
        for q in range(N):
            amps = V.ry(amps, N, q, p[l, q])
        for q in range(l % 2, N - 1, 2):
            amps = V.cz(amps, N, q, q + 1)
    return amps


def exact_ground_energy():
    """Dense diagonalization oracle (64x64 — trivial at N=6)."""
    import functools
    I2 = np.eye(2)
    X = np.array([[0, 1], [1, 0]])
    Z = np.diag([1.0, -1.0])

    def kron_at(op, i, op2=None, j=None):
        mats = [I2] * N
        mats[i] = op
        if op2 is not None:
            mats[j] = op2
        # qubit 0 is the LEAST significant bit -> rightmost kron factor
        return functools.reduce(np.kron, reversed(mats))
    H = np.zeros((1 << N, 1 << N))
    for i in range(N - 1):
        H += -J * kron_at(Z, i, Z, i + 1)
    for i in range(N):
        H += -HF * kron_at(X, i)
    return float(np.linalg.eigvalsh(H)[0])


def main():
    codes, coeffs = hamiltonian()
    energy = V.expectation(ansatz, N, codes, coeffs)
    value_and_grad = jax.jit(jax.value_and_grad(energy))

    rng = np.random.default_rng(7)
    params = jnp.asarray(rng.uniform(-0.1, 0.1, LAYERS * N),
                         dtype=jnp.float32)
    lr = 0.1
    for step in range(300):
        e, g = value_and_grad(params)
        params = params - lr * g
        if step % 50 == 0:
            print(f"step {step:3d}: E = {float(e):+.6f}")
    e_final = float(energy(params))
    e_exact = exact_ground_energy()
    print(f"final   : E = {e_final:+.6f}")
    print(f"exact   : E = {e_exact:+.6f}  "
          f"(gap {abs(e_final - e_exact):.4f} — limited by ansatz depth)")


if __name__ == "__main__":
    main()
