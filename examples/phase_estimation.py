"""Quantum phase estimation: read an eigenphase to t-bit precision.

Estimates the eigenphase of U = phase(2*pi*PHI) acting on |1>, with a
t-qubit counting register: Hadamards, controlled-U^(2^k) powers (all
diagonal — communication-free on every engine), then the INVERSE QFT
via Circuit.inverse() (the adjoint-circuit feature; the reference has
no circuit object to invert). Self-checking: with PHI exactly
representable in t bits the measurement is deterministic.

Run: python examples/phase_estimation.py
"""

import numpy as np

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


T_BITS = 8
PHI = 0.30078125            # 77/256 — exactly t-bit representable


def qpe_circuit(t, phi):
    from quest_tpu.circuit import Circuit, qft_circuit

    n = t + 1                     # counting register [0..t), eigenvector at t
    c = Circuit(n)
    c.x(t)                        # eigenvector |1> of the phase gate
    for q in range(t):
        c.h(q)
    for k in range(t):
        # controlled-U^(2^k): counting qubit k controls phase 2^k * 2pi phi
        c.cphase(2 * np.pi * phi * (1 << k), k, t)
    # inverse QFT on the counting register, bit-reversed convention:
    # qft_circuit includes the final swaps, so its adjoint undoes them too
    iqft = qft_circuit(t).inverse()
    for op in iqft.ops:
        c.ops.append(op)
    return c


def main():
    import jax

    import quest_tpu as qt
    from quest_tpu import measurement as meas

    t = T_BITS
    q = qt.create_qureg(t + 1)
    q = qpe_circuit(t, PHI).apply_banded(q)

    shots = np.asarray(meas.sample(q, 64, jax.random.PRNGKey(3)))
    counting = shots & ((1 << t) - 1)
    # counting register bit k holds phase bit... sample the modal outcome
    vals, counts = np.unique(counting, return_counts=True)
    mode = int(vals[np.argmax(counts)])
    est = mode / (1 << t)
    print(f"t={t} bits, true phase {PHI}")
    print(f"modal outcome {mode} -> estimate {est} "
          f"({counts.max()}/{len(shots)} shots)")
    assert abs(est - PHI) < 1e-12, "QPE missed an exactly-representable phase"
    assert counts.max() == len(shots), "exact phase should be deterministic"
    print("OK")


if __name__ == "__main__":
    main()
