"""QAOA for MaxCut, end to end: differentiable angles, then sampling.

The cost layer e^{-i gamma C} is a product of ZZ parity rotations
(each ONE fused flip-form pass, see ops/apply.py apply_pauli_string),
the mixer is rx on every qubit, and the p-layer energy
<gamma, beta| C |gamma, beta> is a single traced function — so the
angle optimization runs on exact jax.grad gradients (the reference
offers no derivatives; its closest path is finite differences over
full re-simulations). After optimizing, the same state is SAMPLED and
the best observed bitstring is checked against the brute-force MaxCut.

Graph: the 3-regular 8-vertex circulant C8(1, 4) (ring + diameters).

Run: python examples/qaoa_maxcut.py
"""

import dataclasses
import os
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


N = 8
EDGES = [(i, (i + 1) % N) for i in range(N)] + [(i, i + 4) for i in range(4)]
LAYERS = 2


def cut_value(bits):
    return sum((bits >> i & 1) != (bits >> j & 1) for i, j in EDGES)


def ansatz(amps, params):
    from quest_tpu import variational as V

    gammas, betas = params[:LAYERS], params[LAYERS:]
    for q in range(N):
        amps = V.h(amps, N, q)
    for l in range(LAYERS):
        for i, j in EDGES:
            # e^{-i gamma (1 - Z_i Z_j)/2} = global phase * parity(-gamma)
            amps = V.parity(amps, N, (i, j), -gammas[l])
        for q in range(N):
            amps = V.rx(amps, N, q, 2 * betas[l])
    return amps


def main():
    import quest_tpu as qt
    from quest_tpu import measurement as meas
    from quest_tpu import variational as V

    # energy = sum over edges of 0.5 * <Z_i Z_j>; cut = |E|/2 - energy
    codes, coeffs = [], []
    for i, j in EDGES:
        term = [0] * N
        term[i] = term[j] = 3
        codes.append(term)
        coeffs.append(0.5)
    zz_sum = V.expectation(ansatz, N, codes, coeffs)
    value_and_grad = jax.jit(jax.value_and_grad(zz_sum))

    params = jnp.asarray([0.2] * LAYERS + [0.3] * LAYERS, dtype=jnp.float32)
    for step in range(120):
        e, g = value_and_grad(params)
        params = params - 0.05 * g
    exp_cut = len(EDGES) / 2 - float(zz_sum(params))

    best = max(range(1 << N), key=cut_value)
    print(f"p={LAYERS} QAOA expected cut: {exp_cut:.3f} "
          f"(max cut {cut_value(best)}, random baseline {len(EDGES)/2})")
    assert exp_cut > len(EDGES) / 2 + 1, "optimizer did not beat random"

    q = qt.create_qureg(N)
    q = dataclasses.replace(q, amps=ansatz(q.amps, params))
    shots = np.asarray(meas.sample(q, 256, jax.random.PRNGKey(8)))
    cuts = np.array([cut_value(int(s)) for s in shots])
    print(f"sampled best cut: {cuts.max()} "
          f"(mean {cuts.mean():.2f} over {len(shots)} shots)")
    assert cuts.max() == cut_value(best), "never sampled an optimal cut"
    print("OK")


if __name__ == "__main__":
    main()
