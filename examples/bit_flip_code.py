"""Three-qubit bit-flip code: encode, noise, syndrome, CORRECT — compiled.

The full quantum-error-correction cycle as ONE compiled dynamic circuit:
encode a random state across qubits 0-2, inject X noise with known
per-qubit probability, extract the syndrome into two ancillas (CNOT
parity checks), measure the ancillas mid-circuit, and apply the
feedback correction the syndrome dictates (gate_if on both ancilla
outcomes). The reference cannot express this without returning to the
host between the syndrome measurement and the correction.

Self-checking over many shots: whenever at most one data qubit flipped
(probability 1 - O(p^2)), the decoded state equals the input exactly;
the observed logical-failure rate matches the analytic 3p^2 - 2p^3.

Run: python examples/bit_flip_code.py
"""

import numpy as np

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


THETA = 0.9
P_FLIP = 0.15


def qec_circuit():
    """Qubits 0-2 data, 3-4 syndrome ancillas. Measurement indices:
    0 = ancilla 3 (parity of data 0,1), 1 = ancilla 4 (parity 1,2)."""
    from quest_tpu.circuit import Circuit

    c = Circuit(5)
    c.ry(0, THETA)                    # the state to protect
    c.cnot(0, 1)                      # encode |psi>_L
    c.cnot(0, 2)
    return c


def noise_and_correct(c, flips):
    from quest_tpu.ops.matrices import PAULI_X

    for q in range(3):
        if flips[q]:
            c.gate(PAULI_X, (q,))
    # syndrome extraction
    c.cnot(0, 3)
    c.cnot(1, 3)                      # ancilla 3 = q0 XOR q1
    c.cnot(1, 4)
    c.cnot(2, 4)                      # ancilla 4 = q1 XOR q2
    c.measure(3)                      # outcome 0
    c.measure(4)                      # outcome 1
    # decode the syndrome in-circuit: (1,0) -> q0, (1,1) -> q1, (0,1) -> q2
    c.gate_if(PAULI_X, (0,), [(0, 1), (1, 0)])
    c.gate_if(PAULI_X, (1,), [(0, 1), (1, 1)])
    c.gate_if(PAULI_X, (2,), [(0, 0), (1, 1)])
    return c


def main():
    import jax

    import quest_tpu as qt
    from quest_tpu.state import to_dense

    rng = np.random.default_rng(11)
    want = np.zeros(2, dtype=complex)
    want[0], want[1] = np.cos(THETA / 2), np.sin(THETA / 2)

    shots, failures = 400, 0
    for s in range(shots):
        flips = rng.random(3) < P_FLIP
        c = noise_and_correct(qec_circuit(), flips)
        q, outs = c.apply_measured(
            qt.create_qureg(5, dtype=np.complex128), jax.random.PRNGKey(s))
        v = to_dense(q).reshape(4, 2, 2, 2)   # [anc, q2, q1, q0]
        # decode: logical state lives on qubit 0 after un-encoding; here
        # just check the corrected codeword against the ideal encoding
        anc = int(np.asarray(outs)[0]) + 2 * int(np.asarray(outs)[1])
        code = v[anc]
        ideal = np.zeros((2, 2, 2), dtype=complex)
        ideal[0, 0, 0], ideal[1, 1, 1] = want[0], want[1]
        fid = abs(np.vdot(ideal, code)) ** 2
        ok = fid > 1 - 1e-9
        if not ok:
            failures += 1
            assert flips.sum() >= 2, (
                f"shot {s}: correction failed with {flips.sum()} flips")
    rate = failures / shots
    p = P_FLIP
    analytic = 3 * p * p * (1 - p) + p ** 3
    print(f"{shots} shots at p={p}: logical failures {failures} "
          f"({rate:.3f}; analytic {analytic:.3f})")
    assert abs(rate - analytic) < 0.05
    print("OK — every <=1-flip shot recovered the exact state")


if __name__ == "__main__":
    main()
