"""Port of the reference Bernstein--Vazirani circuit
(examples/bernstein_vazirani_circuit.c), 1:1 through the compatible API."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


from quest_tpu.api import (
    createQuESTEnv, createQureg, destroyQureg, destroyQuESTEnv,
    initZeroState, pauliX, controlledNot, calcProbOfOutcome,
)


def main():
    # model parameters (ref bernstein_vazirani_circuit.c:20-22)
    num_qubits = 9
    secret_num = 2 ** 4 + 1

    env = createQuESTEnv()

    # create qureg; let zeroth qubit be ancilla
    qureg = createQureg(num_qubits, env)
    initZeroState(qureg)

    # NOT the ancilla
    pauliX(qureg, 0)

    # CNOT secretNum bits with ancilla
    bits = secret_num
    for qb in range(1, num_qubits):
        bit = bits % 2
        bits //= 2
        if bit:
            controlledNot(qureg, 0, qb)

    # calculate prob of solution state
    success_prob = 1.0
    bits = secret_num
    for qb in range(1, num_qubits):
        bit = bits % 2
        bits //= 2
        success_prob *= calcProbOfOutcome(qureg, qb, bit)

    print(f"solution reached with probability {success_prob:f}")

    destroyQureg(qureg, env)
    destroyQuESTEnv(env)


if __name__ == "__main__":
    main()
