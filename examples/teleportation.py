"""Quantum teleportation — a fully-compiled dynamic circuit.

Teleports a random single-qubit state from qubit 0 to qubit 2 using a
Bell pair, two MID-CIRCUIT measurements, and CLASSICALLY-CONTROLLED
corrections (Circuit.measure / x_if / z_if). The entire protocol —
entangling gates, outcome draws, collapses, and feed-forward — is ONE
compiled XLA program taking a PRNG key; the reference must return to the
host after each measurement to branch.

Self-checking: for every key, qubit 2's post-protocol state equals the
input state exactly (fidelity 1 up to float rounding), regardless of
which of the four outcome branches was taken.

Run: python examples/teleportation.py
"""

import numpy as np

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


THETA, PHI = 1.0471975511965976, 0.6


def teleport_circuit():
    from quest_tpu.circuit import Circuit

    c = Circuit(3)
    # the state to teleport, on qubit 0: Ry(theta) then phase(phi)
    c.ry(0, THETA)
    c.phase(0, PHI)
    # Bell pair between 1 (Alice) and 2 (Bob)
    c.h(1)
    c.cnot(1, 2)
    # Bell-basis measurement of (0, 1)
    c.cnot(0, 1)
    c.h(0)
    c.measure(0)          # outcome index 0
    c.measure(1)          # outcome index 1
    # feed-forward corrections on Bob's qubit
    c.x_if(2, (1, 1))
    c.z_if(2, (0, 1))
    return c


def main():
    import jax
    jax.config.update("jax_enable_x64", True)   # 3 qubits: exactness over speed

    import quest_tpu as qt
    from quest_tpu.state import to_dense

    want = np.zeros(2, dtype=complex)
    want[0] = np.cos(THETA / 2)
    want[1] = np.sin(THETA / 2) * np.exp(1j * PHI)

    c = teleport_circuit()
    branches = set()
    for s in range(24):
        q, outs = c.apply_measured(qt.create_qureg(3, dtype=np.complex128),
                                   jax.random.PRNGKey(s))
        outs = tuple(int(x) for x in np.asarray(outs))
        branches.add(outs)
        v = to_dense(q).reshape(2, 2, 2)       # [q2, q1, q0] (little-endian)
        # qubits 0,1 are collapsed to |outs>; extract Bob's state
        bob = v[:, outs[1], outs[0]]
        fid = abs(np.vdot(want, bob)) ** 2
        assert fid > 1 - 1e-10, f"branch {outs}: fidelity {fid}"
    print(f"teleported across outcome branches {sorted(branches)}: "
          f"fidelity 1.0 on every key")
    assert len(branches) >= 3, "expected to see several outcome branches"
    print("OK")


if __name__ == "__main__":
    main()
