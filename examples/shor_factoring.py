"""Shor's algorithm: factor 15 by quantum order finding.

The full textbook pipeline on the simulator: an 8-qubit counting
register drives controlled modular-multiplication permutations
U_b |x> = |b*x mod 15> on a 4-qubit work register (each a 16x16
permutation matrix applied through the general multi-qubit unitary
path, ref QuEST_cpu.c:1814-1898's op class), then the inverse QFT via
Circuit.inverse(), measurement, and the CLASSICAL half: continued
fractions on the measured phase to recover the order r, and
gcd(a^{r/2} +- 1, M) for the factors.

Self-checking: a=7 has order 4 mod 15, so the algorithm must recover
the factors {3, 5}; the counting distribution concentrates on
multiples of 2^t/r = 64 and the assertion requires >= 90% of shots
there (the ideal distribution puts ALL mass there since r | 2^t).

Run: python examples/shor_factoring.py
"""

import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


M = 15          # the number to factor
A = 7           # coprime base: order 4 mod 15
T_BITS = 8      # counting precision: 2 * ceil(log2 M)
W_BITS = 4      # work register: ceil(log2 M)


def mod_mult_matrix(b, m, w):
    """Permutation |x> -> |b*x mod m> on w qubits (identity above m).
    Matrix index bit j corresponds to targets[j], matching the
    framework's multi-qubit operand convention."""
    d = 1 << w
    u = np.zeros((d, d), dtype=np.complex128)
    for x in range(d):
        u[(b * x) % m if x < m else x, x] = 1.0
    return u


def order_finding_circuit(a, m, t, w):
    from quest_tpu.circuit import Circuit, qft_circuit

    c = Circuit(t + w)
    work = tuple(range(t, t + w))
    c.x(t)                               # work register starts in |1>
    for q in range(t):
        c.h(q)
    for k in range(t):
        b = pow(a, 1 << k, m)            # U^(2^k) is itself a mod-mult
        c.gate(mod_mult_matrix(b, m, w), work, controls=(k,))
    iqft = qft_circuit(t).inverse()
    for op in iqft.ops:
        c.ops.append(op)
    return c


def order_from_phase(y, t, m, a=A):
    """Continued-fraction convergents of y/2^t; the order is the first
    denominator r < m with a^r = 1 (mod m)."""
    frac = y / (1 << t)
    # expand y/2^t and test each convergent's denominator
    num, den = y, 1 << t
    coeffs = []
    while den:
        coeffs.append(num // den)
        num, den = den, num % den
    for upto in range(1, len(coeffs) + 1):
        # rebuild the convergent from the truncated expansion
        p, q = 1, 0
        for c in reversed(coeffs[:upto]):
            p, q = c * p + q, p
        if q < m and q > 0 and abs(frac - (p / q if q else 0)) <= 1 / (1 << (t // 2 + 1)):
            if pow(a, q, m) == 1:
                return q
    return None


def main():
    import jax

    import quest_tpu as qt
    from quest_tpu import measurement as meas

    circ = order_finding_circuit(A, M, T_BITS, W_BITS)
    q = qt.create_qureg(T_BITS + W_BITS)
    q = circ.apply_banded(q)

    shots = np.asarray(meas.sample(q, 128, jax.random.PRNGKey(15)))
    counting = shots & ((1 << T_BITS) - 1)

    # ideal distribution: r | 2^t, so ALL mass sits on multiples of 2^t/r
    step = (1 << T_BITS) // 4
    on_peak = np.mean(counting % step == 0)
    print(f"counting outcomes concentrate on multiples of {step}: "
          f"{on_peak:.0%} of shots")
    assert on_peak >= 0.9, f"phase distribution off the order-4 peaks: {on_peak}"

    orders = [order_from_phase(int(y), T_BITS, M) for y in counting if y]
    r = next(o for o in orders if o)
    print(f"recovered order r = {r} (a={A} mod {M})")
    assert pow(A, r, M) == 1 and r == 4

    f1 = math.gcd(pow(A, r // 2) - 1, M)
    f2 = math.gcd(pow(A, r // 2) + 1, M)
    print(f"factors: {M} = {f1} x {f2}")
    assert sorted((f1, f2)) == [3, 5], (f1, f2)
    print("OK")


if __name__ == "__main__":
    main()
