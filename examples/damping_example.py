"""Port of the reference damping demo (examples/damping_example.c), 1:1
through the compatible API: repeated amplitude damping of a |+> qubit held
as a density matrix."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


from quest_tpu.api import (
    createQuESTEnv, createDensityQureg, destroyQureg, destroyQuESTEnv,
    initPlusState, mixDamping, reportStateToScreen,
)


def main():
    env = createQuESTEnv()

    print("-------------------------------------------------------")
    print("Running quest_tpu damping example:\n\t Basic circuit involving "
          "damping of a qubit.")
    print("-------------------------------------------------------")

    qubits = createDensityQureg(1, env)
    initPlusState(qubits)

    print("\n Reporting the qubit state to screen:")
    reportStateToScreen(qubits, env, 0)

    print("\n Applying damping 10 times with probability 0.1 ")
    for counter in range(10):
        mixDamping(qubits, 0, 0.1)
        print(f"\n Qubit state after applying damping {counter + 1} times:")
        reportStateToScreen(qubits, env, 0)

    destroyQureg(qubits, env)
    destroyQuESTEnv(env)


if __name__ == "__main__":
    main()
