"""Random-circuit sampling with linear-XEB fidelity — the BASELINE.json
single-chip headline workload, end to end:

  1. build a depth-d random circuit (rotation layers + CZ brick),
  2. run it through the band-fusion Pallas engine (one HBM pass per
     segment; on a v5e chip a 30-qubit depth-20 instance takes ~7 s),
  3. draw measurement shots from the final state,
  4. score them with the linear cross-entropy benchmark
     F_XEB = 2^n <p(s)> - 1  (≈1 when sampling from the true output
     distribution, ≈0 for uniform noise).

The reference stops at measurement; XEB is this framework's addition
(calculations.calc_linear_xeb). Run: python examples/rcs_xeb_example.py [n] [depth]
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if __name__ == "__main__":
    # bounded backend probe FIRST — a dead TPU tunnel must not hang the
    # example run; one home for the behavior (examples/_probe.py)
    import os as _os
    import sys as _sys
    _sys.path.insert(0, _os.path.join(_os.path.dirname(__file__), ".."))
    from examples import _probe  # noqa: F401


import numpy as np

from quest_tpu.precision import enable_compile_cache

enable_compile_cache()

import quest_tpu as qt
from quest_tpu import calculations as calc
from quest_tpu import measurement as meas
from quest_tpu.circuit import random_circuit


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20
    depth = int(sys.argv[2]) if len(sys.argv) > 2 else 12
    shots = 2000

    circ = random_circuit(n, depth, seed=42)
    print(f"RCS: {n} qubits, depth {depth}, {len(circ.ops)} gates")

    q = qt.create_qureg(n)
    t0 = time.perf_counter()
    q = circ.apply_fused(q)
    probe = calc.calc_total_prob(q)  # forces completion
    dt = time.perf_counter() - t0
    print(f"simulated in {dt:.2f}s (incl. compile); norm = {probe:.8f}")

    t0 = time.perf_counter()
    import jax
    samples = meas.sample(q, shots, jax.random.key(7))
    xeb = calc.calc_linear_xeb(q, samples)
    print(f"{shots} shots in {time.perf_counter()-t0:.2f}s; "
          f"sampled linear XEB = {xeb:.3f}")

    # the meaningful check: the sampled XEB estimates the state's exact
    # collision XEB (2^n sum p^2 - 1). It approaches 1 only as the
    # circuit family converges to Porter-Thomas (deep circuits); at any
    # depth, sampler and exact value must agree.
    amps = np.asarray(q.amps, dtype=np.float64)
    p = amps[0] ** 2 + amps[1] ** 2
    exact = (1 << n) * float(np.sum(p * p) / np.sum(p)) - 1.0
    print(f"exact collision XEB of the state: {exact:.3f} "
          f"(sampler should estimate this)")

    # uniform-noise control: XEB of random bitstrings should be ~0
    rng = np.random.default_rng(0)
    noise = rng.integers(0, 1 << n, size=shots)
    xeb_noise = calc.calc_linear_xeb(q, noise)
    print(f"uniform-noise control: XEB = {xeb_noise:.4f} (expect ~0.0)")


if __name__ == "__main__":
    main()
