"""quest_tpu.serve (ISSUE 6): the continuous-batching execution service.

Pins the serving contracts from docs/SERVING.md: demux correctness
(N concurrent submits == N sequential library calls, bit-identical),
bucket coalescing under the CompileAuditor (a warmed mixed stream
retraces NOTHING — one compiled program per bucket), loud overflow
rejection, deadline expiry strictly BEFORE dispatch, cancellation,
drain-flushes-partial-bucket, the metrics snapshot schema, and the
satellite fixes that ride along: `measurement.sample` shot-count
bucketing (one compiled program across shots=100/120/128) and
`enable_compile_cache`'s hit/miss tallies as structured counters.
"""

import threading
import time

import numpy as np
import pytest

import jax

from quest_tpu.circuit import Circuit
from quest_tpu.serve import (DeadlineExceeded, RejectedError, ServeEngine,
                             default_buckets, metrics, warmup)

pytestmark = pytest.mark.dtype_agnostic

N = 6


def _circuit_a(n: int = N) -> Circuit:
    c = Circuit(n)
    for q in range(n):
        c.h(q)
    return c.cnot(0, 1).rz(2, 0.25).cz(1, 3).rx(0, 0.5)


def _circuit_b(n: int = N) -> Circuit:
    c = Circuit(n).h(0)
    for q in range(n - 1):
        c.cnot(q, q + 1)
    return c.t(1).ry(3, 0.7)


def _noisy_circuit(n: int = 4) -> Circuit:
    c = Circuit(n).h(0).cnot(0, 1)
    c.depolarising(0, 0.1).damping(1, 0.2)
    return c.ry(2, 0.3).dephasing(2, 0.15)


def _random_states(b: int, n: int = N, seed: int = 0) -> np.ndarray:
    rng = np.random.default_rng(seed)
    s = rng.standard_normal((b, 2, 1 << n)).astype(np.float32)
    return s / np.sqrt((s ** 2).sum(axis=(1, 2), keepdims=True))


def _engine(**kw):
    kw.setdefault("registry", metrics.Registry())
    return ServeEngine(**kw)


# ---------------------------------------------------------------------------
# demux correctness
# ---------------------------------------------------------------------------


def test_apply_demux_matches_sequential_library_calls():
    """N concurrent submits, coalesced into one shared launch, resolve
    to exactly what N sequential library calls through the same bucket
    program produce — the results demux to the right futures,
    bit-identical (padding states are zero and every engine op is a
    linear map, so a state's output never depends on its batch
    neighbours; distinct BUCKETS are distinct XLA programs and may
    differ at the ULP level, which is why the sequential reference
    rides the same bucket)."""
    c = _circuit_a()
    states = _random_states(8)
    fn = c.compiled_batched(8, donate=False)
    seq = [np.asarray(fn(s[None]))[0] for s in states]
    with _engine(max_wait_ms=10_000, max_batch=8) as eng:
        futs = [eng.submit(c, state=s) for s in states]
        outs = [np.asarray(f.result(timeout=120)) for f in futs]
    for got, want in zip(outs, seq):
        np.testing.assert_array_equal(got, want)


def test_apply_demux_from_many_client_threads():
    """Submissions racing from many client threads still demux each
    future to its own request's result (each state carries a distinct
    recognizable payload)."""
    c = _circuit_a()
    states = _random_states(16, seed=3)
    fn = c.compiled_batched(8, donate=False)
    seq = [np.asarray(fn(s[None]))[0] for s in states]
    results: dict = {}
    with _engine(max_wait_ms=10_000, max_batch=8) as eng:
        def client(i):
            results[i] = np.asarray(
                eng.submit(c, state=states[i]).result(timeout=120))
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(states))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
    for i, want in enumerate(seq):
        np.testing.assert_array_equal(results[i], want)


def test_traj_demux_matches_run_batched():
    """A coalesced trajectory request reproduces its standalone
    run_batched result exactly: the per-request key chain
    (split(key, shots)) is preserved through coalescing."""
    from quest_tpu import trajectories as T
    c = _noisy_circuit()
    k1, k2 = jax.random.key(7), jax.random.key(11)
    want1 = T.run_batched(c, k1, 5)
    want2 = T.run_batched(c, k2, 3)
    with _engine(max_wait_ms=20, max_batch=8) as eng:
        f1 = eng.submit(c, shots=5, key=k1)
        f2 = eng.submit(c, shots=3, key=k2)
        p1, d1 = f1.result(timeout=300)
        p2, d2 = f2.result(timeout=300)
    np.testing.assert_array_equal(p1, np.asarray(want1[0]))
    np.testing.assert_array_equal(d1, np.asarray(want1[1]))
    np.testing.assert_array_equal(p2, np.asarray(want2[0]))
    np.testing.assert_array_equal(d2, np.asarray(want2[1]))


def test_traj_mixed_key_styles_never_coalesce():
    """A typed key (jax.random.key) and a raw uint32 PRNGKey are
    different traced inputs whose key data cannot stack into one
    array: the key STYLE rides the queue key, so mixed-style requests
    dispatch separately and each reproduces its standalone run_batched
    result."""
    from quest_tpu import trajectories as T
    c = _noisy_circuit()
    kt, kr = jax.random.key(5), jax.random.PRNGKey(5)
    # 4 shots = exactly the bucket-4 program, ONE launch per style
    # queue (a non-bucket count would cap down and chunk: >1 launch)
    want_t = T.run_batched(c, kt, 4)
    want_r = T.run_batched(c, kr, 4)
    reg = metrics.Registry()
    with _engine(max_wait_ms=10_000, max_batch=8, registry=reg) as eng:
        ft = eng.submit(c, shots=4, key=kt)
        fr = eng.submit(c, shots=4, key=kr)
        eng.drain(timeout_s=300)
        pt, dt = ft.result(timeout=300)
        pr, dr = fr.result(timeout=300)
    assert reg.counter("serve_batches_dispatched").value == 2
    np.testing.assert_array_equal(pt, np.asarray(want_t[0]))
    np.testing.assert_array_equal(dt, np.asarray(want_t[1]))
    np.testing.assert_array_equal(pr, np.asarray(want_r[0]))
    np.testing.assert_array_equal(dr, np.asarray(want_r[1]))


def test_traj_request_larger_than_max_batch_chunks_and_matches():
    """A single request with shots > max_batch chunks through the
    max_batch-bounded bucket program and still demuxes to exactly the
    standalone run_batched result (per-state math and the per-shot key
    chain are batch-size-invariant, pinned per engine)."""
    from quest_tpu import trajectories as T
    c = _noisy_circuit()
    k = jax.random.key(13)
    want_p, want_d = T.run_batched(c, k, 10)
    reg = metrics.Registry()
    with _engine(max_wait_ms=0, max_batch=4, registry=reg) as eng:
        p, d = eng.submit(c, shots=10, key=k).result(timeout=300)
    np.testing.assert_array_equal(p, np.asarray(want_p))
    np.testing.assert_array_equal(d, np.asarray(want_d))
    # 10 slots through the bucket-4 program = 3 launches
    assert reg.snapshot()["counters"]["serve_batches_dispatched"] == 3


def test_traj_observable_matches_run_batched():
    """A trajectory request with `observable=` reduces each chunk on
    device — run_batched's memory contract — and resolves to exactly
    what the standalone run_batched(observable=) call returns."""
    from quest_tpu import trajectories as T

    def z0(planes_b):
        import jax.numpy as jnp
        v = (planes_b[:, 0] ** 2 + planes_b[:, 1] ** 2).reshape(
            planes_b.shape[0], 2, -1)
        return jnp.sum(v[:, 0], axis=1) - jnp.sum(v[:, 1], axis=1)

    c = _noisy_circuit()
    k = jax.random.key(9)
    want_v, want_d = T.run_batched(c, k, 5, observable=z0)
    with _engine(max_wait_ms=5, max_batch=8) as eng:
        got_v, got_d = eng.submit(c, shots=5, key=k,
                                  observable=z0).result(timeout=300)
    # an UNCOALESCED request mirrors run_batched exactly: same capped
    # bucket, same chunk sequence, observable reduces the same padded
    # bucket-shaped chunk with values sliced after — bit-identical
    np.testing.assert_array_equal(got_v, np.asarray(want_v))
    np.testing.assert_array_equal(got_d, np.asarray(want_d))


def test_observable_reduction_applies_per_request():
    """`observable=` reduces each request's planes on the server side:
    the future resolves to the reduced value, never the full planes."""
    c = _circuit_a()

    def z0(planes_b):
        v = (planes_b[:, 0] ** 2 + planes_b[:, 1] ** 2).reshape(
            planes_b.shape[0], 2, -1)
        return np.asarray(v[:, 0].sum(axis=1) - v[:, 1].sum(axis=1))

    s = _random_states(1)[0]
    want = z0(np.asarray(c.compiled_batched(1, donate=False)(s[None])))[0]
    with _engine(max_wait_ms=5) as eng:
        got = eng.submit(c, state=s, observable=z0).result(timeout=120)
    np.testing.assert_allclose(got, want, rtol=1e-6)


# ---------------------------------------------------------------------------
# bucket coalescing: one compiled program per bucket (the acceptance pin)
# ---------------------------------------------------------------------------


def test_mixed_stream_zero_retrace_after_warmup(compile_auditor):
    """THE acceptance gate: a warmed 100-request mixed stream (two
    circuit families interleaved, full buckets) retraces NOTHING — each
    bucket compiled exactly once, every later launch a cache hit."""
    ca, cb = _circuit_a(), _circuit_b()
    states = _random_states(100, seed=5)
    with _engine(max_wait_ms=10_000, max_batch=4) as eng:
        warmup(eng, [ca, cb], buckets=[4])

        def stream():
            futs = []
            for i in range(100):
                c = ca if i % 2 == 0 else cb
                futs.append(eng.submit(c, state=states[i]))
            # 50 requests/family = 12 full bucket-4 launches plus a
            # 2-request tail: drain() flushes the tails NOW (the same
            # padded bucket-2 program in both passes — deterministic
            # shapes, no pad variance between the warm pass and the
            # audited pass) instead of sitting out the wait window
            eng.drain(timeout_s=300)
            for f in futs:
                f.result(timeout=300)

        stream()                      # warms the eager demux ops too
        with compile_auditor as aud:
            stream()
        aud.assert_no_retrace("warmed mixed serve stream")


def test_batches_coalesce_and_occupancy_recorded():
    """Requests arriving within the wait window share launches: 8
    requests at max_batch=8 dispatch as ONE batch with occupancy 1.0."""
    c = _circuit_a()
    reg = metrics.Registry()
    states = _random_states(8, seed=9)
    with _engine(max_wait_ms=10_000, max_batch=8, registry=reg) as eng:
        futs = [eng.submit(c, state=s) for s in states]
        for f in futs:
            f.result(timeout=120)
    snap = reg.snapshot()
    assert snap["counters"]["serve_batches_dispatched"] == 1
    occ = snap["histograms"]["serve_batch_occupancy"]
    assert occ["count"] == 1 and occ["mean"] == pytest.approx(1.0)
    assert snap["counters"]["serve_requests_served"] == 8


def test_no_coalescing_mode_launches_alone():
    """max_wait_ms=0 is the documented no-batching mode (the bench's
    baseline column): every request dispatches as its own launch."""
    c = _circuit_a()
    reg = metrics.Registry()
    states = _random_states(4, seed=13)
    with _engine(max_wait_ms=0, max_batch=8, registry=reg) as eng:
        futs = [eng.submit(c, state=s) for s in states]
        for f in futs:
            f.result(timeout=120)
    assert reg.snapshot()["counters"]["serve_batches_dispatched"] == 4


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------


def test_overflow_rejects_loudly():
    """The bounded queue rejects the overflowing submit with
    RejectedError at submit time — and counts it."""
    c = _circuit_a()
    reg = metrics.Registry()
    s = _random_states(1)[0]
    with _engine(max_wait_ms=60_000, max_queue=2, max_batch=64,
                 registry=reg) as eng:
        f1 = eng.submit(c, state=s)
        f2 = eng.submit(c, state=s)
        with pytest.raises(RejectedError, match="queue is full"):
            eng.submit(c, state=s)
        assert reg.counter("serve_requests_rejected").value == 1
        eng.drain(timeout_s=120)
        assert f1.done() and f2.done()


def test_deadline_expires_before_dispatch():
    """An expired request fails with DeadlineExceeded and never occupies
    a launch: zero batches dispatched for it."""
    c = _circuit_a()
    reg = metrics.Registry()
    s = _random_states(1)[0]
    with _engine(max_wait_ms=60_000, registry=reg) as eng:
        f = eng.submit(c, state=s, deadline_s=0.0)
        with pytest.raises(DeadlineExceeded, match="deadline"):
            f.result(timeout=60)
        assert reg.counter("serve_requests_expired").value == 1
        assert reg.counter("serve_batches_dispatched").value == 0


def test_drain_returns_only_after_expired_futures_complete():
    """drain()'s flush contract covers expired requests too: when it
    returns, their futures are DONE (DeadlineExceeded set), not merely
    removed from the queue — the worker completes them before waking
    the drain waiter."""
    c = _circuit_a()
    s = _random_states(1)[0]
    with _engine(max_wait_ms=60_000) as eng:
        f = eng.submit(c, state=s, deadline_s=0.0)
        eng.drain(timeout_s=60)
        assert f.done()
        assert isinstance(f.exception(timeout=0), DeadlineExceeded)


def test_live_requests_survive_a_neighbours_deadline():
    """One expired request must not take down the live requests queued
    behind the same program key."""
    c = _circuit_a()
    states = _random_states(2, seed=21)
    want = np.asarray(c.compiled_batched(1, donate=False)(
        states[1][None]))[0]
    with _engine(max_wait_ms=150, max_batch=8) as eng:
        dead = eng.submit(c, state=states[0], deadline_s=0.0)
        live = eng.submit(c, state=states[1])
        with pytest.raises(DeadlineExceeded):
            dead.result(timeout=60)
        np.testing.assert_array_equal(
            np.asarray(live.result(timeout=120)), want)


def test_cancel_before_dispatch():
    """Future.cancel() succeeds while queued; the sweep drops the
    request without charging a launch."""
    c = _circuit_a()
    reg = metrics.Registry()
    s = _random_states(1)[0]
    with _engine(max_wait_ms=60_000, registry=reg) as eng:
        f = eng.submit(c, state=s)
        assert f.cancel()
        eng.drain(timeout_s=60)
        assert f.cancelled()
        assert reg.counter("serve_requests_cancelled").value == 1
        assert reg.counter("serve_batches_dispatched").value == 0


def test_drain_flushes_partial_bucket():
    """drain() launches waiting partial buckets immediately instead of
    sitting out the wait window; close() refuses new work afterwards."""
    c = _circuit_a()
    reg = metrics.Registry()
    states = _random_states(3, seed=17)
    eng = _engine(max_wait_ms=600_000, max_batch=8, registry=reg)
    try:
        futs = [eng.submit(c, state=s) for s in states]
        t0 = time.monotonic()
        eng.drain(timeout_s=120)
        assert time.monotonic() - t0 < 590        # not the wait window
        assert all(f.done() for f in futs)
        snap = reg.snapshot()
        assert snap["counters"]["serve_batches_dispatched"] == 1
        # 3 states pad to the bucket-4 program: occupancy 3/4
        occ = snap["histograms"]["serve_batch_occupancy"]
        assert occ["mean"] == pytest.approx(0.75)
    finally:
        eng.close(timeout_s=120)
    # post-close the engine is deterministically rejecting: submit AND
    # drain raise typed RejectedError ("engine closed") instead of
    # racing the dying worker, and close() stays idempotent
    with pytest.raises(RejectedError, match="engine closed"):
        eng.submit(c, state=states[0])
    with pytest.raises(RejectedError, match="engine closed"):
        eng.drain(timeout_s=5)
    eng.close(timeout_s=60)                       # idempotent
    assert eng.state == "closed"
    with pytest.raises(RejectedError, match="engine closed"):
        eng.submit(c, state=states[0])


def test_concurrent_drains_both_flush():
    """drain() is safe to call from several threads at once: each
    drainer holds the flush mode open until its own predicate turns
    true (a drainer COUNT, not a bool a finishing drain could clear
    from under a still-waiting one)."""
    c = _circuit_a()
    states = _random_states(3, seed=27)
    with _engine(max_wait_ms=600_000, max_batch=8) as eng:
        futs = [eng.submit(c, state=s) for s in states]
        errs: list = []

        def do_drain():
            try:
                eng.drain(timeout_s=120)
            except Exception as e:      # noqa: BLE001 - surfaced below
                errs.append(e)

        threads = [threading.Thread(target=do_drain) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errs
        assert all(f.done() for f in futs)


def test_submit_validates_inputs():
    c = _circuit_a()
    s = _random_states(1)[0]
    with _engine(max_wait_ms=0) as eng:
        with pytest.raises(ValueError, match="exactly one"):
            eng.submit(c)
        with pytest.raises(ValueError, match="exactly one"):
            eng.submit(c, state=s, shots=4)
        with pytest.raises(ValueError, match="planes"):
            eng.submit(c, state=s[:, :4])
        with pytest.raises(ValueError, match="shots"):
            eng.submit(c, shots=0)


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_metrics_snapshot_schema():
    """snapshot() is the stable machine-readable feed: counters are
    ints, histograms carry count/mean/p50/p95/p99 floats — the schema
    scripts/serve_stats.py renders and dashboards scrape."""
    c = _circuit_a()
    reg = metrics.Registry()
    with _engine(max_wait_ms=5, registry=reg) as eng:
        eng.submit(c, state=_random_states(1)[0]).result(timeout=120)
    snap = reg.snapshot()
    assert set(snap) == {"counters", "gauges", "histograms"}
    for name, v in snap["counters"].items():
        assert isinstance(name, str) and isinstance(v, int), (name, v)
    for name, v in snap["gauges"].items():
        assert isinstance(name, str) and isinstance(v, float), (name, v)
    for needed in ("serve_requests_submitted", "serve_requests_served",
                   "serve_batches_dispatched"):
        assert snap["counters"][needed] >= 1, snap
    for name, h in snap["histograms"].items():
        assert set(h) == {"count", "mean", "p50", "p95", "p99"}, (name, h)
        assert isinstance(h["count"], int)
        assert all(isinstance(h[k], float)
                   for k in ("mean", "p50", "p95", "p99"))
    for needed in ("serve_batch_occupancy", "serve_queue_wait_s",
                   "serve_e2e_latency_s"):
        assert snap["histograms"][needed]["count"] >= 1, snap
    import json
    json.dumps(snap)                              # JSON-serializable


def test_histogram_percentiles():
    h = metrics.Histogram("t")
    for x in range(1, 101):
        h.observe(float(x))
    s = h.summary()
    assert s["count"] == 100
    assert s["mean"] == pytest.approx(50.5)
    assert s["p50"] == pytest.approx(50.0, abs=1.5)
    assert s["p95"] == pytest.approx(95.0, abs=1.5)
    assert s["p99"] == pytest.approx(99.0, abs=1.5)


def test_compile_cache_counters_are_structured():
    """Satellite: enable_compile_cache's hit/miss tallies are counters
    in the process-wide registry (stderr is derived from them), so the
    numbers are programmatically readable instead of log-scrape-only."""
    from quest_tpu import precision
    # conftest already called enable_compile_cache: the listener is
    # installed and feeds the process-wide registry
    assert precision._cache_listener_installed
    hits, misses = precision._cache_counters()
    snap = metrics.snapshot()
    assert snap["counters"]["compile_cache_hits"] == hits.value
    assert snap["counters"]["compile_cache_misses"] == misses.value
    before = hits.value
    c = Circuit(3).h(0).cnot(0, 1)
    c.compiled_batched(2, donate=False)(_random_states(2, n=3, seed=29))
    assert hits.value + misses.value >= before    # tallies move, not logs


# ---------------------------------------------------------------------------
# warmup
# ---------------------------------------------------------------------------


def test_default_buckets_cover_the_pow2_grid():
    assert default_buckets(8) == (1, 2, 4, 8)
    assert default_buckets(6) == (1, 2, 4, 6)
    assert default_buckets(1) == (1,)


def test_warmup_reports_compile_seconds_and_prevents_cold_start(
        compile_auditor):
    """warmup() pre-compiles the declared (circuit, bucket) grid and
    reports per-program compile_s; the first real request afterwards
    traces nothing."""
    c = _circuit_a()
    with _engine(max_wait_ms=0, max_batch=4) as eng:
        rep = warmup(eng, [c], buckets=[1])
        assert set(rep) == {"programs", "plans", "plan_cache", "total_s"}
        assert rep["programs"] and all(
            isinstance(v, float) and v >= 0 for v in rep["programs"].values())
        s = _random_states(1, seed=23)[0]
        eng.submit(c, state=s).result(timeout=120)    # warm demux ops
        with compile_auditor as aud:
            eng.submit(c, state=s).result(timeout=120)
        aud.assert_no_retrace("warmed serve engine first request")


def test_warmup_noisy_circuit_warms_trajectory_program(compile_auditor):
    c = _noisy_circuit()
    with _engine(max_wait_ms=0, max_batch=4) as eng:
        warmup(eng, [c], buckets=[4])
        f = eng.submit(c, shots=4, key=jax.random.key(3))
        f.result(timeout=300)                         # warm demux ops
        with compile_auditor as aud:
            eng.submit(c, shots=4, key=jax.random.key(3)).result(
                timeout=300)
        aud.assert_no_retrace("warmed trajectory serve request")


def test_warmup_buckets_ride_the_dispatch_bucket_rule(compile_auditor):
    """A declared batch size maps through the SAME bucket rule the
    dispatch side uses: buckets=[3] for a trajectory workload warms
    the CAPPED bucket-2 program (run_batched's largest-that-fits
    rule), not batch_bucket(3)=4 — so a shots=3 request after warmup
    retraces nothing."""
    c = _noisy_circuit()
    with _engine(max_wait_ms=0, max_batch=8) as eng:
        rep = warmup(eng, [c], buckets=[3])
        assert "c0:b2" in rep["programs"], rep     # capped, not b4
        f = eng.submit(c, shots=3, key=jax.random.key(4))
        f.result(timeout=300)                      # warm demux ops
        with compile_auditor as aud:
            eng.submit(c, shots=3, key=jax.random.key(4)).result(
                timeout=300)
        aud.assert_no_retrace("capped-bucket warmed shots=3 request")


def test_warmup_kind_overrides_the_noisiness_heuristic(compile_auditor):
    """The request kind is the CALLER's choice at submit(): shots= is
    valid for a unitary circuit (zero channels), so kind='traj' must
    warm the trajectory program where the heuristic would have warmed
    only the apply one."""
    c = _circuit_a(4)                                  # unitary
    with _engine(max_wait_ms=0, max_batch=4) as eng:
        warmup(eng, [c], buckets=[4], kind="traj")
        eng.submit(c, shots=4, key=jax.random.key(2)).result(timeout=300)
        with compile_auditor as aud:
            eng.submit(c, shots=4, key=jax.random.key(2)).result(
                timeout=300)
        aud.assert_no_retrace("kind='traj' warmed unitary circuit")
    with pytest.raises(ValueError, match="kind"):
        warmup(eng, [c], kind="bogus")


def test_warmup_matches_raw_key_style(compile_auditor):
    """The PRNG key STYLE is part of the queue key (a raw uint32
    PRNGKey is a different traced input than a typed key), so warming a
    raw-key workload means passing warmup a raw key — afterwards the
    first raw-key submit traces nothing."""
    c = _noisy_circuit()
    with _engine(max_wait_ms=0, max_batch=4) as eng:
        warmup(eng, [c], buckets=[4], key=jax.random.PRNGKey(0))
        f = eng.submit(c, shots=4, key=jax.random.PRNGKey(3))
        f.result(timeout=300)                         # warm demux ops
        with compile_auditor as aud:
            eng.submit(c, shots=4, key=jax.random.PRNGKey(3)).result(
                timeout=300)
        aud.assert_no_retrace("warmed raw-key trajectory serve request")


# ---------------------------------------------------------------------------
# satellite: measurement.sample shot-count bucketing
# ---------------------------------------------------------------------------


def test_sample_shot_counts_share_one_compiled_program(compile_auditor):
    """shots=100/120/128 all pad to the 128 bucket inside the traced
    draw and slice after: ONE compiled sampling program across the
    sweep (the serving workload shape), pinned two ways — the jit cache
    grows by exactly one entry, and a warmed rerun retraces nothing."""
    from quest_tpu import measurement as meas
    from quest_tpu import state as st
    from quest_tpu.ops import gates

    q = st.create_qureg(N)
    for t in range(N):
        q = gates.hadamard(q, t)
    key = jax.random.PRNGKey(42)

    cache_size = meas._sample_traced._cache_size
    before = cache_size()
    outs = {s: np.asarray(meas.sample(q, s, key=key))
            for s in (100, 120, 128)}
    assert cache_size() == before + 1, (
        "distinct shot counts in one bucket must share one compiled "
        "sampling program")
    with compile_auditor as aud:
        for s in (100, 120, 128):
            meas.sample(q, s, key=key)
    aud.assert_no_retrace("bucketed sample() shot sweep")

    for s, got in outs.items():
        assert got.shape == (s,)
        assert got.dtype == np.int32
        assert (got >= 0).all() and (got < (1 << N)).all()
    # a shared key + shared bucket means the padded draw is one stream:
    # the shorter counts are prefixes of the longest
    np.testing.assert_array_equal(outs[100], outs[128][:100])
    np.testing.assert_array_equal(outs[120], outs[128][:120])


# ---------------------------------------------------------------------------
# knob registry coverage
# ---------------------------------------------------------------------------


def test_serve_knobs_registered_runtime_scope():
    """Every QUEST_SERVE_* knob is registry-backed (QL004), runtime
    scope (read once at engine construction, never inside a compiled
    path — QL001), layer 'serve', and parses loudly."""
    from quest_tpu.env import KNOBS
    names = {n for n in KNOBS if n.startswith("QUEST_SERVE_")}
    assert names == {"QUEST_SERVE_MAX_WAIT_MS", "QUEST_SERVE_MAX_QUEUE",
                     "QUEST_SERVE_MAX_BATCH", "QUEST_SERVE_RESTART_MAX",
                     "QUEST_SERVE_BREAKER_THRESHOLD",
                     # the fleet layer (ISSUE 12, docs/SERVING.md §fleet)
                     "QUEST_SERVE_REPLICAS", "QUEST_SERVE_TENANT_QUOTA",
                     "QUEST_SERVE_SHED_THRESHOLD",
                     "QUEST_SERVE_PRIORITIES"}
    for n in names:
        k = KNOBS[n]
        assert k.scope == "runtime" and k.layer == "serve", k
        assert k.malformed is not None
        with pytest.raises(ValueError):
            k.parse(k.malformed)


def test_serve_knobs_configure_engine(monkeypatch):
    monkeypatch.setenv("QUEST_SERVE_MAX_WAIT_MS", "0")
    monkeypatch.setenv("QUEST_SERVE_MAX_QUEUE", "1")
    monkeypatch.setenv("QUEST_SERVE_MAX_BATCH", "2")
    eng = _engine()
    try:
        assert eng.max_wait_s == 0.0
        assert eng.max_batch == 2
        assert eng._admission.max_queue == 1
    finally:
        eng.close(timeout_s=60)
