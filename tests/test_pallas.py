"""Pallas fused-segment engine tests (quest_tpu/ops/pallas_engine.py),
run in the Pallas interpreter on CPU: fused execution must match the XLA
per-gate path exactly across every stage type — lane-matmul fusion, row
butterflies, row diagonals, parity phases, controls in every position,
segment breaks, and density duals."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.circuit import Circuit, random_circuit, qft_circuit
from quest_tpu.ops import pallas_engine as PE
from quest_tpu.state import to_dense

N = 10  # 8 rows x 128 lanes — the smallest cleanly-tiled register


def check(circ: Circuit, n=N, density=False, tol=1e-5):
    make = qt.create_density_qureg if density else qt.create_qureg
    q = qt.init_debug_state(make(n if not density else n // 2))
    want = to_dense(circ.apply(q))
    got = to_dense(circ.apply_fused(q, interpret=True))
    # f32 relative precision against the debug state's large amplitudes
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=tol * scale, rtol=0)


def test_lane_gates_fuse():
    c = Circuit(N)
    for q in range(PE.LANE_QUBITS):
        c.h(q)
    c.cnot(0, 1)
    c.z(2)
    c.s(3)
    c.t(4)
    plan = PE.plan_ops(c.ops, N, PE.qmax_for(N))
    # everything merges into ONE lane segment with ONE stage
    assert len(plan.items) == 1
    kind, stages = plan.items[0]
    assert kind == "segment" and len(stages) == 1
    assert isinstance(stages[0], PE.LaneStage)
    check(c)


@pytest.mark.parametrize("q", range(7, N))
def test_row_butterfly(q):
    c = Circuit(N)
    c.h(q)
    c.ry(q, 0.37)
    check(c)


@pytest.mark.parametrize("q", range(7, N))
def test_row_diag(q):
    c = Circuit(N)
    c.s(q)
    c.phase(q, 0.41)
    check(c)


def test_parity_mixed():
    c = Circuit(N)
    c.rz(2, 0.3)
    c.rz(8, 0.5)
    c.multi_rotate_z((1, 5, 9), 0.7)
    check(c)


def test_allones_mixed():
    c = Circuit(N)
    c.cz(0, 1)          # both lanes
    c.cz(2, 9)          # lane target controlled on row qubit
    c.cz(7, 8)          # row target controlled on row qubit
    check(c)


def test_controls_every_position():
    c = Circuit(N)
    c.x(0, 3)            # lane target, lane control
    c.x(1, 8)            # lane target, row control
    c.x(9, 2)            # row target, lane control
    c.x(7, 9)            # row target, row control
    plan = PE.plan_ops(c.ops, N, PE.qmax_for(N))
    # all four fuse into one segment — none falls through to the XLA path
    assert [k for k, _ in plan.items] == ["segment"]
    check(c)


def test_segment_break_on_multi_target_row_gate():
    rng = np.random.default_rng(3)
    z = rng.normal(size=(4, 4)) + 1j * rng.normal(size=(4, 4))
    u, _ = np.linalg.qr(z)
    c = Circuit(N)
    c.h(0)
    c.gate(u, (3, 8))     # row target in a 2q gate -> passthrough
    c.h(9)
    plan = PE.plan_ops(c.ops, N, PE.qmax_for(N))
    kinds = [k for k, _ in plan.items]
    assert "op" in kinds  # the 2q row gate broke the segment
    check(c)


def test_random_circuit_fused_matches():
    c = random_circuit(N, depth=6, seed=11)
    check(c, tol=5e-5)


def test_qft_fused_matches():
    check(qft_circuit(N), tol=5e-5)


def test_density_fused_matches():
    c = Circuit(5)
    c.h(0)
    c.cnot(0, 1)
    c.rz(4, 0.3)
    c.ry(2, 0.8)
    c.cz(1, 3)
    check(c, n=10, density=True, tol=5e-5)


def test_multi_block_grid(monkeypatch):
    """Shrink the row-block cap so the kernel grid has MANY blocks: the
    pid-dependent paths (global row ids for masks/diagonals/parity, the
    BlockSpec index map) must agree with the single-block engine."""
    monkeypatch.setattr(PE, "MAX_ROWS_PER_BLOCK", 8)
    n = 12  # 32 rows -> grid of 4 blocks of 8 rows
    c = Circuit(n)
    c.h(0)
    c.h(8)               # row butterfly within a block
    c.rz(9, 0.3)         # parity on a row bit spanning blocks? (j=2 < 3)
    c.s(7)               # row diagonal
    c.x(1, 9)            # lane target controlled on a row qubit
    c.cz(2, 8)
    plan = PE.plan_ops(c.ops, n, PE.qmax_for(n))
    assert [k for k, _ in plan.items] == ["segment"]
    q = qt.init_debug_state(qt.create_qureg(n))
    want = to_dense(c.apply(q))
    got = to_dense(c.apply_fused(q, interpret=True))
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=1e-5 * scale, rtol=0)


def test_multi_block_grid_high_row_bits(monkeypatch):
    """Gates on row bits ABOVE the block size force rows to grow to cover
    them; bits below still use pid-dependent global ids across blocks."""
    monkeypatch.setattr(PE, "MAX_ROWS_PER_BLOCK", 4)
    n = 12
    c = Circuit(n)
    c.ry(11, 0.7)        # j=4: needs rows=32 -> grid of 1 after growth
    c.ry(8, 0.2)
    q = qt.init_debug_state(qt.create_qureg(n))
    want = to_dense(c.apply(q))
    got = to_dense(c.apply_fused(q, interpret=True))
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=1e-5 * scale, rtol=0)


def test_small_register_falls_back():
    c = Circuit(4)
    c.h(0)
    q = qt.create_qureg(4)
    got = to_dense(c.apply_fused(q, interpret=True))
    want = to_dense(c.apply(qt.create_qureg(4)))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_noisy_circuit_channels():
    """Noise channels compiled into a circuit (superop ops) match the
    eager channel path — on both the XLA and fused engines."""
    from quest_tpu.ops import channels as ch

    c = Circuit(5)
    c.h(0)
    c.cnot(0, 1)
    c.damping(1, 0.2)
    c.depolarising(0, 0.3)
    c.dephasing(2, 0.25)
    c.ry(3, 0.4)

    # eager reference result
    q = qt.init_debug_state(qt.create_density_qureg(5))
    from quest_tpu.ops import gates as G
    e = G.hadamard(q, 0)
    e = G.controlled_not(e, 0, 1)
    e = ch.mix_damping(e, 1, 0.2)
    e = ch.mix_depolarising(e, 0, 0.3)
    e = ch.mix_dephasing(e, 2, 0.25)
    e = G.rotate_y(e, 3, 0.4)
    want = to_dense(e)

    got_xla = to_dense(c.apply(qt.init_debug_state(qt.create_density_qureg(5))))
    got_fused = to_dense(c.apply_fused(
        qt.init_debug_state(qt.create_density_qureg(5)), interpret=True))
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got_xla, want, atol=1e-5 * scale, rtol=0)
    np.testing.assert_allclose(got_fused, want, atol=1e-5 * scale, rtol=0)


def test_channels_need_density_register():
    from quest_tpu.validation import QuESTError
    c = Circuit(3)
    c.damping(0, 0.1)
    with pytest.raises(QuESTError, match="density"):
        c.apply(qt.create_qureg(3))


def test_channels_need_density_register_all_engines():
    from quest_tpu.validation import QuESTError
    from quest_tpu.parallel.mesh import make_amp_mesh
    c = Circuit(12)
    c.damping(0, 0.1)
    with pytest.raises(QuESTError, match="density"):
        c.apply_fused(qt.create_qureg(12), interpret=True)
    mesh = make_amp_mesh(1)
    with pytest.raises(QuESTError, match="density"):
        c.compiled_sharded(12, density=False, mesh=mesh)


def test_channel_builders_validate():
    from quest_tpu.validation import QuESTError
    c = Circuit(3)
    with pytest.raises(QuESTError, match="probability"):
        c.damping(0, 1.2)
    with pytest.raises(QuESTError, match="probability"):
        c.depolarising(0, 0.9)
    with pytest.raises(QuESTError, match="probability"):
        c.dephasing(0, 0.6)
    with pytest.raises(QuESTError):
        c.kraus(0, [np.eye(2) * 0.5])          # non-CPTP
    with pytest.raises(QuESTError):
        c.kraus((0, 1), [np.eye(2)])           # dim mismatch
