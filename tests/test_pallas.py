"""Pallas band-segment engine tests (quest_tpu/ops/pallas_band.py), run
in the Pallas interpreter on CPU: fused execution must match the XLA
per-gate path across every stage type — band-0/1/2 matmuls, diagonal and
parity phases, controls in every position, segment breaks, multi-block
grids, and density duals."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.circuit import Circuit, random_circuit, qft_circuit
from quest_tpu.ops import fusion as F
from quest_tpu.ops import pallas_band as PB
from quest_tpu.state import to_dense

N = 10  # 8 rows x 128 lanes — the smallest cleanly-tiled register


def parts_of(c: Circuit, n=N, scatter_max=PB.SCATTER_MAX):
    items = F.plan(c.ops, n, bands=PB.plan_bands(n))
    return PB.segment_plan(items, n, scatter_max)


def check(circ: Circuit, n=N, density=False, tol=1e-5):
    make = qt.create_density_qureg if density else qt.create_qureg
    q = qt.init_debug_state(make(n if not density else n // 2))
    want = to_dense(circ.apply(q))
    got = to_dense(circ.apply_fused(q, interpret=True))
    # f32 relative precision against the debug state's large amplitudes
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=tol * scale, rtol=0)


def test_band0_gates_fuse_to_one_stage():
    c = Circuit(N)
    for q in range(PB.LANE_QUBITS):
        c.h(q)
    c.cnot(0, 1)
    c.z(2)
    c.s(3)
    c.t(4)
    parts = parts_of(c)
    assert len(parts) == 1
    kind, stages, arrays = parts[0]
    assert kind == "segment" and len(stages) == 1
    assert stages[0].kind == "b0" and len(arrays) == 1
    check(c)


@pytest.mark.parametrize("q", range(7, N))
def test_row_qubit_gates(q):
    c = Circuit(N)
    c.h(q)
    c.ry(q, 0.37)
    parts = parts_of(c)
    assert [p[0] for p in parts] == ["segment"]
    check(c)


@pytest.mark.parametrize("q", range(7, N))
def test_row_diag(q):
    c = Circuit(N)
    c.s(q)
    c.phase(q, 0.41)
    check(c)


def test_parity_mixed():
    c = Circuit(N)
    c.rz(2, 0.3)
    c.rz(8, 0.5)
    c.multi_rotate_z((1, 5, 9), 0.7)
    check(c)


def test_allones_mixed():
    c = Circuit(N)
    c.cz(0, 1)          # both lanes
    c.cz(2, 9)          # lane target controlled on row qubit
    c.cz(7, 8)          # row target controlled on row qubit
    check(c)


def test_controls_every_position():
    c = Circuit(N)
    c.x(0, 3)            # lane target, lane control
    c.x(1, 8)            # lane target, row control
    c.x(9, 2)            # row target, lane control
    c.x(7, 9)            # row target, row control
    parts = parts_of(c)
    # all four fuse — none falls through to the XLA path
    assert [p[0] for p in parts] == ["segment"]
    check(c)


def test_cross_band_2q_fuses_via_kak():
    rng = np.random.default_rng(3)
    z = rng.normal(size=(4, 4)) + 1j * rng.normal(size=(4, 4))
    u, _ = np.linalg.qr(z)
    c = Circuit(N)
    c.h(0)
    c.gate(u, (3, 8))     # cross-band 2q unitary -> KAK, stays fused
    c.h(9)
    parts = parts_of(c)
    assert [p[0] for p in parts] == ["segment"]
    check(c, tol=5e-5)


def test_cross_band_superop_fuses_as_pair_stage():
    # 6q density register: superop targets (1, 7) straddle bands; the
    # non-unitary superoperator fuses as a PairStage (lane op, sliced
    # sublane qubit)
    c = Circuit(6)
    c.damping(1, 0.2)
    items = F.plan(c._flat_ops(12, True), 12, bands=PB.plan_bands(12))
    parts = PB.segment_plan(items, 12)
    assert [p[0] for p in parts] == ["segment"]
    kinds = [type(s).__name__ for s in parts[0][1]]
    assert "PairStage" in kinds


@pytest.mark.parametrize("nq", [6, 8])
def test_density_channels_fuse_at_scale(nq):
    """Channels on registers whose doubled targets straddle bands run
    through PairStages (all three op kinds: lane / b1 / scattered) and
    match the per-gate engine."""
    c = Circuit(nq)
    c.h(0)
    c.cnot(0, nq - 1)
    c.damping(1, 0.2)         # lane-op pair
    c.damping(nq - 1, 0.3)    # nq=8: targets (7,15) -> b1-op pair
    c.depolarising(nq - 2, 0.1)
    c.dephasing(0, 0.15)
    q1 = qt.init_debug_state(qt.create_density_qureg(nq))
    want = to_dense(c.apply(q1))
    got = to_dense(c.apply_fused(
        qt.init_debug_state(qt.create_density_qureg(nq)), interpret=True))
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=5e-5 * scale, rtol=0)


def test_scat_scat_pair_stage():
    """A 2q matrix with both qubits on scattered axes of DIFFERENT high
    bands (the 'sc' op kind PairStage): numerics vs the per-gate
    engine."""
    rng = np.random.default_rng(9)
    n = 23
    m = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    # non-unitary so the KAK path cannot take it
    m = m @ np.diag([1.0, 0.8, 0.9, 1.0])
    c = Circuit(n)
    c.h(0)
    c._add("matrix", (14, 21), m.astype(np.complex128))
    items = F.plan(c.ops, n, bands=PB.plan_bands(n))
    parts = PB.segment_plan(items, n)
    assert [p[0] for p in parts] == ["segment"]
    kinds = [type(s).__name__ for s in parts[0][1]]
    assert "PairStage" in kinds
    import jax.numpy as jnp
    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 3].set(1.0)
    got = np.asarray(c.compiled_fused(n, density=False, donate=False,
                                      interpret=True)(amps)).reshape(2, -1)
    want = np.asarray(c.compiled(n, density=False, donate=False)(amps))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=0)


def test_same_high_band_2q_composes_to_scb():
    """A 2q matrix whose qubits share one high band composes into that
    band's scb operator — no PairStage, no passthrough."""
    rng = np.random.default_rng(9)
    n = 17
    m = rng.standard_normal((4, 4)) + 1j * rng.standard_normal((4, 4))
    m = m @ np.diag([1.0, 0.8, 0.9, 1.0])  # non-unitary: no KAK escape
    c = Circuit(n)
    c.h(0)
    c._add("matrix", (14, 16), m.astype(np.complex128))
    parts = parts_of(c, n=n)
    assert [p[0] for p in parts] == ["segment"]
    kinds = [s.kind for s in parts[0][1]]
    assert kinds == ["b0", "scb"]
    import jax.numpy as jnp
    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 3].set(1.0)
    got = np.asarray(c.compiled_fused(n, density=False, donate=False,
                                      interpret=True)(amps)).reshape(2, -1)
    want = np.asarray(c.compiled(n, density=False, donate=False)(amps))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=0)


def test_small_register_superop_fuses():
    # 4q density register: superop targets (1, 5) sit in ONE band, so the
    # (non-unitary) superoperator embeds straight into the band operator
    c = Circuit(4)
    c.damping(1, 0.2)
    items = F.plan(c._flat_ops(8, True), 8, bands=PB.plan_bands(8))
    parts = PB.segment_plan(items, 8)
    assert [p[0] for p in parts] == ["segment"]


def test_scattered_qubits_fuse():
    """Gates on high qubits compose into ONE scb stage per high band —
    one MXU dot over the band's merged scattered axes, no passthrough."""
    n = 16
    c = Circuit(n)
    c.h(0)
    for q in (14, 15):
        c.ry(q, 0.1 * q)      # both in the (14, 2) high band
    parts = parts_of(c, n=n)
    assert [p[0] for p in parts] == ["segment"]
    kinds = [s.kind for s in parts[0][1]]
    assert kinds == ["b0", "scb"]
    assert parts[0][1][1].dim == 4
    check(c, n=n)


@pytest.mark.slow          # ~9 s — tier-1 budget discipline; the
                           # sparse-high-band SCB test keeps
                           # scattered-bit coverage in tier-1
def test_full_high_band_scb():
    """A whole 7-qubit high band (d=128 scb) plus gates in every other
    band and a cross-band CZ — numerics through the interpreter. The
    rotation layer composes to ONE wide dot: splitting a factorizable
    band op into narrow per-factor dots measured 3.8x SLOWER on chip
    (161 vs 42.6 ms/pass at 30q — a small-M dot idles most of the MXU,
    so narrow-stage time is ~flat in d), so the planner must keep the
    composed d=128 stage."""
    n = 23
    c = Circuit(n)
    for q in range(14, 21):
        c.ry(q, 0.1 * (q - 13))
    c.cz(13, 14)              # crosses the sublane/high-band split
    c.h(2)
    c.ry(9, 0.3)
    c.x(21, 15)               # top-band target, scb-band control — its
    # band's 2 scat bits exceed the budget next to the d=128 scb's 7, so
    # a second segment starts (still no XLA passthrough)
    parts = parts_of(c, n=n)
    assert [p[0] for p in parts] == ["segment", "segment"]
    kinds = [s.kind for s in parts[0][1] if hasattr(s, "kind")]
    assert "scb" in kinds
    assert any(getattr(s, "dim", 0) == 128 and s.kind == "scb"
               for s in parts[0][1])
    check(c, n=n)


def test_oversized_band_passthrough_under_small_budget():
    """A high-band operator spanning more scattered bits than the budget
    allows even in a fresh segment must fall back to an XLA passthrough,
    never silently over-claim axes. (A lone h(14) no longer triggers
    this — sub-band extraction shrinks it to one scattered bit.)"""
    n = 23
    c = Circuit(n)
    c.h(14)
    c.h(20)                   # composed span covers the whole (14, 7) band
    parts = parts_of(c, n=n, scatter_max=5)
    assert [p[0] for p in parts] == ["xla"]
    assert isinstance(parts[0][1], F.BandOp) and parts[0][1].w == 7


def test_sparse_high_band_extracts_sub_band():
    """A lone high-qubit gate costs one scattered-bit butterfly, and a
    2-qubit-support run costs a d=4 sub-band dot — never the padded
    full-band contraction."""
    n = 23
    c = Circuit(n)
    c.h(16)
    parts = parts_of(c, n=n)
    (st,) = parts[0][1]
    assert st.kind == "sc" and st.bit == 9 and st.dim == 2
    check(c, n=n)

    c2 = Circuit(n)
    c2.ry(15, 0.3)
    c2.ry(16, 0.7)
    c2.cz(15, 16)
    parts = parts_of(c2, n=n)
    (st,) = parts[0][1]
    assert st.kind == "scb" and st.bit == 8 and st.dim == 4
    check(c2, n=n)


def test_scatter_overflow_splits_segment():
    """Two high bands whose scattered axes exceed the scatter budget get
    separate segments; numerics still match."""
    n = 23
    c = Circuit(n)
    c.h(14)
    c.h(20)                   # span = the whole (14, 7) band: 7 scat bits
    c.h(21)                   # band (21, 2): 1 more
    parts = parts_of(c, n=n, scatter_max=7)
    assert [p[0] for p in parts] == ["segment", "segment"]
    # numerics at the tiny scatter budget
    import jax.numpy as jnp
    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
    out = amps.reshape(2, -1, PB.LANES)
    for part in parts:
        out = PB.compile_segment(part[1], n, interpret=True)(
            out, part[2])
    want = c.compiled(n, density=False, donate=False)(amps)
    np.testing.assert_allclose(np.asarray(out.reshape(2, -1)),
                               np.asarray(want), atol=1e-5, rtol=0)


def test_random_circuit_fused_matches():
    c = random_circuit(N, depth=6, seed=11)
    check(c, tol=5e-5)


def test_qft_fused_matches():
    check(qft_circuit(N), tol=5e-5)


def test_density_fused_matches():
    c = Circuit(5)
    c.h(0)
    c.cnot(0, 1)
    c.rz(4, 0.3)
    c.ry(2, 0.8)
    c.cz(1, 3)
    check(c, n=10, density=True, tol=5e-5)


def test_multi_block_grid():
    """Small block size -> many grid blocks: pid-dependent paths (global
    row ids for masks/diagonals/parity, BlockSpec index maps) must agree
    with the XLA engine."""
    n = 17  # rows_eff_bits=7 -> grid over 8 blocks of 128 rows
    c = Circuit(n)
    c.h(0)
    c.h(8)               # sublane butterfly within a block
    c.rz(16, 0.3)        # parity on a grid row bit
    c.s(7)
    c.x(1, 16)           # lane target controlled on a GRID row qubit
    c.cz(2, 15)          # phase with a grid row bit
    items = F.plan(c.ops, n, bands=PB.plan_bands(n))
    parts = PB.segment_plan(items, n)
    assert [p[0] for p in parts] == ["segment"]
    import jax.numpy as jnp
    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
    for part in parts:
        amps = PB.compile_segment(part[1], n, rows_eff_bits=7,
                                  interpret=True)(amps, part[2])
    want = c.compiled(n, density=False, donate=False)(
        jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0))
    np.testing.assert_allclose(np.asarray(amps.reshape(2, -1)),
                               np.asarray(want), atol=1e-5, rtol=0)


def test_small_register_falls_back():
    c = Circuit(4)
    c.h(0)
    q = qt.create_qureg(4)
    got = to_dense(c.apply_fused(q, interpret=True))
    want = to_dense(c.apply(qt.create_qureg(4)))
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_noisy_circuit_channels():
    """Noise channels compiled into a circuit (superop ops) match the
    eager channel path — on both the XLA and fused engines."""
    from quest_tpu.ops import channels as ch

    c = Circuit(5)
    c.h(0)
    c.cnot(0, 1)
    c.damping(1, 0.2)
    c.depolarising(0, 0.3)
    c.dephasing(2, 0.25)
    c.ry(3, 0.4)

    # eager reference result
    q = qt.init_debug_state(qt.create_density_qureg(5))
    from quest_tpu.ops import gates as G
    e = G.hadamard(q, 0)
    e = G.controlled_not(e, 0, 1)
    e = ch.mix_damping(e, 1, 0.2)
    e = ch.mix_depolarising(e, 0, 0.3)
    e = ch.mix_dephasing(e, 2, 0.25)
    e = G.rotate_y(e, 3, 0.4)
    want = to_dense(e)

    got_xla = to_dense(c.apply(qt.init_debug_state(qt.create_density_qureg(5))))
    got_fused = to_dense(c.apply_fused(
        qt.init_debug_state(qt.create_density_qureg(5)), interpret=True))
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got_xla, want, atol=1e-5 * scale, rtol=0)
    np.testing.assert_allclose(got_fused, want, atol=1e-5 * scale, rtol=0)


def test_channels_need_density_register():
    from quest_tpu.validation import QuESTError
    c = Circuit(3)
    c.damping(0, 0.1)
    with pytest.raises(QuESTError, match="density"):
        c.apply(qt.create_qureg(3))


def test_channels_need_density_register_all_engines():
    from quest_tpu.validation import QuESTError
    from quest_tpu.parallel.mesh import make_amp_mesh
    c = Circuit(12)
    c.damping(0, 0.1)
    with pytest.raises(QuESTError, match="density"):
        c.apply_fused(qt.create_qureg(12), interpret=True)
    with pytest.raises(QuESTError, match="density"):
        c.apply_banded(qt.create_qureg(12))
    mesh = make_amp_mesh(1)
    with pytest.raises(QuESTError, match="density"):
        c.compiled_sharded(12, density=False, mesh=mesh)


def test_channel_builders_validate():
    from quest_tpu.validation import QuESTError
    c = Circuit(3)
    with pytest.raises(QuESTError, match="[Pp]robabilit"):
        c.damping(0, 1.2)
    with pytest.raises(QuESTError, match="[Pp]robabilit"):
        c.depolarising(0, 0.9)
    with pytest.raises(QuESTError, match="[Pp]robabilit"):
        c.dephasing(0, 0.6)
    with pytest.raises(QuESTError):
        c.kraus(0, [np.eye(2) * 0.5])          # non-CPTP
    with pytest.raises(QuESTError):
        c.kraus((0, 1), [np.eye(2)])           # dim mismatch


@pytest.mark.slow          # ~18 s on this host — tier-1 budget
                           # discipline (runs in the full CI suite step)
def test_deep_circuit_segment_stage_cap():
    """Deep circuits split at MAX_SEGMENT_STAGES so kernel operand blocks
    cannot accumulate without bound in VMEM; numerics unchanged."""
    rng = np.random.default_rng(7)
    n, depth = 12, 60
    c = Circuit(n)
    for d in range(depth):
        for q in range(n):
            c.rx(q, float(rng.uniform(0, 2 * np.pi)))
        for q in range(d % 2, n - 1, 2):
            c.cz(q, q + 1)
    parts = parts_of(c, n=n)
    segs = [p for p in parts if p[0] == "segment"]
    assert len(segs) >= 2
    assert all(len(s[1]) <= PB.MAX_SEGMENT_STAGES + 1 for s in segs)
    check(c, n=n, tol=5e-5)


class TestMatmulPrecisionTiers:
    """The session precision knob on the fused engine: HIGHEST (default,
    6-pass f32-exact) and HIGH (manual double-bf16 3-pass inside the
    kernel — Mosaic lowers only DEFAULT/HIGHEST, so _mxu_dot_general
    splits the operands itself at half the MXU passes, ~5e-6 relative
    error per dot measured vs an f64 oracle)."""

    def _run(self, tier):
        from quest_tpu import precision as P
        rng = np.random.default_rng(3)
        n = 12
        c = Circuit(n)
        for d in range(3):
            for q in range(n):
                c.rx(q, float(rng.uniform(0, 2 * np.pi)))
            for q in range(d % 2, n - 1, 2):
                c.cz(q, q + 1)
        old = P.matmul_precision()
        P.set_matmul_precision(tier)
        try:
            q = qt.init_debug_state(qt.create_qureg(n))
            return to_dense(c.apply_fused(q, interpret=True))
        finally:
            P.set_matmul_precision(old)

    def test_high_tier_accuracy_envelope(self):
        """HIGH must stay within ~1e-4 of the HIGHEST (f32-exact) result
        on a depth-3 mixed circuit (per-dot 5e-6, accumulated) — far
        inside the ~1e-3 drift single-pass bf16 (DEFAULT) shows."""
        got = self._run("high")
        want = self._run("highest")
        scale = float(np.max(np.abs(want)))   # debug-state amps are large
        err = float(np.max(np.abs(got - want))) / scale
        assert err < 1e-4, f"HIGH tier drifted {err} (relative) from HIGHEST"
        # the relative norm must be preserved to the same envelope
        n_got = float(np.sum(np.abs(got.astype(np.complex128)) ** 2))
        n_want = float(np.sum(np.abs(want.astype(np.complex128)) ** 2))
        assert abs(n_got / n_want - 1.0) < 1e-4, (n_got, n_want)

    def test_high_tier_actually_engages(self):
        """The 3-pass path must produce DIFFERENT bits than HIGHEST:
        a silent clamp back to 6-pass would make the knob a no-op (the
        pre-r3 kernel did exactly that)."""
        got = self._run("high")
        want = self._run("highest")
        assert float(np.max(np.abs(got - want))) > 0.0


def test_explain_reports_schedule_without_compiling():
    """Circuit.explain: the fused schedule as text — segments, stage
    mixes, pass/kernel totals — with no jit/compile side effects."""
    rng = np.random.default_rng(42)
    c = Circuit(16)
    for i in range(16):
        c.rx(1 + i % 15, float(rng.uniform(0, 2 * np.pi)))
    text = c.explain()
    assert "kernel segment" in text and "mat:b0" in text
    assert "1 segments, 1 distinct kernels" in text
    assert not c._compiled            # planning only, nothing compiled
    # the CPU-fallback sweep plan rides along when the native host
    # library is available (review r5: plan_summary was test-only)
    from quest_tpu import host as H
    if H._load() is not None:
        assert "cpu fallback host engine:" in text

    # the scheduler composes QFT-12's cross-band phases into ONE
    # segment (was >= 2 pre-scheduler); its stats line rides along
    qft_text = qft_circuit(12).explain()
    assert qft_text.count("kernel segment") >= 1
    assert "scheduler: on" in qft_text and "multiphase" in qft_text

    small = Circuit(6)
    small.h(0)
    assert "banded XLA engine" in small.explain()

    dyn = Circuit(12)
    dyn.h(0)
    dyn.measure(0)
    with pytest.raises(Exception):
        dyn.explain()


def test_explain_estimate_brackets_measurements():
    """The steady-state estimate line exists and its range is anchored
    to the measured cost model: the 30q bench application's range must
    bracket the on-chip measurement (79.9 ms, benchmarks/
    measured_tpu.json), scaled by state size."""
    import re

    rng = np.random.default_rng(42)
    c = Circuit(30)
    for i in range(16):
        c.rx(1 + i % 29, float(rng.uniform(0, 2 * np.pi)))
    text = c.explain()
    m = re.search(r"estimated steady state on one v5e: "
                  r"([0-9.]+)-([0-9.]+) ms", text)
    assert m, text
    lo, hi = float(m.group(1)), float(m.group(2))
    assert lo <= 79.9 <= hi * 1.1, (lo, hi)
    # the estimate scales with state size: 2x amps -> ~2x time
    c29 = Circuit(29)
    for i in range(16):
        c29.rx(1 + i % 28, float(rng.uniform(0, 2 * np.pi)))
    m29 = re.search(r"([0-9.]+)-([0-9.]+) ms", c29.explain())
    assert abs(float(m29.group(1)) * 2 - lo) < 0.2 * lo


def test_cost_model_table_is_chip_keyed():
    """VERDICT r4 item 7: the estimate's constants are per-generation
    with named provenance — v5e measured, v5p projected (datasheet x
    measured derate), unknown chips fall back to v5e WITH matched=False
    so explain() cautions instead of silently mis-scaling."""
    from quest_tpu.circuit import _COST_MODELS, _cost_model_for, _estimate_ms
    from quest_tpu.ops import fusion as F
    from quest_tpu.ops import pallas_band as PB

    v5e, ok_e = _cost_model_for("TPU v5e lite")
    v5p, ok_p = _cost_model_for("TPU v5p")
    unk, ok_u = _cost_model_for("TPU v7x")
    assert ok_e and ok_p and not ok_u
    assert v5e is _COST_MODELS["v5e"] and unk is _COST_MODELS["v5e"]
    assert "MEASURED" in v5e["provenance"]
    assert "PROJECTED" in v5p["provenance"]
    # a faster chip projects faster on the same plan
    rng = np.random.default_rng(1)
    c = Circuit(30)
    for i in range(16):
        c.rx(1 + i % 29, float(rng.uniform(0, 2 * np.pi)))
    parts = PB.segment_plan(
        __import__("quest_tpu.ops.fusion", fromlist=["plan"]).plan(
            c._flat_ops(30, False), 30, bands=PB.plan_bands(30)), 30)
    lo_e, hi_e = _estimate_ms(parts, 30, v5e)
    lo_p, hi_p = _estimate_ms(parts, 30, v5p)
    assert lo_p < lo_e and hi_p < hi_e


def test_stage_report_runs_and_audits():
    """profiling.stage_report (the shipped form of the KERNELS.md
    probes) runs end-to-end on the attached backend: one record per
    stage family with measured + model figures."""
    import io
    from quest_tpu import profiling

    buf = io.StringIO()
    rec = profiling.stage_report(n=12, reps=1, out=buf)
    txt = buf.getvalue()
    assert "phase (DMA floor)" in rec and "b0" in rec and "b1" in rec
    for r in rec.values():
        assert r["measured_ms"] >= 0 and r["model_hi_ms"] >= r["model_lo_ms"]
    assert "DMA floor" in txt
    # CPU host: the caution must be loud
    import jax as _jax
    if _jax.devices()[0].platform not in ("tpu", "axon"):
        assert "INTERPRETER" in txt


def test_scan_partition_groups_identical_structure_runs():
    """QUEST_FUSED_SCAN's grouping logic (circuit._scan_partition),
    previously untestable inline code with zero CI coverage (VERDICT r4
    weak item 4): runs >= scan_min of identical-structure segments
    group; shorter runs and XLA passthroughs stay singletons."""
    from quest_tpu.circuit import _scan_partition

    sA = ("stageA",)
    sB = ("stageB",)
    parts = [("segment", sA, [1]), ("segment", sA, [2]),
             ("segment", sA, [3]), ("sharded-ish", None),
             ("segment", sB, [4]), ("segment", sB, [5]),
             ("segment", sA, [6])]
    out = _scan_partition(parts, scan_min=3)
    assert out[0] == ("scan", sA, [[1], [2], [3]])
    assert out[1] == ("one", parts[3])
    # the two-long B run is below scan_min
    assert out[2] == ("one", parts[4]) and out[3] == ("one", parts[5])
    assert out[4] == ("one", parts[6])
    # disabled grouping passes everything through
    assert all(g[0] == "one" for g in _scan_partition(parts, 0))


def test_scan_applier_matches_sequential_with_stub_segment():
    """make_scan_applier's operand stacking + lax.scan semantics equal
    sequential application — verified with a STUB segment (plain jnp
    matmul apply), since the real kernel's scan execution is chip-only."""
    import jax
    import jax.numpy as jnp
    from quest_tpu.circuit import make_scan_applier

    rng = np.random.default_rng(0)
    mats = [rng.normal(size=(4, 4)).astype(np.float32) for _ in range(5)]
    vecs = [rng.normal(size=(4,)).astype(np.float32) for _ in range(5)]

    def stub_seg(amps, arrays):
        m, v = arrays
        return amps @ m.T + v

    apply = make_scan_applier(stub_seg, [[m, v] for m, v in
                                         zip(mats, vecs)])
    x0 = rng.normal(size=(3, 4)).astype(np.float32)
    got = np.asarray(jax.jit(apply)(jnp.asarray(x0)))
    want = x0
    for m, v in zip(mats, vecs):
        want = want @ m.T + v
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5)


@pytest.mark.slow          # ~11 s — tier-1 budget discipline (runs in
                           # the full CI suite step)
def test_apply_matrix_rows_matches_flat():
    """apply_matrix on the (2, rows, 128) kernel layout must match the
    flat path across target/control placements. The shaped path exists
    because the flat round-trip at capacity costs a full-state layout
    copy (the 8 GiB copy_bitcast that OOMed the 30q density bench)."""
    import jax.numpy as jnp
    from quest_tpu.ops import apply as A
    n = 12
    rng = np.random.default_rng(7)
    amps = rng.standard_normal((2, 1 << n)).astype(np.float32)
    amps3 = jnp.asarray(amps.reshape(2, -1, 128))
    cases = [
        ((0, 8, 9, 11), (), ()),           # low + high targets (laneblock)
        ((8, 10), (), ()),                 # all-row targets
        ((7, 11), (3,), (1,)),             # row targets, lane control
        ((9,), (8, 2), (0, 1)),            # row target, mixed controls
        ((1, 3), (9,), (1,)),              # lane targets, row control
        ((8, 9, 10, 11), (), ()),          # k=4 all-row
        ((0, 5, 8, 11), (2, 10), (1, 0)),  # mixed everything
        ((4, 7), (), ()),                  # straddling lane/row boundary
    ]
    for targets, controls, cstates in cases:
        k = len(targets)
        m = (rng.standard_normal((2, 1 << k, 1 << k)) * 0.5
             ).astype(np.float32)
        pair = (m[0], m[1])                # non-unitary on purpose
        want = A.apply_matrix(jnp.asarray(amps), n, pair, targets,
                              controls, cstates)
        got = A.apply_matrix_rows(amps3, n, pair, targets, controls,
                                  cstates)
        assert got.shape == amps3.shape, (targets, controls)
        np.testing.assert_allclose(
            np.asarray(got).reshape(2, -1), np.asarray(want),
            atol=2e-5, rtol=0, err_msg=f"{targets} {controls} {cstates}")


def test_apply_matrix_rows_traced_operand():
    """The shaped path must accept traced operands (dynamic gate
    parameters) on both the laneblock and row flip-form routes."""
    import jax
    import jax.numpy as jnp
    from quest_tpu.ops import apply as A
    n = 11
    rng = np.random.default_rng(3)
    amps = rng.standard_normal((2, 1 << n)).astype(np.float32)
    amps3 = jnp.asarray(amps.reshape(2, -1, 128))
    for targets in [(0, 9), (8, 10)]:
        m = (rng.standard_normal((2, 4, 4)) * 0.5).astype(np.float32)

        def f(a3, mm):
            return A.apply_matrix_rows(a3, n, (mm[0], mm[1]), targets)

        got = jax.jit(f)(amps3, jnp.asarray(m))
        want = A.apply_matrix(jnp.asarray(amps), n, (m[0], m[1]), targets)
        np.testing.assert_allclose(np.asarray(got).reshape(2, -1),
                                   np.asarray(want), atol=2e-5, rtol=0)


def test_matrix_passthrough_runs_shaped():
    """A scattered multi-target unitary no stage can host must fall
    through as a matrix passthrough AND still match the per-gate engine
    — through apply_matrix_rows, never a flat intermediate."""
    rng = np.random.default_rng(11)
    z = rng.normal(size=(8, 8)) + 1j * rng.normal(size=(8, 8))
    u, _ = np.linalg.qr(z)
    c = Circuit(N)
    c.h(0)
    c.gate(u, (0, 5, 9))
    c.ry(8, 0.3)
    parts = parts_of(c)
    assert any(p[0] != "segment" for p in parts)   # the passthrough
    check(c, tol=5e-5)


def test_density_channel_passthrough_at_bench_shape():
    """The bench's capacity scenario in miniature: a 2q Kraus map whose
    doubled-register superop hits 4 scattered targets (0, nd-1, nd,
    2nd-1) — the exact op that was OOMing nd=15 on chip — must ride the
    shaped passthrough and match the per-gate engine on a density
    register."""
    from quest_tpu.ops import matrices as M
    nd = 8
    rng = np.random.default_rng(5)
    c = Circuit(nd)
    for q in range(nd):
        c.rx(q, float(rng.uniform(0, 2 * np.pi)))
    p = 0.15
    paulis = [np.eye(2), M.PAULI_X, M.PAULI_Y, M.PAULI_Z]
    ops2 = []
    for i, a in enumerate(paulis):
        for j, b in enumerate(paulis):
            w = np.sqrt(1 - 15 * p / 16) if i == j == 0 else np.sqrt(p / 16)
            ops2.append(w * np.kron(b, a))
    c.kraus((0, nd - 1), ops2)
    items = F.plan(c._flat_ops(2 * nd, True), 2 * nd,
                   bands=PB.plan_bands(2 * nd))
    parts = PB.segment_plan(items, 2 * nd)
    kinds = [getattr(p[1].op, "kind", "?") for p in parts
             if p[0] != "segment"]
    assert "matrix" in kinds                      # the 4-target superop
    check(c, n=2 * nd, density=True, tol=5e-5)


def test_laneblock_chunked_sweep_matches():
    """The capacity-mode chunked sweep (fori_loop over a free segment
    axis, in-place chunk updates) must agree exactly with the
    whole-plane sweep and the flat engine — including high controls and
    zero-coefficient skipping."""
    import jax.numpy as jnp
    from quest_tpu.ops import apply as A
    n = 13
    rng = np.random.default_rng(21)
    amps = rng.standard_normal((2, 1 << n)).astype(np.float32)
    st2 = jnp.asarray(amps.reshape(2, -1, 128))
    cases = [
        ((0, 12), (), ()),              # free interior axis q7..q11
        ((2, 8, 12), (), ()),
        ((1, 12), (9,), (0,)),          # high control rides the mask
    ]
    for targets, controls, cstates in cases:
        k = len(targets)
        m = (rng.standard_normal((2, 1 << k, 1 << k)) * 0.5
             ).astype(np.float32)
        pair = (m[0], m[1])
        whole = A._laneblock_core(st2, n, pair, targets,
                                  controls, cstates, chunks=1)
        chunked = A._laneblock_core(st2, n, pair,
                                    targets, controls, cstates, chunks=4)
        np.testing.assert_allclose(np.asarray(chunked),
                                   np.asarray(whole), atol=1e-6, rtol=0,
                                   err_msg=f"{targets} {controls}")
        want = A.apply_matrix(jnp.asarray(amps), n, pair, targets,
                              controls, cstates)
        np.testing.assert_allclose(
            np.asarray(chunked).reshape(2, -1), np.asarray(want),
            atol=2e-5, rtol=0)
