"""Many-target gate tests: the gather+matmul path that replaces the
unrolled butterfly above 4 targets (quest_tpu/ops/apply.py
_apply_matrix_matmul; the analogue of the reference's general
gather/matvec/scatter kernel, QuEST_cpu.c:1814-1898)."""

import numpy as np
import pytest

import quest_tpu as qt
from quest_tpu.ops import channels as ch
from quest_tpu.ops import gates as G
from quest_tpu.state import init_state_from_amps, to_dense

from . import oracle
from .test_calculations import load_dm


@pytest.mark.parametrize("targets", [(0, 1, 2, 3, 4), (0, 2, 3, 5, 6),
                                     (6, 4, 3, 2, 0)])
def test_five_target_unitary(targets, rng):
    n = 7
    u = oracle.random_unitary(5, rng)
    v = oracle.random_statevector(n, rng)
    q = init_state_from_amps(qt.create_qureg(n, dtype=np.complex128),
                             v.real, v.imag)
    out = to_dense(G.multi_qubit_unitary(q, list(targets), u))
    want = oracle.apply_to_vector(v, n, u, list(targets))
    np.testing.assert_allclose(out, want, atol=1e-10)


def test_controlled_five_target_unitary(rng):
    n = 8
    u = oracle.random_unitary(5, rng)
    targets = [0, 2, 4, 6, 7]
    controls = [1, 5]
    v = oracle.random_statevector(n, rng)
    q = init_state_from_amps(qt.create_qureg(n, dtype=np.complex128),
                             v.real, v.imag)
    out = to_dense(G.multi_controlled_multi_qubit_unitary(
        q, controls, targets, u))
    want = oracle.apply_to_vector(v, n, u, targets, controls)
    np.testing.assert_allclose(out, want, atol=1e-10)


def test_six_target_unitary(rng):
    n = 6
    u = oracle.random_unitary(6, rng)
    v = oracle.random_statevector(n, rng)
    q = init_state_from_amps(qt.create_qureg(n, dtype=np.complex128),
                             v.real, v.imag)
    out = to_dense(G.multi_qubit_unitary(q, list(range(6)), u))
    np.testing.assert_allclose(out, u @ v, atol=1e-10)


def test_three_qubit_kraus_map(rng):
    """3 Kraus targets -> a 6-target superoperator apply."""
    rho = oracle.random_density(4, rng)
    ops = oracle.random_kraus_map(3, 4, rng)
    out = to_dense(ch.mix_multi_qubit_kraus_map(load_dm(rho), [0, 1, 3], ops))
    want = oracle.apply_kraus_to_density(rho, 4, ops, [0, 1, 3])
    np.testing.assert_allclose(out, want, atol=1e-9)


def test_five_target_density_dual(rng):
    """Density register: U rho U+ with a 5-target U exercises the matmul
    path twice (row and column spaces)."""
    rho = oracle.random_density(5, rng)
    u = oracle.random_unitary(5, rng)
    out = to_dense(G.multi_qubit_unitary(load_dm(rho), list(range(5)), u))
    np.testing.assert_allclose(out, u @ rho @ u.conj().T, atol=1e-9)


@pytest.mark.slow
def test_laneblock_path_matches_oracle():
    """apply_matrix routes big-register gates touching lane qubits through
    the lane-block formulation (minor dim stays 128 on TPU — tiny-axis
    views padded 64x and OOMed 24-state-qubit channels). Fuzz it against
    the oracle at n=14, where the routing threshold is crossed.

    slow-marked (the ~105 s worst case of the whole suite: 16 fuzz
    iterations, each a fresh multi-qubit compile + dense oracle) so
    tier-1 fits its 870 s budget — the same discipline as the
    test_distributed suite; CI's unfiltered `pytest tests/` and
    `-m slow` runs keep it covered."""
    import jax.numpy as jnp
    from quest_tpu.ops import apply as A
    from . import oracle

    rng = np.random.default_rng(77)
    n = 14
    amps0 = rng.standard_normal((2, 1 << n)).astype(np.float32)
    amps0 /= np.linalg.norm(amps0)
    amps = jnp.asarray(amps0)
    vec = (amps0[0] + 1j * amps0[1]).astype(np.complex128)
    for _ in range(16):
        k = int(rng.integers(1, 5))
        qs = rng.permutation(n)[:k + 2]
        targets = tuple(int(q) for q in qs[:k])
        if not any(t < 7 for t in targets):
            targets = (int(rng.integers(0, 7)),) + targets[1:]
            targets = tuple(dict.fromkeys(targets))
            k = len(targets)
        ncs = int(rng.integers(0, 3))
        controls = tuple(int(q) for q in qs[k:k + ncs]
                         if q not in targets)
        cstates = tuple(int(b) for b in rng.integers(0, 2, len(controls)))
        m = (rng.standard_normal((1 << k, 1 << k))
             + 1j * rng.standard_normal((1 << k, 1 << k)))
        mp = (m.real.astype(np.float32), m.imag.astype(np.float32))
        got = np.asarray(A.apply_matrix(amps, n, mp, targets, controls,
                                        cstates))
        want = oracle.apply_to_vector(vec, n, m, list(targets),
                                      list(controls), list(cstates) or None)
        err = np.abs((got[0] + 1j * got[1]) - want).max()
        assert err < 1e-5, (targets, controls, cstates, err)


@pytest.mark.slow          # ~34 s across the 7 pairs — tier-1 budget
                           # discipline; the randomized sweep oracles
                           # keep cross-band 2q coverage in tier-1
                           # (runs in the full CI suite step)
@pytest.mark.parametrize("pair", [(3, 10), (3, 17), (10, 17), (15, 18),
                                  (16, 22), (3, 22), (10, 22)])
def test_fused_2q_unitary_every_band_pair(pair, rng):
    """Random 2q unitaries across every band-class pair at n=23 (lane,
    sublane, scb-band, top band) stay fused — KAK for cross-band, scb
    composition within a high band — and match the per-gate engine."""
    import jax.numpy as jnp
    from quest_tpu.circuit import Circuit
    from quest_tpu.ops import pallas_band as PB
    from quest_tpu.ops import fusion as F

    n = 23
    u = oracle.random_unitary(2, rng)
    c = Circuit(n)
    c.h(pair[0])
    c.gate(u, pair)
    c.ry(pair[1], 0.3)
    items = F.plan(c._flat_ops(n, False), n, bands=PB.plan_bands(n))
    parts = PB.segment_plan(items, n)
    assert all(p[0] == "segment" for p in parts), [p[0] for p in parts]
    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 5].set(1.0)
    got = np.asarray(c.compiled_fused(n, density=False, donate=False,
                                      interpret=True)(amps)).reshape(2, -1)
    want = np.asarray(c.compiled(n, density=False, donate=False)(amps))
    np.testing.assert_allclose(got, want, atol=5e-5, rtol=0)
