"""Batched execution engine (ISSUE 4): B states through one sweep
launch. Plan-level goldens (launch count independent of B — the
acceptance metric, also gated in CI by scripts/check_batch_golden.py),
bit-identical batched-vs-per-state execution through the interpret-mode
kernels and the f64 banded fallback, bucketing cache discipline (one
compiled program per bucket, CompileAuditor-pinned), the trajectory
fast path against the eager per-shot workers AND the exact density
engine, and the sharded engine's batch-local axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from quest_tpu import trajectories as T
from quest_tpu.circuit import Circuit
from quest_tpu.ops import fusion as F
from quest_tpu.ops import pallas_band as PB

pytestmark = pytest.mark.dtype_agnostic

N = 10
EPS_F32 = 1e-4       # the sweep suite's documented f32 envelope
EPS_F64 = 1e-11


def _unitary_circuit(n: int = N) -> Circuit:
    c = Circuit(n)
    for q in range(7):
        c.h(q)
    c.cz(0, 8)
    c.rz(9, 0.4)
    c.cnot(2, 9)
    c.ry(8, 0.3)
    return c


def _noisy_circuit(n: int) -> Circuit:
    """Unitary stretches with a general-Kraus (damping: launch barrier)
    and mixture channels on lane/sublane qubits."""
    c = Circuit(n)
    for q in range(7):
        c.h(q)
    c.cz(0, 8)
    c.rz(9, 0.4)
    c.damping(2, 0.3)          # lane qubit, state-dependent draw
    c.ry(8, 0.3)
    c.depolarising(8, 0.2)     # sublane qubit, mixture
    c.ry(9, 0.2)
    c.dephasing(0, 0.25)       # lane qubit, mixture
    return c


# ---------------------------------------------------------------------------
# plan goldens: launches independent of B
# ---------------------------------------------------------------------------


def test_traj_plan_launches_independent_of_B():
    """THE acceptance golden: a B=256 trajectory workload at n=20
    reports the SAME hbm_sweeps as the unbatched (B=1) plan — the
    launch count of a B-shot run does not scale with B."""
    c = _noisy_circuit(20)
    one = T.plan_stats(c, 1)
    many = T.plan_stats(c, 256)
    assert many["hbm_sweeps"] == one["hbm_sweeps"], (one, many)
    assert many["states_per_sweep"] == 256
    assert many["batch"] == 256
    assert many["channels"] == 3
    assert many["inline_channels"] == 3        # all 1q -> in-kernel
    # every channel fused into a sweep: no XLA passthrough passes
    assert many["hbm_sweeps"] == many["kernel_sweeps"], many


def test_barrier_channel_bounds_sweep_merging():
    """A general-Kraus channel (state-dependent Born draw) must LEAD its
    launch; mixture channels fuse anywhere. The noisy circuit therefore
    plans exactly 2 sweeps: [pre-damping stages] then [damping + rest],
    and the barrier stage sits at position 0 of its sweep."""
    c = _noisy_circuit(N)
    stats = T.plan_stats(c, 8)
    assert stats["hbm_sweeps"] == 2, stats
    items, channels = T._traj_channels_and_items(c, N, True)
    parts = PB.maybe_sweep(PB.segment_plan(items, N, batch=8), N)
    for part in parts:
        assert part[0] == "segment"
        for j, st in enumerate(part[1]):
            if isinstance(st, PB.BatchSelStage) and st.barrier:
                assert j == 0, part[1]
    # placeholder operands carry the batch through the byte budget
    placeholders = [a for p in parts for st, a in zip(p[1], p[2])
                    if isinstance(st, PB.BatchSelStage)]
    assert placeholders and all(a.shape == (8, 8) for a in placeholders)


def test_compiled_batched_plan_stats_and_explain():
    c = _unitary_circuit()
    rec = c.plan_stats(batch=5)
    assert rec["batched"]["batch"] == 5
    assert rec["batched"]["bucket"] == 8
    assert rec["batched"]["states_per_sweep"] == 8
    assert rec["batched"]["hbm_sweeps"] == rec["fused"]["hbm_sweeps"]
    text = c.explain(batch=5)
    assert "bucket 8" in text and "independent of B" in text


# ---------------------------------------------------------------------------
# bucketing: one compiled program per bucket
# ---------------------------------------------------------------------------


def test_bucketed_batch_sizes_share_one_cache_entry(compile_auditor):
    """B=5 and B=8 both bucket to 8 and must resolve to the SAME
    compiled program object; warm reruns (either size) trace NOTHING."""
    c = _unitary_circuit()
    fn5 = c.compiled_batched(5, interpret=True, donate=False)
    fn8 = c.compiled_batched(8, interpret=True, donate=False)
    assert fn5 is fn8
    assert fn5.bucket == 8
    rng = np.random.default_rng(0)
    a5 = jnp.asarray(rng.standard_normal((5, 2, 1 << N)).astype(np.float32))
    a8 = jnp.asarray(rng.standard_normal((8, 2, 1 << N)).astype(np.float32))
    fn5(a5)
    fn8(a8)                               # warm both call shapes
    with compile_auditor as aud:
        fn5(a5)
        fn8(a8)
    aud.assert_no_retrace("bucketed batched engine")


def test_bucket_off_compiles_exact_sizes(monkeypatch):
    monkeypatch.setenv("QUEST_BATCH_BUCKET", "off")
    c = _unitary_circuit()
    fn5 = c.compiled_batched(5, interpret=True, donate=False)
    fn8 = c.compiled_batched(8, interpret=True, donate=False)
    assert fn5 is not fn8
    assert fn5.bucket == 5 and fn8.bucket == 8


def test_oversized_batch_rejected():
    c = _unitary_circuit()
    fn = c.compiled_batched(4, interpret=True, donate=False)
    with pytest.raises(ValueError, match="bucket"):
        fn(jnp.zeros((5, 2, 1 << N), jnp.float32))


# ---------------------------------------------------------------------------
# execution: batched == per-state, f32 kernels and f64 fallback
# ---------------------------------------------------------------------------


def test_batched_matches_per_state_f32():
    c = _unitary_circuit()
    rng = np.random.default_rng(1)
    amps = rng.standard_normal((5, 2, 1 << N)).astype(np.float32)
    got = np.asarray(c.compiled_batched(5, interpret=True,
                                        donate=False)(jnp.asarray(amps)))
    ref = c.compiled_fused(N, False, donate=False, interpret=True)
    want = np.stack([
        np.asarray(ref(jnp.asarray(amps[i]).reshape(2, -1, PB.LANES))
                   ).reshape(2, -1) for i in range(5)])
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=EPS_F32 * scale, rtol=0)


def test_batched_matches_per_state_f64_limb():
    """f64 batches ride the vmapped banded program at full precision."""
    c = _unitary_circuit()
    rng = np.random.default_rng(2)
    amps = rng.standard_normal((3, 2, 1 << N)).astype(np.float64)
    got = np.asarray(c.compiled_batched(3, interpret=True,
                                        donate=False)(jnp.asarray(amps)))
    ref = c.compiled_banded(N, False, donate=False)
    want = np.stack([np.asarray(ref(jnp.asarray(amps[i])))
                     for i in range(3)])
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=EPS_F64 * scale, rtol=0)


def test_batch_one_mixed_segment_xla_plan():
    """REGRESSION: compiled_batched(1) on a plan that mixes kernel
    segments with vmapped XLA passthroughs. compile_segment used to key
    batched-ness on batch > 1, so the B=1 bucket got the UNBATCHED
    kernel (3D output, leading batch axis dropped) and the vmapped
    passthrough then mapped over the plane axis — a TypeError here, or
    silently corrupt amplitudes for passthroughs whose reshape happens
    to be size-compatible. batch=None now means unbatched; any integer
    bucket (including 1) keeps the (B, 2, rows, 128) convention."""
    c = Circuit(N)
    for q in range(4):
        c.h(q)
    u = np.eye(8, dtype=np.complex64)
    u[6, 6], u[6, 7], u[7, 6], u[7, 7] = 0, 1, 1, 0
    c.gate(u, (0, 2, 9))       # 3-qubit cross-band: XLA passthrough
    c.ry(8, 0.3)
    parts = PB.maybe_sweep(PB.segment_plan(
        F.plan(c._planned_flat(N, False), N, bands=PB.plan_bands(N)),
        N), N)
    assert [p[0] for p in parts] == ["segment", "xla", "segment"], parts
    amps = np.zeros((1, 2, 1 << N), dtype=np.float32)
    amps[0, 0, 0] = 1.0
    got = np.asarray(c.compiled_batched(1, interpret=True,
                                        donate=False)(jnp.asarray(amps)))
    want = np.asarray(c.compiled(N, False, donate=False)(amps[0]))
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got[0], want, atol=EPS_F32 * scale,
                               rtol=0)


def test_zero_padding_is_exact():
    """A padded bucket (B=3 -> 8) returns bit-identical results to the
    full-bucket run's first 3 states: every engine op is a linear map,
    so zero padding states cannot leak into real ones."""
    c = _unitary_circuit()
    rng = np.random.default_rng(3)
    amps8 = rng.standard_normal((8, 2, 1 << N)).astype(np.float32)
    fn = c.compiled_batched(8, interpret=True, donate=False)
    full = np.asarray(fn(jnp.asarray(amps8)))
    part = np.asarray(fn(jnp.asarray(amps8[:3])))
    np.testing.assert_array_equal(part, full[:3])


# ---------------------------------------------------------------------------
# trajectories fast path
# ---------------------------------------------------------------------------


def test_run_batched_matches_eager_per_shot_banded():
    """Batched trajectory shots reproduce the eager module functions
    shot-for-shot on identical keys: same branch draws, same amplitudes
    (the per-state unbatched reference)."""
    import quest_tpu as qt
    from quest_tpu.state import basis_planes

    n = 4
    c = Circuit(n)
    c.h(0).cnot(0, 1).ry(2, 0.7)
    c.damping(0, 0.3)
    c.depolarising(1, 0.2)
    c.h(3)
    c.dephasing(2, 0.25)
    key = jax.random.key(11)
    planes, draws = T.run_batched(c, key, 8, engine="banded")
    keys = jax.random.split(key, 8)

    def eager_shot(k):
        a = basis_planes(0, n=n, rdt=jnp.float32)
        a = qt.variational.h(a, n, 0)
        a = qt.variational.cnot(a, n, 0, 1)
        a = qt.variational.ry(a, n, 2, 0.7)
        a, k, d0 = T.damping(a, k, n, 0, 0.3)
        a, k, d1 = T.depolarising(a, k, n, 1, 0.2)
        a = qt.variational.h(a, n, 3)
        a, k, d2 = T.dephasing(a, k, n, 2, 0.25)
        return a, jnp.stack([d0, d1, d2])

    want = [eager_shot(keys[i]) for i in range(8)]
    want_planes = np.stack([np.asarray(w[0]) for w in want])
    want_draws = np.stack([np.asarray(w[1]) for w in want])
    np.testing.assert_array_equal(np.asarray(draws), want_draws)
    np.testing.assert_allclose(np.asarray(planes), want_planes,
                               atol=EPS_F32, rtol=0)


def test_run_batched_fused_matches_banded():
    """The batched KERNEL path (BatchSelStage channels on lane and
    sublane qubits, interpret mode) draws identically to and matches
    the vmapped banded path within the f32 envelope."""
    c = _noisy_circuit(N)
    key = jax.random.key(7)
    pb, db = T.run_batched(c, key, 4, engine="banded")
    pf, df = T.run_batched(c, key, 4, engine="fused", interpret=True)
    np.testing.assert_array_equal(np.asarray(db), np.asarray(df))
    np.testing.assert_allclose(np.asarray(pf), np.asarray(pb),
                               atol=EPS_F32, rtol=0)


def test_run_batched_host_matches_banded():
    """The native HOST engine (the off-chip default: C++ blocked
    kernels + native channel butterflies, jax draws) takes the same
    branches and matches the banded engine's amplitudes."""
    from quest_tpu import host as H
    if not H.available():
        pytest.skip("native host library unavailable")
    c = _noisy_circuit(N)
    key = jax.random.key(7)
    pb, db = T.run_batched(c, key, 8, engine="banded")
    ph, dh = T.run_batched(c, key, 8, engine="host")
    np.testing.assert_array_equal(np.asarray(db), np.asarray(dh))
    np.testing.assert_allclose(np.asarray(ph), np.asarray(pb),
                               atol=EPS_F32, rtol=0)


def test_run_batched_scattered_qubit_channel():
    """BatchSelStage's third geometry: a channel on a SCATTERED qubit
    (>= 14) butterflies on per-state scalars inside the kernel."""
    n = 15
    c = Circuit(n)
    c.h(14).ry(14, 0.4)
    c.depolarising(14, 0.3)
    c.rz(14, 0.2)
    key = jax.random.key(3)
    pb, db = T.run_batched(c, key, 4, engine="banded")
    pf, df = T.run_batched(c, key, 4, engine="fused", interpret=True)
    np.testing.assert_array_equal(np.asarray(db), np.asarray(df))
    np.testing.assert_allclose(np.asarray(pf), np.asarray(pb),
                               atol=EPS_F32, rtol=0)


def test_run_batched_chunking_and_bucket_reuse():
    """Chunked runs slice the SAME compiled program across chunks and
    concatenate to the unchunked result (identical keys per shot)."""
    c = _noisy_circuit(N)
    key = jax.random.key(5)
    p1, d1 = T.run_batched(c, key, 6, engine="banded")
    p2, d2 = T.run_batched(c, key, 6, engine="banded", chunk=4)
    # chunked draws match shot-for-shot (same per-shot keys)...
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    # ...and amplitudes agree within the f32 envelope (bucket size may
    # legally reassociate XLA reductions)
    np.testing.assert_allclose(np.asarray(p2), np.asarray(p1),
                               atol=EPS_F32, rtol=0)
    assert p2.shape == (6, 2, 1 << N)


def test_run_batched_observable_reduction():
    """`observable=` reduces each chunk before the next one runs (no
    shots x 2^n materialization) and matches reducing the full planes."""
    c = _noisy_circuit(N)
    key = jax.random.key(9)

    def z_top(planes):
        v = (planes[:, 0] ** 2 + planes[:, 1] ** 2).reshape(
            planes.shape[0], 2, -1)
        return jnp.sum(v[:, 0] - v[:, 1], axis=1)

    planes, d1 = T.run_batched(c, key, 6, engine="banded", chunk=4)
    vals, d2 = T.run_batched(c, key, 6, engine="banded", chunk=4,
                             observable=z_top)
    np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
    np.testing.assert_allclose(np.asarray(vals),
                               np.asarray(z_top(planes)),
                               atol=1e-6, rtol=0)
    assert vals.shape == (6,)


def test_trajectory_estimator_matches_density_engine():
    """The batched estimator converges to the exact density engine —
    the same pin tests/test_trajectories.py holds for the eager path,
    here through run_batched."""
    from quest_tpu.ops import channels as ch
    from quest_tpu.state import to_dense
    import quest_tpu as qt

    n = 3
    c = Circuit(n)
    c.h(0).cnot(0, 1).ry(2, 0.7)
    c.damping(0, 0.3)
    c.depolarising(1, 0.2)
    planes, _ = T.run_batched(c, jax.random.key(11), 4096,
                              engine="banded")
    got = np.asarray(T.average_density(planes))

    q = qt.create_density_qureg(n, dtype=np.complex128)
    from quest_tpu.ops import gates as G
    q = G.hadamard(q, 0)
    q = G.controlled_not(q, 0, 1)
    q = G.rotate_y(q, 2, 0.7)
    q = ch.mix_damping(q, 0, 0.3)
    q = ch.mix_depolarising(q, 1, 0.2)
    want = to_dense(q)
    assert np.max(np.abs(got - want)) < 0.05


def test_kraus_validation_runs_once_for_batched_shots(monkeypatch):
    """The hoist regression (ISSUE 4 satellite): B=64 shots of a kraus
    channel validate the CPTP condition EXACTLY once — at plan time —
    not once per shot/trace."""
    from quest_tpu import validation as val

    calls = {"n": 0}
    real = val.validate_kraus_ops

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(val, "validate_kraus_ops", counting)
    T._VALIDATED_KRAUS.clear()
    n = 4
    c = Circuit(n)
    c.h(0)
    # a channel shape the memo has not seen (unique probability)
    c.damping(1, 0.3141592)
    calls["n"] = 0                 # drop the build-time validation
    T._VALIDATED_KRAUS.clear()
    planes, draws = T.run_batched(c, jax.random.key(0), 64,
                                  engine="banded")
    assert planes.shape[0] == 64
    assert calls["n"] == 1, calls


def test_eager_kraus_validation_memoized(monkeypatch):
    """The eager path's per-shot Python loop also validates once per
    distinct channel (the memo), not once per call."""
    from quest_tpu import validation as val
    from quest_tpu.ops import matrices as M
    from quest_tpu.state import basis_planes

    calls = {"n": 0}
    real = val.validate_kraus_ops

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(val, "validate_kraus_ops", counting)
    T._VALIDATED_KRAUS.clear()
    n = 3
    key = jax.random.key(1)
    ops = M.damping_kraus(0.2718281)
    amps = basis_planes(1, n=n, rdt=jnp.float32)
    for _ in range(8):
        _, key, _ = T.kraus(amps, key, n, 0, ops)
    assert calls["n"] == 1, calls


# ---------------------------------------------------------------------------
# sharded: batch axis local to the amplitude mesh
# ---------------------------------------------------------------------------


def test_sharded_batched_matches_per_state():
    from quest_tpu.parallel.mesh import make_amp_mesh

    n = 11                      # local_n = 10: kernel tier per shard
    mesh = make_amp_mesh(2)
    c = Circuit(n)
    for q in range(7):
        c.h(q)
    c.cz(0, 9)
    c.rz(10, 0.4)
    c.cnot(2, 10)               # global-qubit work: vmapped ppermute
    rng = np.random.default_rng(3)
    amps = rng.standard_normal((3, 2, 1 << n)).astype(np.float32)
    fn3 = c.compiled_sharded_batched(3, mesh, donate=False,
                                     interpret=True)
    fn4 = c.compiled_sharded_batched(4, mesh, donate=False,
                                     interpret=True)
    assert fn3 is fn4           # same bucket, one compiled program
    got = np.asarray(fn3(jnp.asarray(amps)))
    ref = c.compiled_sharded_fused(n, False, mesh=mesh, donate=False,
                                   interpret=True)
    want = np.stack([np.asarray(ref(jnp.asarray(amps[i]))).reshape(2, -1)
                     for i in range(3)])
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got, want, atol=EPS_F32 * scale, rtol=0)
    text = c.explain_sharded(mesh, engine="fused", batch=3)
    assert "LOCAL to the amplitude mesh" in text


# ---------------------------------------------------------------------------
# variational sweep helper
# ---------------------------------------------------------------------------


def test_variational_sweep_matches_loop():
    from quest_tpu import variational as V

    n = 3

    def ansatz(amps, params):
        amps = V.ry(amps, n, 0, params[0])
        amps = V.cnot(amps, n, 0, 1)
        amps = V.rz(amps, n, 1, params[1])
        return amps

    codes = [[3, 3, 0]]
    energy = V.expectation(ansatz, n, codes, [1.0])
    rng = np.random.default_rng(4)
    batch = rng.uniform(0, 2 * np.pi, size=(5, 2)).astype(np.float32)
    got = np.asarray(V.sweep(energy, batch, chunk=4))
    want = np.asarray([energy(b) for b in batch])
    np.testing.assert_allclose(got, want, atol=1e-6, rtol=0)
