"""Commutation-aware gate scheduler (quest_tpu/ops/fusion.py schedule):
golden pass-count regressions + equivalence fuzz across every engine.

The scheduler's contract has two halves, tested separately:

  * PLANNING (pure host math, no compile): scheduled plans must show the
    promised pass-count reductions — the QFT-30 fused-engine schedule
    drops >= 2x in full-state HBM passes (the r5 QFT-vs-RCS gap's
    currency), and no benchmark workload regresses. Pass counts come
    from Circuit.plan_stats, the same statistics explain() prints, so
    the asserted metric IS the reported one.

  * SEMANTICS: a scheduled program must equal the unscheduled one.
    Every reorder is justified by the planner's structural commutation
    rule and every composition is a product of commuting diagonals, so
    scheduled engines are fuzzed against the UNSCHEDULED per-gate XLA
    oracle — statevector and density, on the banded, fused(interpret),
    host and sharded engines.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import quest_tpu as qt
from quest_tpu.circuit import (Circuit, flatten_ops, qft_circuit,
                               random_circuit)
from quest_tpu.ops import fusion as F
from quest_tpu.state import to_dense


def _stats(circ, sched: bool, density=False):
    os.environ["QUEST_SCHEDULE"] = "1" if sched else "0"
    try:
        return circ.plan_stats(density=density)
    finally:
        os.environ.pop("QUEST_SCHEDULE", None)


def ghz_circuit(n):
    c = Circuit(n)
    c.h(0)
    for q in range(n - 1):
        c.cnot(q, q + 1)
    return c


# ---------------------------------------------------------------------------
# golden pass-count regressions (CPU-only planning math)
# ---------------------------------------------------------------------------


def test_qft30_scheduled_halves_full_state_passes():
    """THE acceptance metric: the scheduled QFT-30 fused plan must show
    >= 2x fewer full-state passes than the unscheduled plan (the 435
    controlled phases compose into cross-layer groups instead of one
    stage each; measured at this commit: 14 -> 6)."""
    c = qft_circuit(30)
    un = _stats(c, sched=False)["fused"]
    sc = _stats(c, sched=True)["fused"]
    assert sc["full_state_passes"] * 2 <= un["full_state_passes"], (
        sc, un)
    # and the reduction is composition doing the work, not accounting:
    # stage count collapses too
    assert sc["stages"] * 2 <= un["stages"]


def test_qft30_scheduler_stats_surface_the_fusions():
    st = _stats(qft_circuit(30), sched=True)["scheduler"]
    assert st["fused_groups"] > 0
    assert st["fused_ops"] > 300          # most of the 435 phases
    assert st["delayed"] > 0

    text_on = os.environ.get("QUEST_SCHEDULE")
    assert text_on is None                # _stats restored the env
    out = qft_circuit(30).explain()
    assert "scheduler: on" in out


def test_ghz_plan_unchanged_by_scheduler():
    """GHZ has nothing poolable (H + CNOT chain): the scheduler must be
    an exact no-op, not merely equivalence-preserving."""
    n = 24
    c = ghz_circuit(n)
    flat = flatten_ops(c.ops, n, False)
    sched, stats = F.schedule(flat, n)
    assert sched == list(flat)
    assert stats["fused_groups"] == 0 and stats["delayed"] == 0
    un = _stats(c, sched=False)["fused"]
    sc = _stats(c, sched=True)["fused"]
    assert sc == un


@pytest.mark.slow          # ~7 s 30q-class planning — tier-1 budget
                           # discipline; the sweep golden gate holds
                           # the plan ceilings CI-side
def test_rcs30_does_not_regress():
    """The headline workload: scheduling must not add passes (it
    currently removes a couple by composing the CZ brick)."""
    c = random_circuit(30, 20, seed=11)
    un = _stats(c, sched=False)["fused"]
    sc = _stats(c, sched=True)["fused"]
    assert sc["full_state_passes"] <= un["full_state_passes"]
    assert sc["stages"] <= un["stages"]


def test_chain_bench_variant_is_fusion_resistant():
    """bench.py's dependent-chain variant must stay one stage per gate
    UNDER THE SCHEDULER — that is its whole point (VERDICT r5 weak #7:
    the per-stage floor must be publicly bounded)."""
    import bench
    n = 24
    c = bench._build_chain_circuit(n)
    sc = _stats(c, sched=True)["fused"]
    assert sc["stages"] >= len(c.ops)
    assert sc["kernel_segments"] >= 1


def test_scheduler_knob_parses_loudly(monkeypatch):
    monkeypatch.setenv("QUEST_SCHEDULE", "yes")
    with pytest.raises(ValueError, match="QUEST_SCHEDULE"):
        F._schedule_enabled()


def test_scheduler_knob_in_engine_mode_key(monkeypatch):
    """Flipping QUEST_SCHEDULE mid-process must change the compiled
    program cache key (the stale-program class of ADVICE r4 item 2)."""
    from quest_tpu.circuit import _engine_mode_key
    k1 = _engine_mode_key()
    monkeypatch.setenv("QUEST_SCHEDULE", "0")
    assert _engine_mode_key() != k1


def test_composed_diag_survives_target_remapping():
    """ComposedDiag carries its parts target-RELATIVE, so the sharded
    relabel pass's dataclasses.replace on targets keeps them valid."""
    import dataclasses
    c = qft_circuit(12)
    flat = F.maybe_schedule(flatten_ops(c.ops, 12, False), 12)
    groups = [op for op in flat if isinstance(op, F.ComposedDiag)]
    assert groups, "QFT-12 must produce composed diagonals"
    g = groups[0]
    remapped = dataclasses.replace(
        g, targets=tuple(reversed(g.targets)))
    assert remapped.parts == g.parts      # indices, not absolute qubits


def test_wide_diagonal_never_seeds_an_open_group():
    """A forced diagonal WIDER than DIAG_FUSE_MAX (e.g. a many-control
    phase) must emit alone as a CLOSED group: before the fix it seeded a
    group with empty recorded support that later ops joined, composing a
    ComposedDiag past the cap (2^k select-chain blowup in the kernel)."""
    n = 12
    wide = np.exp(1j * np.linspace(0, 1, 1 << 9))   # 9-qubit diagonal
    small = np.exp(1j * np.array([0.0, 0.4, 0.8, 1.2]))
    c = Circuit(n)
    c._add("diagonal", tuple(range(1, 10)), wide)   # spans bands 0 and 1
    c._add("diagonal", (0, 8), small)
    c.h(0)
    c.h(8)
    sched, stats = F.schedule(flatten_ops(c.ops, n, False), n)
    for op in sched:
        if isinstance(op, F.ComposedDiag):
            assert len(op.targets) <= F.DIAG_FUSE_MAX, op.targets
    # the wide diagonal survives un-composed
    assert any(len(op.targets) == 9 and not isinstance(op, F.ComposedDiag)
               for op in sched if op.kind == "diagonal")


def test_duplicate_diag_ops_pool_by_identity():
    """Two structurally-identical diagonal ops with DISTINCT (but equal)
    ndarray operands: pool bookkeeping must use identity — GateOp
    equality compares operands elementwise and raises on ndarrays."""
    n = 10
    d = np.exp(1j * np.array([0.0, 0.1, 0.2, 0.3]))
    c = Circuit(n)
    c._add("diagonal", (0, 8), d.copy())
    c._add("diagonal", (0, 8), d.copy())
    c.h(0)
    sched, stats = F.schedule(flatten_ops(c.ops, n, False), n)
    assert stats["fused_groups"] == 1 and stats["fused_ops"] == 2
    got = np.sort_complex(np.asarray(
        [op for op in sched if isinstance(op, F.ComposedDiag)][0]
        .operand).reshape(-1))
    want = np.sort_complex((np.asarray(d) ** 2).reshape(-1))
    np.testing.assert_allclose(got, want, atol=1e-12)


# ---------------------------------------------------------------------------
# equivalence fuzz: scheduled engines vs the unscheduled XLA oracle
# ---------------------------------------------------------------------------


def _phase_heavy_circuit(n, depth, seed):
    """The scheduler's adversarial mix: interleaved Hadamards, rotations,
    controlled phases (cross- and in-band), parities, diagonals, CNOTs
    and swaps — everything the pool/compose path touches."""
    rng = np.random.default_rng(seed)
    c = Circuit(n)
    for _ in range(depth):
        kind = int(rng.integers(0, 8))
        q = int(rng.integers(0, n))
        q2 = int(rng.integers(0, n))
        a = float(rng.uniform(0, 2 * np.pi))
        if kind == 0:
            c.h(q)
        elif kind == 1:
            c.rx(q, a)
        elif kind == 2 and q2 != q:
            c.cphase(a, q, q2)
        elif kind == 3:
            qs = sorted(rng.choice(n, size=min(3, n), replace=False))
            c.multi_rotate_z(tuple(int(x) for x in qs), a)
        elif kind == 4:
            c.phase(q, a)
        elif kind == 5 and q2 != q:
            c.cnot(q, q2)
        elif kind == 6 and q2 != q:
            c.cz(q, q2)
        elif kind == 7 and q2 != q:
            c.swap(q, q2)
    return c


def _sv(circ, n, runner):
    amps = jnp.zeros((2, 1 << n), dtype=jnp.float32).at[0, 0].set(1.0)
    out = runner(circ, n, amps)
    return np.asarray(out[0]) + 1j * np.asarray(out[1])


def _oracle_state(builder, n):
    """UNSCHEDULED per-gate XLA engine — the semantic reference."""
    os.environ["QUEST_SCHEDULE"] = "0"
    try:
        c = builder()
        return _sv(c, n, lambda c_, n_, a: c_.compiled(
            n_, density=False, donate=False)(a))
    finally:
        os.environ.pop("QUEST_SCHEDULE", None)


@pytest.mark.parametrize("seed", [0, 1])
def test_fuzz_scheduled_banded_and_fused_match_oracle(seed):
    n = 10
    want = _oracle_state(lambda: _phase_heavy_circuit(n, 45, seed), n)
    c = _phase_heavy_circuit(n, 45, seed)
    got_b = _sv(c, n, lambda c_, n_, a: c_.compiled_banded(
        n_, density=False, donate=False)(a))
    np.testing.assert_allclose(got_b, want, atol=3e-5, rtol=0)

    from quest_tpu.state import fused_state_shape
    c2 = _phase_heavy_circuit(n, 45, seed)
    amps = jnp.zeros(fused_state_shape(n),
                     jnp.float32).at[0, 0, 0].set(1.0)
    out = c2.compiled_fused(n, density=False, donate=False,
                            interpret=True)(amps).reshape(2, -1)
    got_f = np.asarray(out[0]) + 1j * np.asarray(out[1])
    np.testing.assert_allclose(got_f, want, atol=3e-5, rtol=0)


def test_fuzz_scheduled_qft_every_single_chip_engine():
    n = 11
    want = _oracle_state(lambda: qft_circuit(n), n)
    c = qft_circuit(n)
    got = _sv(c, n, lambda c_, n_, a: c_.compiled_banded(
        n_, density=False, donate=False)(a))
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=0)


def test_fuzz_scheduled_host_engine_matches():
    """The native host engine consumes Circuit.ops directly (no
    scheduling), so it doubles as an independent oracle here."""
    from quest_tpu import host as H
    if not H.available():
        pytest.skip("native host library unavailable")
    n = 9
    c = _phase_heavy_circuit(n, 50, 7)
    want = _oracle_state(lambda: _phase_heavy_circuit(n, 50, 7), n)
    q = qt.create_qureg(n)
    q = qt.init_zero_state(q)
    got = to_dense(c.apply_host(q))
    np.testing.assert_allclose(got, want, atol=3e-5, rtol=0)


def test_fuzz_scheduled_density_banded_matches():
    """Density register: duals are scheduled too (the flat list carries
    them explicitly); banded scheduled vs unscheduled XLA."""
    n = 4
    c = _phase_heavy_circuit(n, 30, 3)
    c.damping(1, 0.1)
    rho_w = qt.init_debug_state(qt.create_density_qureg(n))
    os.environ["QUEST_SCHEDULE"] = "0"
    try:
        want = to_dense(c.apply(rho_w))
    finally:
        os.environ.pop("QUEST_SCHEDULE", None)
    rho_g = qt.init_debug_state(qt.create_density_qureg(n))
    got = to_dense(c.apply_banded(rho_g))
    np.testing.assert_allclose(got, want, atol=3e-4, rtol=0)


def test_fuzz_scheduled_sharded_engines_match():
    """Scheduled sharded banded + fused(interpret) on a virtual mesh vs
    the unscheduled oracle — the relabel interaction path (engine_flat
    schedules BEFORE plan_full_relabels; its A/B guard judges the
    scheduled list)."""
    from quest_tpu.parallel import make_amp_mesh, shard_qureg
    from quest_tpu.parallel.sharded import (
        compile_circuit_sharded_banded, compile_circuit_sharded_fused)
    from quest_tpu.state import init_state_from_amps
    from .helpers import max_mesh_devices

    mesh = make_amp_mesh(max_mesh_devices())
    n = 8
    want = _oracle_state(lambda: _phase_heavy_circuit(n, 50, 5), n)
    for compiler, kw in ((compile_circuit_sharded_banded, {}),
                         (compile_circuit_sharded_fused,
                          {"interpret": True})):
        c = _phase_heavy_circuit(n, 50, 5)
        q = qt.init_zero_state(qt.create_qureg(n))
        step = compiler(c.ops, n, False, mesh, donate=False, **kw)
        sq = shard_qureg(q, mesh)
        got = to_dense(sq.replace_amps(step(sq.amps)))
        np.testing.assert_allclose(got, want, atol=3e-5, rtol=0)


def test_scheduled_dynamic_circuit_respects_measure_barrier():
    """Mid-circuit measurement is a scheduling barrier: phases must not
    cross the collapse. Same key => identical outcomes and states
    between scheduled-banded and unscheduled-xla dynamic engines."""
    n = 6
    key = jax.random.PRNGKey(42)

    def build():
        c = Circuit(n)
        c.h(0)
        c.cphase(0.7, 0, 5)
        c.h(5)
        c.measure(0)
        c.cphase(1.1, 0, 4)
        c.x_if(4, (0, 1))
        c.h(4)
        return c

    os.environ["QUEST_SCHEDULE"] = "0"
    try:
        q0 = qt.init_zero_state(qt.create_qureg(n))
        q0, out0 = build().apply_measured(q0, key, engine="xla")
    finally:
        os.environ.pop("QUEST_SCHEDULE", None)
    q1 = qt.init_zero_state(qt.create_qureg(n))
    q1, out1 = build().apply_measured(q1, key, engine="banded")
    np.testing.assert_array_equal(np.asarray(out0), np.asarray(out1))
    np.testing.assert_allclose(to_dense(q1), to_dense(q0), atol=3e-5,
                               rtol=0)
