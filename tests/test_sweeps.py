"""Sweep-fusion layer tests (quest_tpu/ops/pallas_band.py sweep_plan):
merge rules, golden hbm_sweeps values for the benchmark circuits, and a
randomized equivalence suite proving sweep-fused execution matches the
unfused semantics within documented eps (docs/SWEEPS.md) — across f32
interpret-mode kernels, the f64 banded fallback, and the sharded fused
engine. CPU-only: the merge decision and the hbm_sweeps metric are pure
host planning; execution runs in the Pallas interpreter.

References are the dense NumPy oracle (tests/oracle.py), NOT the
per-gate XLA engine: a deep unrolled per-gate program costs minutes of
XLA-CPU compile at x64, while the oracle is exact and compile-free.

Structure templates: the randomized circuits draw their GATE PATTERN
from a small template pool and their parameters per instance, so
identical-structure sweeps share one compiled kernel
(compile_segment_cached) and 50 circuits cost ~a dozen interpret-mode
compiles, not 50 (the tier-1 budget note in ROADMAP.md).
"""

import numpy as np
import pytest

import jax.numpy as jnp

import bench
from quest_tpu.circuit import Circuit, GateOp, qft_circuit
from quest_tpu.ops import fusion as F
from quest_tpu.ops import pallas_band as PB
from tests import oracle

pytestmark = pytest.mark.dtype_agnostic

N = 10

# documented equivalence eps (docs/SWEEPS.md): f32 kernels vs the f64
# oracle, relative to the largest amplitude — the same envelope the
# per-stage Pallas tests use, widened for multi-application sweeps
EPS_F32 = 1e-4
EPS_F64 = 1e-11


def plan_parts(c: Circuit, n: int = N, density: bool = False):
    items = F.plan(c._planned_flat(n * (2 if density else 1), density), n,
                   bands=PB.plan_bands(n))
    return PB.segment_plan(items, n)


# ---------------------------------------------------------------------------
# goldens: the benchmark circuits' hbm_sweeps (acceptance metric)
# ---------------------------------------------------------------------------

QFT30_GOLDEN_SWEEPS = 6      # committed golden (scripts/check_sweep_golden.py
CHAIN30_GOLDEN_SWEEPS = 1    # runs the same assertions in CI)


def test_qft30_golden_hbm_sweeps():
    rec = qft_circuit(30).plan_stats()["fused"]
    assert rec["hbm_sweeps"] == QFT30_GOLDEN_SWEEPS, rec
    # strictly below the per-stage pass count (what a no-fusion engine
    # would pay) AND no worse than the pre-sweep segment plan
    assert rec["hbm_sweeps"] < rec["stages"], rec
    assert rec["hbm_sweeps"] <= rec["full_state_passes"], rec
    assert sum(rec["sweep_stages"]) == rec["stages"], rec


def test_chain30_golden_hbm_sweeps():
    """The fusion-resistant chain: every gate is its own stage, yet one
    application is ONE HBM sweep — >= 2x below the per-stage count."""
    rec = bench._build_chain_circuit(30).plan_stats()["fused"]
    assert rec["hbm_sweeps"] == CHAIN30_GOLDEN_SWEEPS, rec
    assert rec["stages"] == bench.GATES_PER_STEP
    assert 2 * rec["hbm_sweeps"] <= rec["stages"], rec


def test_cross_iteration_sweeps_collapse_bench_dispatch():
    """The bench's INNER_STEPS=16 unrolled applications merge across
    iteration boundaries: the headline step becomes ONE kernel launch
    per dispatch (16 -> 1 HBM sweeps) and the chain collapses 16 -> 4
    (the MAX_SWEEP_STAGES budget binds at 64 stages) — the 'G sweeps ->
    ~G/k' floor the sweep layer exists for."""
    for build, want_sweeps in ((bench._build_circuit, 1),
                               (bench._build_chain_circuit, 4)):
        c = build(30)
        parts = plan_parts(c, 30)
        swept = PB.sweep_plan(parts * bench.INNER_STEPS, 30)
        assert len(swept) == want_sweeps, (build.__name__, len(swept))
        assert all(len(p[1]) <= PB.MAX_SWEEP_STAGES for p in swept)
        # stage multiset preserved, order concatenated
        assert sum(len(p[1]) for p in swept) == \
            bench.INNER_STEPS * sum(len(p[1]) for p in parts
                                    if p[0] == "segment")


# ---------------------------------------------------------------------------
# merge rules
# ---------------------------------------------------------------------------


def _seg(stages, arrays=None):
    return ("segment", list(stages),
            list(arrays) if arrays is not None
            else [np.zeros((1, 8), np.float32) for _ in stages])


def test_sweep_respects_xla_barrier():
    c = Circuit(N)
    c.h(0)
    parts = plan_parts(c)
    assert len(parts) == 1
    barrier = ("xla", object())
    swept = PB.sweep_plan([parts[0], barrier, parts[0]], N)
    assert [p[0] for p in swept] == ["segment", "xla", "segment"]


def test_sweep_scatter_budget_blocks_merge():
    """Two segments whose scattered-bit UNION exceeds the scatter budget
    stay separate sweeps; within budget they merge."""
    n = 23
    c1 = Circuit(n)
    for q in range(14, 21):
        c1.ry(q, 0.3)              # scb: scat bits 7..13
    c2 = Circuit(n)
    c2.ry(21, 0.4)
    c2.ry(22, 0.5)                 # scb: scat bits 14, 15
    (p1,) = plan_parts(c1, n)
    (p2,) = plan_parts(c2, n)
    assert len(PB.sweep_plan([p1, p2], n)) == 2      # union: 9 bits > 7
    assert len(PB.sweep_plan([p2, p2], n)) == 1      # union: 2 bits


def test_sweep_row_budget_blocks_merge():
    """A b1 sublane floor plus scattered axes above max_block_row_bits()
    blocks the merge (the same budget compile_segment sizes blocks by)."""
    n = 23
    cb1 = Circuit(n)
    for q in range(7, 14):
        cb1.ry(q, 0.2)             # b1: floor 7
    chigh = Circuit(n)
    for q in range(14, 21):
        chigh.ry(q, 0.3)           # scb: 7 scat bits
    (pb1,) = plan_parts(cb1, n)
    (ph,) = plan_parts(chigh, n)
    # floor 7 + 7 scat = 14 > 13: no merge (the measured Mosaic spill
    # wall of PIPELINED_MAX_BLOCK_ROW_BITS)
    assert len(PB.sweep_plan([pb1, ph], n)) == 2
    assert len(PB.sweep_plan([pb1, pb1], n)) == 1


def test_sweep_stage_and_operand_budgets():
    c = Circuit(N)
    for q in range(7):
        c.h(q)
    (p,) = plan_parts(c)
    assert len(PB.sweep_plan([p] * 4, N, max_stages=2)) == 2
    nbytes = sum(a.nbytes for a in p[2])
    assert len(PB.sweep_plan([p] * 4, N, operand_bytes=2 * nbytes)) == 2
    assert len(PB.sweep_plan([p] * 4, N)) == 1


def test_stage_requirements_matches_segment_geometry():
    """stage_requirements (the shared merge/geometry accounting) agrees
    with what segment_plan reserved: every planned segment fits the
    budgets it was planned under."""
    rng = np.random.default_rng(5)
    for n in (N, 17, 23):
        c = Circuit(n)
        for _ in range(24):
            q = int(rng.integers(0, n))
            c.ry(q, float(rng.uniform(0, 2 * np.pi)))
            if rng.integers(0, 2):
                r = int(rng.integers(0, n))
                if r != q:
                    c.cz(r, q)
        for part in plan_parts(c, n):
            if part[0] != "segment":
                continue
            scat, floor = PB.stage_requirements(part[1])
            assert len(scat) <= PB.SCATTER_MAX
            assert floor + len(scat) <= PB.max_block_row_bits()


def test_maybe_sweep_honors_knob(monkeypatch):
    c = Circuit(N)
    for q in range(7):
        c.h(q)
    (p,) = plan_parts(c)
    monkeypatch.setenv("QUEST_SWEEP_FUSION", "0")
    assert len(PB.maybe_sweep([p, p], N)) == 2
    rec = c.plan_stats()["fused"]
    assert not rec["sweeps_enabled"]
    assert rec["hbm_sweeps"] == rec["full_state_passes"]
    monkeypatch.setenv("QUEST_SWEEP_FUSION", "1")
    assert len(PB.maybe_sweep([p, p], N)) == 1


def test_sweep_stats_shape():
    c = Circuit(N)
    c.h(0)
    parts = plan_parts(c)
    sw = PB.sweep_stats(PB.sweep_plan(parts * 3, N))
    assert sw["hbm_sweeps"] == sw["kernel_sweeps"] == 1
    assert sw["xla_passthroughs"] == 0
    assert sw["sweep_stages"] == [3]


# ---------------------------------------------------------------------------
# randomized equivalence: 50 mixed circuits vs the dense oracle
# ---------------------------------------------------------------------------

_SEG_CACHE: dict = {}   # shared across the suite: identical-structure
# sweeps compile once (operands ride as kernel inputs)


def _template_circuit(n: int, tmpl: int, inst: int) -> Circuit:
    """A random mixed circuit whose gate PATTERN depends only on `tmpl`
    (so kernel structures repeat across instances) and whose parameters
    on (tmpl, inst). Mixes diagonal, non-diagonal and 2-qubit gates
    over every band of the register."""
    srng = np.random.default_rng(1000 + tmpl)        # structure
    arng = np.random.default_rng(7000 + 97 * tmpl + inst)  # angles
    c = Circuit(n)
    for _ in range(10):
        kind = int(srng.integers(0, 8))
        q = int(srng.integers(0, n))
        r = int(srng.integers(0, n))
        if r == q:
            r = (q + 1) % n
        ang = float(arng.uniform(0, 2 * np.pi))
        if kind == 0:
            c.h(q)
        elif kind == 1:
            c.rx(q, ang)
        elif kind == 2:
            c.ry(q, ang)
        elif kind == 3:
            c.rz(q, ang)
        elif kind == 4:
            c.phase(q, ang)                          # diagonal
        elif kind == 5:
            c.cz(q, r)                               # allones
        elif kind == 6:
            c.cnot(q, r)                             # controlled matrix
        else:
            c.multi_rotate_z(sorted({q, r}), ang)    # parity
    return c


def _oracle_vec(amps_planes: np.ndarray) -> np.ndarray:
    return (amps_planes[0].astype(np.complex128)
            + 1j * amps_planes[1].astype(np.complex128))


def _oracle_apply_ops(vec: np.ndarray, n: int, ops) -> np.ndarray:
    """Apply original GateOps to a dense complex vector (tests/oracle)."""
    for op in ops:
        k = len(op.targets)
        if op.kind == "matrix":
            mat = np.asarray(op.operand, dtype=np.complex128)
        elif op.kind == "diagonal":
            mat = np.diag(np.asarray(op.operand,
                                     dtype=np.complex128).reshape(-1))
        elif op.kind == "parity":
            diag = np.ones(1 << k, dtype=np.complex128)
            half = float(op.operand) / 2.0
            for i in range(1 << k):
                par = bin(i).count("1") & 1
                diag[i] = np.exp(-1j * half * (-1.0) ** par)
            mat = np.diag(diag)
        elif op.kind == "allones":
            diag = np.ones(1 << k, dtype=np.complex128)
            diag[-1] = complex(op.operand)
            mat = np.diag(diag)
        else:
            raise AssertionError(op.kind)
        vec = oracle.apply_to_vector(vec, n, mat, op.targets,
                                     op.controls, op.cstates)
    return vec


def _run_swept_parts(parts, n: int, amps_planes: np.ndarray) -> np.ndarray:
    """Execute a (swept) part list in the Pallas interpreter, sharing
    compiled kernels through the suite-wide structure cache."""
    out = jnp.asarray(amps_planes).reshape(2, -1, PB.LANES)
    for part in parts:
        assert part[0] == "segment", "templates avoid XLA passthroughs"
        fn = PB.compile_segment_cached(_SEG_CACHE, tuple(part[1]), n,
                                       interpret=True)
        out = fn(out, part[2])
    return np.asarray(out).reshape(2, -1)


_CASES_F32 = [(t, i) for t in range(8) for i in range(5)]      # 40
_CASES_F64 = [(8, i) for i in range(5)]                        # 5
_CASES_SHARDED = [(9, 0, np.float32), (9, 1, np.float32),
                  (9, 2, np.float32), (10, 0, np.float64),
                  (10, 1, np.float64)]                         # 5 -> 50


@pytest.mark.parametrize("tmpl,inst", _CASES_F32)
def test_sweep_fused_matches_oracle_f32(tmpl, inst):
    """Two applications' segment plans concatenated and sweep-fused
    (the cross-iteration merge in miniature) executed through the
    interpreter must match the oracle applying the circuit twice."""
    c = _template_circuit(N, tmpl, inst)
    rng = np.random.default_rng(inst)
    amps = rng.standard_normal((2, 1 << N)).astype(np.float32)
    parts = plan_parts(c)
    swept = PB.sweep_plan(parts * 2, N)
    assert len(swept) <= len(parts) * 2
    got = _run_swept_parts(swept, N, amps)
    want = _oracle_apply_ops(_oracle_vec(amps), N, list(c.ops) * 2)
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got[0] + 1j * got[1], want,
                               atol=EPS_F32 * scale, rtol=0)


@pytest.mark.parametrize("tmpl,inst", _CASES_F64)
def test_sweep_fused_matches_oracle_f64_limb(tmpl, inst):
    """f64 registers ride the fused engine's banded-XLA fallback; the
    sweep knob must leave their numerics bit-faithful to the oracle at
    f64 eps (sweeps only regroup f32 kernel launches)."""
    c = _template_circuit(N, tmpl, inst)
    rng = np.random.default_rng(100 + inst)
    amps = rng.standard_normal((2, 1 << N)).astype(np.float64)
    fn = c.compiled_fused(N, density=False, donate=False, interpret=True)
    got = np.asarray(fn(jnp.asarray(amps))).reshape(2, -1)
    want = _oracle_apply_ops(_oracle_vec(amps), N, c.ops)
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got[0] + 1j * got[1], want,
                               atol=EPS_F64 * scale, rtol=0)


@pytest.mark.parametrize("tmpl,inst,rdt", _CASES_SHARDED)
def test_sweep_fused_matches_oracle_sharded(tmpl, inst, rdt):
    """Per-shard sweeps (parallel.sharded._plan_fused_parts) on a
    2-device CPU mesh: the sharded fused engine with sweep fusion on
    must match the oracle — f32 through interpret-mode kernels, f64
    through the banded schedule over the same plan."""
    from quest_tpu.parallel.mesh import make_amp_mesh

    n = 11                      # local_n = 10: kernel tier on each shard
    mesh = make_amp_mesh(2)
    c = _template_circuit(n, tmpl, inst)
    rng = np.random.default_rng(200 + 10 * tmpl + inst)
    amps = rng.standard_normal((2, 1 << n)).astype(rdt)
    fn = c.compiled_sharded_fused(n, density=False, mesh=mesh,
                                  donate=False, interpret=True)
    got = np.asarray(fn(jnp.asarray(amps))).reshape(2, -1)
    want = _oracle_apply_ops(_oracle_vec(amps), n, c.ops)
    eps = EPS_F32 if rdt == np.float32 else EPS_F64
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got[0] + 1j * got[1], want,
                               atol=eps * scale, rtol=0)


def test_compiled_fused_cross_iteration_end_to_end():
    """The engine-level integration: compiled_fused(iters=4) merges the
    unrolled applications into one launch (plan-asserted) and matches
    the oracle applying the circuit 4 times."""
    n = N
    c = Circuit(n)
    for q in range(7):
        c.h(q)
    c.cz(0, 8)
    c.rz(9, 0.4)
    parts = plan_parts(c)
    assert len(PB.sweep_plan(parts * 4, n)) == 1
    rng = np.random.default_rng(3)
    amps = rng.standard_normal((2, 1 << n)).astype(np.float32)
    fn = c.compiled_fused(n, density=False, donate=False,
                          interpret=True, iters=4)
    got = np.asarray(fn(jnp.asarray(amps))).reshape(2, -1)
    want = _oracle_apply_ops(_oracle_vec(amps), n, list(c.ops) * 4)
    scale = max(1.0, float(np.max(np.abs(want))))
    np.testing.assert_allclose(got[0] + 1j * got[1], want,
                               atol=EPS_F32 * scale, rtol=0)


def test_explain_reports_sweeps(monkeypatch):
    monkeypatch.setenv("QUEST_SWEEP_FUSION", "1")
    c = bench._build_circuit(16)
    assert "sweep fusion: on" in c.explain()
    monkeypatch.setenv("QUEST_SWEEP_FUSION", "0")
    assert "sweep fusion: OFF" in c.explain()


def test_explain_sharded_reports_sweeps():
    from quest_tpu.parallel.mesh import make_amp_mesh
    c = _template_circuit(11, 0, 0)
    text = c.explain_sharded(make_amp_mesh(2), engine="fused")
    assert "local kernel sweeps:" in text
